"""Synthetic classification data for smoke tests and benchmarks.

Counterpart of the reference's synthetic ``TensorDataset`` walkthrough
(murmura/examples/simple_programmatic.py:24-40): well-separated Gaussian
class clusters so learning progress is visible within a few FL rounds.
Supports flat feature vectors and image-shaped tensors (for CNN models).
"""

from typing import Optional, Sequence, Tuple

import numpy as np


def make_synthetic(
    num_samples: int = 2000,
    input_shape: Sequence[int] = (32,),
    num_classes: int = 10,
    cluster_std: float = 1.0,
    seed: int = 0,
    separation: Optional[float] = None,
    label_noise: float = 0.0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Gaussian class clusters: x ~ N(mu_c, std), y = c.

    ``separation`` controls difficulty: when set, centers are scaled so the
    *expected pairwise center distance* is ``separation * cluster_std``
    (along the discriminant between two classes the projected noise std is
    ``cluster_std``, so Bayes pairwise error ~ Phi(-separation/2) regardless
    of dimensionality).  When ``None``, the legacy smoke-test behavior is
    kept — centers ~ N(0, 2) per dim, which in high dimension is trivially
    separable (round-1 weakness: every paper-matrix experiment saturated at
    accuracy 1.0000 and could not distinguish the aggregation rules).

    ``label_noise`` flips that fraction of labels to a uniformly random
    *other* class, setting an irreducible error floor the way real sensor
    datasets have one.
    """
    rng = np.random.default_rng(seed)
    input_shape = tuple(input_shape)
    dim = int(np.prod(input_shape))
    centers = rng.normal(0.0, 1.0, size=(num_classes, dim))
    if separation is None:
        centers *= 2.0
    else:
        # E||c_i - c_j|| for N(0, s^2) coords is s*sqrt(2*dim); solve for s.
        centers *= float(separation) * cluster_std / np.sqrt(2.0 * dim)
    y = rng.integers(0, num_classes, size=num_samples)
    x = centers[y] + rng.normal(0.0, cluster_std, size=(num_samples, dim))
    if label_noise > 0.0:
        flip = rng.random(num_samples) < label_noise
        shift = rng.integers(1, num_classes, size=num_samples)
        y = np.where(flip, (y + shift) % num_classes, y)
    return x.reshape((num_samples,) + input_shape).astype(np.float32), y.astype(
        np.int32
    )


def make_synthetic_sequences(
    num_samples: int = 2000,
    seq_len: int = 80,
    vocab_size: int = 81,
    seed: int = 0,
) -> Tuple[np.ndarray, np.ndarray]:
    """Synthetic next-char prediction data for the Shakespeare-style LSTM.

    Sequences follow a learnable periodic pattern with noise; the target is
    the next token (LEAF Shakespeare task shape: seq_len 80, vocab ~81 —
    reference: leaf/models/shakespeare/stacked_lstm.py:19-27).
    """
    rng = np.random.default_rng(seed)
    starts = rng.integers(0, vocab_size, size=num_samples)
    steps = rng.integers(1, 4, size=num_samples)
    t = np.arange(seq_len + 1)
    seqs = (starts[:, None] + steps[:, None] * t[None, :]) % vocab_size
    noise = rng.random(size=seqs.shape) < 0.05
    seqs = np.where(noise, rng.integers(0, vocab_size, size=seqs.shape), seqs)
    return seqs[:, :-1].astype(np.int32), seqs[:, -1].astype(np.int32)

"""Wearable sensor datasets: UCI HAR, PAMAP2, PPG-DaLiA
(reference: murmura/examples/wearables/datasets.py:12-531).

On-disk loaders are file-gated (zero-egress environment); every dataset has
a shape-identical synthetic fallback so the wearables configs stay runnable.
Partitioning follows the reference adapter (murmura/examples/wearables/
adapter.py:18-110): dirichlet / iid / natural (by subject id).
"""

from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import numpy as np

from murmura_tpu.data.base import FederatedArrays, stack_partitions
from murmura_tpu.data.partitioners import (
    dirichlet_partition,
    iid_partition,
    natural_partition,
)
from murmura_tpu.data.synthetic import make_synthetic

# (input_dim, num_classes, num_subjects) — reference: wearables/datasets.py
WEARABLE_SPECS = {
    "uci_har": (561, 6, 30),
    "pamap2": (243, 12, 9),
    "ppg_dalia": (16, 7, 15),
}


def _load_uci_har(root: Path, split: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """UCI HAR: 561 engineered features, 6 activities, 30 subjects
    (reference: wearables/datasets.py:12-89)."""
    d = root / split
    x = np.loadtxt(d / f"X_{split}.txt", dtype=np.float32)
    y = np.loadtxt(d / f"y_{split}.txt", dtype=np.int32) - 1  # 1-based -> 0-based
    subjects = np.loadtxt(d / f"subject_{split}.txt", dtype=np.int32)
    return x, y, subjects


def load_wearable_federated(
    dataset: str,
    params: Dict[str, Any],
    num_nodes: int,
    seed: int = 42,
    max_samples: Optional[int] = None,
) -> FederatedArrays:
    if dataset not in WEARABLE_SPECS:
        raise ValueError(f"Unknown wearable dataset: {dataset}")
    input_dim, num_classes, num_subjects = WEARABLE_SPECS[dataset]
    params = dict(params or {})
    data_path = params.get("data_path")
    split = params.get("split", "train")

    x = y = subjects = None
    if data_path and Path(data_path).exists():
        if dataset == "uci_har":
            x, y, subjects = _load_uci_har(Path(data_path), split)
        else:
            raise NotImplementedError(
                f"On-disk loading for wearables.{dataset} not implemented yet; "
                "omit data_path for synthetic data"
            )

    if x is None:
        n_total = int(params.get("num_samples", max(2000, 300 * num_nodes)))
        x, y = make_synthetic(
            num_samples=n_total,
            input_shape=(input_dim,),
            num_classes=num_classes,
            cluster_std=float(params.get("cluster_std", 1.5)),
            seed=seed,
        )
        rng = np.random.default_rng(seed)
        subjects = rng.integers(0, num_subjects, size=n_total)

    method = params.get("partition_method", "dirichlet")
    if method == "dirichlet":
        parts = dirichlet_partition(
            y, num_nodes, alpha=float(params.get("alpha", 0.5)), seed=seed
        )
    elif method == "iid":
        parts = iid_partition(len(y), num_nodes, seed=seed)
    elif method == "natural":
        nat, actual = natural_partition(subjects)
        # Fold natural subject groups round-robin onto the requested nodes.
        parts = [[] for _ in range(num_nodes)]
        for g, p in enumerate(nat):
            parts[g % num_nodes].extend(p)
    else:
        raise ValueError(f"Unknown partition_method: {method}")

    return stack_partitions(
        x, y, parts, max_samples=max_samples, num_classes=num_classes
    )

"""Wearable sensor datasets: UCI HAR, PAMAP2, PPG-DaLiA
(reference: murmura/examples/wearables/datasets.py:12-531).

On-disk loaders are file-gated (zero-egress environment); every dataset has
a shape-identical synthetic fallback so the wearables configs stay runnable.
Partitioning follows the reference adapter (murmura/examples/wearables/
adapter.py:18-110): dirichlet / iid / natural (by subject id).
"""

from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import numpy as np

from murmura_tpu.data.base import (
    DEFAULT_HOLDOUT_FRACTION,
    FederatedArrays,
    split_holdout,
    stack_partitions,
)
from murmura_tpu.data.partitioners import (
    dirichlet_partition,
    iid_partition,
    natural_partition,
)
from murmura_tpu.data.synthetic import make_synthetic

# UCI HAR prefers its official on-disk test split over a carved holdout
# (reference adapter's split arg: murmura/examples/wearables/adapter.py:25);
# holdout_fraction: 0.0 disables held-out eval entirely.

# (input_dim, num_classes, num_subjects) — reference: wearables/datasets.py
# and models.py:195-300 (UCI HAR 561; PAMAP2 100-sample window x 40 features;
# PPG-DaLiA 32-sample window x 6 features).
WEARABLE_SPECS = {
    "uci_har": (561, 6, 30),
    "pamap2": (4000, 12, 9),
    "ppg_dalia": (192, 7, 15),
}

# Synthetic-fallback difficulty (separation in cluster-std units, label-noise
# fraction), calibrated so 50-round *held-out* FL accuracy of clean fedavg
# lands on the reference's published numbers (RESULTS_SUMMARY.md: UCI HAR
# 85.3, PAMAP2 90.2, PPG-DaLiA 66.5) instead of saturating at 1.0 —
# saturated data can't distinguish aggregation rules.  Recalibrated in
# round 3 after evaluation moved to held-out splits (measured fedavg
# finals: 0.85 / 0.90 / 0.67).
WEARABLE_DIFFICULTY = {
    "uci_har": (6.25, 0.06),
    "pamap2": (25.0, 0.02),
    "ppg_dalia": (6.0, 0.14),
}

# PAMAP2 protocol-file layout (reference: wearables/datasets.py:117-126):
# col 0 timestamp, 1 activity, 2 heart rate; IMUs (hand/chest/ankle) start at
# 3/20/37, 17 cols each; the first 13 per IMU (temp + accel16g + accel6g +
# gyro + mag) are valid features, the trailing 4 orientation cols are not.
PAMAP2_ACTIVITIES = [1, 2, 3, 4, 5, 6, 7, 12, 13, 16, 17, 24]
PAMAP2_IMU_STARTS = (3, 20, 37)
PAMAP2_HEART_RATE_COL = 2
PAMAP2_ACTIVITY_COL = 1

# PPG-DaLiA wrist-sensor rates (reference: wearables/datasets.py:333-340):
# ACC 32 Hz, BVP 64 Hz, EDA/TEMP 4 Hz; labels at 4 Hz.
PPG_ACTIVITIES = [1, 2, 3, 4, 5, 6, 7]


def _load_uci_har(root: Path, split: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """UCI HAR: 561 engineered features, 6 activities, 30 subjects
    (reference: wearables/datasets.py:12-89)."""
    d = root / split
    x = np.loadtxt(d / f"X_{split}.txt", dtype=np.float32)
    y = np.loadtxt(d / f"y_{split}.txt", dtype=np.int32) - 1  # 1-based -> 0-based
    subjects = np.loadtxt(d / f"subject_{split}.txt", dtype=np.int32)
    return x, y, subjects


def _nan_to_column_mean(features: np.ndarray) -> np.ndarray:
    """Replace NaNs with the column mean, or 0 where a column is all-NaN
    (reference: wearables/datasets.py:233-244)."""
    col_mean = np.nanmean(
        np.where(np.isnan(features).all(0), 0.0, features), axis=0
    )
    col_mean = np.nan_to_num(col_mean, nan=0.0)
    return np.where(np.isnan(features), col_mean[None, :], features)


def _majority_windows(
    features: np.ndarray,
    activities: np.ndarray,
    window: int,
    stride: int,
) -> Tuple[np.ndarray, np.ndarray]:
    """Sliding windows with majority-activity labels, vectorized.

    The reference loops per window and takes the np.unique argmax (smallest
    activity id wins ties — wearables/datasets.py:246-275); a 2-D bincount
    over window rows reproduces that tie-break exactly.
    Returns (flattened windows [W, window*F], majority activity ids [W]).
    """
    num = len(features)
    if num < window:
        return (
            np.empty((0, window * features.shape[1]), np.float32),
            np.empty((0,), np.int64),
        )
    n_win = (num - window) // stride + 1
    idx = np.arange(n_win)[:, None] * stride + np.arange(window)[None, :]
    flat = features[idx].reshape(n_win, -1).astype(np.float32)

    acts = activities[idx]  # [W, window] of small non-negative ints
    n_ids = int(acts.max()) + 1
    counts = np.zeros((n_win, n_ids), np.int64)
    np.add.at(counts, (np.arange(n_win)[:, None], acts), 1)
    return flat, counts.argmax(axis=1)


def _zscore(x: np.ndarray) -> np.ndarray:
    """Per-column standardization with zero-std guard
    (reference: wearables/datasets.py:277-282)."""
    mean = x.mean(axis=0)
    std = x.std(axis=0)
    std[std == 0] = 1.0
    return (x - mean) / std


def _load_pamap2(
    root: Path, params: Dict[str, Any]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """PAMAP2: per-subject protocol files -> activity-filtered rows ->
    NaN fill -> sliding windows with majority labels -> global z-score
    (reference: wearables/datasets.py:92-301)."""
    window = int(params.get("window_size", 100))
    stride = int(params.get("window_stride", 50))
    include_hr = bool(params.get("include_heart_rate", True))
    normalize = bool(params.get("normalize", True))
    activities = list(params.get("activities", PAMAP2_ACTIVITIES))
    subjects = list(params.get("subjects", range(101, 110)))
    act_to_idx = {a: i for i, a in enumerate(activities)}

    cols = ([PAMAP2_HEART_RATE_COL] if include_hr else []) + [
        c for start in PAMAP2_IMU_STARTS for c in range(start, start + 13)
    ]

    xs, ys, subs = [], [], []
    for sid in subjects:
        f = root / "Protocol" / f"subject{sid}.dat"
        if not f.exists():
            continue
        raw = np.loadtxt(f)
        act = raw[:, PAMAP2_ACTIVITY_COL].astype(np.int64)
        keep = np.isin(act, activities)
        feats = _nan_to_column_mean(raw[keep][:, cols])
        win, maj = _majority_windows(feats, act[keep], window, stride)
        if len(win):
            xs.append(win)
            ys.append(np.array([act_to_idx[a] for a in maj], np.int32))
            subs.append(np.full(len(win), sid, np.int32))

    if not xs:
        raise ValueError(f"No PAMAP2 data under {root}")
    x = np.vstack(xs)
    if normalize:
        x = _zscore(x)
    return x.astype(np.float32), np.concatenate(ys), np.concatenate(subs)


def _load_ppg_dalia(
    root: Path, params: Dict[str, Any]
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """PPG-DaLiA: per-subject pickles -> wrist signals downsampled to the
    4 Hz label rate -> [EDA, TEMP, ACC xyz, BVP] stack -> activity filter ->
    windows -> global z-score (reference: wearables/datasets.py:304-531)."""
    import pickle

    window = int(params.get("window_size", 32))
    stride = int(params.get("window_stride", 16))
    normalize = bool(params.get("normalize", True))
    activities = list(params.get("activities", PPG_ACTIVITIES))
    subjects = list(params.get("subjects", range(1, 16)))
    act_to_idx = {a: i for i, a in enumerate(activities)}

    xs, ys, subs = [], [], []
    for sid in subjects:
        f = root / f"S{sid}" / f"S{sid}.pkl"
        if not f.exists():
            continue
        with open(f, "rb") as fh:
            blob = pickle.load(fh, encoding="latin1")
        wrist = blob["signal"]["wrist"]
        eda = np.asarray(wrist["EDA"]).reshape(-1)  # native 4 Hz
        temp = np.asarray(wrist["TEMP"]).reshape(-1)  # native 4 Hz
        acc = np.asarray(wrist["ACC"])[::8, :]  # 32 Hz -> 4 Hz
        bvp = np.asarray(wrist["BVP"]).reshape(-1)[::16]  # 64 Hz -> 4 Hz
        act = np.asarray(blob["activity"]).reshape(-1).astype(np.int64)

        m = min(len(eda), len(temp), len(acc), len(bvp), len(act))
        feats = np.column_stack([eda[:m], temp[:m], acc[:m], bvp[:m]])
        feats = np.nan_to_num(feats, nan=0.0).astype(np.float32)
        keep = np.isin(act[:m], activities)
        win, maj = _majority_windows(feats[keep], act[:m][keep], window, stride)
        if len(win):
            xs.append(win)
            ys.append(np.array([act_to_idx[a] for a in maj], np.int32))
            subs.append(np.full(len(win), sid, np.int32))

    if not xs:
        raise ValueError(f"No PPG-DaLiA data under {root}")
    x = np.vstack(xs)
    if normalize:
        x = _zscore(x)
    return x.astype(np.float32), np.concatenate(ys), np.concatenate(subs)


def load_wearable_federated(
    dataset: str,
    params: Dict[str, Any],
    num_nodes: int,
    seed: int = 42,
    max_samples: Optional[int] = None,
) -> FederatedArrays:
    if dataset not in WEARABLE_SPECS:
        raise ValueError(f"Unknown wearable dataset: {dataset}")
    input_dim, num_classes, num_subjects = WEARABLE_SPECS[dataset]
    params = dict(params or {})
    data_path = params.get("data_path")
    split = params.get("split", "train")

    # The synthetic fallback mirrors the on-disk feature dimensionality,
    # including non-default window params (window_size x features/step).
    if dataset == "pamap2":
        feats = (1 if params.get("include_heart_rate", True) else 0) + 39
        input_dim = int(params.get("window_size", 100)) * feats
    elif dataset == "ppg_dalia":
        input_dim = int(params.get("window_size", 32)) * 6

    holdout = float(params.get("holdout_fraction", DEFAULT_HOLDOUT_FRACTION))
    x = y = subjects = None
    x_heldout = y_heldout = subjects_heldout = None
    if data_path and Path(data_path).exists():
        if dataset == "uci_har":
            x, y, subjects = _load_uci_har(Path(data_path), split)
            if split == "train" and holdout > 0.0:
                # Official held-out split (the reference adapter only ever
                # loads one split and evaluates on it); partitioned onto
                # nodes below with the same method as train.  UCI HAR test
                # subjects are disjoint from train subjects, so under
                # natural partitioning a node's test shard comes from
                # different people — the harder, standard HAR protocol.
                try:
                    x_heldout, y_heldout, subjects_heldout = _load_uci_har(
                        Path(data_path), "test"
                    )
                except OSError:
                    pass
        elif dataset == "pamap2":
            x, y, subjects = _load_pamap2(Path(data_path), params)
        elif dataset == "ppg_dalia":
            x, y, subjects = _load_ppg_dalia(Path(data_path), params)

    if x is None:
        n_total = int(params.get("num_samples", max(2000, 300 * num_nodes)))
        default_sep, default_noise = WEARABLE_DIFFICULTY[dataset]
        x, y = make_synthetic(
            num_samples=n_total,
            input_shape=(input_dim,),
            num_classes=num_classes,
            cluster_std=float(params.get("cluster_std", 1.5)),
            seed=seed,
            separation=float(params.get("separation", default_sep)),
            label_noise=float(params.get("label_noise", default_noise)),
        )
        rng = np.random.default_rng(seed)
        subjects = rng.integers(0, num_subjects, size=n_total)

    method = params.get("partition_method", "dirichlet")

    def _make_parts(yy, subs):
        if method == "dirichlet":
            return dirichlet_partition(
                yy, num_nodes, alpha=float(params.get("alpha", 0.5)), seed=seed
            )
        if method == "iid":
            return iid_partition(len(yy), num_nodes, seed=seed)
        if method == "natural":
            nat, _actual = natural_partition(subs)
            # Fold natural subject groups round-robin onto the requested nodes.
            parts = [[] for _ in range(num_nodes)]
            for g, p in enumerate(nat):
                parts[g % num_nodes].extend(p)
            return parts
        raise ValueError(f"Unknown partition_method: {method}")

    parts = _make_parts(y, subjects)
    if x_heldout is not None:
        # Official test split, partitioned onto nodes by the same method.
        test_parts = _make_parts(y_heldout, subjects_heldout)
        return stack_partitions(
            x, y, parts, max_samples=max_samples, num_classes=num_classes,
            test_partitions=test_parts, x_test=x_heldout, y_test=y_heldout,
        )
    test_parts = None
    if holdout > 0.0:
        parts, test_parts = split_holdout(parts, holdout, seed)
    return stack_partitions(
        x, y, parts, max_samples=max_samples, num_classes=num_classes,
        test_partitions=test_parts,
    )

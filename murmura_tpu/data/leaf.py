"""LEAF benchmark datasets (femnist, celeba, shakespeare) from the LEAF JSON
layout (reference: murmura/examples/leaf/datasets.py:23-199, 300-377).

Loads per-split JSON shards with user->samples maps, applies the reference's
natural user partitioning (seeded user shuffle, round-robin users -> nodes,
paired train/test partitions — datasets.py:300-377).  When no ``data_path``
is given (or ``synthetic: true``), emits shape-identical synthetic data so
every config remains runnable in a zero-egress environment.
"""

import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from murmura_tpu.data.base import (
    DEFAULT_HOLDOUT_FRACTION,
    FederatedArrays,
    split_holdout,
    stack_partitions,
)
from murmura_tpu.data.synthetic import make_synthetic, make_synthetic_sequences

FEMNIST_CLASSES = 62

# LEAF's fixed 80-char alphabet (reference: leaf/models/utils/
# language_utils.py:11); chars outside it map to index 80, hence vocab 81
# (LEAF itself folds unknowns onto the last position via str.find -> -1).
SHAKESPEARE_ALPHABET = (
    "\n !\"&'(),-.0123456789:;>?ABCDEFGHIJKLMNOPQRSTUVWXYZ[]"
    "abcdefghijklmnopqrstuvwxyz}"
)
SHAKESPEARE_VOCAB = len(SHAKESPEARE_ALPHABET) + 1  # 81


def _load_leaf_json_dir(split_dir: Path) -> Tuple[List[str], Dict[str, Dict]]:
    """Merge all JSON shards in a LEAF split dir into (users, user_data)
    (reference: datasets.py:23-93)."""
    users: List[str] = []
    user_data: Dict[str, Dict] = {}
    for shard in sorted(split_dir.glob("*.json")):
        with open(shard) as f:
            blob = json.load(f)
        users.extend(blob.get("users", []))
        user_data.update(blob.get("user_data", {}))
    return users, user_data


def _round_robin_users(
    users: List[str], num_nodes: int, seed: int
) -> List[List[str]]:
    """Seeded user shuffle then round-robin users -> nodes
    (reference: datasets.py:300-340)."""
    rng = np.random.default_rng(seed)
    order = list(users)
    rng.shuffle(order)
    groups: List[List[str]] = [[] for _ in range(num_nodes)]
    for i, u in enumerate(order):
        groups[i % num_nodes].append(u)
    return groups


def _decode_users(users: List[str], load_user):
    """Decode users via ``load_user(u) -> (ux, uy)`` into pooled arrays plus
    per-user (start, end) offsets."""
    xs, ys = [], []
    offsets: Dict[str, Tuple[int, int]] = {}
    cursor = 0
    for u in users:
        ux, uy = load_user(u)
        xs.append(ux)
        ys.append(uy)
        offsets[u] = (cursor, cursor + len(uy))
        cursor += len(uy)
    return np.concatenate(xs), np.concatenate(ys), offsets


def _stack_user_groups(
    users: List[str],
    groups: List[List[str]],
    load_user,
    num_classes: int,
    max_samples: Optional[int],
    test_users: Optional[List[str]] = None,
    load_user_test=None,
    holdout_fraction: float = DEFAULT_HOLDOUT_FRACTION,
    seed: int = 0,
) -> FederatedArrays:
    """Shared scaffolding for all LEAF loaders: decode each user's samples,
    then map the round-robin user groups onto node partitions.

    Held-out evaluation mirrors the reference's *paired* per-user train/test
    partitions (murmura/examples/leaf/datasets.py:300-377): when the LEAF
    ``test/`` split is available, each node's test shard holds exactly its
    own users' test samples; without one, ``holdout_fraction`` of each
    node's train shard is carved off instead.  ``holdout_fraction: 0``
    restores the reference's evaluate-on-train behavior for both cases.
    """
    x, y, offsets = _decode_users(users, load_user)
    partitions = [
        [i for u in group for i in range(*offsets[u])] for group in groups
    ]

    have = []
    if load_user_test is not None and test_users and holdout_fraction > 0.0:
        in_test = set(test_users)
        have = [u for u in users if u in in_test]
    if have:
        x_t, y_t, offsets_t = _decode_users(have, load_user_test)
        test_partitions = [
            [i for u in group if u in offsets_t for i in range(*offsets_t[u])]
            for group in groups
        ]
        # A node whose users all lack test/ samples evaluates on its train
        # shard (reference behavior) instead of on an empty mask, which
        # would score the node 0.0 and drag mean_accuracy.
        extra_x, extra_y = [], []
        cursor = len(y_t)
        for i, tp in enumerate(test_partitions):
            if not tp and partitions[i]:
                tr = partitions[i]
                test_partitions[i] = list(range(cursor, cursor + len(tr)))
                extra_x.append(x[tr])
                extra_y.append(y[tr])
                cursor += len(tr)
        if extra_x:
            x_t = np.concatenate([x_t] + extra_x)
            y_t = np.concatenate([y_t] + extra_y)
        return stack_partitions(
            x, y, partitions, max_samples=max_samples, num_classes=num_classes,
            test_partitions=test_partitions, x_test=x_t, y_test=y_t,
        )

    test_partitions = None
    if holdout_fraction > 0.0:
        partitions, test_partitions = split_holdout(
            partitions, holdout_fraction, seed
        )
    return stack_partitions(
        x, y, partitions, max_samples=max_samples, num_classes=num_classes,
        test_partitions=test_partitions,
    )


def _load_test_split(data_path: Path):
    """(users, user_data) of the LEAF ``test/`` split, or ([], {}) when the
    dataset ships without one."""
    test_dir = data_path / "test"
    if test_dir.exists():
        return _load_leaf_json_dir(test_dir)
    return [], {}


def _femnist_from_json(
    data_path: Path, num_nodes: int, seed: int, max_samples: Optional[int],
    holdout_fraction: float,
) -> FederatedArrays:
    train_users, train_data = _load_leaf_json_dir(data_path / "train")
    test_users, test_data = _load_test_split(data_path)
    groups = _round_robin_users(train_users, num_nodes, seed)

    def decode(user_data):
        def load_user(u):
            ux = np.asarray(user_data[u]["x"], dtype=np.float32).reshape(-1, 28, 28, 1)
            uy = np.asarray(user_data[u]["y"], dtype=np.int32)
            return ux, uy

        return load_user

    return _stack_user_groups(
        train_users, groups, decode(train_data), FEMNIST_CLASSES, max_samples,
        test_users=test_users,
        load_user_test=decode(test_data) if test_users else None,
        holdout_fraction=holdout_fraction, seed=seed,
    )


def _celeba_from_json(
    data_path: Path,
    num_nodes: int,
    seed: int,
    max_samples: Optional[int],
    params: Dict[str, Any],
) -> FederatedArrays:
    """CelebA: JSON shards hold per-celebrity image filenames + binary
    labels; pixels come from raw/img_align_celeba, resized to
    image_size x image_size RGB in [0, 1], NHWC for TPU convs
    (reference semantics: examples/leaf/datasets.py:96-199, which emits CHW
    for torch)."""
    try:
        from PIL import Image
    except ImportError as e:
        raise ImportError(
            "CelebA image decoding needs Pillow: pip install 'murmura-tpu[data]'"
        ) from e

    image_size = int(params.get("image_size", 84))
    users, user_data = _load_leaf_json_dir(data_path / "train")
    test_users, test_data = _load_test_split(data_path)
    groups = _round_robin_users(users, num_nodes, seed)
    images_dir = Path(params.get("image_dir", data_path / "raw" / "img_align_celeba"))

    def decode(blob):
        def load_user(u):
            fnames = blob[u]["x"]
            uy = np.asarray(blob[u]["y"], dtype=np.int32)
            if max_samples is not None:
                # Per-node truncation happens in stack_partitions; capping
                # each user here too keeps full-dataset decode memory bounded
                # (~85 KB/image x 200k images otherwise).
                fnames = fnames[:max_samples]
                uy = uy[:max_samples]
            ux = np.empty((len(fnames), image_size, image_size, 3), np.float32)
            for i, name in enumerate(fnames):
                p = images_dir / name
                if not p.exists():
                    p = images_dir.parent / name  # raw/<name> fallback
                img = Image.open(p).resize((image_size, image_size)).convert("RGB")
                ux[i] = np.asarray(img, dtype=np.float32) / 255.0
            return ux, uy

        return load_user

    return _stack_user_groups(
        users, groups, decode(user_data), 2, max_samples,
        test_users=test_users,
        load_user_test=decode(test_data) if test_users else None,
        holdout_fraction=float(
            params.get("holdout_fraction", DEFAULT_HOLDOUT_FRACTION)
        ),
        seed=seed,
    )


def _shakespeare_from_json(
    data_path: Path, num_nodes: int, seed: int, max_samples: Optional[int],
    holdout_fraction: float,
) -> FederatedArrays:
    """Shakespeare next-char prediction: JSON x = 80-char contexts,
    y = next char, one user per role; chars indexed by the fixed LEAF
    alphabet with unknowns -> index 80 (reference layout:
    leaf/data/shakespeare; vocab: leaf/models/utils/language_utils.py:11)."""
    lut = np.full(256, len(SHAKESPEARE_ALPHABET), dtype=np.int32)
    for i, ch in enumerate(SHAKESPEARE_ALPHABET):
        lut[ord(ch)] = i

    def encode(s: str) -> np.ndarray:
        # Vectorized codepoint extraction; anything outside Latin-1 folds to
        # codepoint 0 (NUL, not in the alphabet) so it lands in the unknown
        # bucket 80 — a latin1 errors="replace" encode would instead emit
        # '?', which IS in the alphabet, silently mislabeling those chars.
        cp = np.frombuffer(s.encode("utf-32-le"), dtype=np.uint32)
        return lut[np.where(cp < 256, cp, 0).astype(np.uint8)]

    users, user_data = _load_leaf_json_dir(data_path / "train")
    test_users, test_data = _load_test_split(data_path)
    groups = _round_robin_users(users, num_nodes, seed)

    def decode(blob):
        def load_user(u):
            ux = encode("".join(blob[u]["x"])).reshape(len(blob[u]["x"]), -1)
            uy = encode(
                "".join(c[0] if c else "\0" for c in blob[u]["y"])
            ).astype(np.int32)
            return ux, uy

        return load_user

    return _stack_user_groups(
        users, groups, decode(user_data), SHAKESPEARE_VOCAB, max_samples,
        test_users=test_users,
        load_user_test=decode(test_data) if test_users else None,
        holdout_fraction=holdout_fraction, seed=seed,
    )


def load_leaf_federated(
    dataset: str,
    params: Dict[str, Any],
    num_nodes: int,
    seed: int = 42,
    max_samples: Optional[int] = None,
) -> FederatedArrays:
    """Load a LEAF dataset (reference: murmura/examples/leaf/adapter.py:19-61)."""
    params = dict(params or {})
    data_path = params.get("data_path")
    use_synthetic = bool(params.get("synthetic", data_path is None))
    holdout = float(params.get("holdout_fraction", DEFAULT_HOLDOUT_FRACTION))

    if not use_synthetic:
        root = Path(data_path)
        if not root.exists():
            raise FileNotFoundError(
                f"LEAF data path not found: {root}. Pass data.params.synthetic: true "
                "for shape-identical synthetic data."
            )
        if dataset == "femnist":
            return _femnist_from_json(root, num_nodes, seed, max_samples, holdout)
        if dataset == "celeba":
            return _celeba_from_json(root, num_nodes, seed, max_samples, params)
        if dataset == "shakespeare":
            return _shakespeare_from_json(root, num_nodes, seed, max_samples, holdout)
        raise ValueError(f"Unknown LEAF dataset: {dataset}")

    # ---- synthetic, shape-identical fallbacks ----------------------------
    n_total = int(params.get("num_samples", max(2000, 200 * num_nodes)))
    if dataset == "femnist":
        x, y = make_synthetic(
            num_samples=n_total,
            input_shape=(28, 28, 1),
            num_classes=FEMNIST_CLASSES,
            cluster_std=float(params.get("cluster_std", 2.0)),
            seed=seed,
        )
        num_classes = FEMNIST_CLASSES
    elif dataset == "celeba":
        x, y = make_synthetic(
            num_samples=n_total,
            input_shape=(84, 84, 3),
            num_classes=2,
            seed=seed,
        )
        num_classes = 2
    elif dataset == "shakespeare":
        x, y = make_synthetic_sequences(
            num_samples=n_total,
            seq_len=int(params.get("seq_len", 80)),
            vocab_size=SHAKESPEARE_VOCAB,
            seed=seed,
        )
        num_classes = SHAKESPEARE_VOCAB
    else:
        raise ValueError(f"Unknown LEAF dataset: {dataset}")

    from murmura_tpu.data.partitioners import dirichlet_partition, iid_partition

    if params.get("partition_method", "dirichlet") == "dirichlet" and num_classes > 2:
        parts = dirichlet_partition(
            y, num_nodes, alpha=float(params.get("alpha", 0.5)), seed=seed
        )
    else:
        parts = iid_partition(len(y), num_nodes, seed=seed)
    test_parts = None
    if holdout > 0.0:
        parts, test_parts = split_holdout(parts, holdout, seed)
    return stack_partitions(
        x, y, parts, max_samples=max_samples, num_classes=num_classes,
        test_partitions=test_parts,
    )

"""Stacked federated array containers.

Replaces the reference's per-node ``Subset``/``DataLoader`` machinery
(murmura/data/adapters.py:7-57, murmura/core/network.py:275-294) with padded
device-friendly arrays: node i's shard occupies row i, padded to the network
max and tagged with a validity mask.  ``effective_batch`` reproduces the
reference's per-node batch-size rule ``min(batch, max(2, n_samples))``
(murmura/core/network.py:278-287).
"""

from dataclasses import dataclass, field
from typing import List, Optional, Sequence

import numpy as np

# Fraction of each node's shard carved off for held-out evaluation by every
# loader that has no dataset-provided test split.  The reference evaluates
# on training data (murmura/core/network.py:289-294);
# ``data.params.holdout_fraction: 0.0`` restores that behavior.
DEFAULT_HOLDOUT_FRACTION = 0.2


@dataclass
class FederatedArrays:
    """One network's worth of per-node training (and optional test) data.

    Attributes:
        x: [N, S, ...] padded features.
        y: [N, S] padded int labels.
        mask: [N, S] validity mask (1.0 = real sample, 0.0 = padding).
        num_samples: [N] count of real samples per node.
        x_test / y_test / mask_test: optional separate held-out arrays; when
            None, evaluation reuses the training shard exactly as the
            reference does (murmura/core/network.py:289-294).
    """

    x: np.ndarray
    y: np.ndarray
    mask: np.ndarray
    num_samples: np.ndarray
    x_test: Optional[np.ndarray] = None
    y_test: Optional[np.ndarray] = None
    mask_test: Optional[np.ndarray] = None
    num_classes: int = field(default=0)

    @property
    def num_nodes(self) -> int:
        return self.x.shape[0]

    @property
    def max_samples(self) -> int:
        return self.x.shape[1]

    @property
    def eval_arrays(self):
        """(x, y, mask) used for evaluation — test split if present else train."""
        if self.x_test is not None:
            return self.x_test, self.y_test, self.mask_test
        return self.x, self.y, self.mask

    def effective_batch(self, batch_size: int) -> np.ndarray:
        """Per-node effective batch size b_i = min(B, max(2, n_i))
        (reference: murmura/core/network.py:278-287)."""
        return np.minimum(batch_size, np.maximum(2, self.num_samples)).astype(np.int32)

    def steps_per_epoch(self, batch_size: int) -> np.ndarray:
        """Per-node batches per epoch with the reference's drop_last rule:
        drop the ragged tail only when n_i > b_i (murmura/core/network.py:286)."""
        b = self.effective_batch(batch_size)
        n = self.num_samples
        return np.where(n > b, n // b, 1).astype(np.int32)

    def get_client_data(self, node_id: int):
        """Unpadded (x, y) view of one node's shard — reference
        ``DatasetAdapter.get_client_data`` parity (murmura/data/adapters.py:30-52)."""
        n = int(self.num_samples[node_id])
        return self.x[node_id, :n], self.y[node_id, :n]

    def get_client_eval_data(self, node_id: int):
        """Unpadded held-out (x, y) view for one node, falling back to its
        training shard when no test split exists (reference behavior,
        murmura/core/network.py:289-294)."""
        if self.x_test is None:
            return self.get_client_data(node_id)
        n = int(self.mask_test[node_id].sum())
        if n == 0:
            return self.get_client_data(node_id)
        return self.x_test[node_id, :n], self.y_test[node_id, :n]


def split_holdout(
    partitions: Sequence[Sequence[int]],
    fraction: float,
    seed: int,
    min_train: int = 2,
):
    """Split each node's index list into paired (train, test) lists.

    The reference evaluates on training data for most adapters
    (murmura/core/network.py:289-294); the paired per-node split mirrors its
    LEAF per-user train/test pairing (murmura/examples/leaf/
    datasets.py:300-377) for every loader, so held-out accuracy keeps the
    node's own (non-IID) label distribution.  Nodes keep at least
    ``min_train`` training samples (the reference's effective-batch floor,
    network.py:278-287); a node too small to spare any test samples
    evaluates on its training shard (reference behavior) so its accuracy
    row stays meaningful instead of dividing by an empty mask.
    """
    rng = np.random.default_rng(seed)
    train: List[List[int]] = []
    test: List[List[int]] = []
    for p in partitions:
        p = list(p)
        n_test = int(round(len(p) * fraction))
        n_test = min(n_test, max(0, len(p) - min_train))
        order = rng.permutation(len(p))
        if n_test == 0:
            train.append(p)
            test.append(p)
        else:
            test.append([p[i] for i in order[:n_test]])
            train.append([p[i] for i in order[n_test:]])
    return train, test


def stack_partitions(
    x: np.ndarray,
    y: np.ndarray,
    partitions: Sequence[Sequence[int]],
    max_samples: Optional[int] = None,
    num_classes: Optional[int] = None,
    test_partitions: Optional[Sequence[Sequence[int]]] = None,
    x_test: Optional[np.ndarray] = None,
    y_test: Optional[np.ndarray] = None,
) -> FederatedArrays:
    """Pad per-node index lists into stacked [N, S, ...] arrays.

    Args:
        x, y: full dataset arrays.
        partitions: per-node sample index lists (ragged).
        max_samples: optional per-node truncation (reference:
            murmura/examples/leaf/adapter.py:12-16 "for quick tests").
        test_partitions: optional per-node index lists into (x_test, y_test)
            — defaults to evaluation on the training shard.
    """
    x = np.asarray(x)
    y = np.asarray(y)

    def _stack(xs, ys, parts):
        parts = [list(p) for p in parts]
        if max_samples is not None:
            parts = [p[:max_samples] for p in parts]
        n_nodes = len(parts)
        counts = np.array([len(p) for p in parts], dtype=np.int32)
        cap = max(1, int(counts.max()))
        fx = np.zeros((n_nodes, cap) + xs.shape[1:], dtype=xs.dtype)
        fy = np.zeros((n_nodes, cap), dtype=np.int32)
        fm = np.zeros((n_nodes, cap), dtype=np.float32)
        for i, p in enumerate(parts):
            if p:
                fx[i, : len(p)] = xs[p]
                fy[i, : len(p)] = ys[p]
                fm[i, : len(p)] = 1.0
        return fx, fy, fm, counts

    fx, fy, fm, counts = _stack(x, y, partitions)
    k = int(num_classes) if num_classes else int(y.max()) + 1 if y.size else 0

    tx = ty = tm = None
    if test_partitions is not None:
        xs = x if x_test is None else np.asarray(x_test)
        ys = y if y_test is None else np.asarray(y_test)
        tx, ty, tm, _ = _stack(xs, ys, test_partitions)

    return FederatedArrays(
        x=fx, y=fy, mask=fm, num_samples=counts,
        x_test=tx, y_test=ty, mask_test=tm, num_classes=k,
    )

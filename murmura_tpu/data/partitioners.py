"""Client partitioning strategies (reference: murmura/data/partitioners.py:7-223).

Host-side numpy; same statistical semantics as the reference: per-class
Dirichlet proportions with remainder assignment and min-samples
redistribution, shuffled IID splits, natural grouping by subject/user id,
and Dirichlet re-partitioning of natural groups.  Uses an explicit
``np.random.default_rng`` generator instead of the reference's global
``np.random.seed`` state.
"""

from typing import List, Optional, Tuple

import numpy as np


def dirichlet_partition(
    labels: np.ndarray,
    num_clients: int,
    alpha: float = 0.5,
    min_samples_per_client: int = 1,
    seed: Optional[int] = None,
) -> List[List[int]]:
    """Non-IID partition via per-class Dirichlet proportions
    (reference: partitioners.py:7-77).

    Lower ``alpha`` = more heterogeneous label distributions per client.
    """
    rng = np.random.default_rng(seed)
    labels = np.asarray(labels)
    classes = np.unique(labels)

    client_indices: List[List[int]] = [[] for _ in range(num_clients)]

    for c in classes:
        indices = np.flatnonzero(labels == c)
        proportions = rng.dirichlet(np.full(num_clients, alpha))
        counts = (proportions * len(indices)).astype(int)
        remaining = len(indices) - counts.sum()
        if remaining > 0:
            extra = rng.choice(num_clients, remaining, replace=False)
            counts[extra] += 1
        rng.shuffle(indices)
        offsets = np.concatenate([[0], np.cumsum(counts)])
        for i in range(num_clients):
            client_indices[i].extend(indices[offsets[i] : offsets[i + 1]].tolist())

    _ensure_minimum_samples(client_indices, min_samples_per_client)

    for idx in client_indices:
        rng.shuffle(idx)
    return client_indices


def _ensure_minimum_samples(client_indices: List[List[int]], min_samples: int) -> None:
    """Move samples from surplus clients to deficit clients in place
    (reference: partitioners.py:80-124)."""
    if min_samples <= 0:
        return
    deficits = [
        i for i, idx in enumerate(client_indices) if len(idx) < min_samples
    ]
    for d in deficits:
        needed = min_samples - len(client_indices[d])
        for s, idx in enumerate(client_indices):
            if needed <= 0:
                break
            surplus = len(idx) - min_samples
            if s == d or surplus <= 0:
                continue
            take = min(needed, surplus)
            client_indices[d].extend(idx[-take:])
            client_indices[s] = idx[:-take]
            needed -= take


def iid_partition(
    num_samples: int,
    num_clients: int,
    seed: Optional[int] = None,
) -> List[List[int]]:
    """Uniform shuffled split (reference: partitioners.py:127-150)."""
    rng = np.random.default_rng(seed)
    indices = rng.permutation(num_samples)
    return [split.tolist() for split in np.array_split(indices, num_clients)]


def natural_partition(
    client_ids: np.ndarray,
    num_clients: Optional[int] = None,
) -> Tuple[List[List[int]], int]:
    """Group samples by their natural subject/user id
    (reference: partitioners.py:153-181)."""
    client_ids = np.asarray(client_ids)
    unique_clients = np.unique(client_ids)
    if num_clients is not None and num_clients < len(unique_clients):
        unique_clients = unique_clients[:num_clients]
    partitions = [
        np.flatnonzero(client_ids == cid).tolist() for cid in unique_clients
    ]
    return partitions, len(unique_clients)


def combine_partitions_with_dirichlet(
    natural_partitions: List[List[int]],
    labels: np.ndarray,
    num_clients: int,
    alpha: float = 0.5,
    seed: Optional[int] = None,
) -> List[List[int]]:
    """Dirichlet re-partition of naturally grouped data
    (reference: partitioners.py:184-223)."""
    all_indices = [i for part in natural_partitions for i in part]
    sub = dirichlet_partition(
        labels=np.asarray(labels)[all_indices],
        num_clients=num_clients,
        alpha=alpha,
        seed=seed,
    )
    return [[all_indices[i] for i in part] for part in sub]

"""Dataset adapter registry: config adapter strings -> FederatedArrays.

Mirrors the reference's string-addressed adapter factories
(murmura/utils/factories.py:16-42): ``synthetic`` / ``synthetic_sequences``
are always available (zero-dependency smoke/bench data); ``leaf.*`` and
``wearables.*`` load from disk when a data_path exists (see data/leaf.py,
data/wearables.py).
"""

from typing import Any, Dict, Optional

import numpy as np

from murmura_tpu.data.base import (
    DEFAULT_HOLDOUT_FRACTION,
    FederatedArrays,
    split_holdout,
    stack_partitions,
)
from murmura_tpu.data.partitioners import dirichlet_partition, iid_partition
from murmura_tpu.data.synthetic import make_synthetic, make_synthetic_sequences


def _partition(labels: np.ndarray, num_nodes: int, params: Dict[str, Any], seed: int):
    method = params.get("partition_method", "iid")
    if method == "dirichlet":
        return dirichlet_partition(
            labels,
            num_nodes,
            alpha=float(params.get("alpha", 0.5)),
            seed=seed,
        )
    if method == "iid":
        return iid_partition(len(labels), num_nodes, seed=seed)
    raise ValueError(f"Unknown partition_method: {method}")


def _with_holdout(parts, params: Dict[str, Any], seed: int):
    """(train_partitions, test_partitions|None) per data.params.holdout_fraction."""
    frac = float(params.get("holdout_fraction", DEFAULT_HOLDOUT_FRACTION))
    if frac <= 0.0:
        return parts, None
    return split_holdout(parts, frac, seed)


def build_federated_data(
    adapter: str,
    params: Dict[str, Any],
    num_nodes: int,
    seed: int = 42,
    max_samples: Optional[int] = None,
) -> FederatedArrays:
    """Resolve a config ``data.adapter`` string to stacked federated arrays."""
    params = dict(params or {})

    if adapter == "synthetic":
        x, y = make_synthetic(
            num_samples=int(params.get("num_samples", 2000)),
            input_shape=tuple(params.get("input_shape", [params.get("input_dim", 32)])),
            num_classes=int(params.get("num_classes", 10)),
            cluster_std=float(params.get("cluster_std", 1.0)),
            seed=seed,
        )
        parts = _partition(y, num_nodes, params, seed)
        parts, test_parts = _with_holdout(parts, params, seed)
        return stack_partitions(
            x, y, parts, max_samples=max_samples,
            num_classes=int(params.get("num_classes", 10)),
            test_partitions=test_parts,
        )

    if adapter in ("synthetic_sequences", "synthetic_seq"):
        x, y = make_synthetic_sequences(
            num_samples=int(params.get("num_samples", 2000)),
            seq_len=int(params.get("seq_len", 80)),
            vocab_size=int(params.get("vocab_size", 81)),
            seed=seed,
        )
        parts = _partition(y, num_nodes, params, seed)
        parts, test_parts = _with_holdout(parts, params, seed)
        return stack_partitions(
            x, y, parts, max_samples=max_samples,
            num_classes=int(params.get("vocab_size", 81)),
            test_partitions=test_parts,
        )

    if adapter.startswith("leaf."):
        from murmura_tpu.data.leaf import load_leaf_federated

        return load_leaf_federated(
            adapter.split(".", 1)[1], params, num_nodes, seed, max_samples
        )

    if adapter.startswith("wearables."):
        from murmura_tpu.data.wearables import load_wearable_federated

        return load_wearable_federated(
            adapter.split(".", 1)[1], params, num_nodes, seed, max_samples
        )

    raise ValueError(f"Unknown dataset adapter: {adapter}")

"""Federated data layer (reference: murmura/data/).

TPU-first design: rather than the reference's per-node ragged
``torch.utils.data.Subset`` + ``DataLoader`` objects (murmura/data/adapters.py,
murmura/core/network.py:275-294), every node's shard is padded into one stacked
array family ``x[N, S, ...], y[N, S], mask[N, S]`` so the whole network's data
lives device-resident and the per-round batch loop is a static-shape gather.
"""

from murmura_tpu.data.partitioners import (
    combine_partitions_with_dirichlet,
    dirichlet_partition,
    iid_partition,
    natural_partition,
)
from murmura_tpu.data.base import FederatedArrays, stack_partitions

__all__ = [
    "dirichlet_partition",
    "iid_partition",
    "natural_partition",
    "combine_partitions_with_dirichlet",
    "FederatedArrays",
    "stack_partitions",
]

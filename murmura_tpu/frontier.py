"""`murmura frontier <yaml>`: the robustness frontier at gang speed
(ISSUE 11; docs/ROBUSTNESS.md "The robustness frontier").

For every (rule x adaptive attack x topology) cell of the configured grid
this driver charts honest accuracy against attack strength and locates
the rule's empirical **breaking point** — the strength where the honest-
accuracy cliff happens — then writes one committed ``frontier.json``
artifact placing that number next to the rule's MUR800 *declared*
influence bound (``AggregatorDef.influence``, verified statically by
`murmura check --flow`).  The artifact is the static-vs-dynamic
comparison ROADMAP item 4 calls for: what the dataflow analyzer proves a
rule CAN admit, against what an adversary that fights back actually
achieves.

Execution model — compile-compatible buckets, stages without recompiles:

- One cell's strength x seed grid becomes ONE gang (core/gang.py): every
  strength is a per-member ``attack_scale`` traced input (the ``sweep:``
  plumbing), the member count pads to the next power of two, and the
  whole stage runs in one vmapped compiled program.  A 0-strength member
  rides every stage as the benign reference.
- The outer successive-halving loop re-aims the strength grid at the
  cliff between stages via :meth:`GangNetwork.reset_run` — a value-only
  reset of params/RNG/state over the SAME warm executables, so a whole
  multi-stage cell costs the bucket's initial compiles and nothing more
  (<= 2: the fused train program and nothing else, or train + eval on
  the per-round path; asserted by the battery's ``--frontier``
  pre-flight under ``tpu.recompile_guard``).
- The attacks are ADAPTIVE (attacks/adaptive.py): each member's attacker
  bisects/walks its own strength multiplier against the acceptance taps
  *within* the member's base strength, so a strength-grid point reports
  the best closed-loop attack at that budget, not a fixed perturbation.
"""

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from murmura_tpu.config.schema import Config, FrontierConfig

FRONTIER_SCHEMA_VERSION = 1

# Attack-strength grid floor: successive halving must not chase the cliff
# into denormal territory (a strength this small is "the rule filters the
# attack outright", which the artifact records as such).
_MIN_STRENGTH = 1e-3


@dataclass
class FrontierCell:
    """One (rule, attack, topology[, percentage]) cell's accumulated
    results."""

    rule: str
    attack: str
    topology: str
    degree: int
    percentage: Optional[float] = None
    # strength -> list of per-seed records
    curve: Dict[float, Dict[str, Any]] = field(default_factory=dict)
    benign_accuracy: float = float("nan")
    compiles: int = 0
    stages_run: int = 0


def _geom_grid(lo: float, hi: float, points: int) -> List[float]:
    lo = max(float(lo), _MIN_STRENGTH)
    hi = max(float(hi), lo * (1.0 + 1e-6))
    return [float(g) for g in np.geomspace(lo, hi, points)]


def _cell_config(
    config: Config,
    f: FrontierConfig,
    rule: str,
    attack: str,
    topology: str,
    members: Optional[List[Dict[str, Any]]] = None,
    percentage: Optional[float] = None,
) -> Config:
    """Derive one cell's runnable config from the base experiment.

    The cell keeps the base data/model/training setup; rule params come
    from the user's config when the cell runs the configured rule, else
    the canonical AGG_CASES defaults (the same inventory every analysis
    grid uses).  Telemetry/durability are stripped — the frontier's
    artifact IS its output, and per-member writer trees for hundreds of
    stage-members would be noise.
    """
    from murmura_tpu.analysis.ir import AGG_CASES

    raw = config.model_dump()
    raw["aggregation"] = {
        "algorithm": rule,
        "params": (
            dict(config.aggregation.params)
            if rule == config.aggregation.algorithm
            else dict(AGG_CASES.get(rule, {}))
        ),
    }
    base_attack = config.attack
    if percentage is not None:
        # The breakdown-point axis (frontier.percentages): this cell runs
        # with an explicit compromised fraction.  Each percentage is its
        # own gang bucket — the compromised set is a static attack
        # closure, so it cannot vary inside one compiled bucket the way
        # the strength grid does.
        pct = float(percentage)
    else:
        pct = base_attack.percentage if base_attack.enabled else 0.25
    params: Dict[str, Any] = {}
    if attack == "gaussian":
        params["noise_std"] = float(
            base_attack.params.get("noise_std", 10.0)
        ) if base_attack.type == "gaussian" else 10.0
    elif base_attack.type == "alie" and "z" in base_attack.params:
        params["z"] = base_attack.params["z"]
    # Pin the compromised placement to the base experiment seed so every
    # member of every stage shares the attack's static closures (the gang
    # contract, core/gang.py).
    params["seed"] = int(
        base_attack.params.get("seed", config.experiment.seed)
    )
    raw["attack"] = {
        "enabled": True,
        "type": attack,
        "percentage": pct,
        "params": params,
        "adaptive": {"enabled": True},
    }
    n = config.topology.num_nodes
    if topology == "sparse":
        raw["topology"] = {"type": "exponential", "num_nodes": n}
    elif config.topology.type in ("exponential", "one_peer"):
        # The base config is itself sparse; the dense cell needs a dense
        # stand-in — the canonical k-regular(4) graph at the same size.
        raw["topology"] = {
            "type": "k-regular", "num_nodes": n, "k": min(4, n - 1),
        }
    else:
        raw["topology"] = config.topology.model_dump()
    if f.rounds is not None:
        raw["experiment"] = {
            **raw["experiment"], "rounds": int(f.rounds),
        }
    raw["experiment"]["verbose"] = False
    raw.pop("telemetry", None)
    raw.pop("durability", None)
    raw.pop("sweep", None)
    raw.pop("frontier", None)
    if members is not None:
        raw["sweep"] = {"members": members}
    try:
        return Config.model_validate(raw)
    except Exception as e:  # noqa: BLE001 — surface as the CLI's error kind
        from murmura_tpu.utils.factories import ConfigError

        raise ConfigError(
            f"frontier cell {rule} x {attack} x {topology} does not "
            f"validate against the base config: {e}"
        ) from e


def _members_for(
    strengths: Sequence[float], seeds: Sequence[int]
) -> List[Dict[str, Any]]:
    return [
        {"seed": int(s), "attack_scale": float(g)}
        for g in strengths
        for s in seeds
    ]


def _honest_final(history: Dict[str, List[float]]) -> float:
    rows = history.get("honest_accuracy") or history.get("mean_accuracy")
    return float(rows[-1]) if rows else float("nan")


def _adaptive_summary(gang, member: int) -> Dict[str, float]:
    """Mean adaptation state over the member's compromised rows — the
    attacker's own account of where it converged (bisection bracket /
    ALIE z / acceptance EMA)."""
    comp = np.asarray(gang.compromised) > 0
    out: Dict[str, float] = {}
    for key, arr in gang.agg_state.items():
        if not key.startswith("atk_"):
            continue
        rows = np.asarray(arr)[member]
        out[key] = float(rows[comp].mean()) if comp.any() else float("nan")
    return out


def _locate_break(
    curve: Dict[float, Dict[str, Any]], benign: float, break_fraction: float
):
    """(last_held, first_broken) from the accumulated curve: the largest
    strength whose mean honest accuracy still clears the threshold and
    the smallest that falls below it."""
    thr = break_fraction * benign
    held = [g for g, rec in curve.items() if g > 0 and rec["mean"] >= thr]
    broken = [g for g, rec in curve.items() if g > 0 and rec["mean"] < thr]
    last_held = max(held) if held else None
    first_broken = min(broken) if broken else None
    return last_held, first_broken, thr


def run_cell(
    config: Config,
    f: FrontierConfig,
    rule: str,
    attack: str,
    topology: str,
    seeds: Sequence[int],
    progress: Optional[Callable[[str], None]] = None,
    percentage: Optional[float] = None,
) -> FrontierCell:
    """Run one (rule, attack, topology[, percentage]) cell: stage-0
    grid, then successive-halving refinement around the cliff, all on
    one gang bucket with value-only resets between stages."""
    from murmura_tpu.analysis.sanitizers import track_compiles
    from murmura_tpu.core.gang import GangMember
    from murmura_tpu.utils.factories import build_gang_from_config

    say = progress or (lambda s: None)
    grid = _geom_grid(f.strength_lo, f.strength_hi, f.points)
    strengths = [0.0] + grid
    cfg = _cell_config(
        config, f, rule, attack, topology,
        members=_members_for(strengths, seeds),
        percentage=percentage,
    )
    rounds = cfg.experiment.rounds
    gang = build_gang_from_config(cfg, retain_init=True)
    if topology == "sparse":
        degree = len(gang.topology.offsets)
    else:
        degree = int(np.asarray(gang.topology.mask()).sum(axis=1).max())

    cell = FrontierCell(
        rule=rule, attack=attack, topology=topology, degree=degree,
        percentage=(
            float(percentage) if percentage is not None
            else float(cfg.attack.percentage)
        ),
    )

    def run_stage(stage: int, stage_strengths: Sequence[float]) -> None:
        members = [
            GangMember(seed=int(s), attack_scale=float(g))
            for g in stage_strengths
            for s in seeds
        ]
        if stage > 0:
            gang.reset_run(members)
        histories = gang.train(
            rounds=rounds, eval_every=rounds,
            rounds_per_dispatch=rounds,
        )
        comp = np.asarray(gang.compromised) > 0
        for i, m in enumerate(members):
            acc = _honest_final(histories[i])
            g = float(m.attack_scale)
            rec = cell.curve.setdefault(
                g, {"per_seed": {}, "adaptive": {}, "stage": stage}
            )
            rec["per_seed"][str(m.seed)] = acc
            if comp.any():
                rec["adaptive"][str(m.seed)] = _adaptive_summary(gang, i)
        for rec in cell.curve.values():
            vals = list(rec["per_seed"].values())
            rec["mean"] = float(np.mean(vals))
            rec["std"] = float(np.std(vals))
        cell.stages_run = stage + 1

    with track_compiles() as tracker:
        say(f"  stage 0: strengths {['%.3g' % g for g in strengths]}")
        run_stage(0, strengths)
        cell.benign_accuracy = cell.curve[0.0]["mean"]
        for stage in range(1, f.stages):
            last_held, first_broken, _thr = _locate_break(
                cell.curve, cell.benign_accuracy, f.break_fraction
            )
            if last_held is None and first_broken is None:
                break
            if first_broken is None:
                # Nothing broke: push the grid upward.
                nxt = _geom_grid(last_held, last_held * 4.0, f.points)
            elif last_held is None:
                # Everything broke: pull the grid downward.
                nxt = _geom_grid(first_broken / 8.0, first_broken, f.points)
            else:
                if first_broken <= last_held * (1.0 + 1e-6):
                    break  # non-monotone overlap — the bracket is as
                    # tight as this grid can make it
                inner = _geom_grid(last_held, first_broken, f.points + 2)
                nxt = inner[1:-1]
            fresh = [
                g for g in nxt
                if all(abs(g - g0) > 1e-9 for g0 in cell.curve)
            ]
            if not fresh:
                break
            while len(fresh) < f.points:
                fresh.append(grid[len(fresh) % len(grid)])
            say(
                f"  stage {stage}: refining "
                f"{['%.3g' % g for g in fresh[: f.points]]}"
            )
            run_stage(stage, [0.0] + fresh[: f.points])
    cell.compiles = tracker.total
    return cell


def declared_influence(rule: str, degree: int) -> Optional[Dict[str, Any]]:
    """The rule's MUR800 declared influence contract at this cell's
    degree — the static half of the static-vs-dynamic comparison."""
    try:
        from murmura_tpu.aggregation import build_aggregator
        from murmura_tpu.analysis.ir import AGG_CASES

        agg = build_aggregator(
            rule, dict(AGG_CASES.get(rule, {})), model_dim=8,
            total_rounds=1,
        )
    except Exception:  # noqa: BLE001 — the artifact stays writable
        return None
    decl = agg.influence
    if decl is None:
        return None
    return {
        "kind": decl.kind,
        "bound": decl.bound(degree) if decl.kind == "bounded" else None,
        "describe": decl.describe(degree),
    }


def run_frontier(
    config: Config,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict[str, Any]:
    """Run the full configured grid; returns the frontier artifact dict
    (the ``frontier.json`` payload)."""
    from murmura_tpu.aggregation import AGGREGATORS
    from murmura_tpu.utils.factories import ConfigError

    say = progress or (lambda s: None)
    f = config.frontier or FrontierConfig()
    unknown = sorted(set(f.rules) - set(AGGREGATORS))
    if unknown:
        raise ConfigError(
            f"frontier.rules names unregistered aggregation rule(s) "
            f"{unknown}; known: {sorted(AGGREGATORS)}"
        )
    # Fail loud BEFORE any cell trains: every cell runs a closed-loop
    # adaptive attack, whose schema-level composition limits the base
    # config must already satisfy (config/schema.py
    # _adaptive_attack_is_wirable gives the full rationale).
    if config.dmtt is not None:
        raise ConfigError(
            "frontier cells run adaptive attacks, which do not compose "
            "with dmtt — remove the dmtt block from the frontier config"
        )
    if config.backend == "distributed":
        raise ConfigError(
            "frontier cells close the attack feedback loop inside the "
            "jitted round program; use backend: simulation or tpu"
        )
    seeds = list(f.seeds) if f.seeds is not None else [config.experiment.seed]

    # The breakdown-point axis (frontier.percentages): each compromised
    # fraction runs the full strength x seed successive-halving search as
    # its own compile-compatible bucket.  None = the base attack fraction
    # only (the pre-axis behavior; the artifact still records which).
    percentages: List[Optional[float]] = (
        [float(p) for p in f.percentages]
        if f.percentages is not None else [None]
    )

    cells: List[Dict[str, Any]] = []
    for rule in f.rules:
        for attack in f.attacks:
            for topology in f.topologies:
                for pct in percentages:
                    pct_label = "" if pct is None else f" x pct={pct:g}"
                    say(f"cell {rule} x {attack} x {topology}{pct_label}")
                    cell = run_cell(
                        config, f, rule, attack, topology, seeds,
                        progress=progress, percentage=pct,
                    )
                    last_held, first_broken, thr = _locate_break(
                        cell.curve, cell.benign_accuracy, f.break_fraction
                    )
                    curve_rows = [
                        {"strength": g, **rec}
                        for g, rec in sorted(cell.curve.items())
                    ]
                    cells.append({
                        "rule": rule,
                        "attack": attack,
                        "topology": topology,
                        "percentage": cell.percentage,
                        "degree": cell.degree,
                        "benign_accuracy": cell.benign_accuracy,
                        "curve": curve_rows,
                        "breaking_point": {
                            "last_held": last_held,
                            "first_broken": first_broken,
                            "threshold_accuracy": thr,
                            "criterion": (
                                f"mean honest accuracy < "
                                f"{f.break_fraction} x benign "
                                "(0-strength) accuracy"
                            ),
                        },
                        "declared_influence": declared_influence(
                            rule, cell.degree
                        ),
                        "stages": cell.stages_run,
                        "compiles": cell.compiles,
                    })

    return {
        "schema_version": FRONTIER_SCHEMA_VERSION,
        "generated_by": "murmura frontier",
        "experiment": config.experiment.name,
        "grid": {
            "rules": list(f.rules),
            "attacks": list(f.attacks),
            "topologies": list(f.topologies),
            "percentages": (
                list(f.percentages) if f.percentages is not None else None
            ),
            "seeds": seeds,
            "points": f.points,
            "stages": f.stages,
            "rounds": f.rounds or config.experiment.rounds,
            "strength_lo": f.strength_lo,
            "strength_hi": f.strength_hi,
            "break_fraction": f.break_fraction,
            "num_nodes": config.topology.num_nodes,
        },
        "cells": cells,
    }


def write_frontier(artifact: Dict[str, Any], path) -> Path:
    """Durably write the artifact (the checkpoint fsync discipline — a
    frontier run is minutes of compute the write must not tear)."""
    from murmura_tpu.utils.checkpoint import durable_replace

    path = Path(path).resolve()
    path.parent.mkdir(parents=True, exist_ok=True)
    durable_replace(
        path.parent, path.name,
        (json.dumps(artifact, indent=2) + "\n").encode("utf-8"),
    )
    return path


def load_frontier(path) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as fh:
        artifact = json.load(fh)
    if "cells" not in artifact:
        raise ValueError(
            f"{path} is not a frontier artifact (no 'cells' section)"
        )
    return artifact


def frontier_break_summary(artifact: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Flat per-cell summary rows for `murmura report --frontier`:
    empirical breaking point next to the declared MUR800 bound."""
    rows = []
    for c in artifact.get("cells", []):
        decl = c.get("declared_influence") or {}
        bp = c.get("breaking_point") or {}
        rows.append({
            "rule": c.get("rule"),
            "attack": c.get("attack"),
            "topology": c.get("topology"),
            # Pre-percentage-axis artifacts (schema v1 before ISSUE 13)
            # have no percentage field; render as unknown, not 0.
            "percentage": c.get("percentage"),
            "degree": c.get("degree"),
            "benign_accuracy": c.get("benign_accuracy"),
            "last_held": bp.get("last_held"),
            "first_broken": bp.get("first_broken"),
            "declared": decl.get("describe"),
            "declared_kind": decl.get("kind"),
            "declared_bound": decl.get("bound"),
            "compiles": c.get("compiles"),
        })
    return rows

"""``python -m murmura_tpu`` entry point."""

from murmura_tpu.cli import main

main()

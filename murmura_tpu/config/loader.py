"""Config load/save with suffix dispatch (reference: murmura/config/loader.py:11-67)."""

import json
from pathlib import Path
from typing import Union

import yaml

from murmura_tpu.config.schema import Config


def load_config(path: Union[str, Path]) -> Config:
    """Load and validate a Config from a .yaml/.yml/.json file."""
    path = Path(path)
    if not path.exists():
        raise FileNotFoundError(f"Config file not found: {path}")

    suffix = path.suffix.lower()
    with open(path, "r") as f:
        if suffix in (".yaml", ".yml"):
            raw = yaml.safe_load(f)
        elif suffix == ".json":
            raw = json.load(f)
        else:
            raise ValueError(
                f"Unsupported config format '{suffix}' (expected .yaml/.yml/.json)"
            )
    return Config.model_validate(raw)


def save_config(config: Config, path: Union[str, Path]) -> None:
    """Serialize a Config to .yaml/.yml/.json, chosen by suffix."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    data = config.model_dump(mode="json", exclude_none=True)

    suffix = path.suffix.lower()
    with open(path, "w") as f:
        if suffix in (".yaml", ".yml"):
            yaml.safe_dump(data, f, sort_keys=False)
        elif suffix == ".json":
            json.dump(data, f, indent=2)
        else:
            raise ValueError(
                f"Unsupported config format '{suffix}' (expected .yaml/.yml/.json)"
            )

"""Configuration layer (reference: murmura/config/)."""

from murmura_tpu.config.schema import (
    AggregationConfig,
    AttackConfig,
    Config,
    DataConfig,
    DistributedConfig,
    DMTTConfig,
    ExperimentConfig,
    GridConfig,
    MobilityConfig,
    ModelConfig,
    ServeConfig,
    SweepConfig,
    SweepMemberConfig,
    TopologyConfig,
    TPUConfig,
    TrainingConfig,
)
from murmura_tpu.config.loader import load_config, save_config

__all__ = [
    "Config",
    "ExperimentConfig",
    "TopologyConfig",
    "AggregationConfig",
    "AttackConfig",
    "MobilityConfig",
    "DMTTConfig",
    "TrainingConfig",
    "DataConfig",
    "ModelConfig",
    "DistributedConfig",
    "TPUConfig",
    "SweepConfig",
    "SweepMemberConfig",
    "GridConfig",
    "ServeConfig",
    "load_config",
    "save_config",
]

"""Pydantic configuration schema.

Byte-compatible with the reference YAML surface (reference:
murmura/config/schema.py:7-203) plus the new ``backend: tpu`` enum and an
optional ``tpu:`` section controlling mesh layout / precision / exchange
strategy.  ``extra = "forbid"`` everywhere, like the reference
(murmura/config/schema.py:200-202).
"""

from typing import Any, Dict, List, Literal, Optional

from pydantic import BaseModel, ConfigDict, Field, model_validator

from murmura_tpu.levers import refusal_reason


class _Strict(BaseModel):
    model_config = ConfigDict(extra="forbid")


class ExperimentConfig(_Strict):
    """Experiment-level settings (reference: murmura/config/schema.py:54-59)."""

    name: str = Field(description="Experiment name")
    seed: int = Field(default=42, description="Random seed for reproducibility")
    rounds: int = Field(default=20, description="Number of training rounds")
    verbose: bool = Field(default=False, description="Enable verbose logging")


class TopologyConfig(_Strict):
    """Static graph topology (reference: murmura/config/schema.py:62-70).

    ``exponential`` and ``one_peer`` are *sparse* families
    (topology/sparse.py; docs/SCALING.md): offset-list circulants whose
    round programs take a [k, N] edge mask instead of a dense [N, N]
    adjacency — the large-N path (4096+ nodes on one chip)."""

    type: Literal[
        "ring", "fully", "erdos", "k-regular",
        # Sparse offset-list families (degree O(log N), never [N, N]):
        "exponential", "one_peer",
    ] = Field(description="Topology type")
    num_nodes: int = Field(description="Number of nodes in the network")
    p: Optional[float] = Field(default=None, description="Edge probability (erdos)")
    k: Optional[int] = Field(default=None, description="Degree (k-regular)")
    seed: int = Field(default=12345, description="Topology generation seed")


class AggregationConfig(_Strict):
    """Aggregation rule selection (reference: murmura/config/schema.py:73-81)."""

    algorithm: Literal[
        "fedavg", "krum", "balance", "sketchguard", "ubar", "evidential_trust",
        # Beyond reference parity (coordinate-wise robust statistics):
        "median", "trimmed_mean", "geometric_median",
    ] = Field(description="Aggregation algorithm")
    params: Dict[str, Any] = Field(
        default_factory=dict, description="Algorithm-specific parameters"
    )


class AdaptiveAttackConfig(_Strict):
    """In-jit closed-loop attack adaptation (attacks/adaptive.py;
    docs/ROBUSTNESS.md "Adaptive adversaries").

    With ``enabled``, the configured attack tunes its own strength each
    round against the audit-tap acceptance signal inside the compiled
    round program: ``type: alie`` becomes adaptive ALIE (the deviation
    factor z walks the defense's selection margin); ``type: ipm``
    becomes adaptive IPM (the negation factor epsilon walks the same
    signal as carried state — the paper's own strength axis); every
    other broadcast attack (gaussian/directed_deviation) is wrapped in
    the generic scale bisection ("largest strength still accepted").  The
    adaptation state rides ``agg_state`` under the reserved
    ATTACK_STATE_KEYS, so durability snapshots resume a mid-bisection
    attacker byte-identically (MUR901's adaptive cell).  Default off =>
    byte-identical programs and histories.
    """

    enabled: bool = Field(
        default=False, description="Enable closed-loop adaptation"
    )
    ema_beta: float = Field(
        default=0.5, gt=0.0, le=1.0,
        description="Acceptance-EMA smoothing factor",
    )
    accept_target: float = Field(
        default=0.0, ge=0.0, lt=1.0,
        description=(
            "Acceptance fraction STRICTLY above which a round counts as "
            "accepted (0 = some peer selected/accepted the row — the "
            "right reading for single-winner rules like krum)"
        ),
    )
    eta: float = Field(
        default=0.25, gt=0.0, lt=1.0,
        description="Adaptive-ALIE multiplicative z step (1 +/- eta)",
    )
    scale_init: float = Field(
        default=1.0, gt=0.0,
        description="Bisection wrapper: first probed strength multiplier",
    )
    scale_max: float = Field(
        default=8.0, gt=0.0,
        description="Bisection wrapper: strength cap / growth-phase limit",
    )
    growth: float = Field(
        default=2.0, gt=1.0,
        description=(
            "Bisection wrapper: growth factor while no rejection has "
            "been observed"
        ),
    )
    z_min: float = Field(
        default=0.05, gt=0.0, description="Adaptive-ALIE z floor"
    )
    z_cap: Optional[float] = Field(
        default=None, gt=0.0,
        description="Adaptive-ALIE z ceiling (default: max(4*z0, 4))",
    )

    @model_validator(mode="after")
    def _bracket_sane(self):
        if self.scale_init > self.scale_max:
            raise ValueError(
                f"adaptive.scale_init={self.scale_init} > "
                f"scale_max={self.scale_max} — the first probe would "
                "start outside the bracket"
            )
        return self


class AttackConfig(_Strict):
    """Byzantine attack scenario (reference: murmura/config/schema.py:84-94)."""

    enabled: bool = Field(default=False, description="Enable Byzantine attacks")
    type: Optional[Literal[
        "gaussian", "directed_deviation", "topology_liar", "alie", "ipm",
        "label_flip",
    ]] = Field(
        default=None, description="Attack type"
    )
    percentage: float = Field(default=0.0, description="Fraction of nodes compromised")
    params: Dict[str, Any] = Field(
        default_factory=dict, description="Attack-specific parameters"
    )
    adaptive: AdaptiveAttackConfig = Field(
        default_factory=AdaptiveAttackConfig,
        description=(
            "In-jit closed-loop adaptation (docs/ROBUSTNESS.md); default "
            "off => byte-identical to no adaptive block"
        ),
    )


class MobilityConfig(_Strict):
    """Random-walk mobility model G^t (reference: murmura/config/schema.py:97-111)."""

    area_size: float = Field(default=100.0, description="2-D arena side length")
    comm_range: float = Field(
        default=30.0, description="Edge (i,j) in G^t iff torus-dist < comm_range"
    )
    max_speed: float = Field(default=5.0, description="Max displacement per round")
    seed: int = Field(default=42, description="RNG seed for positions and movement")
    ensure_connected: bool = Field(
        default=True, description="Attach isolated nodes to their nearest peer"
    )


class DMTTConfig(_Strict):
    """DMTT trust-protocol hyperparameters (reference: murmura/config/schema.py:114-139)."""

    budget_B: int = Field(default=5, description="Max collaborators per round")
    rho: float = Field(default=0.1, description="Link-reliability EMA factor")
    lambda_forget: float = Field(default=0.9, description="Beta-evidence forgetting")
    w_d: float = Field(default=1.0, description="Direct confirmation evidence weight")
    w_c: float = Field(default=0.5, description="Corroboration evidence weight")
    w_x: float = Field(default=1.0, description="Contradiction evidence weight")
    tau_U: float = Field(default=0.3, description="Uncertainty tolerance threshold")
    eta: float = Field(default=5.0, description="Uncertainty penalty scale")
    w_a: float = Field(default=0.7, description="Accuracy weight in model score")
    tau_u: float = Field(default=0.5, description="Uncertainty threshold, model score")
    lambda1: float = Field(default=0.4, description="Model compatibility weight")
    lambda2: float = Field(default=0.3, description="Topology trust weight")
    lambda3: float = Field(default=0.2, description="Link reliability weight")
    lambda4: float = Field(default=0.1, description="Communication cost weight")
    allow_static: bool = Field(
        default=False,
        description=(
            "Permit DMTT without a mobility section: claim verification uses "
            "the static topology as ground truth G^t.  Off by default so a "
            "missing mobility block is an explicit choice, not a silent "
            "fallback (murmura_tpu extension; the reference accepts it "
            "silently — murmura/dmtt/node_process.py:247)"
        ),
    )


class FaultsConfig(_Strict):
    """Operational fault model: churn, link drops, stragglers, NaN
    quarantine (murmura_tpu extension; no reference counterpart — the
    reference's only degradation path is the ZMQ deadline).

    Default off => byte-identical behavior to a config without this block:
    the compiled round program, history arrays, and random streams are
    untouched unless ``enabled`` is true.  See docs/ROBUSTNESS.md.
    """

    enabled: bool = Field(default=False, description="Enable the fault model")
    seed: int = Field(
        default=777,
        description=(
            "Fault-schedule seed; every process reconstructs the identical "
            "schedule from it (crash/recovery churn, link drops, stragglers)"
        ),
    )
    crash_prob: float = Field(
        default=0.0, ge=0.0, le=1.0,
        description="Per-round P(alive node crashes)",
    )
    recovery_prob: float = Field(
        default=0.0, ge=0.0, le=1.0,
        description=(
            "Per-round P(crashed node recovers), after min_down_rounds"
        ),
    )
    min_down_rounds: int = Field(
        default=1, ge=1,
        description="Minimum rounds a crashed node stays down",
    )
    link_drop_prob: float = Field(
        default=0.0, ge=0.0, le=1.0,
        description="Per-round per-undirected-edge drop probability",
    )
    straggler_prob: float = Field(
        default=0.0, ge=0.0, le=1.0,
        description=(
            "Per-round P(node straggles): its update misses the delivery "
            "deadline (jitted backends: outgoing contributions masked; "
            "distributed: the node actually sleeps).  With "
            "exchange.max_staleness >= 1 a straggle becomes a bounded "
            "DELAY instead of a drop: receivers aggregate the "
            "straggler's last delivered payload until the age bound "
            "expires (docs/ROBUSTNESS.md 'Bounded staleness')"
        ),
    )
    straggler_factor: float = Field(
        default=2.0, ge=1.0,
        description=(
            "Training-time multiplier a straggle simulates on the "
            "distributed backend (sleep of (factor-1) x training time, "
            "capped at the round window)"
        ),
    )
    nan_quarantine: bool = Field(
        default=True,
        description=(
            "In-jit numerical sentinel: after local training, nodes whose "
            "flattened update contains non-finite values are quarantined "
            "for the round — masked out of the exchange, params rolled "
            "back to the pre-round value — instead of poisoning the fleet"
        ),
    )
    nan_inject_nodes: List[int] = Field(
        default_factory=list,
        description=(
            "Deterministic divergence injection for chaos testing: these "
            "nodes emit NaN updates from nan_inject_from_round on"
        ),
    )
    nan_inject_from_round: int = Field(
        default=0, ge=0,
        description="First round nan_inject_nodes emit NaNs",
    )


class ExchangeConfig(_Strict):
    """Exchange-layer semantics: bounded-staleness gossip (ISSUE 13 —
    docs/ROBUSTNESS.md "Bounded staleness") and pipelined rounds
    (ISSUE 14 — docs/PERFORMANCE.md "Pipelined rounds"); PAPERS.md:
    asynchronous quantized decentralized SGD arXiv:1910.12308, delayed
    averaging arXiv:2002.01119.

    With ``max_staleness`` >= 1 the round program carries a per-sender
    payload cache + integer age stamp in ``agg_state`` (reserved
    ``STALE_STATE_KEYS``, core/stale.py): when the fault model disrupts a
    sender — a straggler, a crashed node, a link-isolated one — its
    base-graph edges are re-added with the last *delivered* payload
    instead of being dropped, as long as that payload's age stays within
    the bound.  Quarantined/attack-scrubbed rows are withheld from the
    cache path exactly like the fresh path (the MUR1103 replay-hole
    contract), and ages past the bound degrade to today's drop-the-edge
    behavior.

    Default (``max_staleness: 0``, ``pipeline: false``) => byte-identical
    behavior to a config without this block: the compiled round program,
    histories, and random streams are untouched.
    """

    max_staleness: int = Field(
        default=0, ge=0,
        description=(
            "Maximum rounds a cached neighbor payload may be served after "
            "its sender last delivered (0 = off: disrupted edges drop, "
            "today's strict-synchronous behavior)"
        ),
    )
    staleness_discount: float = Field(
        default=1.0, gt=0.0, le=1.0,
        description=(
            "Per-round-of-age multiplier on a re-added stale edge's "
            "adjacency weight (weight = discount ** age).  Mean-family "
            "rules honor the fraction; selection rules (krum/median/"
            "trimmed) treat any positive weight as a full candidate"
        ),
    )
    pipeline: bool = Field(
        default=False,
        description=(
            "Pipelined rounds (ISSUE 14; docs/PERFORMANCE.md 'Pipelined "
            "rounds'): overlap round r's local training with round "
            "r-1's exchange + aggregation through a double-buffered "
            "pipeline stage riding agg_state (one-round-delayed "
            "averaging, arXiv:2002.01119).  Round r's params then "
            "contain round r's local step plus round r-1's aggregation "
            "displacement.  Composes with compression, faults, "
            "staleness, sparse topologies and gang sweeps; default off "
            "=> byte-identical programs and histories"
        ),
    )


class CompressionConfig(_Strict):
    """Compressed neighbor exchange (murmura_tpu extension; ISSUE 7 —
    docs/PERFORMANCE.md, PAPERS.md: quantized decentralized SGD,
    arXiv:1910.12308).

    The round's exchanged [N, P] broadcast is quantized in-jit — per-block
    int8, or top-k of the round-over-round delta — the exchange moves the
    compressed representation, and receivers dequantize before rule math.
    ``error_feedback`` carries the quantization residual in the aggregation
    state and adds it back to the next round's transmission, the condition
    under which compressed decentralized SGD converges like full precision.

    Default (``algorithm: none``) => byte-identical behavior to a config
    without this block: the compiled round program, histories, and random
    streams are untouched.
    """

    algorithm: Literal["none", "int8", "topk"] = Field(
        default="none",
        description=(
            "Exchange codec: none (full-precision, the default), int8 "
            "(per-block symmetric 8-bit quantization of the broadcast), or "
            "topk (sparse top-k delta against a carried reference estimate)"
        ),
    )
    error_feedback: bool = Field(
        default=False,
        description=(
            "Carry the quantization residual (update - dequant(quant)) in "
            "agg_state and add it back to next round's transmission, so "
            "compression error telescopes instead of accumulating"
        ),
    )
    block: int = Field(
        default=256, ge=8,
        description=(
            "int8 quantization block along the parameter axis (one f32 "
            "scale per block; smaller blocks = finer scales, more scale "
            "bytes)"
        ),
    )
    topk_ratio: float = Field(
        default=0.05, gt=0.0, le=1.0,
        description=(
            "Fraction of the [P] coordinates the topk codec transmits per "
            "round (values + int32 indices)"
        ),
    )


class DurabilityConfig(_Strict):
    """Run-level durability (murmura_tpu extension; ISSUE 10 —
    docs/ROBUSTNESS.md "Run durability").

    Crash-equivalent checkpoint/resume for the jitted backends (single
    runs, gangs, population streaming) plus the elastic dispatch
    envelope: transient-error retries with exponential backoff and the
    ``require_tpu`` hard-fail replacing the silent CPU fallback.  CLI
    flags (``--checkpoint-dir``/``--resume``/``--require-tpu``/
    ``--retries``) override these; the block makes a run's durability
    posture part of its committed config.

    Default (no checkpoint_dir, retries 0, require_tpu off) =>
    byte-identical behavior to a config without this block.
    """

    checkpoint_dir: Optional[str] = Field(
        default=None,
        description=(
            "Snapshot the complete run state here every checkpoint_every "
            "rounds through the fsync'd durable-replace path "
            "(durability/snapshot.py); None disables checkpointing"
        ),
    )
    checkpoint_every: int = Field(
        default=5, ge=1,
        description="Rounds between snapshots (with checkpoint_dir)",
    )
    resume: bool = Field(
        default=False,
        description=(
            "Resume from checkpoint_dir when a snapshot exists (the CLI "
            "--resume twin); the telemetry event stream appends instead "
            "of rotating, and continuation is byte-identical to the "
            "uninterrupted run (MUR901)"
        ),
    )
    require_tpu: bool = Field(
        default=False,
        description=(
            "Hard-fail (BackendRequirementError) unless the default JAX "
            "backend is a TPU — replaces the silent CPU fallback.  Env "
            "twin: MURMURA_REQUIRE_TPU=1"
        ),
    )
    retries: int = Field(
        default=0, ge=0,
        description=(
            "Transient-error retries for the training dispatch: on a "
            "classified-transient failure (device/tunnel/transport — "
            "durability/dispatch.py) the run restores from its last "
            "snapshot and retries with exponential backoff + jitter.  "
            "Requires checkpoint_dir (retrying consumed/donated buffers "
            "without a restore is never safe)"
        ),
    )
    retry_base_delay_s: float = Field(
        default=1.0, ge=0.0,
        description="First backoff delay; doubles per retry",
    )
    retry_max_delay_s: float = Field(
        default=60.0, ge=0.0, description="Backoff delay ceiling",
    )


class TelemetryConfig(_Strict):
    """Unified runtime telemetry (murmura_tpu extension; ISSUE 4 —
    docs/OBSERVABILITY.md).

    One versioned run manifest + JSONL event stream every backend emits
    through (telemetry/writer.py), rendered by ``murmura report``.
    Default off => byte-identical behavior to a config without this block:
    the compiled round program, histories, and random streams are
    untouched unless ``enabled`` is true.
    """

    enabled: bool = Field(default=False, description="Enable the telemetry run manifest")
    dir: Optional[str] = Field(
        default=None,
        description=(
            "Run directory for manifest.json + events.jsonl "
            "(default: murmura_runs/<experiment.name>)"
        ),
    )
    audit_taps: bool = Field(
        default=False,
        description=(
            "In-jit aggregator audit taps: per-node decision tensors "
            "(krum/ubar/balance acceptance masks, evidential trust scores, "
            "quarantine/scrub flags) ride the round program's history "
            "output as agg_tap_* arrays.  Guaranteed collective- and "
            "recompile-clean (check --ir MUR400/MUR402)."
        ),
    )
    phase_times: bool = Field(
        default=True,
        description=(
            "Per-round phase_times events (per-round wall times; fused "
            "dispatch records elapsed/k amortized per round — the "
            "round_times semantics, now in one schema)"
        ),
    )
    memory_stats: bool = Field(
        default=False,
        description=(
            "Sample device memory_stats() into a per-round memory event "
            "(no-op on platforms that expose none)"
        ),
    )
    profile_dir: Optional[str] = Field(
        default=None,
        description=(
            "Profiler trace output dir for the round-window capture "
            "(default: <dir>/trace).  The whole-train trace remains "
            "tpu.profile_dir."
        ),
    )
    profile_start_round: int = Field(
        default=0, ge=0,
        description="First round of the profiler capture window",
    )
    profile_rounds: int = Field(
        default=0, ge=0,
        description=(
            "Rounds to capture a perfetto/xprof trace for, starting at "
            "profile_start_round (0 = no window capture; murmura run "
            "--profile sets this to the whole run when unset)"
        ),
    )


class PopulationConfig(_Strict):
    """Sampled-cohort streaming over a virtual population (murmura_tpu
    extension; ISSUE 6 — docs/SCALING.md).

    Teleportation-style sampled activation (arXiv:2501.15259): every round
    runs over a ``topology.num_nodes``-sized *cohort* drawn from a much
    larger virtual population.  Per-user model rows persist in a host-side
    state bank (``population/bank.py``: memory-mapped, lazily initialized);
    the active cohort is device-resident, and the next cohort's rows are
    staged while the current round computes.  Cohort draws are a pure
    function of ``(seed, draw_index)`` so distributed processes agree with
    zero communication, and cohort membership reaches the compiled round
    program as input *values* — one compile covers the whole population
    (the faults-subsystem mechanism, MUR302).

    Default off => byte-identical behavior to a config without this block.
    """

    enabled: bool = Field(default=False, description="Enable cohort streaming")
    virtual_size: int = Field(
        default=0, ge=0,
        description="Virtual population size U (users; >= topology.num_nodes)",
    )
    cohort_size: Optional[int] = Field(
        default=None,
        description=(
            "Resident cohort size; must equal topology.num_nodes (the "
            "compiled round program's node axis) — present for config "
            "legibility, defaulted from the topology when omitted"
        ),
    )
    sampler: Literal["uniform", "stratified"] = Field(
        default="uniform",
        description=(
            "Cohort sampler: uniform (without replacement over all users) "
            "or stratified (the user id space is split into cohort_size "
            "contiguous strata, one draw per stratum — every region of the "
            "population is touched every round)"
        ),
    )
    seed: int = Field(
        default=1234,
        description=(
            "Cohort-draw seed; draws are a pure function of (seed, "
            "draw_index), identical in every process"
        ),
    )
    rounds_per_cohort: int = Field(
        default=1, ge=1,
        description="Rounds a cohort stays resident before the next swap",
    )
    data_binding: Literal["user", "slot"] = Field(
        default="user",
        description=(
            "user: a user's data shard follows them (shard user_id mod N, "
            "re-staged at each swap); slot: shards stay bound to cohort "
            "slots (no data restaging — params-only streaming)"
        ),
    )
    inherit: Literal["teleport", "slot_init"] = Field(
        default="teleport",
        description=(
            "First-activation model for a user with no banked row: "
            "teleport (arXiv:2501.15259) adopts the OUTGOING cohort's "
            "trained slot model, so learning accumulates across cohorts "
            "even when re-activation is rare; slot_init starts fresh from "
            "the slot's seed init (isolated per-user models)"
        ),
    )
    bank_dir: Optional[str] = Field(
        default=None,
        description=(
            "Directory for the memory-mapped state bank (default: a "
            "TemporaryDirectory; small populations stay in RAM)"
        ),
    )


class SweepMemberConfig(_Strict):
    """One gang member's overrides (core/gang.py; docs/PERFORMANCE.md).

    A member is the base experiment with a different seed and optionally
    different *traced-scalar* hyperparameters — values the compiled round
    program takes as inputs, so every member rides one jit.  Shape-affecting
    knobs (num_nodes, batch_size, krum's num_compromised selection count,
    model size) cannot vary inside a gang: they change the traced program
    and belong in separate sweeps.
    """

    seed: Optional[int] = Field(
        default=None,
        description="Member experiment seed (default: experiment.seed)",
    )
    lr: Optional[float] = Field(
        default=None, gt=0.0,
        description="Member learning-rate override (default: training.lr)",
    )
    attack_scale: Optional[float] = Field(
        default=None, ge=0.0,
        description=(
            "Multiplier on the attack's broadcast perturbation "
            "(bcast = own + scale * (attacked - own)); 1.0 = the configured "
            "attack, 0.0 = attack off for this member"
        ),
    )
    noise_std: Optional[float] = Field(
        default=None, ge=0.0,
        description=(
            "Gaussian-attack noise std override — sugar for attack_scale = "
            "noise_std / attack.params.noise_std (gaussian attacks only)"
        ),
    )


class SweepConfig(_Strict):
    """Gang-batched multi-seed execution (murmura_tpu extension; ISSUE 5 —
    docs/PERFORMANCE.md).

    Stacks S independent experiments — differing in seed and optionally in
    traced scalar hyperparameters — into leading-axis-[S, ...] inputs and
    ``jax.vmap``s the round program over that axis: one XLA compile and one
    saturated device program cover the whole sweep instead of S compiles +
    S underfilled executions.  ``sweep:`` absent => byte-identical behavior
    to today; with it, each member's history is byte-identical on CPU to
    the single run with that member's seed (gang-parity contract,
    tests/test_gang.py).
    """

    seeds: Optional[List[int]] = Field(
        default=None,
        description="Explicit member seeds (one gang member per entry)",
    )
    num_seeds: Optional[int] = Field(
        default=None, ge=1,
        description=(
            "Sugar for seeds = [experiment.seed, experiment.seed + 1, ...]"
        ),
    )
    members: Optional[List[SweepMemberConfig]] = Field(
        default=None,
        description=(
            "Explicit member list with per-member hyperparameter overrides "
            "(mutually exclusive with seeds/num_seeds)"
        ),
    )
    bucket: bool = Field(
        default=True,
        description=(
            "Pad the gang to the next power-of-two size so growing S within "
            "a bucket reuses the compiled program (zero recompiles — check "
            "--ir MUR501); padding members replicate member 0 and are "
            "never recorded"
        ),
    )

    @model_validator(mode="after")
    def _exactly_one_member_source(self):
        sources = [
            s for s in (self.seeds, self.num_seeds, self.members)
            if s is not None
        ]
        if len(sources) != 1:
            raise ValueError(
                "sweep needs exactly one of seeds / num_seeds / members"
            )
        if self.seeds is not None and len(self.seeds) != len(set(self.seeds)):
            raise ValueError("sweep.seeds must be distinct")
        if self.seeds is not None and not self.seeds:
            raise ValueError("sweep.seeds must be non-empty")
        if self.members is not None and not self.members:
            raise ValueError("sweep.members must be non-empty")
        return self


class FrontierConfig(_Strict):
    """`murmura frontier <yaml>`: gang-powered adversarial search for each
    rule's empirical breaking point (docs/ROBUSTNESS.md "The robustness
    frontier").

    For every (rule x attack x topology) cell the driver stacks an
    attack-strength x seed grid into ONE compile-compatible gang bucket
    (per-member ``attack_scale`` — the sweep plumbing — padded to the
    next power of two), trains it, and runs an outer successive-halving
    loop that re-aims the strength grid at the honest-accuracy cliff
    WITHOUT recompiling (strengths are traced inputs; the gang is reset
    value-only between stages).  The committed ``frontier.json`` charts
    honest accuracy vs strength per cell plus each bounded rule's MUR800
    declared influence bound next to its empirical breaking point.
    """

    rules: List[str] = Field(
        default=["krum", "median", "trimmed_mean", "balance"],
        description="Aggregation rules to chart",
    )
    attacks: List[Literal["alie", "gaussian"]] = Field(
        default=["alie", "gaussian"],
        description=(
            "Adaptive attacks per cell: 'alie' = adaptive ALIE, "
            "'gaussian' = bisection-wrapped gaussian"
        ),
    )
    topologies: List[Literal["dense", "sparse"]] = Field(
        default=["dense", "sparse"],
        description=(
            "'dense' = the config's own (dense) topology; 'sparse' = the "
            "degree-log(N) exponential graph (arXiv:2110.13363)"
        ),
    )
    strength_lo: float = Field(
        default=0.25, gt=0.0,
        description="Initial strength grid lower edge (attack_scale units)",
    )
    strength_hi: float = Field(
        default=4.0, gt=0.0,
        description="Initial strength grid upper edge",
    )
    points: int = Field(
        default=4, ge=2,
        description=(
            "Nonzero strengths per stage (a 0-strength benign reference "
            "member is always added)"
        ),
    )
    seeds: Optional[List[int]] = Field(
        default=None,
        description="Member seeds per strength (default: [experiment.seed])",
    )
    percentages: Optional[List[float]] = Field(
        default=None,
        description=(
            "Sweep axis over attack.percentage — the BREAKDOWN-POINT "
            "axis: each value runs the full strength x seed successive-"
            "halving search with that fraction of nodes compromised, as "
            "its own compile-compatible gang bucket (the compromised set "
            "is a trace-time attack closure, so percentages cannot share "
            "a bucket the way strengths do).  None (default) = the base "
            "config's attack.percentage only"
        ),
    )
    stages: int = Field(
        default=2, ge=1,
        description="Successive-halving refinement stages per cell",
    )
    rounds: Optional[int] = Field(
        default=None, ge=1,
        description="Training rounds per stage (default: experiment.rounds)",
    )
    break_fraction: float = Field(
        default=0.5, gt=0.0, le=1.0,
        description=(
            "A strength is 'broken' when mean honest accuracy falls "
            "below break_fraction * the 0-strength benign accuracy"
        ),
    )

    @model_validator(mode="after")
    def _grid_sane(self):
        if self.strength_lo >= self.strength_hi:
            raise ValueError(
                f"frontier.strength_lo={self.strength_lo} must be < "
                f"strength_hi={self.strength_hi}"
            )
        for fieldname in ("rules", "attacks", "topologies"):
            vals = getattr(self, fieldname)
            if not vals:
                raise ValueError(f"frontier.{fieldname} must be non-empty")
            if len(vals) != len(set(vals)):
                raise ValueError(
                    f"frontier.{fieldname} has duplicates: {vals}"
                )
        if self.seeds is not None:
            if not self.seeds:
                raise ValueError("frontier.seeds must be non-empty")
            if len(self.seeds) != len(set(self.seeds)):
                raise ValueError("frontier.seeds must be distinct")
        if self.percentages is not None:
            if not self.percentages:
                raise ValueError("frontier.percentages must be non-empty")
            if len(self.percentages) != len(set(self.percentages)):
                raise ValueError("frontier.percentages must be distinct")
            bad = [p for p in self.percentages if not 0.0 < p < 1.0]
            if bad:
                raise ValueError(
                    f"frontier.percentages must be in (0, 1), got {bad}"
                )
        return self


class GridConfig(_Strict):
    """`murmura grid <yaml>`: the compile-compatible grid scheduler
    (serve/scheduler.py; docs/ROBUSTNESS.md "Serving").

    Expands a rule x attack x topology x strength x seed cell set and
    partitions it into **buckets keyed by the traced round program's
    jaxpr skeleton** (analysis/ir.py ``jaxpr_signature`` — the MUR203/
    MUR500 structural-equality machinery): cells whose programs are
    structurally equal share ONE gang bucket and therefore ONE compile;
    strength and seed ride as traced inputs (``attack_scale`` / the RNG
    lane) inside a bucket.  The full grid executes back-to-back off the
    warm compile cache and emits one cross-cell manifest for
    ``murmura report --grid``.
    """

    rules: List[str] = Field(
        default=["krum", "median", "trimmed_mean", "balance", "fedavg"],
        description="Aggregation rules (one bucket per rule, typically)",
    )
    attacks: List[Literal["gaussian", "alie", "ipm", "none"]] = Field(
        default=["gaussian"],
        description=(
            "Attack types per cell; 'none' runs benign cells (their "
            "program has no perturbation ops, so they bucket separately)"
        ),
    )
    topologies: List[Literal["dense", "sparse"]] = Field(
        default=["dense"],
        description=(
            "'dense' = the config's own (dense) topology; 'sparse' = the "
            "degree-log(N) exponential graph"
        ),
    )
    strengths: List[float] = Field(
        default=[0.0, 0.5, 1.0, 2.0, 4.0],
        description=(
            "Attack-strength axis (attack_scale units; 0.0 = the benign "
            "reference member).  A traced input — strengths share a "
            "bucket's single compile.  Ignored for attacks: ['none']"
        ),
    )
    seeds: Optional[List[int]] = Field(
        default=None,
        description=(
            "Member seeds per strength (default: [experiment.seed, "
            "experiment.seed + 1])"
        ),
    )
    rounds: Optional[int] = Field(
        default=None, ge=1,
        description="Training rounds per cell (default: experiment.rounds)",
    )

    @model_validator(mode="after")
    def _grid_sane(self):
        for fieldname in ("rules", "attacks", "topologies", "strengths"):
            vals = getattr(self, fieldname)
            if not vals:
                raise ValueError(f"grid.{fieldname} must be non-empty")
            if len(vals) != len(set(vals)):
                raise ValueError(f"grid.{fieldname} has duplicates: {vals}")
        if self.seeds is not None:
            if not self.seeds:
                raise ValueError("grid.seeds must be non-empty")
            if len(self.seeds) != len(set(self.seeds)):
                raise ValueError("grid.seeds must be distinct")
        bad = [g for g in self.strengths if g < 0.0]
        if bad:
            raise ValueError(f"grid.strengths must be >= 0, got {bad}")
        return self


class ServeConfig(_Strict):
    """`murmura serve <yaml>`: the crash-surviving multi-tenant daemon
    (serve/daemon.py; docs/ROBUSTNESS.md "Serving").

    The daemon accepts experiment submissions over a local socket and
    admits them into **warm gang buckets** keyed by the submission's
    structural fingerprint: tenants whose configs differ only in
    ``experiment.seed`` / ``experiment.name`` / ``training.lr`` (traced
    inputs) share one compiled bucket, admitted generation-by-generation
    via value-only ``GangNetwork.reset_run`` — zero recompiles
    (MUR1601).  Every bucket is built at ``capacity`` lanes up front
    (the power-of-two ``next_bucket`` shape), so admission never changes
    the compile shape; the queue simply waits for the next generation
    when more than ``capacity`` tenants target one bucket.  All daemon
    state (the submission ledger, generation records, gang snapshots on
    ``checkpoint_every`` cadence) lives under ``state_dir`` through the
    fsync'd durable-replace path, so a SIGKILL'd daemon restarts and
    resumes every in-flight run byte-identically (MUR1603).
    """

    state_dir: str = Field(
        description=(
            "Daemon state root: submission ledger + generation records + "
            "per-bucket gang snapshots (all fsync'd durable writes)"
        ),
    )
    socket: Optional[str] = Field(
        default=None,
        description=(
            "Unix-domain socket path for submissions (default: "
            "<state_dir>/daemon.sock)"
        ),
    )
    capacity: int = Field(
        default=4, ge=1,
        description=(
            "Gang lanes per bucket (power of two — the next_bucket "
            "compile shape).  Buckets are built at full capacity so "
            "within-capacity admission is value-only; a larger tenant "
            "backlog waits for the next generation instead of growing "
            "the compiled shape"
        ),
    )
    checkpoint_every: int = Field(
        default=1, ge=1,
        description=(
            "Gang snapshot cadence in rounds (durability/snapshot.py) — "
            "the resume granularity after a daemon SIGKILL"
        ),
    )
    poll_interval_s: float = Field(
        default=0.05, gt=0.0,
        description="Scheduler idle-poll interval between generations",
    )

    @model_validator(mode="after")
    def _capacity_is_bucket(self):
        c = self.capacity
        if c & (c - 1):
            raise ValueError(
                f"serve.capacity={c} must be a power of two — it IS the "
                "gang's next_bucket compile shape"
            )
        return self


class TrainingConfig(_Strict):
    """Local training hyperparameters (reference: murmura/config/schema.py:142-150)."""

    local_epochs: int = Field(default=1, description="Local epochs per round")
    batch_size: int = Field(default=64, description="Training batch size")
    lr: float = Field(default=0.01, description="Learning rate")
    max_samples: Optional[int] = Field(
        default=None, description="Max samples per client (None for all)"
    )


class DataConfig(_Strict):
    """Dataset selection (reference: murmura/config/schema.py:153-159)."""

    adapter: str = Field(description="Dataset adapter id (e.g. 'leaf.femnist')")
    params: Dict[str, Any] = Field(
        default_factory=dict, description="Dataset-specific parameters"
    )


class ModelConfig(_Strict):
    """Model selection (reference: murmura/config/schema.py:162-168)."""

    factory: str = Field(description="Model factory identifier")
    params: Dict[str, Any] = Field(
        default_factory=dict, description="Model-specific parameters"
    )


class DistributedConfig(_Strict):
    """ZeroMQ distributed backend (reference: murmura/config/schema.py:7-51)."""

    transport: Literal["ipc", "tcp"] = Field(
        default="ipc", description="ipc (single machine) or tcp (multi-machine)"
    )
    ipc_dir: str = Field(
        default="/tmp/murmura_tpu", description="Base dir for IPC socket files"
    )
    host: str = Field(default="127.0.0.1", description="Coordinator host (tcp)")
    coordinator_pub_port: int = Field(default=5500, description="Coordinator PUB port")
    coordinator_pull_port: int = Field(default=5501, description="Coordinator PULL port")
    base_port: int = Field(
        default=5550, description="Node i binds its PULL socket on base_port + i"
    )
    node_hosts: Optional[Dict[int, str]] = Field(
        default=None, description="Per-node host overrides for tcp: {node_id: host}"
    )
    round_duration_s: float = Field(
        default=60.0, description="Wall-clock budget per round in seconds"
    )
    startup_grace_s: float = Field(
        default=5.0, description="Seconds between launch and the first round start"
    )


class TPUConfig(_Strict):
    """TPU backend settings — new in murmura_tpu (no reference counterpart).

    Controls how the ``nodes`` axis of the stacked network state is laid out
    over a :class:`jax.sharding.Mesh` and how the per-round neighbor exchange
    is realized as XLA collectives.
    """

    num_devices: Optional[int] = Field(
        default=None,
        description="Devices in the mesh (None = all available devices)",
    )
    multihost: bool = Field(
        default=False,
        description=(
            "Initialize jax.distributed before building the mesh so the "
            "node axis spans all hosts of a multi-host TPU slice (ICI "
            "within a slice, DCN across slices). Coordinator settings come "
            "from the standard JAX env vars unless given below."
        ),
    )
    coordinator_address: Optional[str] = Field(
        default=None, description="host:port of process 0 (multihost)"
    )
    num_processes: Optional[int] = Field(
        default=None, description="Total JAX processes (multihost)"
    )
    process_id: Optional[int] = Field(
        default=None, description="This process's id (multihost)"
    )
    exchange: Literal["allgather", "ppermute"] = Field(
        default="allgather",
        description=(
            "Neighbor exchange strategy: allgather (every node sees [N,P]; "
            "O(N) memory, right for dense graphs) or ppermute (ring shifts, "
            "O(degree); right for ring/k-regular at large N)"
        ),
    )
    param_shards: int = Field(
        default=1,
        ge=1,
        description=(
            "Param-axis sharding (docs/PERFORMANCE.md 'Param-axis "
            "sharding'): split the flattened parameter vector over a "
            "third ('seed', 'nodes', 'param') mesh axis so every [N, P] "
            "round tensor — broadcast, stale cache, pipeline buffers, EF "
            "residual, the aggregation output — is resident at "
            "N x P/shards per device (ZeRO-style, arXiv:2004.13336).  "
            "The flat vector zero-pads to a multiple of the shard count; "
            "1 (default) is byte-identical to the unsharded program.  "
            "Largest-dividing-factor fallback picks the actual mesh axis "
            "when the device count cannot honor the full request."
        ),
    )
    param_dtype: Optional[Literal["float32", "bfloat16"]] = Field(
        default=None,
        description=(
            "Resident model-parameter dtype. None = auto: bfloat16 at "
            "num_nodes >= 64 (the documented large-N setting — halves the "
            "[N, P] state and the SGD update's HBM traffic; bench_sgd_micro "
            "measures the lever), float32 below. Set explicitly to pin."
        ),
    )
    conv_impl: Literal["direct", "im2col"] = Field(
        default="direct",
        description=(
            "CNN conv lowering: direct (lax.conv) or im2col (patch "
            "extraction + batched GEMM — the other bench_sgd_micro "
            "local-SGD lever candidate; same HWIO params, checkpoints "
            "interchangeable). Chip-measurement-gated: flip per run."
        ),
    )
    compute_dtype: Literal["float32", "bfloat16"] = Field(
        default="bfloat16", description="Matmul/conv compute dtype (MXU-friendly)"
    )
    donate_state: bool = Field(
        default=True, description="Donate round-step input buffers to XLA"
    )
    compilation_cache_dir: Optional[str] = Field(
        default=None,
        description=(
            "Enable JAX's persistent compilation cache at this path: "
            "recompiles of an identical round program (across runs and "
            "processes) become disk hits instead of 10-60s XLA compiles."
        ),
    )
    rounds_per_dispatch: int = Field(
        default=1,
        ge=1,
        description=(
            "Fuse this many FL rounds into one lax.scan program (device-"
            "resident round loop; one dispatch + one metrics fetch per "
            "chunk). Eval keeps the eval_every cadence via lax.cond."
        ),
    )
    profile_dir: Optional[str] = Field(
        default=None, description="If set, write a jax.profiler trace here"
    )
    pallas_agg: bool = Field(
        default=False,
        description=(
            "Route the aggregation hot loop's distance/selection passes "
            "through the fused Pallas TPU kernels (ops/pallas_agg.py): one "
            "streamed read of the [N, P] broadcast instead of one per "
            "offset/candidate.  Interpreted (and parity-tested) on CPU; "
            "ignored on a sharded node axis (pallas_call does not "
            "decompose under GSPMD).  Env twin: MURMURA_PALLAS_AGG=1."
        ),
    )
    recompile_guard: bool = Field(
        default=False,
        description=(
            "Runtime sanitizer: count XLA compilations per round and fail "
            "the run (analysis.sanitizers.RecompileError) if any occur "
            "after a program's warmup execution — post-warmup compiles "
            "mean the round signature is unstable and each one stalls the "
            "device for a full XLA build. Works on every backend."
        ),
    )
    transfer_guard: bool = Field(
        default=False,
        description=(
            "Runtime sanitizer: run the round loop under "
            "jax.transfer_guard('disallow') so implicit host<->device "
            "transfers raise instead of silently serializing the hot "
            "path (explicit jnp.asarray/device_get traffic still passes)."
        ),
    )


class Config(_Strict):
    """Top-level config object (reference: murmura/config/schema.py:171-198)."""

    experiment: ExperimentConfig
    topology: TopologyConfig
    aggregation: AggregationConfig
    attack: AttackConfig = Field(default_factory=AttackConfig)
    training: TrainingConfig
    data: DataConfig
    model: ModelConfig
    backend: Literal["simulation", "distributed", "tpu"] = Field(
        default="simulation",
        description=(
            "Execution backend: simulation (single-device vmap), distributed "
            "(ZMQ multi-process), or tpu (node axis sharded over a device mesh)"
        ),
    )
    distributed: DistributedConfig = Field(
        default_factory=DistributedConfig,
        description="ZMQ backend settings (used when backend=distributed)",
    )
    tpu: TPUConfig = Field(
        default_factory=TPUConfig,
        description="TPU backend settings (used when backend=tpu)",
    )
    mobility: Optional[MobilityConfig] = Field(
        default=None,
        description="Mobility model; if set, topology varies per round via G^t",
    )
    dmtt: Optional[DMTTConfig] = Field(
        default=None,
        description="DMTT protocol settings; requires mobility to also be set",
    )
    faults: FaultsConfig = Field(
        default_factory=FaultsConfig,
        description=(
            "Operational fault model (churn/link drops/stragglers/NaN "
            "quarantine); default off => byte-identical to no faults block"
        ),
    )
    telemetry: TelemetryConfig = Field(
        default_factory=TelemetryConfig,
        description=(
            "Unified telemetry (run manifest + event stream + audit taps); "
            "default off => byte-identical to no telemetry block"
        ),
    )
    compression: CompressionConfig = Field(
        default_factory=CompressionConfig,
        description=(
            "Compressed neighbor exchange (int8/topk with error feedback); "
            "default (none) => byte-identical to no compression block"
        ),
    )
    exchange: ExchangeConfig = Field(
        default_factory=ExchangeConfig,
        description=(
            "Exchange-layer semantics: bounded-staleness gossip "
            "(stale-tolerant cache + age-bounded re-delivery under "
            "faults; docs/ROBUSTNESS.md) and pipelined rounds (delayed "
            "aggregation overlapping local training; "
            "docs/PERFORMANCE.md); default (max_staleness 0, pipeline "
            "false) => byte-identical to no exchange block"
        ),
    )
    sweep: Optional[SweepConfig] = Field(
        default=None,
        description=(
            "Gang-batched multi-seed execution (`murmura sweep`): vmap the "
            "round program over an [S] experiment axis — one compile, one "
            "saturated dispatch for the whole sweep; absent => byte-"
            "identical behavior to today"
        ),
    )
    population: Optional[PopulationConfig] = Field(
        default=None,
        description=(
            "Sampled-cohort streaming over a virtual population "
            "(docs/SCALING.md); absent or disabled => byte-identical "
            "behavior to today"
        ),
    )
    durability: DurabilityConfig = Field(
        default_factory=DurabilityConfig,
        description=(
            "Run-level durability: crash-equivalent checkpoint/resume + "
            "retry/backoff dispatch envelope + require-tpu hard-fail; "
            "default off => byte-identical to no durability block"
        ),
    )
    frontier: Optional[FrontierConfig] = Field(
        default=None,
        description=(
            "`murmura frontier` adversarial-search grid (rule x adaptive "
            "attack x topology breaking-point curves; docs/ROBUSTNESS.md); "
            "absent => byte-identical behavior (only the frontier command "
            "reads it)"
        ),
    )
    grid: Optional[GridConfig] = Field(
        default=None,
        description=(
            "`murmura grid` compile-compatible scheduler grid (rule x "
            "attack x topology cells partitioned into jaxpr-skeleton "
            "buckets; docs/ROBUSTNESS.md \"Serving\"); absent => "
            "byte-identical behavior (only the grid command reads it)"
        ),
    )
    serve: Optional[ServeConfig] = Field(
        default=None,
        description=(
            "`murmura serve` multi-tenant daemon settings (state dir, "
            "socket, bucket capacity, checkpoint cadence; "
            "docs/ROBUSTNESS.md \"Serving\"); absent => byte-identical "
            "behavior (only the serve command reads it)"
        ),
    )

    @model_validator(mode="after")
    def _adaptive_attack_is_wirable(self):
        a = self.attack
        if not a.adaptive.enabled:
            return self
        if not a.enabled or a.type is None:
            # Same fail-loud discipline as the telemetry sub-settings: an
            # adaptive block without an attack would silently run benign.
            raise ValueError(
                "attack.adaptive.enabled requires attack.enabled: true "
                "and an attack.type — there is no attack to adapt"
            )
        if a.type in ("label_flip", "topology_liar"):
            raise ValueError(
                f"attack.adaptive does not support attack.type "
                f"'{a.type}': label_flip poisons data (no broadcast "
                "perturbation to scale) and topology_liar's claims "
                "channel is not modeled by the adaptation state; use "
                "gaussian/directed_deviation (bisection), alie "
                "(adaptive ALIE) or ipm (adaptive IPM)"
            )
        if self.backend == "distributed":
            raise ValueError(
                "adaptive attacks close the feedback loop inside the "
                "jitted round program; backend: distributed trains in "
                "per-node OS processes — use backend: simulation or tpu"
            )
        if self.dmtt is not None:
            raise ValueError(refusal_reason("adaptive", "dmtt"))
        return self

    @model_validator(mode="after")
    def _telemetry_requires_enabled(self):
        t = self.telemetry
        if not t.enabled and (
            t.audit_taps or t.memory_stats or t.profile_rounds
            or t.profile_start_round or t.dir is not None
            or t.profile_dir is not None
        ):
            # A sub-feature without the master switch would silently record
            # nothing — the experiment would *look* instrumented.  Fail loud.
            raise ValueError(
                "telemetry sub-settings (audit_taps/memory_stats/"
                "profile_rounds/profile_start_round/profile_dir/dir) "
                "require telemetry.enabled: true"
            )
        return self

    @model_validator(mode="after")
    def _sweep_is_wirable(self):
        if self.sweep is None:
            return self
        if self.backend == "distributed":
            raise ValueError(
                "sweep (gang-batched execution) runs the vmapped round "
                "program in one process; backend: distributed trains in "
                "per-node OS processes — use backend: simulation or tpu"
            )
        for i, m in enumerate(self.sweep.members or []):
            if m.noise_std is not None:
                if not (
                    self.attack.enabled and self.attack.type == "gaussian"
                ):
                    raise ValueError(
                        f"sweep.members[{i}].noise_std requires an enabled "
                        "gaussian attack (it rescales the gaussian "
                        "perturbation); use attack_scale for other attacks"
                    )
                if m.attack_scale is not None:
                    raise ValueError(
                        f"sweep.members[{i}] sets both noise_std and "
                        "attack_scale — they are two spellings of the same "
                        "multiplier; pick one"
                    )
            if (
                m.attack_scale is not None or m.noise_std is not None
            ) and not self.attack.enabled:
                raise ValueError(
                    f"sweep.members[{i}] overrides the attack but "
                    "attack.enabled is false — there is no perturbation "
                    "to scale"
                )
        return self

    @model_validator(mode="after")
    def _faults_injection_in_range(self):
        if self.faults.enabled and self.faults.nan_inject_nodes:
            bad = [
                i for i in self.faults.nan_inject_nodes
                if not 0 <= i < self.topology.num_nodes
            ]
            if bad:
                raise ValueError(
                    f"faults.nan_inject_nodes {bad} out of range for "
                    f"topology.num_nodes={self.topology.num_nodes}"
                )
        return self

    @model_validator(mode="after")
    def _sparse_topology_is_wirable(self):
        if self.topology.type not in ("exponential", "one_peer"):
            return self
        if self.backend == "distributed":
            raise ValueError(
                "sparse topologies (exponential/one_peer) run the [k, N] "
                "edge-mask exchange engine, which lives in the jitted "
                "backends; backend: distributed is not wired for it — use "
                "backend: simulation or tpu"
            )
        if self.mobility is not None:
            raise ValueError(refusal_reason("mobility", "sparse"))
        if self.dmtt is not None:
            raise ValueError(refusal_reason("dmtt", "sparse"))
        return self

    @model_validator(mode="after")
    def _population_is_wirable(self):
        p = self.population
        if p is None:
            return self
        if not p.enabled:
            if p.virtual_size or p.cohort_size is not None:
                # Same fail-loud discipline as the telemetry sub-settings:
                # a sized population without the master switch would
                # silently run as a plain N-node experiment.
                raise ValueError(
                    "population.virtual_size/cohort_size require "
                    "population.enabled: true"
                )
            return self
        n = self.topology.num_nodes
        if p.cohort_size is not None and p.cohort_size != n:
            raise ValueError(
                f"population.cohort_size={p.cohort_size} must equal "
                f"topology.num_nodes={n} — the cohort IS the compiled "
                "round program's node axis"
            )
        if p.virtual_size < n:
            raise ValueError(
                f"population.virtual_size={p.virtual_size} must be >= "
                f"topology.num_nodes={n} (the cohort is drawn without "
                "replacement)"
            )
        if self.backend == "distributed":
            raise ValueError(
                "population (cohort streaming) swaps device-resident "
                "state between rounds; backend: distributed keeps state "
                "in per-node OS processes — use backend: simulation or tpu"
            )
        if self.sweep is not None:
            raise ValueError(refusal_reason("population", "sweep"))
        if self.dmtt is not None:
            raise ValueError(refusal_reason("dmtt", "population"))
        return self

    @model_validator(mode="after")
    def _compression_is_wirable(self):
        c = self.compression
        if c.algorithm == "none":
            if c.error_feedback:
                # Same fail-loud discipline as the telemetry sub-settings:
                # error feedback without a codec would silently run an
                # uncompressed exchange while the config *looks* compressed.
                raise ValueError(
                    "compression.error_feedback requires a codec "
                    "(compression.algorithm: int8 or topk)"
                )
            return self
        if self.backend == "distributed":
            raise ValueError(
                "compressed exchange runs inside the jitted round program; "
                "backend: distributed exchanges full states over ZMQ — use "
                "backend: simulation or tpu"
            )
        if self.dmtt is not None:
            raise ValueError(refusal_reason("compression", "dmtt"))
        if self.population is not None and self.population.enabled:
            if c.error_feedback or c.algorithm == "topk":
                # Both the error-feedback residual and the topk reference
                # estimate are per-slot [N, P] state; cohort swaps reassign
                # slots to different users, so the carried state would be
                # fed into the wrong user's stream.  Stateless int8 is fine.
                raise ValueError(
                    refusal_reason("compression", "population", "carried_state")
                )
        return self

    @model_validator(mode="after")
    def _exchange_is_wirable(self):
        e = self.exchange
        if e.max_staleness == 0:
            if e.staleness_discount != 1.0:
                # Same fail-loud discipline as the telemetry sub-settings:
                # a discount without the staleness bound would silently
                # run strict-synchronous while the config *looks* stale-
                # tolerant.
                raise ValueError(
                    "exchange.staleness_discount requires "
                    "exchange.max_staleness >= 1 (there is no stale edge "
                    "to discount)"
                )
            return self
        if not self.faults.enabled:
            raise ValueError(
                refusal_reason("faults", "staleness", "requires_faults")
            )
        if self.backend == "distributed":
            raise ValueError(
                "bounded staleness runs inside the jitted round program "
                "(the cache rides the scan carry); backend: distributed "
                "realizes deadlines physically over ZMQ — use backend: "
                "simulation or tpu"
            )
        if self.dmtt is not None:
            raise ValueError(refusal_reason("dmtt", "staleness"))
        if self.mobility is not None:
            raise ValueError(refusal_reason("mobility", "staleness"))
        if self.topology.type == "one_peer":
            raise ValueError(
                refusal_reason("sparse", "staleness", "one_peer")
            )
        if self.population is not None and self.population.enabled:
            raise ValueError(refusal_reason("population", "staleness"))
        return self

    @model_validator(mode="after")
    def _pipeline_is_wirable(self):
        if not self.exchange.pipeline:
            return self
        if self.backend == "distributed":
            raise ValueError(
                "exchange.pipeline runs the delayed aggregation inside "
                "the jitted round program (the buffer rides the scan "
                "carry); backend: distributed exchanges full states over "
                "ZMQ per round — use backend: simulation or tpu"
            )
        if self.dmtt is not None:
            raise ValueError(refusal_reason("dmtt", "pipeline"))
        if self.attack.adaptive.enabled:
            raise ValueError(refusal_reason("adaptive", "pipeline"))
        if self.population is not None and self.population.enabled:
            raise ValueError(refusal_reason("pipeline", "population"))
        return self

    @model_validator(mode="after")
    def _param_shards_are_wirable(self):
        s = self.tpu.param_shards
        if s == 1:
            return self
        if self.backend != "tpu":
            raise ValueError(
                "tpu.param_shards > 1 requires backend: tpu — the param "
                "axis is a mesh axis; the simulation backend has no mesh "
                "to shard over"
            )
        if self.dmtt is not None:
            raise ValueError(refusal_reason("dmtt", "sharding"))
        if self.compression.algorithm == "topk":
            raise ValueError(
                refusal_reason("compression", "sharding", "topk")
            )
        # sweep x sharding LIFTED (ISSUE 16): the gang mesh grew a
        # "param" role — make_gang_param_mesh lays ("seed", "nodes",
        # "param") and the [S, N, P] stacked state shards on it.
        if self.population is not None and self.population.enabled:
            raise ValueError(refusal_reason("population", "sharding"))
        return self

    @model_validator(mode="after")
    def _durability_is_wirable(self):
        d = self.durability
        if d.checkpoint_dir is None and (d.resume or d.retries):
            # Same fail-loud discipline as the telemetry sub-settings: a
            # resume/retry posture without a snapshot location would
            # silently run non-durable while the config *looks* durable.
            raise ValueError(
                "durability.resume/retries require durability."
                "checkpoint_dir (there is nothing to restore from)"
            )
        if d.retry_max_delay_s < d.retry_base_delay_s:
            raise ValueError(
                f"durability.retry_max_delay_s={d.retry_max_delay_s} < "
                f"retry_base_delay_s={d.retry_base_delay_s}"
            )
        if d.checkpoint_dir is not None and self.backend == "distributed":
            raise ValueError(
                "durability.checkpoint_dir is not supported with "
                "backend: distributed — run state lives in per-node "
                "processes, which keep their own per-node fsync'd "
                "checkpoints (faults.enabled crash recovery)"
            )
        return self

    @model_validator(mode="after")
    def _dmtt_requires_mobility(self):
        if self.dmtt is not None and self.mobility is None and not self.dmtt.allow_static:
            raise ValueError(
                refusal_reason("dmtt", "mobility", "requires_mobility")
            )
        return self

"""Lever manifests: the declared cross-feature composition grid.

The framework's orthogonal levers (gang sweep, population streaming,
param-axis sharding, compressed exchange, bounded staleness, pipelined
rounds, adaptive attacks, fault schedules, sparse topologies, mobility,
DMTT) interact through a web of ``ConfigError`` refusals in
``config/schema.py`` and ``utils/factories.py``.  Historically each
refusal was hand-written at its guard site; this module makes every
lever declare its composition surface EXACTLY ONCE:

- the reserved ``*_STATE_KEYS`` group it rides in ``agg_state`` (if any),
- its mesh-axis placement ("seed" / "nodes" / "param"),
- its ``jax.named_scope`` stage hook in the round program (if any),
- an explicit per-peer verdict: ``composes()`` | ``refuses(reason)``,
  with constrained composition expressed as ``composes(tag=reason)``
  (the pair composes EXCEPT under the tagged sub-configuration).

Guard sites cite ``refusal_reason(a, b)`` instead of a literal string,
so the message a user sees and the verdict an analyzer checks are the
same object — `murmura check --compose` (analysis/composition.py,
MUR1400-1403) verifies the bijection both ways: every guard resolves to
a declared verdict, every declared refusal has a live guard, and every
declared-compatible pair's composed round program actually composes
(zero recompiles, collective-inventory parity, flow-taint preservation).

Declaration convention: for each unordered pair the alphabetically
LATER lever declares the verdict about the EARLIER peer, so the grid
has exactly one owner per pair and ``lever_manifests()`` can check
coverage is total.  Each manifest lives as a module-level
``LEVER_MANIFEST`` in the lever's home module (next to its
``*_STATE_KEYS`` tuple where one exists) and is AST-discoverable the
same way ``durability/snapshot.py`` discovers state-key groups.

This module imports nothing from the package at import time (lever
modules import it at module level; manifests are pulled lazily).
"""
from __future__ import annotations

import ast
import importlib
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, List, Optional, Tuple

# ---------------------------------------------------------------------------
# Verdicts
# ---------------------------------------------------------------------------

COMPOSES = "composes"
REFUSES = "refuses"


@dataclass(frozen=True)
class Verdict:
    """One lever's declared compatibility with one peer.

    ``kind`` is ``"composes"`` or ``"refuses"``.  A refusal carries the
    user-facing ``reason`` verbatim (guard sites raise it unchanged).  A
    constrained composition carries ``constraints``: (tag, reason) pairs
    for the sub-configurations that DO refuse — e.g. staleness composes
    with sparse topologies except ``one_peer``.
    """

    kind: str
    reason: Optional[str] = None
    constraints: Tuple[Tuple[str, str], ...] = ()

    def __post_init__(self):
        if self.kind not in (COMPOSES, REFUSES):
            raise ValueError(f"verdict kind must be composes|refuses: {self.kind!r}")
        if self.kind == REFUSES and not self.reason:
            raise ValueError("refuses() verdicts need a reason")
        if self.kind == COMPOSES and self.reason is not None:
            raise ValueError("composes() verdicts carry constraints, not a reason")


def composes(**constraints: str) -> Verdict:
    """The pair composes; keyword args declare refused sub-configs."""
    return Verdict(COMPOSES, None, tuple(sorted(constraints.items())))


def refuses(reason: str) -> Verdict:
    """The pair refuses outright; ``reason`` is the guard's message."""
    return Verdict(REFUSES, reason)


@dataclass(frozen=True)
class LeverManifest:
    """One lever's single-source composition declaration."""

    name: str                         # grid name, e.g. "staleness"
    module: str                       # home module (where this lives)
    state_keys_group: Optional[str] = None   # reserved *_STATE_KEYS name
    mesh_axes: Tuple[str, ...] = ()   # mesh roles it occupies
    stage: Optional[str] = None       # named_scope hook in the round program
    verdicts: Dict[str, Verdict] = field(default_factory=dict)

    def __post_init__(self):
        for peer, v in self.verdicts.items():
            if peer >= self.name:
                raise ValueError(
                    f"lever '{self.name}' declares a verdict for "
                    f"'{peer}' — the alphabetically later lever owns "
                    "each pair's verdict, so only earlier peers belong "
                    "here"
                )
            if not isinstance(v, Verdict):
                raise ValueError(
                    f"lever '{self.name}' verdict for '{peer}' is not a "
                    "Verdict (use composes()/refuses())"
                )


# ---------------------------------------------------------------------------
# Registry: lever name -> home module
# ---------------------------------------------------------------------------

# Every orthogonal lever and the module that owns its LEVER_MANIFEST.
# analysis/composition.py MUR1400 checks this table against an AST scan
# of the package (the MUR900 discovery pattern), so a manifest added
# without a registry row — or a row whose module lost its manifest — is
# a finding, not a silent gap.
LEVER_MODULES: Dict[str, str] = {
    "adaptive": "murmura_tpu.attacks.adaptive",
    "compression": "murmura_tpu.ops.compress",
    "dmtt": "murmura_tpu.dmtt.protocol",
    "faults": "murmura_tpu.faults.schedule",
    "mobility": "murmura_tpu.topology.dynamic",
    "pipeline": "murmura_tpu.core.pipeline",
    "population": "murmura_tpu.population.engine",
    "sharding": "murmura_tpu.parallel.mesh",
    "sparse": "murmura_tpu.topology.sparse",
    "staleness": "murmura_tpu.core.stale",
    "sweep": "murmura_tpu.core.gang",
}

# The round program's named_scope stage labels in execution order
# (core/rounds.py) — MUR1402 checks each manifest's ``stage`` against
# the traced first-occurrence order, so this list and the jaxpr agree.
STAGE_ORDER: Tuple[str, ...] = (
    "murmura.train",
    "murmura.exchange",
    "murmura.compress",
    "murmura.stale",
    "murmura.aggregate",
    "murmura.pipeline",
    "murmura.eval",
)


_MANIFEST_MEMO: Optional[Dict[str, LeverManifest]] = None


def lever_manifests(force: bool = False) -> Dict[str, LeverManifest]:
    """Import every lever module and collect its ``LEVER_MANIFEST``.

    Fails loudly (KeyError/ValueError) on a missing manifest, a name
    mismatch, or incomplete pair coverage — a manifest that cannot be
    loaded is a bug in the declaration layer itself, not a finding.
    """
    global _MANIFEST_MEMO
    if _MANIFEST_MEMO is not None and not force:
        return _MANIFEST_MEMO
    manifests: Dict[str, LeverManifest] = {}
    for name, modname in LEVER_MODULES.items():
        mod = importlib.import_module(modname)
        manifest = getattr(mod, "LEVER_MANIFEST", None)
        if manifest is None:
            raise ValueError(
                f"lever module {modname} has no LEVER_MANIFEST "
                f"(declared in LEVER_MODULES as lever '{name}')"
            )
        if manifest.name != name or manifest.module != modname:
            raise ValueError(
                f"LEVER_MANIFEST in {modname} declares "
                f"name={manifest.name!r} module={manifest.module!r}; the "
                f"LEVER_MODULES registry says ({name!r}, {modname!r})"
            )
        manifests[name] = manifest
    # Coverage: the later lever of every unordered pair declares it.
    names = sorted(manifests)
    for j, later in enumerate(names):
        declared = set(manifests[later].verdicts)
        expected = set(names[:j])
        missing = expected - declared
        extra = declared - expected
        if missing or extra:
            raise ValueError(
                f"lever '{later}' verdict coverage is not total: "
                f"missing={sorted(missing)} unknown={sorted(extra)}"
            )
    _MANIFEST_MEMO = manifests
    return manifests


def pair_verdict(a: str, b: str) -> Verdict:
    """The declared verdict for the unordered pair {a, b}."""
    if a == b:
        raise KeyError(f"a lever does not pair with itself: {a!r}")
    earlier, later = sorted((a, b))
    return lever_manifests()[later].verdicts[earlier]


def refusal_reason(a: str, b: str, constraint: Optional[str] = None) -> str:
    """The single-source refusal message for a guard site.

    ``constraint=None`` -> the pair's outright refusal reason;
    ``constraint="tag"`` -> the tagged constrained-composition reason.
    Raises KeyError/ValueError if the guard cites a verdict the
    manifests do not declare — a guard with no declaration is a bug the
    composition analyzer (MUR1400) surfaces before this ever raises in
    production.
    """
    v = pair_verdict(a, b)
    if constraint is None:
        if v.kind != REFUSES:
            raise ValueError(
                f"pair ({a}, {b}) is declared '{v.kind}' — a guard site "
                "citing an outright refusal needs a refuses() verdict"
            )
        assert v.reason is not None
        return v.reason
    reasons = dict(v.constraints)
    if constraint not in reasons:
        raise KeyError(
            f"pair ({a}, {b}) declares no constraint {constraint!r} "
            f"(has: {sorted(reasons)})"
        )
    return reasons[constraint]


def declared_refusals() -> List[Tuple[str, str, Optional[str]]]:
    """Every declared refusal as (earlier, later, constraint|None),
    sorted — outright refusals plus constrained-composition tags."""
    out: List[Tuple[str, str, Optional[str]]] = []
    for later, manifest in sorted(lever_manifests().items()):
        for earlier, v in sorted(manifest.verdicts.items()):
            if v.kind == REFUSES:
                out.append((earlier, later, None))
            else:
                for tag, _reason in v.constraints:
                    out.append((earlier, later, tag))
    return out


def compatible_pairs() -> List[Tuple[str, str]]:
    """Every declared-compatible unordered pair (earlier, later), sorted.
    Constrained compositions count as compatible — their grid cell arms
    the pair OUTSIDE the refused sub-configuration."""
    out: List[Tuple[str, str]] = []
    for later, manifest in sorted(lever_manifests().items()):
        for earlier, v in sorted(manifest.verdicts.items()):
            if v.kind == COMPOSES:
                out.append((earlier, later))
    return out


# ---------------------------------------------------------------------------
# AST discovery (the durability/snapshot.py discover_state_key_groups
# pattern): find every module-level LEVER_MANIFEST without importing.
# ---------------------------------------------------------------------------

def discover_lever_manifests(pkg_root: Path) -> Dict[str, str]:
    """AST-scan the package for module-level ``LEVER_MANIFEST``
    assignments -> {module name: source path}.  MUR1400 checks this
    against LEVER_MODULES both ways."""
    found: Dict[str, str] = {}
    for py in sorted(pkg_root.rglob("*.py")):
        try:
            tree = ast.parse(py.read_text(), filename=str(py))
        except SyntaxError:
            continue
        modname = ".".join(py.relative_to(pkg_root.parent).with_suffix("").parts)
        if modname.endswith(".__init__"):
            modname = modname[: -len(".__init__")]
        for node in tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.target is not None:
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "LEVER_MANIFEST":
                    found[modname] = str(py)
    return found

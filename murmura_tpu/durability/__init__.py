"""Run-level durability: crash-equivalent checkpoint/resume + the elastic
dispatch envelope (docs/ROBUSTNESS.md "Run durability").

Two halves:

- :mod:`murmura_tpu.durability.snapshot` — the versioned run-state
  snapshot every in-jit orchestrator (Network, PopulationNetwork,
  GangNetwork) saves and restores through, written via the fsync'd
  ``utils.checkpoint.durable_replace`` path.  The reserved carried-state
  key registry lives here too; `murmura check` rule MUR900 keeps it in
  bijection with every ``*_STATE_KEYS`` tuple in the package.
- :mod:`murmura_tpu.durability.dispatch` — transient-error
  classification, exponential-backoff-with-jitter retry, and the
  ``--require-tpu`` hard-fail replacing the silent CPU fallback.
"""

from murmura_tpu.durability.dispatch import (
    BackendRequirementError,
    RetryPolicy,
    classify_error,
    require_tpu,
    run_with_retry,
    tpu_required,
)
from murmura_tpu.durability.snapshot import (
    RESERVED_AGG_STATE_KEY_GROUPS,
    SNAPSHOT_BASE_SECTIONS,
    restore_run_snapshot,
    save_run_snapshot,
)

__all__ = [
    "BackendRequirementError",
    "RetryPolicy",
    "classify_error",
    "require_tpu",
    "run_with_retry",
    "tpu_required",
    "RESERVED_AGG_STATE_KEY_GROUPS",
    "SNAPSHOT_BASE_SECTIONS",
    "restore_run_snapshot",
    "save_run_snapshot",
]

"""The elastic dispatch envelope: retry classification, backoff, and the
``--require-tpu`` hard-fail (docs/ROBUSTNESS.md "Run durability").

The bench record shows what this exists for: BENCH r03–r05 died to tunnel
timeouts mid-battery and were silently mislabeled as CPU results.  The
envelope gives every long-lived driver (CLI runs, the battery, a future
``murmura serve`` daemon) three primitives:

- :func:`classify_error` — transient (device/tunnel/transport) vs fatal.
  Deliberately conservative: only errors that a reconnect or a re-dispatch
  can plausibly cure classify transient; everything else (shape errors,
  OOM, config errors) is fatal and re-raised immediately — retrying a
  deterministic failure just burns the backoff budget.
- :class:`RetryPolicy` / :func:`run_with_retry` — exponential backoff with
  deterministic seeded jitter (reproducible schedules in tests; decorrelated
  retries in a fleet).  The attempt callable receives the try index so the
  caller can restore from its last snapshot before re-dispatching —
  retrying with donated (consumed) buffers is never safe, so the restore
  IS the retry mechanism, not an optimization.
- :func:`require_tpu` / :func:`tpu_required` — the hard-fail replacing the
  silent CPU fallback: ``--require-tpu``, ``durability.require_tpu``, or
  ``MURMURA_REQUIRE_TPU=1`` abort loudly when the default JAX backend is
  not a TPU, instead of producing CPU numbers labeled by hope.
"""

import errno
import os
import random
import time
from dataclasses import dataclass
from typing import Callable, Iterator, Optional


class BackendRequirementError(RuntimeError):
    """The run required a TPU backend and did not get one."""


# Substrings that mark an exception message as transient: transport/tunnel
# deaths, device unavailability, and gRPC/PJRT deadline failures.  Matched
# case-insensitively against str(exc) and its type name.
TRANSIENT_ERROR_MARKERS = (
    "deadline_exceeded",
    "deadline exceeded",
    "unavailable",
    "connection reset",
    "connection refused",
    "connection closed",
    "broken pipe",
    "socket closed",
    "timed out",
    "timeout",
    "failed to connect",
    "transport",
    "tunnel",
    "heartbeat",
    "address already in use",
)

# Exception types that are transient by construction (transport layer).
# ConnectionResetError / BrokenPipeError / ConnectionRefusedError are
# ConnectionError subclasses and socket.timeout aliases TimeoutError, so
# the daemon's socket layer (serve/protocol.py) is covered wholesale.
TRANSIENT_ERROR_TYPES = (ConnectionError, TimeoutError)

# OSError errnos that mark a socket-layer transient even when the
# exception is a bare OSError (no ConnectionError subclass): a killed
# daemon's stale socket file (EADDRINUSE on rebind), a peer that died
# mid-write, a refused/aborted connect during restart.
TRANSIENT_ERRNOS = frozenset(
    getattr(errno, name)
    for name in (
        "EADDRINUSE",
        "ECONNRESET",
        "ECONNREFUSED",
        "ECONNABORTED",
        "EPIPE",
        "ETIMEDOUT",
        "EAGAIN",
    )
    if hasattr(errno, name)
)


def classify_error(exc: BaseException) -> str:
    """``"transient"`` (retry may cure it) or ``"fatal"`` (re-raise).

    A :class:`BackendRequirementError` is always fatal — retrying cannot
    conjure a chip, and the whole point of ``--require-tpu`` is to stop.
    """
    if isinstance(exc, BackendRequirementError):
        return "fatal"
    if isinstance(exc, TRANSIENT_ERROR_TYPES):
        return "transient"
    if (
        isinstance(exc, OSError)
        and getattr(exc, "errno", None) in TRANSIENT_ERRNOS
    ):
        return "transient"
    text = f"{type(exc).__name__}: {exc}".lower()
    if any(marker in text for marker in TRANSIENT_ERROR_MARKERS):
        return "transient"
    return "fatal"


@dataclass(frozen=True)
class RetryPolicy:
    """Exponential backoff with jitter.

    Delay before retry ``i`` (0-based) is
    ``min(max_delay_s, base_delay_s * 2**i) * (1 + U(-jitter, +jitter))``,
    with the uniform draw from a seeded stream so schedules are
    reproducible (``seed=None`` derives one from the PID — decorrelated
    across fleet processes, still loggable).
    """

    max_retries: int = 3
    base_delay_s: float = 1.0
    max_delay_s: float = 60.0
    jitter: float = 0.25
    seed: Optional[int] = None

    def __post_init__(self):
        if self.max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {self.max_retries}")
        if self.base_delay_s < 0 or self.max_delay_s < self.base_delay_s:
            raise ValueError(
                f"need 0 <= base_delay_s <= max_delay_s, got "
                f"{self.base_delay_s}/{self.max_delay_s}"
            )
        if not 0.0 <= self.jitter < 1.0:
            raise ValueError(f"jitter must be in [0, 1), got {self.jitter}")


def backoff_delays(policy: RetryPolicy) -> Iterator[float]:
    """The policy's delay sequence (one entry per retry)."""
    rng = random.Random(
        policy.seed if policy.seed is not None else os.getpid()
    )
    for i in range(policy.max_retries):
        base = min(policy.max_delay_s, policy.base_delay_s * (2.0 ** i))
        yield base * (1.0 + rng.uniform(-policy.jitter, policy.jitter))


class RetryStats:
    """Mutable retry accounting for one dispatch envelope.

    The observability plane's view of the retry loop (ISSUE 19): pass
    :meth:`hook` as ``run_with_retry(on_retry=...)`` (or chain it from
    an existing hook) and the envelope's transient retries and
    cumulative backoff become scrapeable — the offline fold turns the
    matching ``backend_degraded`` events into
    ``murmura_degradations``/``murmura_backoff_seconds``
    (telemetry/metrics.py)."""

    def __init__(self):
        self.retries = 0
        self.backoff_s = 0.0
        self.last_reason: Optional[str] = None

    def hook(self, exc: BaseException, try_idx: int, delay: float) -> None:
        self.retries += 1
        self.backoff_s += float(delay)
        self.last_reason = f"{type(exc).__name__}: {exc}"

    def counters(self) -> dict:
        """The accumulated totals, keyed for
        ``TelemetryWriter.add_counters`` / the manifest counter fold."""
        return {
            "dispatch_retries": self.retries,
            "dispatch_backoff_s": self.backoff_s,
        }


def run_with_retry(
    attempt: Callable[[int], object],
    *,
    policy: RetryPolicy = RetryPolicy(),
    classify: Callable[[BaseException], str] = classify_error,
    on_retry: Optional[Callable[[BaseException, int, float], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
):
    """Call ``attempt(try_index)`` until it succeeds or retries exhaust.

    Fatal errors re-raise immediately; transient errors sleep the
    policy's backoff delay and retry (``on_retry(exc, next_try, delay)``
    fires first — the hook for ``backend_degraded`` telemetry and the
    caller's snapshot restore logging).  The final transient failure
    re-raises the original exception, so the caller's stack trace is the
    real one.
    """
    delays = backoff_delays(policy)
    try_idx = 0
    while True:
        try:
            return attempt(try_idx)
        except BaseException as exc:  # noqa: BLE001 — classified below
            if classify(exc) != "transient":
                raise
            delay = next(delays, None)
            if delay is None:
                raise
            try_idx += 1
            if on_retry is not None:
                on_retry(exc, try_idx, delay)
            sleep(delay)


# ----------------------------------------------------------------------
# --require-tpu


def tpu_required(config=None) -> bool:
    """Whether this run demands a TPU: the ``MURMURA_REQUIRE_TPU=1`` env
    twin, or ``durability.require_tpu`` in the config."""
    if os.environ.get("MURMURA_REQUIRE_TPU") == "1":
        return True
    if config is not None:
        dur = getattr(config, "durability", None)
        if dur is not None and getattr(dur, "require_tpu", False):
            return True
    return False


def require_tpu(source: str = "--require-tpu") -> None:
    """Hard-fail unless the default JAX backend is a TPU.

    Replaces the silent CPU fallback: the r03–r05 bench mislabeling
    happened because a dead tunnel degraded to CPU without anyone
    deciding that.  ``source`` names the knob that demanded the chip so
    the error is self-explaining.
    """
    import jax

    try:
        backend = jax.default_backend()
        kind = jax.devices()[0].device_kind
    except Exception as e:  # noqa: BLE001 — surfacing WHY counts as loud
        raise BackendRequirementError(
            f"{source}: TPU required but the JAX backend failed to "
            f"initialize ({type(e).__name__}: {e})"
        ) from e
    if backend != "tpu":
        raise BackendRequirementError(
            f"{source}: TPU required but the default JAX backend is "
            f"'{backend}' (device_kind={kind!r}); refusing the silent CPU "
            "fallback — fix the device/tunnel or drop the requirement"
        )

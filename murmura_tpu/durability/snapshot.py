"""The unified run-state snapshot: one schema for every in-jit orchestrator.

PR 3 gave the ZMQ backend real SIGKILL recovery, but the jitted
simulation/tpu backends — and everything layered on them since (gang
sweeps, compression's carried EF residual / topk reference, the
population engine) — lost the whole run on any interruption.  This module
is the one place that knows what "the whole run" is:

- the **base sections** every orchestrator carries
  (:data:`SNAPSHOT_BASE_SECTIONS`): stacked params, the FULL ``agg_state``
  dict (which is where every reserved carried-state key group lives —
  compression's EF residual and topk reference, DMTT trust state), the
  RNG base key, the round counter, history, and round times;
- orchestrator-specific **extra sections** collected through the
  ``_durability_extra_state()`` / ``_durability_restore_extra()`` hooks:
  the population engine's cohort binding + sampler draw index + state
  bank, the gang's per-member histories/labels, the telemetry run id.

Crash-equivalence is provable rather than aspirational because every
random stream in the framework is already a pure function of
``(seed, round)``: the round key is ``fold_in(base, round)``, the
FaultSchedule and MobilityModel regenerate from their seeds, and cohort
draws are keyed by ``(seed, draw_idx)``.  So the snapshot only needs the
*carried* state — everything else reconstructs deterministically — and a
restore into the warm compiled program is value-only: zero recompiles
(MUR902), byte-identical continuation (MUR901, tests/test_durability.py).

Storage rides :mod:`murmura_tpu.utils.checkpoint` — the fsync'd
``durable_replace`` path shared with the ZMQ per-node checkpoints and the
telemetry manifest, so there is ONE durability story in the repo, not
three.

The reserved carried-state key registry
---------------------------------------

Subsystems that carry state across rounds inside ``agg_state`` reserve
their keys in a module-level ``*_STATE_KEYS`` tuple (``ops/compress.py``
COMPRESS_STATE_KEYS, ``core/rounds.py`` DMTT_STATE_KEYS).  Because the
snapshot saves ``agg_state`` whole, those keys are durable *today* — the
risk is tomorrow: a future "save only the cheap keys" optimization, or a
new subsystem whose reserved tuple never gets audited.
:data:`RESERVED_AGG_STATE_KEY_GROUPS` is the registry `murmura check`
rule MUR900 (analysis/contracts.py) keeps honest, two ways:

1. every module-level ``*_STATE_KEYS`` assignment discovered in the
   package source must be registered here (and resolve to a tuple of
   strings) — an unregistered reserved group is a finding;
2. a payload containing every reserved key must survive the
   save→restore roundtrip byte-for-byte (executed, negative-tested).
"""

import ast
from pathlib import Path
from typing import Any, Dict, Tuple

import numpy as np

# Sections every snapshot carries, regardless of orchestrator.  The
# MUR900 completeness contract asserts a snapshot roundtrip preserves
# each of them; the names double as the payload keys in the
# state.<round>.msgpack / meta.json pair (utils/checkpoint.py).
SNAPSHOT_BASE_SECTIONS: Tuple[str, ...] = (
    "params",       # stacked [N, ...] model pytree (optimizer state is
                    # SGD-free today; a stateful optimizer's slots would
                    # ride params or agg_state and be covered either way)
    "agg_state",    # FULL carried aggregation state, reserved keys included
    "rng",          # the base PRNG key (round keys are fold_in(base, r))
    "round",        # the persistent round counter
    "history",      # recorded metrics (the run's output so far)
    "round_times",  # per-round wall times
)

# Registry of every reserved carried-state key-group tuple in the
# package: group name -> defining module.  MUR900 discovers
# ``*_STATE_KEYS`` assignments by AST scan and fails the check when one
# is missing here (or when an entry here no longer resolves).
RESERVED_AGG_STATE_KEY_GROUPS: Dict[str, str] = {
    "ATTACK_STATE_KEYS": "murmura_tpu.attacks.adaptive",
    "COMPRESS_STATE_KEYS": "murmura_tpu.ops.compress",
    "DMTT_STATE_KEYS": "murmura_tpu.core.rounds",
    "PIPELINE_STATE_KEYS": "murmura_tpu.core.pipeline",
    "STALE_STATE_KEYS": "murmura_tpu.core.stale",
}


def resolve_reserved_agg_state_keys() -> Dict[str, Tuple[str, ...]]:
    """Import every registered group; raises if an entry is stale."""
    import importlib

    out: Dict[str, Tuple[str, ...]] = {}
    for group, module in RESERVED_AGG_STATE_KEY_GROUPS.items():
        mod = importlib.import_module(module)
        keys = getattr(mod, group)
        if not (
            isinstance(keys, tuple)
            and keys
            and all(isinstance(k, str) for k in keys)
        ):
            raise TypeError(
                f"{module}.{group} must be a non-empty tuple of str "
                f"agg_state keys, got {keys!r}"
            )
        out[group] = keys
    return out


def discover_state_key_groups(pkg_root) -> Dict[str, str]:
    """AST-scan the package for module-level ``*_STATE_KEYS`` tuple
    assignments — the discovery half of the MUR900 bijection.  Returns
    ``{group_name: module_dotted_path}``."""
    pkg_root = Path(pkg_root)
    found: Dict[str, str] = {}
    for py in sorted(pkg_root.rglob("*.py")):
        try:
            tree = ast.parse(py.read_text())
        except (OSError, SyntaxError):
            continue  # unreadable files are MUR000 findings in lint
        rel = py.relative_to(pkg_root.parent).with_suffix("")
        module = ".".join(rel.parts)
        for node in tree.body:
            targets = []
            if isinstance(node, ast.Assign):
                targets = [
                    t.id for t in node.targets if isinstance(t, ast.Name)
                ]
            elif isinstance(node, ast.AnnAssign) and isinstance(
                node.target, ast.Name
            ):
                targets = [node.target.id]
            for name in targets:
                if name.endswith("_STATE_KEYS"):
                    found[name] = module
    return found


# ----------------------------------------------------------------------
# save / restore


def save_run_snapshot(directory, network) -> None:
    """Write ``network``'s complete run state to ``directory``.

    Collects the base sections from the orchestrator plus its
    ``_durability_extra_state()`` sections, and writes them through the
    fsync'd checkpoint path (utils/checkpoint.py): a crash at ANY point
    leaves either the previous complete snapshot or the new one.
    """
    from murmura_tpu.utils.checkpoint import save_checkpoint

    extra_arrays, extra_meta = network._durability_extra_state()
    save_checkpoint(
        directory,
        params=network.params,
        agg_state=network.agg_state,
        rng=network._rng,
        round_num=network.current_round,
        history=network._durability_history(),
        round_times=network.round_times,
        extra_arrays=extra_arrays,
        extra_meta=extra_meta,
    )


def restore_run_snapshot(directory, network) -> int:
    """Restore ``network`` from ``directory``; returns the round to
    continue from.

    The restore is value-only: the arrays land with the shapes/dtypes the
    warm compiled program already specialized on and are re-placed on the
    mesh (``_place_resident_state``), so continuing costs zero extra
    compiles (MUR902) and a resumed history is byte-identical to the
    uninterrupted run (MUR901).
    """
    import jax
    import jax.numpy as jnp

    from murmura_tpu.utils.checkpoint import restore_checkpoint

    (params, agg_state, rng, round_num, history, times,
     extra_arrays, extra_meta) = restore_checkpoint(
        directory,
        params_target=network.params,
        agg_state_target=network.agg_state,
        rng_target=network._rng,
    )
    # Refuse BEFORE mutating any live state: first the orchestrator's own
    # pure validation (kind/config identity — the specific messages), then
    # the generic shape guard (flax's from_bytes restores leaves at their
    # SAVED shapes without validating them against the target, so a
    # foreign snapshot would otherwise land silently and crash opaquely
    # later).
    network._durability_validate_extra(extra_arrays, extra_meta)
    saved = [np.shape(x) for x in jax.tree_util.tree_leaves(params)]
    live = [np.shape(x) for x in jax.tree_util.tree_leaves(network.params)]
    if saved != live:
        raise ValueError(
            f"snapshot params shapes {saved} do not match this run's "
            f"compiled shapes {live} — the snapshot was written by a "
            "different orchestrator (a single run vs a gang's "
            "[S, ...]-stacked lanes) or a different config; rebuild with "
            "the matching config"
        )
    network.params = jax.tree_util.tree_map(jnp.asarray, params)
    network.agg_state = {k: jnp.asarray(v) for k, v in agg_state.items()}
    network._place_resident_state()
    network._rng = jnp.asarray(rng)
    network.current_round = round_num
    network._durability_set_history(history)
    network.round_times = times
    network._durability_restore_extra(extra_arrays, extra_meta)
    return round_num


def embed_bool_mask(mask: np.ndarray) -> np.ndarray:
    """Pack a boolean mask for the snapshot (8x smaller; a 1M-user
    activation mask costs ~125 KB)."""
    return np.packbits(np.asarray(mask, dtype=bool))


def unpack_bool_mask(packed: np.ndarray, size: int) -> np.ndarray:
    return np.unpackbits(np.asarray(packed, dtype=np.uint8))[:size].astype(bool)


# ----------------------------------------------------------------------
# MUR900 executable completeness probe (used by analysis/contracts.py and
# negative-tested in tests/test_durability.py)


def snapshot_roundtrip_missing_sections(
    directory, payload_sections: Dict[str, Any]
) -> Tuple[list, list]:
    """Write a synthetic snapshot from ``payload_sections`` (a dict with
    the base-section names) into ``directory``, read it back, and return
    ``(missing_sections, corrupted_agg_keys)``.

    This is the executable half of MUR900: the registry says what a
    complete snapshot must carry; this function proves the serialization
    path actually carries it.  Callers (analysis/contracts.py) populate
    ``agg_state`` with every reserved key; a key that does not survive
    byte-for-byte is returned in ``corrupted_agg_keys``.
    """
    from murmura_tpu.utils.checkpoint import (
        restore_checkpoint,
        save_checkpoint,
    )

    missing = [s for s in SNAPSHOT_BASE_SECTIONS if s not in payload_sections]
    if missing:
        return missing, []
    save_checkpoint(
        directory,
        params=payload_sections["params"],
        agg_state=payload_sections["agg_state"],
        rng=payload_sections["rng"],
        round_num=payload_sections["round"],
        history=payload_sections["history"],
        round_times=payload_sections["round_times"],
    )
    params, agg_state, rng, round_num, history, times, _, _ = (
        restore_checkpoint(
            directory,
            params_target=payload_sections["params"],
            agg_state_target=payload_sections["agg_state"],
            rng_target=payload_sections["rng"],
        )
    )
    restored = {
        "params": params, "agg_state": agg_state, "rng": rng,
        "round": round_num, "history": history, "round_times": times,
    }
    missing = [
        s for s in SNAPSHOT_BASE_SECTIONS
        if restored.get(s) is None and payload_sections[s] is not None
    ]
    corrupted = [
        k for k, v in payload_sections["agg_state"].items()
        if k not in agg_state
        or not np.array_equal(
            np.asarray(agg_state[k]), np.asarray(v), equal_nan=True
        )
    ]
    return missing, corrupted


# Re-exported for existing importers; the .npz container helpers live
# with the file format they serialize (utils/checkpoint.py).
from murmura_tpu.utils.checkpoint import (  # noqa: E402,F401
    load_npz_bytes,
    npz_bytes,
)

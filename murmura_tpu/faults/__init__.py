"""Fault-injection & churn subsystem.

Deterministic, seeded operational faults — node crash/recovery churn, link
drops, stragglers, NaN quarantine — composing into every backend without
touching the compiled round's structure (docs/ROBUSTNESS.md).
"""

from murmura_tpu.faults.injector import FaultInjector
from murmura_tpu.faults.schedule import FaultSchedule, FaultSpec

__all__ = ["FaultSchedule", "FaultSpec", "FaultInjector"]

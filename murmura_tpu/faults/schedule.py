"""Deterministic operational-fault model: churn, link drops, stragglers.

The reproduction could only stress the *adversarial* axis (attacks/) — a
node that crashes, recovers, straggles, or emits NaNs took the run down
instead of degrading it.  :class:`FaultSchedule` is the operational twin of
the attack model: a seeded, precomputed per-round description of which
nodes are alive, which links dropped, and who straggles — the same
shape of object as the mobility model's time-varying G^t
(topology/dynamic.py) and consumed the same way, as per-round *values* fed
to an unchanged compiled round program.

Determinism is the load-bearing property: every consumer — the simulation
orchestrator folding masks into the adjacency, each ZMQ node process
re-resolving its expected-neighbor set, and the :class:`FaultInjector`
deciding whom to SIGKILL — reconstructs the identical schedule from the
seed with zero communication (the MobilityModel contract, dynamic.py:1-8).
To keep the random stream identical regardless of which probabilities are
zero, every per-round draw happens with a fixed shape in a fixed order.

Churn is a two-state Markov chain per node: an alive node crashes with
``crash_prob``; a node dead for at least ``min_down_rounds`` recovers with
``recovery_prob``.  ``alive_at(0)`` is the first transition from the
all-alive state, so a nonzero ``crash_prob`` can produce churn from the
very first round.
"""

from dataclasses import dataclass, field
from typing import Tuple

import numpy as np


@dataclass(frozen=True)
class FaultSpec:
    """Trace-time fault behavior baked into the round program.

    The *schedule* (who is alive when) stays host-side and reaches the
    compiled step as input values; this spec controls what the traced
    program itself contains: the ``alive`` argument and update-mask
    freeze always (its presence IS what makes a program "faulted"), the
    NaN sentinel when ``nan_quarantine``, and deterministic divergence
    injection for chaos tests.  A program built with ``faults=None`` is
    byte-identical to one built before this subsystem existed.
    """

    nan_quarantine: bool = True
    nan_inject_nodes: Tuple[int, ...] = field(default_factory=tuple)
    nan_inject_from_round: int = 0


class FaultSchedule:
    """Seeded per-round alive/link/straggler masks for ``num_nodes`` peers.

    Args:
        num_nodes: Network size N.
        crash_prob: Per-round P(alive -> dead) per node.
        recovery_prob: Per-round P(dead -> alive) per node, gated on having
            been down for at least ``min_down_rounds`` rounds.
        min_down_rounds: Minimum rounds a crashed node stays down before a
            recovery draw can succeed.
        link_drop_prob: Per-round per-undirected-edge drop probability.
            Drops are symmetric: if (i, j) is down, neither direction
            delivers that round — matching a failed transport link, and
            keeping the ZMQ backend's sender/receiver expectations
            consistent without communication.
        straggler_prob: Per-round P(node straggles).  A straggling node
            misses the round deadline for *delivery*: its outgoing
            contributions are dropped (column zeroed in
            :meth:`masked_adjacency`) but it still receives and aggregates
            — the deadline-based partial-aggregation semantics of the
            distributed backend (node_process.py), applied to the jitted
            backends.  With bounded staleness armed
            (``exchange.max_staleness``, core/stale.py) the schedule
            becomes a DELAY model instead of a pure drop: receivers
            aggregate the straggler's last delivered payload at age >= 1
            until the bound expires — the jitted twin of the ZMQ
            backend's "physically late, may deliver next window"
            behavior, closing the documented semantic gap between the
            two realizations (docs/ROBUSTNESS.md "Bounded staleness").
        straggler_factor: Training-time multiplier the distributed backend
            uses to *realize* a straggle as an actual delay (sleep); the
            jitted backends only consume the boolean.
        seed: RNG seed; same seed => identical schedule in every process.
    """

    def __init__(
        self,
        num_nodes: int,
        *,
        crash_prob: float = 0.0,
        recovery_prob: float = 0.0,
        min_down_rounds: int = 1,
        link_drop_prob: float = 0.0,
        straggler_prob: float = 0.0,
        straggler_factor: float = 2.0,
        seed: int = 777,
    ):
        if not 0.0 <= crash_prob <= 1.0:
            raise ValueError(f"crash_prob must be in [0, 1], got {crash_prob}")
        if not 0.0 <= recovery_prob <= 1.0:
            raise ValueError(
                f"recovery_prob must be in [0, 1], got {recovery_prob}"
            )
        if not 0.0 <= link_drop_prob <= 1.0:
            raise ValueError(
                f"link_drop_prob must be in [0, 1], got {link_drop_prob}"
            )
        if not 0.0 <= straggler_prob <= 1.0:
            raise ValueError(
                f"straggler_prob must be in [0, 1], got {straggler_prob}"
            )
        if min_down_rounds < 1:
            raise ValueError(
                f"min_down_rounds must be >= 1, got {min_down_rounds}"
            )
        self.num_nodes = num_nodes
        self.crash_prob = crash_prob
        self.recovery_prob = recovery_prob
        self.min_down_rounds = min_down_rounds
        self.link_drop_prob = link_drop_prob
        self.straggler_prob = straggler_prob
        self.straggler_factor = straggler_factor
        self.seed = seed

        self._rng = np.random.default_rng(seed)
        # Lazily extended per-round records (MobilityModel idiom): index r
        # holds the state *during* round r.
        self._alive = []  # list of [N] float32
        self._link_up = []  # list of [N, N] float32 (1 = link up)
        self._straggle = []  # list of [N] bool
        # Markov chain state after the last generated round.
        self._state_alive = np.ones(num_nodes, dtype=bool)
        self._down_rounds = np.zeros(num_nodes, dtype=np.int64)

    # ------------------------------------------------------------------

    def _advance(self) -> None:
        """Generate one more round.  All draws happen with fixed shapes in
        a fixed order so the stream — and therefore every later round — is
        identical across parameterizations that share a seed."""
        n = self.num_nodes
        crash_u = self._rng.random(n)
        recover_u = self._rng.random(n)
        link_u = self._rng.random((n, n))
        straggle_u = self._rng.random(n)

        alive = self._state_alive
        crash = alive & (crash_u < self.crash_prob)
        recover = (
            (~alive)
            & (self._down_rounds >= self.min_down_rounds)
            & (recover_u < self.recovery_prob)
        )
        new_alive = (alive & ~crash) | recover
        self._down_rounds = np.where(new_alive, 0, self._down_rounds + 1)
        self._state_alive = new_alive

        drop = np.triu(link_u < self.link_drop_prob, k=1)
        link_up = 1.0 - (drop | drop.T).astype(np.float32)
        np.fill_diagonal(link_up, 0.0)

        self._alive.append(new_alive.astype(np.float32))
        self._link_up.append(link_up)
        self._straggle.append(straggle_u < self.straggler_prob)

    def _ensure(self, round_idx: int) -> None:
        if round_idx < 0:
            raise ValueError(f"round_idx must be >= 0, got {round_idx}")
        while len(self._alive) <= round_idx:
            self._advance()

    # ------------------------------------------------------------------

    def alive_at(self, round_idx: int) -> np.ndarray:
        """[N] float32 alive mask during ``round_idx`` (1 = up)."""
        self._ensure(round_idx)
        return self._alive[round_idx].copy()

    def link_mask_at(self, round_idx: int) -> np.ndarray:
        """[N, N] float32 link-up mask (symmetric, zero diagonal)."""
        self._ensure(round_idx)
        return self._link_up[round_idx].copy()

    def straggler_at(self, round_idx: int) -> np.ndarray:
        """[N] bool: nodes whose round-``round_idx`` update misses the
        delivery deadline."""
        self._ensure(round_idx)
        return self._straggle[round_idx].copy()

    def alive_stack(self, round0: int, k: int) -> np.ndarray:
        """[k, N] alive masks for rounds ``round0 .. round0+k-1`` — the
        fused-dispatch twin of the orchestrator's adj_stack."""
        self._ensure(round0 + k - 1)
        return np.stack([self._alive[round0 + i] for i in range(k)])

    def delivering_at(self, round_idx: int) -> np.ndarray:
        """[N] float32: senders whose round-``round_idx`` payload meets
        the delivery deadline under the schedule's own masks (alive and
        not straggling).  The host-side view of the stale layer's
        delivery inference — an APPROXIMATION of it: core/stale.py
        infers delivery from the fully-folded adjacency, so in-jit
        sentinels (quarantine/scrub) and total link isolation can veto
        senders this method reports as delivering.  Consumed by
        bench_breakdown's staleness cells as the schedule-side count
        next to the observed in-jit stale-edge counts."""
        self._ensure(round_idx)
        return self._alive[round_idx] * (
            1.0 - self._straggle[round_idx].astype(np.float32)
        )

    def masked_adjacency(self, adj: np.ndarray, round_idx: int) -> np.ndarray:
        """Fold this round's faults into an adjacency mask.

        ``adj * alive_i * alive_j * link_mask`` — the exact no-recompile
        trick the ``compromised`` mask uses (core/rounds.py): the compiled
        round's structure never changes, only this input's values.  A
        straggler's *column* is zeroed (its update misses everyone's
        deadline) while its row survives (it still aggregates what it
        received).  The zero diagonal is re-asserted last (MUR301): the
        aggregation rules' neighbor masks lean on it.
        """
        self._ensure(round_idx)
        alive = self._alive[round_idx]
        out = np.asarray(adj, dtype=np.float32)
        out = out * alive[:, None] * alive[None, :]
        out = out * self._link_up[round_idx]
        out = out * (1.0 - self._straggle[round_idx].astype(np.float32))[None, :]
        np.fill_diagonal(out, 0.0)
        return out

    def masked_edge_mask(
        self, edge_mask: np.ndarray, offsets, round_idx: int
    ) -> np.ndarray:
        """Fold this round's faults into a sparse [k, N] edge mask.

        The sparse-exchange twin of :meth:`masked_adjacency`
        (topology/sparse.py): entry ``[j, i]`` is the edge
        ``i <- (i + offsets[j]) % N``, so the same multiplicative fold —
        receiver alive, sender alive, link up, sender not straggling —
        runs per offset row instead of over an [N, N] matrix.  Same
        contract (MUR301): masks may only *remove* edges.
        """
        self._ensure(round_idx)
        alive = self._alive[round_idx]
        link = self._link_up[round_idx]
        not_straggling = 1.0 - self._straggle[round_idx].astype(np.float32)
        out = np.asarray(edge_mask, dtype=np.float32).copy()
        idx = np.arange(self.num_nodes)
        for j, o in enumerate(offsets):
            sender = (idx + int(o)) % self.num_nodes
            out[j] *= (
                alive * alive[sender] * link[idx, sender]
                * not_straggling[sender]
            )
        return out

    # ------------------------------------------------------------------
    # Transition views (FaultInjector / node self-enforcement)

    def died_at(self, round_idx: int) -> np.ndarray:
        """[N] bool: nodes that were alive in round ``round_idx - 1`` (or
        at the all-alive origin for round 0) and are dead in ``round_idx``
        — the injector's SIGKILL set for this round."""
        self._ensure(round_idx)
        prev = (
            np.ones(self.num_nodes, dtype=bool)
            if round_idx == 0
            else self._alive[round_idx - 1] > 0
        )
        return prev & (self._alive[round_idx] <= 0)

    def recovered_at(self, round_idx: int) -> np.ndarray:
        """[N] bool: nodes dead in round ``round_idx - 1`` and alive in
        ``round_idx`` — the injector's respawn set for this round."""
        self._ensure(round_idx)
        if round_idx == 0:
            return np.zeros(self.num_nodes, dtype=bool)
        return (self._alive[round_idx - 1] <= 0) & (self._alive[round_idx] > 0)


# ---------------------------------------------------------------------------
# Composition manifest (murmura_tpu/levers.py; `murmura check --compose`).
# The single source of truth for this lever's cross-feature verdicts —
# guard sites in config/schema.py and utils/factories.py cite
# refusal_reason() so user-facing messages and the analyzer's grid can
# never drift apart (MUR1400).
# ---------------------------------------------------------------------------
from murmura_tpu.levers import LeverManifest, composes, refuses

LEVER_MANIFEST = LeverManifest(
    name="faults",
    module="murmura_tpu.faults.schedule",
    verdicts={
        # The fault mask is an input every program variant consumes;
        # attacks, codecs and claims all see the thinned graph.
        "adaptive": composes(),
        "compression": composes(),
        "dmtt": composes(),
    },
)

"""Crash realism for the distributed backend: SIGKILL on schedule, respawn
on recovery.

The :class:`FaultSchedule` *describes* churn; on the jitted backends the
orchestrator folds it into adjacency masks, but on the ZMQ backend a dead
node must actually BE dead — a killed OS process, not a masked tensor row.
:class:`FaultInjector` is the enforcement layer: a watcher thread in the
runner parent that, at each wall-clock round boundary, SIGKILLs the
processes of nodes the schedule crashes this round (mid-round, after
``kill_fraction`` of the window, so round-in-flight state is really lost)
and respawns recovering nodes one round *early* so the fresh process can
pay its import/compile boot cost during its last scheduled-dead round and
rejoin — restored from its per-node checkpoint — exactly at the scheduled
recovery round (node self-enforcement skips the still-dead boot round; see
node_process.py).

The injector never decides *who* dies: the schedule does, deterministically
from the seed, so survivors' expected-neighbor sets (re-resolved from the
same schedule) stay consistent with the kills without any control messages.
"""

import threading
import time
from typing import Callable, Optional

from murmura_tpu.faults.schedule import FaultSchedule


class FaultInjector:
    """Watcher thread enacting a FaultSchedule on live node processes.

    Args:
        schedule: The shared deterministic schedule.
        rounds: Experiment horizon (no kills/respawns past it).
        round_duration: Wall-clock seconds per round.
        t_start: Shared monotonic round-0 start (the runner's t_start).
        kill: ``kill(node_id)`` — SIGKILL the node's current process.
        respawn: ``respawn(node_id)`` — start a fresh process for the node
            (with resume-from-checkpoint semantics).
        kill_fraction: Where inside the round window the kill lands
            (0.5 = mid-round: after training has typically started, before
            the exchange completes — the maximally disruptive point).
    """

    def __init__(
        self,
        schedule: FaultSchedule,
        rounds: int,
        round_duration: float,
        t_start: float,
        kill: Callable[[int], None],
        respawn: Callable[[int], None],
        kill_fraction: float = 0.5,
    ):
        self.schedule = schedule
        self.rounds = rounds
        self.round_duration = round_duration
        self.t_start = t_start
        self._kill = kill
        self._respawn = respawn
        self.kill_fraction = min(max(kill_fraction, 0.0), 0.95)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Telemetry for tests/post-mortems: (round, "kill"|"respawn", node).
        self.events = []

    # ------------------------------------------------------------------

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._run, name="murmura-fault-injector", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def _sleep_until(self, target: float) -> bool:
        """Sleep until monotonic ``target``; False if stopped meanwhile."""
        while not self._stop.is_set():
            delay = target - time.monotonic()
            if delay <= 0:
                return True
            self._stop.wait(min(delay, 0.2))
        return False

    def _do_respawn(self, node_id: int, recovery_round: int) -> None:
        try:
            self._respawn(node_id)
            self.events.append((recovery_round, "respawn", node_id))
        except Exception as e:  # pragma: no cover - spawn races
            print(
                f"[injector] respawn of node {node_id} failed: {e}",
                flush=True,
            )

    def _run(self) -> None:
        import numpy as np

        for r in range(self.rounds):
            died = self.schedule.died_at(r)
            # Respawn one round early: nodes scheduled to recover at r+1
            # boot (imports, dataset load, jit warmup, checkpoint restore)
            # during round r — which they self-skip as still-dead — and are
            # ready at the r+1 window open.  A node down for exactly ONE
            # round (dying at r AND recovering at r+1) must wait for its
            # own kill first: its old process is still alive at window
            # start, so an early respawn would be skipped — and had it
            # succeeded, the r+0.5 kill would SIGKILL the replacement.
            recovering_next = (
                self.schedule.recovered_at(r + 1)
                if r + 1 < self.rounds
                else np.zeros(self.schedule.num_nodes, dtype=bool)
            )
            if not self._sleep_until(self.t_start + r * self.round_duration):
                return
            for node_id in map(int, (recovering_next & ~died).nonzero()[0]):
                self._do_respawn(node_id, r + 1)
            if died.any():
                if not self._sleep_until(
                    self.t_start + (r + self.kill_fraction) * self.round_duration
                ):
                    return
                for node_id in map(int, died.nonzero()[0]):
                    try:
                        self._kill(node_id)
                        self.events.append((r, "kill", node_id))
                    except Exception as e:  # pragma: no cover - kill races
                        print(
                            f"[injector] kill of node {node_id} failed: {e}",
                            flush=True,
                        )
                for node_id in map(int, (recovering_next & died).nonzero()[0]):
                    self._do_respawn(node_id, r + 1)

"""Count-Sketch compression (reference: murmura/aggregation/sketchguard.py:71-124).

The reference computes the sketch host-side with ``np.bincount``; here it is
``jax.ops.segment_sum`` of the sign-flipped parameter vector, so sketching all
N nodes is one vmapped traced op inside the round step and the sketch itself
is what would travel on the wire (sketchguard.py:126-155).
"""

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def make_sketch_tables(
    model_dim: int, sketch_size: int, seed: int = 42
) -> Tuple[np.ndarray, np.ndarray]:
    """Seeded hash/sign tables, matching the reference's RandomState draws
    (sketchguard.py:71-76): hash ~ randint(0, sketch_size, model_dim),
    sign ~ choice({-1,+1}, model_dim)."""
    rng = np.random.RandomState(seed)
    hash_table = rng.randint(0, sketch_size, size=model_dim).astype(np.int32)
    sign_table = rng.choice([-1, 1], size=model_dim).astype(np.float32)
    return hash_table, sign_table


def count_sketch(
    vector: jnp.ndarray,
    hash_table: jnp.ndarray,
    sign_table: jnp.ndarray,
    sketch_size: int,
    use_pallas: "bool | None" = None,
) -> jnp.ndarray:
    """Compress a [P] vector to a [sketch_size] Count-Sketch
    (reference: sketchguard.py:91-112).

    On TPU this dispatches to the Pallas MXU kernel
    (ops/pallas_sketch.py) — XLA lowers segment_sum with random indices
    to a serialized scatter, the one non-vectorizing op in the
    Sketchguard round.  Elsewhere (CPU tests) it stays a segment_sum.
    """
    if use_pallas is None:
        from murmura_tpu.ops.pallas_sketch import MAX_SKETCH_PAD

        use_pallas = (
            jax.default_backend() == "tpu" and sketch_size <= MAX_SKETCH_PAD
        )
    if use_pallas:
        from murmura_tpu.ops.pallas_sketch import count_sketch_pallas

        return count_sketch_pallas(vector, hash_table, sign_table, sketch_size)
    return jax.ops.segment_sum(
        sign_table * vector, hash_table, num_segments=sketch_size
    )

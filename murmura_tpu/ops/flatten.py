"""Pytree <-> flat-vector utilities.

The aggregation library operates on flattened float parameter vectors: one
node's model is a row [P], the gathered network is [N, P] (reference
counterpart: murmura/aggregation/base.py:138-170 ``flatten_model_state`` /
``calculate_model_dimension``, applied per dict in Python; here flattening is
a traced op so it fuses into the jitted round step).
"""

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree


def make_flatteners(
    template: Any,
) -> Tuple[Callable[[Any], jnp.ndarray], Callable[[jnp.ndarray], Any], int]:
    """Build (ravel, unravel, dim) for a single-node param pytree.

    ``ravel`` and ``unravel`` are jit/vmap-compatible; vmap them to map
    stacked [N, ...] params to the [N, P] neighbor tensor and back.
    """
    flat0, unravel = ravel_pytree(template)

    def ravel(tree: Any) -> jnp.ndarray:
        return ravel_pytree(tree)[0]

    return ravel, unravel, int(flat0.size)


def model_dimension(template: Any) -> int:
    """Total float parameter count (reference: aggregation/base.py:155-170).

    Works on concrete arrays and on ``jax.eval_shape`` ShapeDtypeStructs.
    """
    return sum(
        int(np.prod(leaf.shape)) for leaf in jax.tree_util.tree_leaves(template)
    )

"""Pytree <-> flat-vector utilities.

The aggregation library operates on flattened float parameter vectors: one
node's model is a row [P], the gathered network is [N, P] (reference
counterpart: murmura/aggregation/base.py:138-170 ``flatten_model_state`` /
``calculate_model_dimension``, applied per dict in Python; here flattening is
a traced op so it fuses into the jitted round step).
"""

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.flatten_util import ravel_pytree


def _leaf_spec(leaf):
    """(shape, dtype) of a pytree leaf — arrays and eval_shape structs via
    their attributes (no device transfer), raw Python scalars (which
    ravel_pytree accepts) via numpy inference."""
    if hasattr(leaf, "dtype") and hasattr(leaf, "shape"):
        return tuple(leaf.shape), np.dtype(leaf.dtype)
    arr = np.asarray(leaf)
    return arr.shape, arr.dtype


def make_flatteners(
    template: Any,
) -> Tuple[Callable[[Any], jnp.ndarray], Callable[[jnp.ndarray], Any], int]:
    """Build (ravel, unravel, dim) for a single-node param pytree.

    ``ravel`` and ``unravel`` are jit/vmap-compatible; vmap them to map
    stacked [N, ...] params to the [N, P] neighbor tensor and back.

    Rejects non-float leaves loudly: the aggregation library operates on
    float parameter vectors (models/core.py's LayerNorm-over-BatchNorm
    design note exists precisely to keep model state all-float), and a
    silently ravelled integer buffer would (a) be "aggregated" by means —
    meaningless — and (b) disagree with :func:`model_dimension`'s
    documented float-only count, desynchronizing every consumer that sizes
    buffers from it (sketchguard's sketch tables).
    """
    bad = []
    for leaf in jax.tree_util.tree_leaves(template):
        shape, dtype = _leaf_spec(leaf)
        if not jnp.issubdtype(dtype, jnp.floating):
            bad.append(f"{type(leaf).__name__}{shape}:{dtype}")
    if bad:
        raise TypeError(
            "aggregation operates on float parameter vectors; the model "
            f"template carries non-float leaves {bad} — keep trainable "
            "state float (see models/core.py normalization note) or strip "
            "non-float buffers before handing params to the round program"
        )
    flat0, unravel = ravel_pytree(template)

    def ravel(tree: Any) -> jnp.ndarray:
        return ravel_pytree(tree)[0]

    return ravel, unravel, int(flat0.size)


def padded_dim(dim: int, multiple: int) -> int:
    """``dim`` rounded up to a whole multiple of ``multiple`` — the padded
    flat width of a param-sharded program (docs/PERFORMANCE.md "Param-axis
    sharding").  The pad is what lets the ``"param"`` mesh axis split the
    flat vector into equal shards for ANY model size."""
    if multiple < 1:
        raise ValueError(f"pad multiple must be >= 1, got {multiple}")
    return -(-int(dim) // int(multiple)) * int(multiple)


def make_sharded_flatteners(
    template: Any, param_shards: int
) -> Tuple[Callable[[Any], jnp.ndarray], Callable[[jnp.ndarray], Any], int, int]:
    """Build (ravel, unravel, dim, flat_dim) with the flat vector zero-padded
    so ``param_shards`` divides its width.

    ``ravel`` emits [flat_dim] rows whose last ``flat_dim - dim`` columns are
    exact zeros; ``unravel`` strips the pad before reconstructing the pytree.
    Exact-zero padding is inert through every consumer by the same algebra
    the int8 codec's block padding relies on (ops/compress.py): distances add
    (0-0)^2, means of zeros stay zero, and the optimizer update never reads
    the pad back (unravel slices it off).  At ``param_shards=1`` (or when the
    shard count already divides the dimension) this degenerates to
    :func:`make_flatteners` exactly — flat_dim == dim and ravel/unravel are
    the unpadded pair, so the shards=1 program is byte-identical (MUR1302).
    """
    ravel0, unravel0, dim = make_flatteners(template)
    flat_dim = padded_dim(dim, param_shards)
    if flat_dim == dim:
        return ravel0, unravel0, dim, dim

    pad = flat_dim - dim

    def ravel(tree: Any) -> jnp.ndarray:
        return jnp.pad(ravel0(tree), (0, pad))

    def unravel(flat: jnp.ndarray) -> Any:
        return unravel0(flat[:dim])

    return ravel, unravel, dim, flat_dim


def model_dimension(template: Any) -> int:
    """Total float parameter count (reference: aggregation/base.py:155-170).

    Works on concrete arrays and on ``jax.eval_shape`` ShapeDtypeStructs.
    Counts only floating-dtype leaves, as documented: the reference's
    ``calculate_model_dimension`` skips non-float state (BatchNorm's
    integer ``num_batches_tracked`` buffers) because only float parameters
    are aggregated.  The repo's own models are all-float by design
    (models/core.py LayerNorm note), but externally supplied factories may
    carry integer buffers — those must not inflate the sketch sizing /
    model_dim plumbing that consumes this count.
    """
    return sum(
        int(np.prod(_leaf_spec(leaf)[0]))
        for leaf in jax.tree_util.tree_leaves(template)
        if jnp.issubdtype(_leaf_spec(leaf)[1], jnp.floating)
    )

"""Losses and evidential uncertainty, as masked pure functions.

- masked cross-entropy mirrors the reference's CE eval sweep
  (murmura/utils/metrics.py:9-53);
- the evidential loss is Sensoy et al.'s MSE + annealed KL(Dir(alpha_tilde)||Dir(1))
  (reference: murmura/examples/wearables/models.py:89-179);
- uncertainty metrics are the Dirichlet vacuity/entropy/strength used by
  evidential evaluation and trust scoring (reference:
  murmura/examples/wearables/models.py:49-86, murmura/core/node.py:134-196).

All functions take a sample-validity ``mask`` so padded batch slots
contribute nothing to means.
"""

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.scipy.special import digamma, gammaln


def _safe_mean(values: jnp.ndarray, mask: jnp.ndarray) -> jnp.ndarray:
    denom = jnp.maximum(mask.sum(), 1.0)
    return (values * mask).sum() / denom


def masked_cross_entropy(
    logits: jnp.ndarray, labels: jnp.ndarray, mask: jnp.ndarray
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Mean CE loss and accuracy over valid samples.

    Args:
        logits: [B, K] unnormalized scores.
        labels: [B] int class ids.
        mask: [B] validity (0/1).

    Returns:
        (mean_loss, accuracy) scalars.
    """
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]
    loss = _safe_mean(nll, mask)
    acc = _safe_mean((jnp.argmax(logits, -1) == labels).astype(jnp.float32), mask)
    return loss, acc


def uncertainty_metrics(alpha: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Dirichlet uncertainty decomposition (reference: wearables/models.py:49-86).

    Args:
        alpha: [B, K] Dirichlet concentration parameters.

    Returns:
        dict with per-sample 'probs' [B, K], 'vacuity' [B], 'entropy' [B],
        'strength' [B].
    """
    S = alpha.sum(-1, keepdims=True)
    K = alpha.shape[-1]
    probs = alpha / S
    vacuity = K / S[..., 0]
    entropy = -(probs * jnp.log(probs + 1e-10)).sum(-1)
    return {
        "probs": probs,
        "vacuity": vacuity,
        "entropy": entropy,
        "strength": S[..., 0],
    }


def evidential_loss(
    alpha: jnp.ndarray,
    labels: jnp.ndarray,
    mask: jnp.ndarray,
    num_classes: int,
    lambda_t: jnp.ndarray,
) -> jnp.ndarray:
    """Evidential MSE + annealed KL regularizer
    (reference: wearables/models.py:118-179).

    L = mean_b[ sum_k (y - p)^2 ] + lambda_t * mean_b[ KL(Dir(alpha~)||Dir(1)) ]
    where alpha~ removes evidence for the true class.

    Args:
        alpha: [B, K] Dirichlet parameters.
        labels: [B] int labels.
        mask: [B] validity.
        num_classes: K.
        lambda_t: annealing coefficient (already scaled by lambda_weight).
    """
    y = jax.nn.one_hot(labels, num_classes)
    S = alpha.sum(-1, keepdims=True)
    p = alpha / S
    mse = ((y - p) ** 2).sum(-1)

    alpha_tilde = y + (1.0 - y) * alpha
    kl = _kl_dirichlet_to_uniform(alpha_tilde)

    return _safe_mean(mse, mask) + lambda_t * _safe_mean(kl, mask)


def _kl_dirichlet_to_uniform(alpha: jnp.ndarray) -> jnp.ndarray:
    """Per-sample KL(Dir(alpha) || Dir(1)) (reference: wearables/models.py:158-179)."""
    K = alpha.shape[-1]
    sum_alpha = alpha.sum(-1)
    return (
        gammaln(sum_alpha)
        - gammaln(jnp.asarray(float(K)))
        - gammaln(alpha).sum(-1)
        + ((alpha - 1.0) * (digamma(alpha) - digamma(sum_alpha)[..., None])).sum(-1)
    )

"""Compressed neighbor exchange: int8 block quantization and top-k
sparsification with error feedback (docs/PERFORMANCE.md).

The round's exchanged tensor — the post-attack broadcast [N, P] — is the
dominant mover of bytes once the model is non-trivial: every edge of the
graph reads a full [P] row per round.  Quantized decentralized SGD
(PAPERS.md: arXiv:1910.12308) shows that compressing the exchanged
representation to int8 (or a top-k sparse slice) converges like
full-precision as long as the quantization residual is fed back into the
next round's transmission (error feedback), and it composes multiplicatively
with the degree-O(log N) sparse exponential graphs (docs/SCALING.md): fewer
edges x fewer bytes per edge.

Two codecs:

``int8`` — per-block symmetric scale.  The [P] row is split into
``block``-sized chunks; each chunk is quantized as ``q = round(x / scale)``
with ``scale = max|x| / 127`` per chunk.  Symmetric (no zero-point) by
design: exact zeros stay exact zeros through the codec, which is what the
padded-tail algebra and the masked-edge semantics (0-weighted neighbors
contribute nothing) rely on; the asymmetry loss is absorbed by error
feedback.  The compressed representation is ``(q int8 [N, P], scale f32
[N, P/block])`` — 8 bits + 32/block bits per element instead of 16/32.

``topk`` — sparse delta against a carried reference estimate.  Raw
parameter states are dense (top-k of a *state* would zero most of the
model); what is sparse is the round-over-round *change*.  The round
program carries a reference estimate ``x̂`` [N, P] in ``agg_state`` —
initialized from the (protocol-known) initial broadcast and updated to
exactly what receivers reconstruct — and transmits the k largest-magnitude
coordinates of ``x - x̂`` as (values f32, indices int32) pairs; receivers
apply the sparse delta to their copy of ``x̂``.  This is the CHOCO-SGD
memory-vector construction; with error feedback the untransmitted mass is
retried next round instead of lost.

The in-jit wiring lives in ``core/rounds.py`` (the ``compression=`` spec of
``build_round_program``); the int8 payload additionally rides the circulant
exchange kernels as an :class:`Int8Blocks` pytree so the ppermutes that
realize ``jnp.roll`` on a sharded node axis move the int8 payload, not a
dequantized float tensor (``murmura check --ir`` MUR700).
"""

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

# Reserved round-program-level agg_state keys (the DMTT_STATE_KEYS pattern,
# core/rounds.py): carried by the round step but never handed to the
# aggregation rule's state dict.
RESIDUAL_KEY = "compress_residual"
REF_KEY = "compress_ref"
COMPRESS_STATE_KEYS = (RESIDUAL_KEY, REF_KEY)


@dataclasses.dataclass(frozen=True)
class CompressionSpec:
    """Trace-time compressed-exchange spec (config: ``compression:``).

    Static under trace — the codec choice and its shape parameters are
    program structure; everything data-dependent (scales, residuals, the
    reference estimate) is traced values, so rounds never recompile
    (MUR701).
    """

    algorithm: str  # "int8" | "topk"
    block: int = 256
    topk_ratio: float = 0.05
    error_feedback: bool = False

    def __post_init__(self):
        if self.algorithm not in ("int8", "topk"):
            raise ValueError(
                f"compression algorithm must be 'int8' or 'topk', got "
                f"{self.algorithm!r}"
            )
        if self.block < 1:
            raise ValueError(f"compression block must be >= 1, got {self.block}")
        if not 0.0 < self.topk_ratio <= 1.0:
            raise ValueError(
                f"topk_ratio must be in (0, 1], got {self.topk_ratio}"
            )

    def topk_k(self, p: int) -> int:
        """Static number of transmitted coordinates for a [P] row."""
        return max(1, min(p, int(round(self.topk_ratio * p))))

    def state_keys(self) -> Tuple[str, ...]:
        """agg_state keys this spec carries across rounds."""
        keys = []
        if self.error_feedback:
            keys.append(RESIDUAL_KEY)
        if self.algorithm == "topk":
            keys.append(REF_KEY)
        return tuple(keys)

    def payload_bytes(self, p: int, uncompressed_itemsize: int) -> int:
        """Analytic bytes of one node's exchanged representation for a [P]
        row — what actually crosses an edge, the number the bench commits
        next to the measured cost line (bench.py compression variants)."""
        if self.algorithm == "int8":
            nblocks = -(-p // self.block)
            return p * 1 + nblocks * 4  # int8 payload + f32 scale per block
        k = self.topk_k(p)
        return k * (4 + 4)  # f32 value + int32 index per coordinate


# ---------------------------------------------------------------------------
# int8 per-block symmetric quantization
# ---------------------------------------------------------------------------


@jax.tree_util.register_pytree_node_class
class Int8Blocks:
    """The int8 compressed exchange representation as a pytree.

    ``q`` is the int8 payload [N, C*B] (P zero-padded up to whole blocks —
    symmetric quantization maps the zero padding to exact zero codes, so
    padded columns are inert in every consumer); ``scale`` is the per-block
    f32 scale [N, C].  ``p`` is the true parameter length and ``out_dtype``
    the dtype ``dequantize`` restores (the resident param dtype, MUR201).

    The circulant exchange kernels (aggregation/base.py) accept this in
    place of the float broadcast tensor and roll ``q``/``scale`` along the
    node axis *before* dequantizing, so on a sharded node mesh the boundary
    collective-permutes move int8 + the tiny scale rows — never a full-size
    float [*, P] operand (the MUR700 contract).
    """

    def __init__(self, q, scale, block: int, p: int, out_dtype):
        self.q = q
        self.scale = scale
        self.block = int(block)
        self.p = int(p)
        self.out_dtype = jnp.dtype(out_dtype)

    # -- pytree protocol ----------------------------------------------------
    def tree_flatten(self):
        return (self.q, self.scale), (self.block, self.p, str(self.out_dtype))

    @classmethod
    def tree_unflatten(cls, aux, children):
        block, p, out_dtype = aux
        q, scale = children
        return cls(q, scale, block, p, out_dtype)

    # -- views --------------------------------------------------------------
    @property
    def dtype(self):
        """The dequantized dtype — lets value-dtype consumers (e.g.
        ``circulant_masked_mean``'s ``out_dtype=bcast.dtype``) treat the
        payload like the float tensor it stands in for."""
        return self.out_dtype

    @property
    def num_nodes(self) -> int:
        return self.q.shape[0]

    @property
    def num_blocks(self) -> int:
        return self.scale.shape[1]

    @property
    def padded_p(self) -> int:
        return self.q.shape[1]

    def roll(self, shift: int) -> "Int8Blocks":  # murmura: traced
        """Roll along the node axis — the circulant neighbor exchange.  On
        a sharded node axis each roll lowers to boundary collective-permutes
        of the int8 payload and the [*, C] scale rows."""
        return Int8Blocks(
            jnp.roll(self.q, shift, axis=0),
            jnp.roll(self.scale, shift, axis=0),
            self.block,
            self.p,
            self.out_dtype,
        )

    def slice_blocks(self, start_block, nblocks: int) -> "Int8Blocks":  # murmura: traced
        """Static-width slice of ``nblocks`` whole quant blocks starting at
        (possibly traced) block index ``start_block`` — the P-chunking hook
        the exchange kernels use (chunk widths are whole blocks, so scales
        slice consistently with the payload)."""
        n = self.num_nodes
        q = jax.lax.dynamic_slice(
            self.q, (0, start_block * self.block), (n, nblocks * self.block)
        )
        s = jax.lax.dynamic_slice(self.scale, (0, start_block), (n, nblocks))
        return Int8Blocks(q, s, self.block, nblocks * self.block, self.out_dtype)

    def dequantize_f32(self) -> jnp.ndarray:  # murmura: traced
        """[N, padded_p] float32 values (the fused-consumer form: XLA folds
        the convert+scale into whatever elementwise chain reads it, so the
        int8 payload is what HBM serves)."""
        n = self.num_nodes
        qf = self.q.astype(jnp.float32).reshape(n, self.num_blocks, self.block)
        return (qf * self.scale[:, :, None]).reshape(n, self.padded_p)

    def dequantize(self) -> jnp.ndarray:  # murmura: traced
        """[N, p] values in ``out_dtype`` (padding stripped) — the
        receiver-side tensor rules that do arbitrary math get."""
        return self.dequantize_f32()[:, : self.p].astype(self.out_dtype)


def quantize_int8(  # murmura: traced
    x: jnp.ndarray, block: int, out_dtype=None
) -> Int8Blocks:
    """Per-block symmetric int8 quantization of a [N, P] tensor.

    ``scale = max|x| / 127`` per ``block``-wide chunk of the parameter
    axis; ``q = round(x / scale)`` clipped to [-127, 127].  All-zero blocks
    quantize to zero codes with zero scale (dequantizing to exact zeros),
    and the zero padding up to whole blocks is likewise exact — no masking
    is ever needed downstream.
    """
    n, p = x.shape
    out_dtype = x.dtype if out_dtype is None else jnp.dtype(out_dtype)
    pad = (-p) % block
    xf = x.astype(jnp.float32)
    # Static shape math: p is x.shape[1] and block is a trace-time int —
    # the name-based taint pass cannot see through the int param.
    if pad:  # murmura: ignore[MUR001]
        xf = jnp.pad(xf, ((0, 0), (0, pad)))
    nblocks = xf.shape[1] // block
    xb = xf.reshape(n, nblocks, block)
    amax = jnp.max(jnp.abs(xb), axis=-1)  # [N, C]
    scale = amax / 127.0
    inv = jnp.where(scale > 0.0, 1.0 / jnp.maximum(scale, 1e-30), 0.0)
    q = jnp.clip(jnp.round(xb * inv[:, :, None]), -127.0, 127.0).astype(
        jnp.int8
    )
    return Int8Blocks(
        q.reshape(n, nblocks * block), scale, block, p, out_dtype
    )


# ---------------------------------------------------------------------------
# top-k sparse delta codec
# ---------------------------------------------------------------------------


def topk_encode(delta: jnp.ndarray, k: int):  # murmura: traced
    """(values f32 [N, k], indices int32 [N, k]) of the k largest-magnitude
    coordinates per row — the transmitted representation."""
    mag = jnp.abs(delta.astype(jnp.float32))
    _, idx = jax.lax.top_k(mag, k)
    idx = idx.astype(jnp.int32)
    values = jnp.take_along_axis(delta.astype(jnp.float32), idx, axis=1)
    return values, idx


def topk_decode(  # murmura: traced
    values: jnp.ndarray, idx: jnp.ndarray, p: int
) -> jnp.ndarray:
    """Dense [N, p] float32 reconstruction of the sparse delta (zeros off
    the transmitted support)."""
    n = values.shape[0]
    rows = jnp.arange(n)[:, None]
    return jnp.zeros((n, p), jnp.float32).at[rows, idx].set(values)


# ---------------------------------------------------------------------------
# The round-step codec: one entry point for core/rounds.py
# ---------------------------------------------------------------------------


def compress_exchange(
    spec: CompressionSpec,
    bcast: jnp.ndarray,
    agg_state,
    quantized_exchange: bool,
):  # murmura: traced
    """Apply the compressed-exchange codec to the round's broadcast.

    Returns ``(exchanged, decoded, state_updates, stats)``:

    - ``exchanged`` is what the aggregation rule receives as its broadcast
      operand — an :class:`Int8Blocks` payload when the rule's exchange
      kernels can move compressed data (``AggregatorDef.quantized_exchange``
      and int8), else the dense ``decoded`` tensor;
    - ``decoded`` is the receiver-side dequantized [N, P] tensor (resident
      dtype) — what every receiver's rule math sees;
    - ``state_updates`` carries the error-feedback residual and/or the
      top-k reference estimate for the next round (``agg_state`` keys in
      :data:`COMPRESS_STATE_KEYS`);
    - ``stats`` are per-node history metrics (``agg_compress_*``).

    Error feedback: the residual ``e`` rides ``agg_state``; the round
    transmits ``Q(bcast + e)`` and carries ``e' = (bcast + e) - Q(bcast +
    e)`` forward, so quantization error telescopes instead of accumulating
    (tests/test_compression.py pins the telescoping identity).
    """
    state_updates = {}
    outgoing = bcast.astype(jnp.float32)
    if spec.error_feedback:
        outgoing = outgoing + agg_state[RESIDUAL_KEY].astype(jnp.float32)

    if spec.algorithm == "int8":
        qb = quantize_int8(outgoing, spec.block, out_dtype=bcast.dtype)
        decoded = qb.dequantize()
        exchanged = qb if quantized_exchange else decoded
    else:  # topk: sparse delta against the carried reference estimate
        ref = agg_state[REF_KEY].astype(jnp.float32)
        values, idx = topk_encode(outgoing - ref, spec.topk_k(bcast.shape[1]))
        decoded32 = ref + topk_decode(values, idx, bcast.shape[1])
        decoded = decoded32.astype(bcast.dtype)
        # The reference advances to exactly what receivers reconstructed —
        # stored in the resident dtype so both ends of next round's delta
        # agree bit-for-bit with what the rules actually consumed.
        state_updates[REF_KEY] = decoded
        exchanged = decoded

    err = outgoing - decoded.astype(jnp.float32)
    if spec.error_feedback:
        state_updates[RESIDUAL_KEY] = err.astype(
            agg_state[RESIDUAL_KEY].dtype
        )
    stats = {
        # Per-node L2 of what this round's codec did NOT deliver (before
        # feedback): the drift bound the error-feedback property test rides.
        "compress_error": jnp.sqrt(jnp.sum(err * err, axis=1)),
    }
    if spec.error_feedback:
        stats["compress_residual_norm"] = jnp.sqrt(
            jnp.sum(
                state_updates[RESIDUAL_KEY].astype(jnp.float32) ** 2, axis=1
            )
        )
    return exchanged, decoded, state_updates, stats


def init_compress_state(
    spec: Optional[CompressionSpec], init_flat, dtype
):
    """Initial ``agg_state`` entries for a compressed program.

    ``init_flat`` is the raveled [N, P] initial broadcast — the
    protocol-known starting point the top-k reference estimate adopts (a
    real deployment broadcasts full states once at setup), killing the
    cold-start round where a zero reference would make every delta dense.
    """
    import numpy as np

    if spec is None:
        return {}
    out = {}
    if spec.error_feedback:
        out[RESIDUAL_KEY] = np.zeros(init_flat.shape, dtype)
    if spec.algorithm == "topk":
        out[REF_KEY] = np.asarray(init_flat, dtype)
    return out


# ---------------------------------------------------------------------------
# Composition manifest (murmura_tpu/levers.py; `murmura check --compose`).
# The single source of truth for this lever's cross-feature verdicts —
# guard sites in config/schema.py and utils/factories.py cite
# refusal_reason() so user-facing messages and the analyzer's grid can
# never drift apart (MUR1400).
# ---------------------------------------------------------------------------
from murmura_tpu.levers import LeverManifest, composes, refuses

LEVER_MANIFEST = LeverManifest(
    name="compression",
    module="murmura_tpu.ops.compress",
    state_keys_group="COMPRESS_STATE_KEYS",
    stage="murmura.compress",
    verdicts={
        # The codec quantizes whatever broadcast the attack produced —
        # the adaptation loop observes acceptance, not payload bytes.
        "adaptive": composes(),
    },
)

"""Pallas TPU kernels for the aggregation hot loop: fused distance
accumulation and candidate selection (docs/PERFORMANCE.md).

BENCH_r02 pins the round at ~1.4% MFU — exchange/aggregation-bound, not
FLOP-bound.  The aggregation hot loop's HBM traffic is dominated by
re-reading the [N, P] broadcast tensor: the circulant distance pass reads
it once per offset (k rolled passes), and the candidate-stack rules
materialize rolled copies before sorting.  These kernels stream the
parameter axis through VMEM once and fuse everything downstream of the
read:

``circulant_sq_distances``
    [k, N] squared neighbor distances in ONE pass over own/bcast: each
    [N, C] chunk is loaded once and all k rolled subtract-square-reduce
    chains run in VMEM — 2·N·P HBM reads instead of (k+1)·N·P.

``pairwise_sq_distances``
    The dense [N, M] distance matrix (krum/ubar/balance stage 1) with the
    Gram matmul, the squared norms, and the final combination fused in one
    streamed pass; the MXU does the per-chunk dot.

``fused_candidate_select``
    The static circulant median/trimmed-mean: per P-chunk, the [m, N, C]
    candidate stack is built from rolls in VMEM, sorted along the small
    static m axis with an odd-even transposition network, and reduced to
    the median / trimmed mean — the [N, m, P]-class intermediate the lax
    path sorts over never exists.

Deployment contract (mirrors ``ops/pallas_sketch.py``):

- ``interpret=True`` on non-TPU backends, automatically — the tier-1 suite
  (pinned to CPU) runs every kernel through the Pallas interpreter, so
  parity with the lax reference path is tested everywhere
  (tests/test_pallas_agg.py).
- Opt-in via ``tpu.pallas_agg: true`` (or ``MURMURA_PALLAS_AGG=1``), wired
  by the factories as an aggregator param; off by default.  Sharded-axis
  policy (precise, per entry point): a sharded **nodes** axis is refused
  (in-kernel rolls are node-axis wrap-arounds; pallas_call does not
  decompose under GSPMD) — the entry points return ``None`` and callers
  keep the lax kernels.  A sharded **param** axis is accepted with
  SHARD-LOCAL grids: the kernel runs under ``shard_map`` over the mesh's
  ``"param"`` axis on each device's own column block, and the distance
  kernels finish with one small ``psum`` of the [k, N]/[N, M] scalars —
  exactly the sharded-P collective contract (MUR1300).  Anything else
  (both axes sharded, a width the shard count does not divide) falls back
  to lax by returning ``None``.
- Each entry point returns ``None`` when the shapes fall outside the
  kernel's support envelope (tiling alignment on a real TPU, VMEM budget);
  callers (aggregation/base.py) fall back to the lax path, so enabling the
  toggle is always safe.
- Parity is to documented tolerance, not bit-exact: the kernels accumulate
  chunk sums in float32 like the lax kernels but group them differently,
  and candidate stacks are compared/summed in f32 before the final cast.

Budget cells for the kernels land in ``analysis/BUDGETS.json`` under the
``pallas`` mode (analysis/budgets.py), so the FLOP/bytes delta of the
fused formulation is committed, reviewable perf history.
"""

import functools
import os
from typing import Optional, Sequence

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Per-input-block VMEM budget (bytes).  The distance kernels hold two
# [N, C] f32 blocks plus the [k, N]/[N, M] accumulator; the candidate
# kernel holds an m-high stack.  ~16 MB VMEM/core; stay well under.
_VMEM_BLOCK_BYTES = 4 * 1024 * 1024

# Hard cap on the resident accumulator (pairwise kernel holds [N, M] f32
# in VMEM for the whole sweep).
_MAX_PAIRWISE_CELLS = 1024 * 1024


def _sharded_axis_mode():
    """(mode, mesh) of the active param-axis trace scope
    (parallel/mesh.py): ``("nodes", mesh)`` = a sharded node axis — every
    entry point must REFUSE (return None; in-kernel rolls wrap at the
    resident row count, which is wrong on a split node axis);
    ``("param", mesh)`` = param-only sharding — run with shard-local
    grids via :func:`_param_shard_map`; ``(None, None)`` = no sharded
    scope (plain single-device call, or both axes size 1)."""
    from murmura_tpu.parallel.mesh import (
        active_param_scope,
        mesh_node_axis,
        mesh_param_shards,
    )

    scope = active_param_scope()
    if scope is None:
        return None, None
    mesh = scope[0]
    if mesh_node_axis(mesh) > 1:
        return "nodes", mesh
    if mesh_param_shards(mesh) > 1:
        return "param", mesh
    return None, None


def _param_shard_map(fn, mesh, n_in: int, reduce_out: bool):
    """Wrap a per-column-block kernel call for a param-sharded mesh:
    inputs split their LAST axis over ``"param"`` (shard-local grids —
    each device streams only its own columns), and the output either
    ``psum``s over the param groups (distance accumulations: the one
    small scalar collective of the sharded-P contract) or stays a
    column-sharded map (candidate selection)."""
    from jax.experimental.shard_map import shard_map
    from jax.sharding import PartitionSpec as P

    col = P(None, "param")

    def local(*blocks):
        out = fn(*blocks)
        if reduce_out:
            out = jax.lax.psum(out, "param")
        return out

    return shard_map(
        local,
        mesh=mesh,
        in_specs=(col,) * n_in,
        out_specs=P() if reduce_out else col,
        check_rep=False,
    )


def _param_shards_of(mesh) -> int:
    from murmura_tpu.parallel.mesh import mesh_param_shards

    return mesh_param_shards(mesh)


def _interpret_default() -> bool:
    """Interpreter mode everywhere but a real TPU (the test-suite path);
    MURMURA_PALLAS_INTERPRET=1 forces it for on-chip debugging."""
    if os.environ.get("MURMURA_PALLAS_INTERPRET") == "1":
        return True
    return jax.default_backend() != "tpu"


def _chunk_cols(n_rows: int, p: int, copies: int) -> int:
    """Lane-aligned chunk width so ``copies`` [n_rows, C] f32 blocks fit
    the VMEM budget."""
    c = _VMEM_BLOCK_BYTES // max(1, 4 * n_rows * copies)
    c = max(128, (c // 128) * 128)
    return min(c, max(128, (-(-p // 128)) * 128))


def _pad_cols(x: jnp.ndarray, width: int) -> jnp.ndarray:
    if x.shape[-1] == width:
        return x
    return jnp.pad(x, ((0, 0), (0, width - x.shape[-1])))


def _tiling_ok(interpret: bool, *dims) -> bool:
    """Compiled Mosaic wants sublane-aligned logical rows; the interpreter
    takes anything.  (Lane dims are always padded to 128 via _chunk_cols /
    output padding.)"""
    if interpret:
        return True
    return all(d % 8 == 0 for d in dims)


# ---------------------------------------------------------------------------
# circulant fused distances
# ---------------------------------------------------------------------------


def _circ_dist_kernel(own_ref, b_ref, out_ref, *, offsets, k_pad):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    o_blk = own_ref[:].astype(jnp.float32)
    b_blk = b_ref[:].astype(jnp.float32)
    rows = []
    for off in offsets:
        d = o_blk - jnp.roll(b_blk, -off, axis=0)
        rows.append(jnp.sum(d * d, axis=1))
    acc = jnp.stack(rows)
    if k_pad > len(offsets):
        acc = jnp.pad(acc, ((0, k_pad - len(offsets)), (0, 0)))
    out_ref[:] += acc


@functools.partial(
    jax.jit, static_argnames=("offsets", "interpret")
)
def _circ_dist_call(own, bcast, offsets, interpret):
    n, p = bcast.shape
    k = len(offsets)
    chunk = _chunk_cols(n, p, 2)
    p_pad = -(-p // chunk) * chunk
    # Zero padding is inert: both operands pad identically, so padded
    # columns contribute (0 - 0)^2 to every distance.
    own_p = _pad_cols(own.astype(jnp.float32), p_pad)
    b_p = _pad_cols(bcast.astype(jnp.float32), p_pad)
    k_pad = k if interpret else -(-k // 8) * 8
    n_pad = n if interpret else -(-n // 128) * 128
    if n_pad != n:
        # Row padding would corrupt the wrap-around of in-kernel rolls;
        # the caller falls back (see circulant_sq_distances).
        raise ValueError("unaligned n reached the kernel")
    out = pl.pallas_call(
        functools.partial(
            _circ_dist_kernel, offsets=tuple(offsets), k_pad=k_pad
        ),
        grid=(p_pad // chunk,),
        in_specs=[
            pl.BlockSpec((n, chunk), lambda i: (0, i)),
            pl.BlockSpec((n, chunk), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((k_pad, n), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((k_pad, n), jnp.float32),
        interpret=interpret,
    )(own_p, b_p)
    return out[:k]


def circulant_sq_distances(
    own: jnp.ndarray,
    bcast: jnp.ndarray,
    offsets: Sequence[int],
    interpret: Optional[bool] = None,
) -> Optional[jnp.ndarray]:
    """[k, N] squared distances D2[o, i] = ||own_i - bcast[(i+o) % N]||^2
    in one fused streaming pass, or ``None`` when the shapes fall outside
    the kernel envelope (caller falls back to the lax path)."""
    if interpret is None:
        interpret = _interpret_default()
    n, p = bcast.shape
    if not offsets or own.shape != bcast.shape:
        return None
    # Compiled mode: in-kernel rolls wrap at the block's row count, so the
    # node dim must be exactly resident (no row padding) and lane-aligned
    # for the [k, N] output.
    if not interpret and (n % 128 != 0):
        return None
    if not _tiling_ok(interpret, n):
        return None
    mode, mesh = _sharded_axis_mode()
    if mode == "nodes":
        return None  # rolls wrap at the resident row count — lax path
    if mode == "param":
        if p % _param_shards_of(mesh):
            return None
        return _param_shard_map(
            lambda o_l, b_l: _circ_dist_call(
                o_l, b_l, tuple(int(o) for o in offsets), interpret
            ),
            mesh, n_in=2, reduce_out=True,
        )(own, bcast)
    return _circ_dist_call(own, bcast, tuple(int(o) for o in offsets), interpret)


# ---------------------------------------------------------------------------
# dense fused pairwise distances
# ---------------------------------------------------------------------------


def _pairwise_kernel(a_ref, b_ref, out_ref, g_ref, sa_ref, sb_ref):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        g_ref[:] = jnp.zeros_like(g_ref)
        sa_ref[:] = jnp.zeros_like(sa_ref)
        sb_ref[:] = jnp.zeros_like(sb_ref)

    a = a_ref[:].astype(jnp.float32)
    b = b_ref[:].astype(jnp.float32)
    g_ref[:] += jax.lax.dot_general(
        a, b, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    sa_ref[:] += jnp.sum(a * a, axis=1)[None, :]
    sb_ref[:] += jnp.sum(b * b, axis=1)[None, :]

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        out_ref[:] = (
            sa_ref[0, :][:, None] + sb_ref[0, :][None, :] - 2.0 * g_ref[:]
        )


@functools.partial(jax.jit, static_argnames=("interpret",))
def _pairwise_call(a, b, interpret):
    from jax.experimental.pallas import tpu as pltpu

    n, p = a.shape
    m = b.shape[0]
    chunk = _chunk_cols(max(n, m), p, 2)
    p_pad = -(-p // chunk) * chunk
    a_p = _pad_cols(a.astype(jnp.float32), p_pad)
    b_p = _pad_cols(b.astype(jnp.float32), p_pad)
    scratch = [
        pltpu.VMEM((n, m), jnp.float32),
        pltpu.VMEM((1, n), jnp.float32),
        pltpu.VMEM((1, m), jnp.float32),
    ]
    return pl.pallas_call(
        _pairwise_kernel,
        grid=(p_pad // chunk,),
        in_specs=[
            pl.BlockSpec((n, chunk), lambda i: (0, i)),
            pl.BlockSpec((m, chunk), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((n, m), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, m), jnp.float32),
        scratch_shapes=scratch,
        interpret=interpret,
    )(a_p, b_p)


def pairwise_sq_distances(
    a: jnp.ndarray,
    b: jnp.ndarray,
    interpret: Optional[bool] = None,
) -> Optional[jnp.ndarray]:
    """[N, M] squared distances with the Gram matmul and norm combination
    fused into one streamed pass.  Inputs are expected pre-centered (the
    caller owns the cancellation guard — aggregation/base.py); returns
    ``None`` outside the kernel envelope."""
    if interpret is None:
        interpret = _interpret_default()
    n, p = a.shape
    m = b.shape[0]
    if b.shape[1] != p:
        return None
    if n * m > _MAX_PAIRWISE_CELLS:
        return None  # the [N, M] accumulator must stay VMEM-resident
    if not interpret and (n % 8 != 0 or m % 128 != 0):
        return None
    mode, mesh = _sharded_axis_mode()
    if mode == "nodes":
        return None  # the [N, M] accumulator spans the split node axis
    if mode == "param":
        if p % _param_shards_of(mesh):
            return None
        # Shard-local Gram/norm partials over each device's columns, one
        # [N, M] psum at the end: d2 = sum over shards of local d2.
        return _param_shard_map(
            lambda a_l, b_l: _pairwise_call(a_l, b_l, interpret),
            mesh, n_in=2, reduce_out=True,
        )(a, b)
    return _pairwise_call(a, b, interpret)


# ---------------------------------------------------------------------------
# fused candidate selection (static circulant median / trimmed mean)
# ---------------------------------------------------------------------------


def _candidate_kernel(own_ref, b_ref, out_ref, *, offsets, trim, median):
    o_blk = own_ref[:].astype(jnp.float32)
    b_blk = b_ref[:].astype(jnp.float32)
    cand = [o_blk] + [jnp.roll(b_blk, -off, axis=0) for off in offsets]
    m = len(cand)
    # Odd-even transposition network: m passes of compare-exchange sort the
    # m-candidate stack coordinate-wise (exact — same sorted values as
    # jnp.sort over the stacked axis).
    for sweep in range(m):
        for j in range(sweep % 2, m - 1, 2):
            lo = jnp.minimum(cand[j], cand[j + 1])
            hi = jnp.maximum(cand[j], cand[j + 1])
            cand[j], cand[j + 1] = lo, hi
    if median:
        res = 0.5 * (cand[(m - 1) // 2] + cand[m // 2])
    else:
        kept = cand[trim : m - trim]
        acc = kept[0]
        # Static unroll over a Python list of tracers (len is the static
        # candidate count) — not traced control flow.
        for c in kept[1:]:  # murmura: ignore[MUR001]
            acc = acc + c
        res = acc / float(len(kept))
    out_ref[:] = res.astype(out_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("offsets", "trim", "median", "interpret")
)
def _candidate_call(own, bcast, offsets, trim, median, interpret):
    n, p = bcast.shape
    m = len(offsets) + 1
    chunk = _chunk_cols(n, p, m + 2)
    p_pad = -(-p // chunk) * chunk
    own_p = _pad_cols(own, p_pad)
    b_p = _pad_cols(bcast, p_pad)
    out = pl.pallas_call(
        functools.partial(
            _candidate_kernel,
            offsets=tuple(offsets),
            trim=trim,
            median=median,
        ),
        grid=(p_pad // chunk,),
        in_specs=[
            pl.BlockSpec((n, chunk), lambda i: (0, i)),
            pl.BlockSpec((n, chunk), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((n, chunk), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((n, p_pad), own.dtype),
        interpret=interpret,
    )(own_p, b_p)
    return out[:, :p]


def candidate_select_supported(
    own,
    bcast,
    offsets: Sequence[int],
    trim: int = 0,
    interpret: Optional[bool] = None,
) -> bool:
    """Static envelope predicate for :func:`fused_candidate_select` — lets
    rules pick the kernel vs the lax path with a plain Python branch (no
    traced operand, MUR001-clean) at trace time."""
    if interpret is None:
        interpret = _interpret_default()
    if not offsets or tuple(own.shape) != tuple(bcast.shape):
        return False
    m = len(offsets) + 1
    if trim < 0 or m - 2 * trim < 1:
        return False
    if not interpret and bcast.shape[0] % 128 != 0:
        return False  # in-kernel rolls wrap at the resident row count
    mode, mesh = _sharded_axis_mode()
    if mode == "nodes":
        return False  # rolls wrap at the resident row count — lax path
    if mode == "param" and bcast.shape[1] % _param_shards_of(mesh):
        return False  # columns must split evenly into shard-local grids
    return True


def fused_candidate_select(
    own: jnp.ndarray,
    bcast: jnp.ndarray,
    offsets: Sequence[int],
    trim: int = 0,
    median: bool = False,
    interpret: Optional[bool] = None,
) -> Optional[jnp.ndarray]:
    """[N, P] coordinate-wise median (``median=True``) or ``trim``-trimmed
    mean over the static circulant candidate stack {own} ∪ {k rolled
    broadcasts}, fused with the streaming read.  ``None`` outside the
    envelope (masked/sparse candidate sets keep the lax path — their
    per-node counts are traced)."""
    if interpret is None:
        interpret = _interpret_default()
    if not candidate_select_supported(
        own, bcast, offsets, trim=0 if median else trim, interpret=interpret
    ):
        return None
    mode, mesh = _sharded_axis_mode()
    if mode == "param":
        # Coordinate-wise along P: a pure shard-local map over each
        # device's column block, no collective at all.
        return _param_shard_map(
            lambda o_l, b_l: _candidate_call(
                o_l, b_l, tuple(int(o) for o in offsets), int(trim),
                bool(median), interpret,
            ),
            mesh, n_in=2, reduce_out=False,
        )(own, bcast)
    return _candidate_call(
        own, bcast, tuple(int(o) for o in offsets), int(trim), bool(median),
        interpret,
    )

"""Pallas TPU kernel for Count-Sketch compression.

The sketch is a scatter-add of a sign-flipped [P] vector into S buckets
(reference semantics: murmura/aggregation/sketchguard.py:91-112, host-side
np.bincount).  On TPU, XLA lowers ``segment_sum`` with random indices to a
serialized scatter — the one op in the Sketchguard round that does not
vectorize.  This kernel reformulates it as a chunked one-hot matmul:

    for each chunk c of the parameter axis:
        onehot = (hash[c] == bucket_ids)        # [C, S] built in VMEM
        out   += signed_vals[c] @ onehot        # [1, C] x [C, S] on the MXU

The one-hot never touches HBM and every accumulation is an MXU matmul, so
the sketch runs at matmul throughput instead of scatter throughput.

CPU/debug path: ``interpret=True`` runs the same kernel through the Pallas
interpreter (used by the test suite, which pins JAX to CPU).
"""

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

# Chunk of the parameter axis processed per grid step. 1024 x S(<=2048) f32
# one-hot stays well under the ~16 MB VMEM budget.
_CHUNK = 1024

# Largest supported (padded) sketch width: the [_CHUNK, S] one-hot is the
# dominant VMEM tenant (1024 x 2048 f32 = 8 MB). count_sketch() falls back
# to segment_sum above this.
MAX_SKETCH_PAD = 2048


def _sketch_kernel(vals_ref, hash_ref, out_ref, *, chunk, sketch_pad):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        out_ref[:] = jnp.zeros_like(out_ref)

    h = hash_ref[:].reshape(chunk, 1)  # [C, 1] int32
    buckets = jax.lax.broadcasted_iota(jnp.int32, (chunk, sketch_pad), 1)
    onehot = (h == buckets).astype(jnp.float32)  # [C, S]
    out_ref[:] += jnp.dot(
        vals_ref[:], onehot, preferred_element_type=jnp.float32
    )  # [1, C] @ [C, S]


@functools.partial(jax.jit, static_argnames=("sketch_size", "interpret"))
def count_sketch_pallas(
    vector: jnp.ndarray,
    hash_table: jnp.ndarray,
    sign_table: jnp.ndarray,
    sketch_size: int,
    interpret: bool = False,
) -> jnp.ndarray:
    """Count-Sketch of a [P] vector -> [sketch_size], MXU formulation.

    Matches ``ops.sketch.count_sketch`` (segment_sum) bit-for-bit up to
    float accumulation order.
    """
    p = vector.shape[-1]
    signed = sign_table * vector

    pad_p = (-p) % _CHUNK
    # Padded tail gets bucket id sketch_pad-1 with value 0: no contribution.
    sketch_pad = ((sketch_size + 127) // 128) * 128
    if sketch_pad > MAX_SKETCH_PAD:
        raise ValueError(
            f"sketch_size {sketch_size} exceeds the kernel's VMEM budget "
            f"(padded {sketch_pad} > {MAX_SKETCH_PAD}); use the segment_sum "
            "path (count_sketch with use_pallas=False)"
        )
    if pad_p:
        signed = jnp.pad(signed, (0, pad_p))
        hash_table = jnp.pad(
            hash_table, (0, pad_p), constant_values=sketch_pad - 1
        )

    n_chunks = signed.shape[-1] // _CHUNK
    out = pl.pallas_call(
        functools.partial(
            _sketch_kernel, chunk=_CHUNK, sketch_pad=sketch_pad
        ),
        grid=(n_chunks,),
        in_specs=[
            pl.BlockSpec((1, _CHUNK), lambda i: (0, i)),
            pl.BlockSpec((1, _CHUNK), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, sketch_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((1, sketch_pad), jnp.float32),
        interpret=interpret,
    )(signed.reshape(1, -1), hash_table.reshape(1, -1).astype(jnp.int32))
    return out[0, :sketch_size]

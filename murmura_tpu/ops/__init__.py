"""Core numeric ops: pytree flattening, losses, uncertainty, count-sketch."""

from murmura_tpu.ops.flatten import make_flatteners, model_dimension
from murmura_tpu.ops.losses import (
    evidential_loss,
    masked_cross_entropy,
    uncertainty_metrics,
)
from murmura_tpu.ops.sketch import count_sketch, make_sketch_tables

__all__ = [
    "make_flatteners",
    "model_dimension",
    "masked_cross_entropy",
    "evidential_loss",
    "uncertainty_metrics",
    "count_sketch",
    "make_sketch_tables",
]

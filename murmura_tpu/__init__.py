"""murmura_tpu — TPU-native decentralized federated learning.

A from-scratch JAX/XLA framework with the capabilities of Cloudslab/murmura
(reference: /root/reference/murmura/__init__.py:10-33): YAML-driven
decentralized FL over configurable graph topologies with Byzantine-resilient
aggregation, re-designed TPU-first:

- every per-node quantity carries a leading ``nodes`` axis on stacked pytrees,
- one FL round is a single jitted program (local SGD -> attack -> adjacency-
  masked exchange -> vmapped robust aggregation -> eval),
- the ``tpu`` backend shards the node axis over a ``jax.sharding.Mesh`` so the
  neighbor exchange rides ICI collectives instead of ZeroMQ sockets.
"""

__version__ = "0.1.0"

from murmura_tpu.config import Config, load_config, save_config
from murmura_tpu.topology import Topology, create_topology
from murmura_tpu.topology.dynamic import MobilityModel

__all__ = [
    "Config",
    "load_config",
    "save_config",
    "Topology",
    "create_topology",
    "MobilityModel",
    "__version__",
]

"""The FL round as one jitted program.

The reference executes a round as Python orchestration — per-node
``local_train`` loops (murmura/core/node.py:59-109), a state snapshot, attack
application, per-node aggregation calls, then per-node evaluation
(murmura/core/network.py:80-199).  Here the whole round body is one traced
function over stacked [N, ...] pytrees:

    round_step(params, agg_state, key, adj, compromised, round_idx, data)
        -> (params', agg_state', metrics)

- local training is a ``lax.scan`` over the per-epoch batch schedule with
  per-node effective batch sizes / step counts as masks (reproducing the
  reference's ragged DataLoaders, network.py:278-287);
- compromised nodes skip training via an update mask instead of a Python
  ``if`` (network.py:99-101);
- the attack transforms the *broadcast* tensor only (network.py:108-119);
- aggregation is an adjacency-masked rule over the gathered [N, P] tensor;
- evaluation is a vmapped masked sweep including evidential uncertainty
  (node.py:111-196).

Under ``backend: simulation`` this runs vmapped on one device; under
``backend: tpu`` the same function is jitted with the node axis sharded over
a mesh so the gather rides ICI (see parallel/mesh.py).
"""

import dataclasses
import functools
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from murmura_tpu.aggregation.base import AggContext, AggregatorDef
from murmura_tpu.aggregation.probe import combined_probe_metric, pairwise_probe_eval
from murmura_tpu.attacks.adaptive import AdaptiveAttack, acceptance_feedback
from murmura_tpu.attacks.base import Attack
from murmura_tpu.data.base import FederatedArrays
from murmura_tpu.faults.schedule import FaultSpec
from murmura_tpu.dmtt.protocol import (
    DMTTParams,
    dmtt_round_update,
    init_dmtt_state,
)
from murmura_tpu.models.core import Model
from murmura_tpu.core.pipeline import (
    ADJ_KEY as PIPE_ADJ_KEY,
    BCAST_KEY as PIPE_BCAST_KEY,
    OWN_KEY as PIPE_OWN_KEY,
    VALID_KEY as PIPE_VALID_KEY,
    init_pipeline_state,
    pipeline_state_keys,
)
from murmura_tpu.core.stale import (
    CACHE_KEY as STALE_CACHE_KEY,
    STALE_STATE_KEYS,
    StalenessSpec,
    init_stale_state,
    make_stale_fold,
)
from murmura_tpu.ops.compress import (
    COMPRESS_STATE_KEYS,
    CompressionSpec,
    compress_exchange,
    init_compress_state,
)
from murmura_tpu.ops.flatten import make_flatteners, make_sharded_flatteners
from murmura_tpu.parallel.mesh import constrain_flat, constrain_replicated
from murmura_tpu.ops.losses import (
    evidential_loss,
    masked_cross_entropy,
    uncertainty_metrics,
)


DMTT_STATE_KEYS = (
    "dmtt_c_hat",
    "dmtt_alpha",
    "dmtt_beta",
    "dmtt_collab",
    "dmtt_selected",
)


@dataclass(frozen=True)
class RoundProgram:
    """A compiled round step plus the pieces needed to drive it.

    ``train_step`` is the per-round program (local SGD + attack + exchange +
    aggregation); ``eval_step`` is the full test-set sweep, compiled
    separately so the orchestrator pays for it only on recorded rounds
    (``eval_every``) instead of fusing it into every round the way the
    reference's loop does (murmura/core/network.py:80-94).
    """

    train_step: Callable  # (params, agg_state, key, adj, compromised, round_idx, data)
    eval_step: Callable  # (params, data) -> eval metrics
    init_params: Any  # stacked [N, ...] pytree
    init_agg_state: Dict[str, np.ndarray]
    data_arrays: Dict[str, np.ndarray]
    num_nodes: int
    model_dim: int
    evidential: bool
    # Built with a FaultSpec: train_step takes an extra [N] ``alive`` mask
    # after ``compromised`` (dead nodes freeze via the update mask, NaN
    # sentinel quarantines non-finite updates).  False => the signature and
    # traced program are byte-identical to pre-faults builds.
    faulted: bool = False
    # Traced-scalar hyperparameters lifted from closure constants into
    # ``data_arrays["hp_*"]`` inputs (build_round_program(hp_inputs=...)) so
    # a gang (core/gang.py) can vary them per member under vmap.  () =>
    # the traced program is byte-identical to pre-gang builds.
    hp_inputs: Tuple[str, ...] = ()
    # Sparse exchange mode (topology/sparse.py; docs/SCALING.md): when
    # non-empty, the program's adjacency input is the [k, N] per-offset
    # edge mask of a SparseTopology instead of the dense [N, N] matrix —
    # nothing O(N^2) enters the lowered HLO (MUR600).  () => byte-identical
    # to pre-sparse builds.
    sparse_offsets: Tuple[int, ...] = ()
    # Compressed exchange (ops/compress.py; docs/PERFORMANCE.md): the
    # broadcast tensor is quantized in-jit before the exchange (int8 blocks
    # or top-k delta), receivers dequantize before rule math, and the
    # quantization residual optionally rides ``agg_state`` as error
    # feedback.  None (default) => the traced program is byte-identical to
    # pre-compression builds.
    compression: Optional[CompressionSpec] = None
    # Closed-loop adaptive attack (attacks/adaptive.py;
    # docs/ROBUSTNESS.md): the attack's adaptation state rides
    # ``agg_state`` under ATTACK_STATE_KEYS and each round's acceptance
    # taps update it in-jit.  False (default) => the traced program is
    # byte-identical to pre-adaptive builds.
    adaptive_attack: bool = False
    # Bounded-staleness gossip (core/stale.py; docs/ROBUSTNESS.md
    # "Bounded staleness"): a per-sender payload cache + age stamp ride
    # ``agg_state`` under STALE_STATE_KEYS, and disrupted base-graph
    # edges are re-added with the (discounted) cached payload while its
    # age stays within ``max_staleness``.  None (default) => the traced
    # program is byte-identical to pre-staleness builds.
    staleness: Optional[StalenessSpec] = None
    # Pipelined rounds (core/pipeline.py; docs/PERFORMANCE.md "Pipelined
    # rounds"): round r's local training overlaps round r-1's
    # exchange + aggregation through a double-buffered pipeline stage
    # riding ``agg_state`` under PIPELINE_STATE_KEYS — one-round-delayed
    # averaging (arXiv:2002.01119).  False (default) => the traced
    # program is byte-identical to pre-pipeline builds.
    pipelined: bool = False
    # The training-only stage of the round — the delayed-averaging
    # reference hook (core/pipeline.run_delayed_reference): same
    # signature as ``train_step`` but returns ``(own_flat, train_ok)``,
    # the post-scrub trained [N, P] flat params and the [N] quarantine
    # verdict (1.0 = clean).  A pure sub-computation of ``train_step``
    # (jit DCEs the attack/codec/exchange stages), present on every
    # build.
    train_flat: Optional[Callable] = None
    # Param-axis sharding (parallel/mesh.py, docs/PERFORMANCE.md
    # "Param-axis sharding"): the flat vector is zero-padded so this
    # shard count divides its width, and on a ("seed", "nodes", "param")
    # mesh every [N, flat_dim] tensor — broadcast, stale cache, pipeline
    # buffers, EF residual/top-k reference, the aggregation output —
    # shards its columns over the param axis.  1 (default) => flat_dim ==
    # model_dim and the traced program is byte-identical to pre-sharding
    # builds (MUR1302).
    param_shards: int = 1
    # Padded flat width (== model_dim unless param_shards pads it).
    flat_dim: int = 0

    @property
    def sparse(self) -> bool:
        return bool(self.sparse_offsets)

    @property
    def stale(self) -> bool:
        return self.staleness is not None


def _broadcast_to_leaf(mask: jnp.ndarray, leaf: jnp.ndarray) -> jnp.ndarray:
    return mask.reshape(mask.shape + (1,) * (leaf.ndim - 1))


def build_round_program(
    model: Model,
    agg: AggregatorDef,
    data: FederatedArrays,
    *,
    local_epochs: int = 1,
    batch_size: int = 64,
    lr: float = 0.01,
    total_rounds: int = 20,
    attack: Optional[Attack] = None,
    seed: int = 42,
    probe_size: Optional[int] = None,
    annealing_rounds: Optional[int] = None,
    lambda_weight: float = 0.1,
    eval_chunk: int = 1024,
    dmtt: Optional[DMTTParams] = None,
    param_dtype: Optional[str] = None,
    node_axis_sharded: bool = False,
    faults: Optional[FaultSpec] = None,
    audit_taps: bool = False,
    hp_inputs: Tuple[str, ...] = (),
    sparse_offsets: Optional[Tuple[int, ...]] = None,
    compression: Optional[CompressionSpec] = None,
    staleness: Optional[StalenessSpec] = None,
    pipeline: bool = False,
    param_shards: int = 1,
) -> RoundProgram:
    """Trace-ready round step for a network of ``data.num_nodes`` nodes.

    Args:
        probe_size: samples per node handed to probe-based aggregators
            (UBAR's one batch — ubar.py:169; evidential trust's
            max_eval_samples — evidential_trust.py:62-63).
        annealing_rounds: evidential-loss KL annealing horizon (reference
            wiring: rounds // 2, factories.py:114).
        dmtt: when set, the trust protocol runs inside the round step —
            TOPO_CLAIM verification, Beta trust, TopB collaborator selection
            gate the exchange mask handed to the aggregator
            (murmura/dmtt/node_process.py:150-250).
        faults: when set, the round step takes an extra per-round ``alive``
            mask (after ``compromised``) and gains the operational-fault
            semantics (docs/ROBUSTNESS.md): dead nodes freeze params via
            the update mask exactly like compromised ones; an in-jit
            numerical sentinel quarantines nodes whose post-training
            update is non-finite (masked out of the exchange, params
            rolled back to the pre-round value); a node with zero alive
            neighbors degrades to self-model.  ``None`` (default) leaves
            the traced program byte-identical to pre-faults builds.
        audit_taps: telemetry.audit_taps — aggregation rules surface
            per-node decision tensors (``tap_*`` stats) and the fault
            sentinel emits per-node quarantine/scrub/alive flags, all
            riding the normal history-output path as ``agg_tap_*``
            metrics.  Taps are collective- and recompile-clean by
            contract (``murmura check --ir`` MUR400/MUR402); False
            (default) leaves the traced program byte-identical.
        hp_inputs: scalar hyperparameters to lift from trace-time closure
            constants into round-program *inputs* riding ``data_arrays``
            (gang-batched execution, core/gang.py — a vmapped gang member
            gets its own value from the [S]-leading stacked entry):
            ``"lr"`` => the SGD step reads ``d["hp_lr"]``;
            ``"attack_scale"`` => the attack's broadcast perturbation is
            scaled by ``d["hp_attack_scale"]``
            (``own + scale * (attacked - own)``; requires an attack).
            () (default) leaves the traced program byte-identical.
    """
    n = data.num_nodes
    num_classes = data.num_classes or model.num_classes
    evidential = model.evidential

    # Param-axis sharding (tpu.param_shards; docs/PERFORMANCE.md
    # "Param-axis sharding"): the flat vector pads to a multiple of the
    # shard count and every [N, P]-shaped tensor of the round shards its
    # columns over the mesh's "param" axis.  Mode rejections are loud and
    # config-time, like every other exchange-mode combination above.
    param_shards = int(param_shards)
    if param_shards < 1:
        raise ValueError(f"param_shards must be >= 1, got {param_shards}")
    if param_shards > 1:
        if dmtt is not None:
            raise ValueError(
                "param-axis sharding does not compose with DMTT (the "
                "N x N claim cross-evaluation unravels every broadcast "
                "row into a full model per pair — there is no sharded "
                "formulation of that sweep)"
            )
        if compression is not None and compression.algorithm == "topk":
            raise ValueError(
                "param-axis sharding does not compose with topk "
                "compression: the per-row global top-k needs the full "
                "[P] row resident on one device, defeating the shard — "
                "use the int8 codec (its per-block scales shard with P)"
            )

    # Sparse exchange mode: the adjacency input is the [k, N] per-offset
    # edge mask of a SparseTopology (edge i <- (i + o) % N active), never a
    # dense [N, N] matrix.  Every adjacency manipulation below then runs in
    # edge-mask space via rolls of [N] node flags (which lower to boundary
    # ppermutes on a sharded node axis, like the circulant rules' rolls).
    sparse_offsets = (
        tuple(int(o) for o in sparse_offsets) if sparse_offsets else ()
    )
    sparse = bool(sparse_offsets)
    if sparse and dmtt is not None:
        raise ValueError(
            "sparse exchange mode does not compose with DMTT (claim "
            "verification needs the dense per-round exchange graph)"
        )
    if compression is not None and dmtt is not None:
        raise ValueError(
            "compressed exchange does not compose with DMTT (the claim "
            "cross-evaluation consumes the uncompressed broadcast — a "
            "compressed probe sweep would verify against different models "
            "than the rules aggregate)"
        )

    # Bounded-staleness gossip (core/stale.py): the exchange layer that
    # serves a disrupted sender's last delivered payload (age-bounded,
    # optionally discount-weighted) instead of dropping its edges.
    if staleness is not None:
        if faults is None:
            raise ValueError(
                "bounded staleness (exchange.max_staleness) requires the "
                "fault model (build_round_program(faults=...)): without "
                "a fault schedule nothing ever misses a round and the "
                "cache layer would be dead weight in every program"
            )
        if dmtt is not None:
            raise ValueError(
                "bounded staleness does not compose with DMTT (the "
                "exchange graph is trust-gated per round; serving a "
                "cached row would bypass the round's claim verification)"
            )
        if staleness.base_mask is None:
            raise ValueError(
                "StalenessSpec.base_mask must carry the static base "
                "exchange graph (the topology mask / all-active sparse "
                "edge mask) — re-added edges are drawn from it"
            )
        expect = (
            (len(sparse_offsets or ()), n) if sparse_offsets else (n, n)
        )
        if tuple(np.shape(staleness.base_mask)) != expect:
            raise ValueError(
                f"staleness base mask shape "
                f"{tuple(np.shape(staleness.base_mask))} does not match "
                f"this build's exchange layout {expect}"
            )
    # Closed-loop adaptive attack (attacks/adaptive.py): the attacker's
    # adaptation state rides agg_state (ATTACK_STATE_KEYS) and the audit
    # taps ARE its feedback channel, so tapping is forced on — taps are
    # collective- and recompile-inert by contract (MUR400/402), so this
    # changes metrics surface, never communication.  attack=None or a
    # static attack leaves every adaptive branch below untaken: the
    # traced program is byte-identical to pre-adaptive builds.
    adaptive = isinstance(attack, AdaptiveAttack)
    if adaptive:
        if dmtt is not None:
            raise ValueError(
                "adaptive attacks do not compose with DMTT (the claims "
                "channel is a second feedback path the adaptation state "
                "does not model)"
            )
        audit_taps = True

    # Pipelined rounds (core/pipeline.py): round r's delayed aggregation
    # of the buffered round-(r-1) exchange overlaps round r's training.
    if pipeline:
        if dmtt is not None:
            raise ValueError(
                "pipelined rounds do not compose with DMTT (the claim "
                "exchange + trust gate runs between production and "
                "aggregation every round; delaying the aggregation would "
                "verify claims against a different round's graph)"
            )
        if adaptive:
            raise ValueError(
                "pipelined rounds do not compose with adaptive attacks: "
                "the acceptance feedback would observe round r-1's "
                "aggregation while the attack state already advanced at "
                "round r's production, changing the closed loop's timing "
                "semantics — run adaptive experiments serialized"
            )

    # Built after the adaptive block so the fold's audit taps follow the
    # final audit_taps value (adaptive attacks force tapping on).
    if staleness is not None:
        stale_fold = make_stale_fold(
            staleness, sparse_offsets=tuple(sparse_offsets or ()),
            audit=audit_taps,
        )
    else:
        stale_fold = None

    def _sender_view(vec):  # murmura: traced
        """[k, N] sender-side view of a [N] node flag: row j holds
        vec[(i + offsets[j]) % N] at column i."""
        return jnp.stack([jnp.roll(vec, -o) for o in sparse_offsets])

    def _edges_mask_both(adj, vec):  # murmura: traced
        """Drop edges whose receiver OR sender has flag 0."""
        if sparse:
            return adj * vec[None, :] * _sender_view(vec)
        return adj * vec[:, None] * vec[None, :]

    def _edges_mask_sender(adj, vec):  # murmura: traced
        """Drop edges whose sender has flag 0."""
        if sparse:
            return adj * _sender_view(vec)
        return adj * vec[None, :]

    def _in_degree(adj):  # murmura: traced
        return adj.sum(axis=0) if sparse else adj.sum(axis=1)

    hp_inputs = tuple(hp_inputs)
    unknown_hp = set(hp_inputs) - {"lr", "attack_scale"}
    if unknown_hp:
        raise ValueError(f"unknown hp_inputs: {sorted(unknown_hp)}")
    if "attack_scale" in hp_inputs and attack is None:
        raise ValueError(
            "hp_inputs includes 'attack_scale' but no attack is configured "
            "— there is no broadcast perturbation to scale"
        )

    # ---- static per-node batch schedule (network.py:278-287) -------------
    eff_batch = data.effective_batch(batch_size)  # [N]
    steps = data.steps_per_epoch(batch_size)  # [N]
    max_steps = int(steps.max())
    global_batch = int(eff_batch.max())

    if annealing_rounds is None:
        annealing_rounds = max(1, total_rounds // 2)

    # ---- initial stacked params ------------------------------------------
    init_keys = jax.random.split(jax.random.PRNGKey(seed), n)
    init_params = jax.vmap(model.init)(init_keys)
    if param_dtype not in (None, "float32"):
        # tpu.param_dtype=bfloat16: store the stacked [N, ...] state (and
        # therefore the gathered/exchanged [N, P] tensor) in bf16 — halves
        # resident HBM and ICI bytes at the cost of parameter precision.
        # compute_dtype independently controls matmul input precision.
        dt = jnp.dtype(param_dtype)
        init_params = jax.tree_util.tree_map(
            lambda l: l.astype(dt), init_params
        )
    template = jax.tree_util.tree_map(lambda l: l[0], init_params)
    if param_shards > 1:
        ravel, unravel, model_dim, flat_dim = make_sharded_flatteners(
            template, param_shards
        )
    else:
        ravel, unravel, model_dim = make_flatteners(template)
        flat_dim = model_dim
    if param_shards > 1 and compression is not None:
        # int8 per-block scales must shard WITH the payload: a quant block
        # straddling a shard boundary would compute its scale from two
        # shards' columns (a silent cross-shard amax collective every
        # round) — reject at config time, loudly.
        local = flat_dim // param_shards
        if local % compression.block:
            raise ValueError(
                f"compression.block={compression.block} does not divide "
                f"the shard-local flat width {local} (flat_dim "
                f"{flat_dim} over {param_shards} param shards) — a quant "
                "block straddling a shard boundary would compute its "
                "scale across shards; pick a block dividing "
                f"{local} (or adjust tpu.param_shards)"
            )

    # ---- probe batches for loss/trust-probe rules ------------------------
    p_size = int(min(data.max_samples, probe_size or global_batch))
    probe_x = data.x[:, :p_size]
    probe_y = data.y[:, :p_size]
    probe_mask = data.mask[:, :p_size]

    eval_x, eval_y, eval_mask = data.eval_arrays

    data_arrays = {
        "x": data.x,
        "y": data.y,
        "mask": data.mask,
        "num_samples": data.num_samples.astype(np.int32),
        "eff_batch": eff_batch,
        "steps": steps,
        "probe_x": probe_x,
        "probe_y": probe_y,
        "probe_mask": probe_mask,
        "eval_x": eval_x,
        "eval_y": eval_y,
        "eval_mask": eval_mask,
    }
    # Lifted scalar hyperparameters ride the data dict (one input pytree to
    # thread, one sharding rule: rank-0 leaves replicate).  The defaults
    # reproduce the closure-constant behavior exactly — x * 1.0 and a
    # traced scalar holding the same f32 value multiply bit-identically.
    if "lr" in hp_inputs:
        data_arrays["hp_lr"] = np.asarray(lr, np.float32)
    if "attack_scale" in hp_inputs:
        data_arrays["hp_attack_scale"] = np.asarray(1.0, np.float32)

    # ---- per-node loss ----------------------------------------------------
    def node_loss(params_i, xb, yb, mb, key, round_idx):  # murmura: traced
        outputs = model.apply(params_i, xb, key, True)
        if evidential:
            lambda_t = (
                jnp.minimum(1.0, round_idx / max(1, annealing_rounds)) * lambda_weight
            )
            return evidential_loss(outputs, yb, mb, num_classes, lambda_t)
        loss, _ = masked_cross_entropy(outputs, yb, mb)
        return loss

    grad_fn = jax.grad(node_loss)

    def local_training(params, d, honest, key, round_idx):  # murmura: traced
        """local_epochs x masked-batch SGD (reference: node.py:59-109)."""

        def epoch_body(params, epoch_key):
            perm_key, step_key = jax.random.split(epoch_key)
            # Shuffle valid samples to the front: invalid slots sort last.
            # The draw is pinned replicated under a param-sharded mesh
            # (identity otherwise): the legacy threefry lowering is
            # sharding-dependent, and an output partitioned over "param"
            # would shuffle DIFFERENT batches than the unsharded program
            # (parallel/mesh.constrain_replicated).
            u = constrain_replicated(
                jax.random.uniform(perm_key, d["mask"].shape)
            ) + (1.0 - d["mask"]) * 10.0
            perm = jnp.argsort(u, axis=1)  # [N, S]

            def step_body(params, t):
                j = jnp.arange(global_batch)
                pos = t * d["eff_batch"][:, None] + j[None, :]
                pos = pos % jnp.maximum(d["num_samples"], 1)[:, None]
                idx = jnp.take_along_axis(perm, pos, axis=1)  # [N, B]
                xb = jax.vmap(lambda xs, ii: xs[ii])(d["x"], idx)
                yb = jax.vmap(lambda ys, ii: ys[ii])(d["y"], idx)
                batch_mask = (j[None, :] < d["eff_batch"][:, None]).astype(jnp.float32)

                node_keys = jax.random.split(jax.random.fold_in(step_key, t), n)
                grads = jax.vmap(grad_fn, in_axes=(0, 0, 0, 0, 0, None))(
                    params, xb, yb, batch_mask, node_keys, round_idx
                )
                update = honest * (t < d["steps"]).astype(jnp.float32)  # [N]
                # lr is a closure constant unless lifted to an input
                # (hp_inputs — gang members vary it per member under vmap).
                eff_lr = d["hp_lr"] if "lr" in hp_inputs else lr
                # Update math in float32, cast back: keeps bf16 params
                # (tpu.param_dtype) dtype-stable through the scan carry and
                # rounds once per step instead of per multiply.
                new_params = jax.tree_util.tree_map(
                    lambda p, g: (
                        p - eff_lr * _broadcast_to_leaf(update, p) * g.astype(jnp.float32)
                    ).astype(p.dtype),
                    params,
                    grads,
                )
                return new_params, None

            params, _ = jax.lax.scan(step_body, params, jnp.arange(max_steps))
            return params, None

        epoch_keys = jax.random.split(key, local_epochs)
        params, _ = jax.lax.scan(epoch_body, params, epoch_keys)
        return params

    # ---- evaluation (node.py:111-196) ------------------------------------
    def evaluate(params, x, y, mask):  # murmura: traced
        s = x.shape[1]
        chunk = min(eval_chunk, s)
        n_chunks = -(-s // chunk)
        pad = n_chunks * chunk - s
        if pad:
            x = jnp.pad(x, [(0, 0), (0, pad)] + [(0, 0)] * (x.ndim - 2))
            y = jnp.pad(y, [(0, 0), (0, pad)])
            mask = jnp.pad(mask, [(0, 0), (0, pad)])

        def eval_node(params_i, x_i, y_i, m_i):
            def chunk_body(carry, sl):
                xc = jax.lax.dynamic_slice_in_dim(x_i, sl * chunk, chunk, 0)
                yc = jax.lax.dynamic_slice_in_dim(y_i, sl * chunk, chunk, 0)
                mc = jax.lax.dynamic_slice_in_dim(m_i, sl * chunk, chunk, 0)
                outputs = model.apply(params_i, xc, None, False)
                cnt = mc.sum()
                if evidential:
                    unc = uncertainty_metrics(outputs)
                    probs = unc["probs"]
                    nll = -jnp.log(
                        jnp.take_along_axis(probs, yc[:, None], axis=-1)[:, 0] + 1e-10
                    )
                    row = {
                        "loss": (nll * mc).sum(),
                        "correct": (
                            (jnp.argmax(outputs, -1) == yc).astype(jnp.float32) * mc
                        ).sum(),
                        "vacuity": (unc["vacuity"] * mc).sum(),
                        "entropy": (unc["entropy"] * mc).sum(),
                        "strength": (unc["strength"] * mc).sum(),
                        "count": cnt,
                    }
                else:
                    logp = jax.nn.log_softmax(outputs, -1)
                    nll = -jnp.take_along_axis(logp, yc[:, None], axis=-1)[:, 0]
                    row = {
                        "loss": (nll * mc).sum(),
                        "correct": (
                            (jnp.argmax(outputs, -1) == yc).astype(jnp.float32) * mc
                        ).sum(),
                        "count": cnt,
                    }
                return carry, row

            _, rows = jax.lax.scan(chunk_body, 0, jnp.arange(n_chunks))
            total = jnp.maximum(rows["count"].sum(), 1.0)
            out = {k: v.sum() / total for k, v in rows.items() if k != "count"}
            out["accuracy"] = out.pop("correct")
            return out

        return jax.vmap(eval_node)(params, x, y, mask)

    # ---- the round --------------------------------------------------------
    ctx = AggContext(
        apply_fn=model.apply,
        unravel=unravel,
        evidential=evidential,
        num_classes=num_classes,
        total_rounds=total_rounds,
        node_axis_sharded=node_axis_sharded,
        audit=audit_taps,
    )

    attack_apply = attack.apply if attack is not None else None
    claims_fn = attack.claims_fn if attack is not None else None

    if faults is not None and faults.nan_inject_nodes:
        _inject_rows = np.zeros(n, dtype=np.float32)
        _inject_rows[list(faults.nan_inject_nodes)] = 1.0
    else:
        _inject_rows = None

    # Whether rules with quantized exchange kernels receive the Int8Blocks
    # payload itself.  Both the stale fold and the pipeline buffer carry
    # ONE decoded [N, P] row per sender (a fresh/stale row mix — or a
    # buffered one — cannot be expressed inside one Int8Blocks payload),
    # so either layer forces the receiver-side dequantized path: wire
    # bytes are unchanged (the codec still runs, EF still telescopes) but
    # the MUR700 s8-collective property is a stale-off AND pipeline-off
    # contract (docs/PERFORMANCE.md).
    quantized_payload = (
        agg.quantized_exchange and stale_fold is None and not pipeline
    )

    def _produce_exchange(params, agg_state, key, adj, compromised, alive, round_idx, d):  # murmura: traced
        """Steps 1-2d of the round: local training, the broadcast with
        attack + sentinel scrubs, the codec, and the stale fold — the
        *production* of one round's exchange, shared verbatim by the
        serialized and pipelined bodies (and, via ``train_flat``, the
        delayed-averaging reference) so the three cannot drift.

        Returns a dict with the trained ``params`` pytree, the
        post-scrub ``own_flat``/``bcast``/``adj`` triple exactly as the
        serialized aggregation would consume it, the quarantine
        bookkeeping (``pre_flat``/``finite``), the updated ``agg_state``
        (codec/stale keys), the per-stage stats dicts, and the adaptive
        attack's consumed state.
        """
        train_key, attack_key = jax.random.split(key)
        honest = 1.0 - compromised

        # 1. local training (compromised nodes frozen — network.py:99-101 —
        # except under data-poisoning attacks, whose compromised nodes
        # must train on their poisoned shards; Attack.trains_locally)
        if attack is not None and attack.trains_locally:
            train_mask = jnp.ones_like(honest)
        else:
            train_mask = honest
        if alive is not None:
            # Dead nodes freeze via the update mask, exactly like
            # compromised ones; pre-round snapshot for quarantine rollback
            # and the dead-node param freeze below.  The adjacency is
            # re-masked by alive IN-JIT even though the orchestrator's
            # masked_adjacency already folds it host-side (idempotent:
            # alive*alive == alive) — the program must not depend on a
            # two-sources-of-truth contract between its adj and alive
            # inputs to keep dead nodes out of the exchange.  (Sparse
            # exchange mode runs the same fold in [k, N] edge-mask space.)
            adj = _edges_mask_both(adj, alive)
            train_mask = train_mask * alive
            pre_flat = constrain_flat(jax.vmap(ravel)(params))
        # named_scope brackets label the `# murmura: traced` phases in
        # profiler traces (xprof/perfetto op names) — metadata only, the
        # lowered program is identical (the telemetry-off byte-identity
        # contract, tests/test_telemetry.py).
        with jax.named_scope("murmura.train"):
            params = local_training(params, d, train_mask, train_key, round_idx)

        # 2. snapshot + attack on outgoing states (network.py:105-119).
        # constrain_flat pins the [N, P] tensors to ("nodes", "param")
        # when a param-sharded mesh scope is active (parallel/mesh.py) —
        # identity otherwise, so unsharded programs are byte-identical.
        own_flat = constrain_flat(jax.vmap(ravel)(params))
        fault_stats = {}
        if _inject_rows is not None:
            # Deterministic divergence injection (chaos testing): scheduled
            # nodes emit a NaN update from the configured round on.
            inject = _inject_rows * (
                round_idx >= faults.nan_inject_from_round
            ).astype(jnp.float32)
            own_flat = jnp.where(
                inject[:, None] > 0, jnp.full_like(own_flat, jnp.nan), own_flat
            )
        if faults is not None and faults.nan_quarantine:
            # Numerical sentinel: a non-finite update quarantines the node
            # for the round.  Its row is REPLACED (not just masked) before
            # any rule math — masked aggregation alone cannot contain a
            # NaN row because 0 * nan == nan in every Gram/matmul path —
            # and its exchange edges are zeroed both ways.  The
            # where-style replacement here (and the attack-scrub stage
            # below) is a STATIC contract: `murmura check --flow` MUR803
            # interval-analyzes this faulted round program with
            # divergence-capable seeds and fails if non-finiteness can
            # reach the output params — switching either scrub back to a
            # multiplicative mask fails the check, not just the runtime.
            finite = jnp.isfinite(own_flat).all(axis=1)
            alive_f = alive if alive is not None else jnp.ones_like(compromised)
            fault_stats["quarantined"] = (
                (1.0 - finite.astype(jnp.float32)) * alive_f
            ).sum()
            if audit_taps:
                # Per-node quarantine flags (telemetry.audit_taps): WHICH
                # node diverged, not just how many — elementwise over
                # node-local rows, so no collectives are added (MUR400).
                fault_stats["tap_quarantined"] = (
                    1.0 - finite.astype(jnp.float32)
                ) * alive_f
            own_flat = jnp.where(finite[:, None], own_flat, pre_flat)
            fin = finite.astype(adj.dtype)
            adj = _edges_mask_both(adj, fin)
        else:
            finite = None
        bcast_finite = None
        attack_state = None
        if attack_apply is not None:
            # Cast back: float32 attack noise must not promote the exchanged
            # [N, P] tensor when params are stored bfloat16 (tpu.param_dtype).
            with jax.named_scope("murmura.exchange"):
                if adaptive:
                    # Closed-loop attack: last round's adaptation state
                    # (carried in agg_state under ATTACK_STATE_KEYS — the
                    # feedback update below writes the next round's) sets
                    # this round's strength per compromised row.
                    attack_state = {
                        k: agg_state[k] for k in attack.state_keys
                    }
                    bcast = attack.apply_adaptive(
                        own_flat, compromised, attack_key, round_idx,
                        attack_state,
                    ).astype(own_flat.dtype)
                else:
                    bcast = attack_apply(
                        own_flat, compromised, attack_key, round_idx
                    ).astype(own_flat.dtype)
            if "attack_scale" in hp_inputs:
                # Per-member attack intensity (gang sweeps): scale the
                # perturbation the attack added to the broadcast.  For
                # additive attacks (gaussian/directed/alie/ipm noise or
                # deviation terms) this is the attack's own magnitude
                # knob; scale 0 turns the member's attack off.  Placed
                # BEFORE the sentinel scrub so an amplified-to-inf
                # perturbation is still contained.
                scale = d["hp_attack_scale"].astype(jnp.float32)
                bcast = (
                    own_flat.astype(jnp.float32)
                    + scale * (bcast - own_flat).astype(jnp.float32)
                ).astype(own_flat.dtype)
            if finite is not None:
                # Second sentinel stage: the pre-training check cannot see
                # an ATTACK that overflows to inf/NaN (huge noise_std,
                # crafted states).  Mask such broadcast rows out of
                # everyone's exchange and replace them with the sender's
                # (already-scrubbed) own state so no rule math sees a
                # non-finite row.  No rollback: the sender's own params
                # are untouched by its broadcast.  Counted separately from
                # `quarantined` (which implies a rollback) so the
                # containment is visible in history, not silent.
                bfin = jnp.isfinite(bcast).all(axis=1)
                bcast_finite = bfin
                bcast = jnp.where(bfin[:, None], bcast, own_flat)
                adj = _edges_mask_sender(adj, bfin.astype(adj.dtype))
                fault_stats["attack_scrubbed"] = (
                    1.0 - bfin.astype(jnp.float32)
                ).sum()
                if audit_taps:
                    fault_stats["tap_attack_scrubbed"] = 1.0 - bfin.astype(
                        jnp.float32
                    )
        else:
            bcast = own_flat

        # 2c. compressed exchange (ops/compress.py; docs/PERFORMANCE.md):
        # the outgoing broadcast — post-attack, post-sentinel, so the codec
        # only ever sees finite values — is quantized in-jit; the rule
        # receives either the int8 payload (rules whose exchange kernels
        # move compressed data, AggregatorDef.quantized_exchange) or the
        # receiver-side dequantized tensor.  Error-feedback residual and
        # the top-k reference estimate ride ``agg_state`` (same shapes and
        # dtypes every round: donation-clean, recompile-free — MUR701/702).
        compress_stats = {}
        if compression is not None:
            with jax.named_scope("murmura.compress"):
                # With staleness (or the pipeline buffer) armed the rule
                # consumes the receiver-side dequantized tensor even for
                # quantized_exchange rules — see the quantized_payload
                # comment above.
                bcast, _decoded, comp_updates, compress_stats = (
                    compress_exchange(
                        compression, bcast, agg_state, quantized_payload,
                    )
                )
            agg_state = {**agg_state, **comp_updates}

        # 2d. bounded-staleness fold (core/stale.py; docs/ROBUSTNESS.md):
        # between scrub and aggregation, disrupted senders' base-graph
        # edges are re-added with the cached payload while its age stays
        # within the bound.  scrub_ok taint-kills a caught row's cached
        # copy for the round (MUR1103) — quarantine and attack-scrub
        # apply to cached rows exactly as to fresh ones.
        stale_stats = {}
        if stale_fold is not None:
            with jax.named_scope("murmura.stale"):
                scrub_ok = jnp.ones_like(compromised)
                if finite is not None:
                    scrub_ok = scrub_ok * finite.astype(jnp.float32)
                if bcast_finite is not None:
                    scrub_ok = scrub_ok * bcast_finite.astype(jnp.float32)
                # Receiver eligibility mirrors the fresh-exchange folds:
                # dead receivers (alive) and quarantined ones (finite —
                # _edges_mask_both zeroed their edges BOTH ways) get no
                # re-added stale in-edges.  bcast_finite does NOT gate
                # the receiver side: an attack-scrubbed sender still
                # aggregates normally (_edges_mask_sender).
                recv_ok = (
                    alive if alive is not None
                    else jnp.ones_like(compromised)
                )
                if finite is not None:
                    recv_ok = recv_ok * finite.astype(jnp.float32)
                bcast, adj, stale_updates, stale_stats = stale_fold(
                    bcast, adj,
                    {k: agg_state[k] for k in STALE_STATE_KEYS},
                    recv_ok, scrub_ok,
                )
            agg_state = {**agg_state, **stale_updates}

        return {
            "params": params,
            "own_flat": own_flat,
            "bcast": constrain_flat(bcast),
            "adj": adj,
            "pre_flat": pre_flat if alive is not None else None,
            "finite": finite,
            "agg_state": agg_state,
            "attack_state": attack_state,
            "fault_stats": fault_stats,
            "compress_stats": compress_stats,
            "stale_stats": stale_stats,
        }

    def _step_ctx(d) -> AggContext:  # murmura: traced
        return AggContext(
            apply_fn=ctx.apply_fn,
            unravel=ctx.unravel,
            probe_x=d["probe_x"],
            probe_y=d["probe_y"],
            probe_mask=d["probe_mask"],
            evidential=ctx.evidential,
            num_classes=ctx.num_classes,
            total_rounds=ctx.total_rounds,
            node_axis_sharded=ctx.node_axis_sharded,
            audit=ctx.audit,
        )

    def _round_body(params, agg_state, key, adj, compromised, alive, round_idx, d):  # murmura: traced
        prod = _produce_exchange(
            params, agg_state, key, adj, compromised, alive, round_idx, d
        )
        params = prod["params"]
        own_flat = prod["own_flat"]
        bcast = prod["bcast"]
        adj = prod["adj"]
        pre_flat = prod["pre_flat"]
        finite = prod["finite"]
        agg_state = prod["agg_state"]
        attack_state = prod["attack_state"]
        fault_stats = prod["fault_stats"]
        compress_stats = prod["compress_stats"]
        stale_stats = prod["stale_stats"]

        step_ctx = _step_ctx(d)

        # 2b. DMTT: claim exchange + trust update gate the exchange mask
        # (murmura/dmtt/node_process.py:187-241).  The N x N probe cross-eval
        # is computed once here and shared with probe-based aggregation rules
        # via ctx.probe_cross.
        dmtt_stats = {}
        if dmtt is not None:
            if claims_fn is not None:
                claims = claims_fn(adj, compromised)
            else:
                claims = adj
            cross = pairwise_probe_eval(
                bcast, step_ctx, combined_probe_metric(evidential)
            )
            exchange, dmtt_state, dmtt_stats = dmtt_round_update(
                {k: agg_state[k] for k in DMTT_STATE_KEYS},
                adj,
                claims,
                cross["accuracy"],
                cross["vacuity"],
                dmtt,
            )
            agg_state = {**agg_state, **dmtt_state}
            adj = exchange
            step_ctx = dataclasses.replace(step_ctx, probe_cross=cross)

        # 3. adjacency-masked aggregation (network.py:121-139)
        reserved = set(DMTT_STATE_KEYS) | set(COMPRESS_STATE_KEYS)
        if stale_fold is not None:
            reserved |= set(STALE_STATE_KEYS)
        if adaptive:
            reserved |= set(attack.state_keys)
        rule_state = {
            k: v for k, v in agg_state.items() if k not in reserved
        }
        with jax.named_scope("murmura.aggregate"):
            new_flat, rule_state, agg_stats = agg.aggregate(
                own_flat, bcast, adj, round_idx, rule_state, step_ctx
            )
        new_flat = constrain_flat(new_flat)
        agg_state = {**agg_state, **rule_state}

        # 3b. adaptive-attack feedback (attacks/adaptive.py): the attacker
        # reads the acceptance taps the rule just emitted for its own rows
        # (scrub/quarantine flags fold in as rejections; dead rows are not
        # observations) and writes the next round's strength back into its
        # ATTACK_STATE_KEYS slice of agg_state.  Everything is elementwise
        # over node-local rows — the feedback path adds no collectives and
        # no recompiles (MUR1001/1002, analysis/adaptive.py).
        attack_round_stats = {}
        if adaptive:
            accept, observed = acceptance_feedback(
                agg_stats, fault_stats, _in_degree(adj), alive
            )
            attack_state = attack.update_attack_state(
                attack_state, accept, observed, compromised
            )
            agg_state = {**agg_state, **attack_state}
            attack_round_stats = dict(
                attack.strength_stats(attack_state, compromised)
            )
            attack_round_stats["atk_accept"] = accept * compromised

        if alive is not None:
            # Zero alive neighbors (everyone crashed/dropped/straggled)
            # degrades to self-model — some rules divide by degree and
            # jnp.where cleanly discards whatever they produced there.
            deg = _in_degree(adj)
            new_flat = jnp.where((deg > 0)[:, None], new_flat, own_flat)
            # Dead nodes' params freeze at the pre-round value (their
            # process is gone; nothing may advance) and quarantined nodes
            # roll back their divergent local step.
            keep = alive > 0
            if finite is not None:
                keep = keep & finite
            new_flat = jnp.where(keep[:, None], new_flat, pre_flat)
            fault_stats["alive"] = alive.sum()
            if audit_taps:
                fault_stats["tap_alive"] = alive
        params = jax.vmap(unravel)(new_flat)

        metrics = {f"agg_{k}": v for k, v in agg_stats.items()}
        metrics.update({f"agg_{k}": v for k, v in dmtt_stats.items()})
        metrics.update({f"agg_{k}": v for k, v in fault_stats.items()})
        metrics.update({f"agg_{k}": v for k, v in compress_stats.items()})
        metrics.update({f"agg_{k}": v for k, v in stale_stats.items()})
        metrics.update({f"agg_{k}": v for k, v in attack_round_stats.items()})
        return params, agg_state, metrics

    # Reserved agg_state keys a pipelined aggregation must never hand to
    # the rule (the serialized body's ``reserved`` plus the pipeline's
    # own buffer keys; dmtt/adaptive were rejected above).
    pipe_keys = pipeline_state_keys(stale=staleness is not None)
    pipe_reserved = (
        set(COMPRESS_STATE_KEYS) | set(pipe_keys)
    )
    if stale_fold is not None:
        pipe_reserved |= set(STALE_STATE_KEYS)

    def _round_body_pipelined(params, agg_state, key, adj, compromised, alive, round_idx, d):  # murmura: traced
        """One pipelined round (core/pipeline.py; docs/PERFORMANCE.md
        "Pipelined rounds"): stage A aggregates the BUFFERED round-(r-1)
        exchange, stage B produces round r's exchange (training included)
        with no data dependence on stage A, and stage C applies the
        delayed displacement and swaps the buffer.  Stage A is issued
        first so its collectives on the buffered tensor precede the
        training scan in program order — XLA's async dispatch can overlap
        them with the training matmuls (the tentpole's point)."""
        # ---- stage A: delayed aggregation of the buffered exchange ----
        valid = agg_state[PIPE_VALID_KEY]
        buf_own = agg_state[PIPE_OWN_KEY]
        if stale_fold is not None:
            # Buffer reuse (core/stale.py): after round r-1 the stale
            # fold's payload cache holds exactly the post-fold broadcast
            # the delayed aggregation must consume — read it instead of
            # carrying a duplicate [N, P] buffer.  Read BEFORE stage B
            # advances the cache to round r's payload.
            buf_bcast = agg_state[STALE_CACHE_KEY].astype(buf_own.dtype)
        else:
            buf_bcast = agg_state[PIPE_BCAST_KEY]
        buf_adj = agg_state[PIPE_ADJ_KEY]
        if sparse:
            # Stored node-leading [N, k] for mesh placement
            # (init_pipeline_state); the rules consume [k, N].
            buf_adj = buf_adj.T
        rule_state = {
            k: v for k, v in agg_state.items() if k not in pipe_reserved
        }
        step_ctx = _step_ctx(d)
        with jax.named_scope("murmura.aggregate"):
            # The buffered exchange belongs to round r-1; rules with
            # round schedules (BALANCE tightening, trust annealing) see
            # the round the payload was produced in.  Round 0's buffer
            # is the invalid placeholder — clamped index, output and
            # rule-state update all where-discarded below.
            agg_ridx = jnp.maximum(round_idx - 1.0, 0.0)
            agg_out, rule_state_new, agg_stats = agg.aggregate(
                buf_own, buf_bcast, buf_adj, agg_ridx, rule_state, step_ctx
            )
            agg_out = constrain_flat(agg_out)
        if alive is not None:
            # The serialized zero-alive-neighbor guard, applied at the
            # buffered graph (a sender-isolated receiver at round r-1
            # degrades to self-model there, exactly as the serialized
            # round r-1 would have).
            deg_b = _in_degree(buf_adj)
            agg_out = jnp.where((deg_b > 0)[:, None], agg_out, buf_own)
        # The displacement the serialized round r-1 would have applied.
        # where, not multiply: a hypothetical non-finite value in the
        # warm-up placeholder aggregation must be DISCARDED, not scaled
        # (0 * inf == nan — the fault sentinels' static-scrub contract).
        disp = jnp.where(
            valid > 0, agg_out - buf_own, jnp.zeros_like(buf_own)
        )
        # Warm-up exactness for carried rule state too: the round-0
        # placeholder aggregation must not write trust/threshold state.
        rule_state = {
            k: (
                jnp.where(valid > 0, v, rule_state[k])
                if k in rule_state else v
            )
            for k, v in rule_state_new.items()
        }

        # ---- stage B: production of round r's exchange ----------------
        prod = _produce_exchange(
            params, agg_state, key, adj, compromised, alive, round_idx, d
        )
        own_flat = prod["own_flat"]
        pre_flat = prod["pre_flat"]
        finite = prod["finite"]
        agg_state = prod["agg_state"]
        fault_stats = prod["fault_stats"]

        # ---- stage C: combine + buffer swap ---------------------------
        with jax.named_scope("murmura.pipeline"):
            new_flat = own_flat + disp.astype(own_flat.dtype)
            if alive is not None:
                # Dead nodes freeze and quarantined nodes roll back —
                # own_flat already equals pre_flat on those rows, so the
                # keep-mask reduces to discarding the delayed
                # displacement (mirrored bit-for-bit by
                # core/pipeline.run_delayed_reference).
                keep = alive > 0
                if finite is not None:
                    keep = keep & finite
                new_flat = jnp.where(keep[:, None], new_flat, pre_flat)
                fault_stats["alive"] = alive.sum()
                if audit_taps:
                    fault_stats["tap_alive"] = alive
            params = jax.vmap(unravel)(new_flat)
        buffer_updates = {
            PIPE_OWN_KEY: own_flat,
            PIPE_ADJ_KEY: prod["adj"].T if sparse else prod["adj"],
            PIPE_VALID_KEY: jnp.ones_like(valid),
        }
        if stale_fold is None:
            buffer_updates[PIPE_BCAST_KEY] = prod["bcast"]
        agg_state = {**agg_state, **rule_state, **buffer_updates}

        metrics = {f"agg_{k}": v for k, v in agg_stats.items()}
        metrics.update({f"agg_{k}": v for k, v in fault_stats.items()})
        metrics.update(
            {f"agg_{k}": v for k, v in prod["compress_stats"].items()}
        )
        metrics.update(
            {f"agg_{k}": v for k, v in prod["stale_stats"].items()}
        )
        # 0.0 on the warm-up round: this round's agg_* stats describe
        # the invalid placeholder aggregation, not a real exchange.
        metrics["agg_pipe_valid"] = valid
        return params, agg_state, metrics

    body = _round_body_pipelined if pipeline else _round_body
    if faults is None:
        def train_round(params, agg_state, key, adj, compromised, round_idx, d):  # murmura: traced
            return body(
                params, agg_state, key, adj, compromised, None, round_idx, d
            )

        def train_flat(params, agg_state, key, adj, compromised, round_idx, d):  # murmura: traced
            prod = _produce_exchange(
                params, agg_state, key, adj, compromised, None, round_idx, d
            )
            ok = (
                prod["finite"].astype(jnp.float32)
                if prod["finite"] is not None
                else jnp.ones_like(compromised)
            )
            return prod["own_flat"], ok
    else:
        def train_round(params, agg_state, key, adj, compromised, alive, round_idx, d):  # murmura: traced
            return body(
                params, agg_state, key, adj, compromised, alive, round_idx, d
            )

        def train_flat(params, agg_state, key, adj, compromised, alive, round_idx, d):  # murmura: traced
            prod = _produce_exchange(
                params, agg_state, key, adj, compromised, alive, round_idx, d
            )
            ok = (
                prod["finite"].astype(jnp.float32)
                if prod["finite"] is not None
                else jnp.ones_like(compromised)
            )
            return prod["own_flat"], ok

    def eval_step(params, d):  # murmura: traced
        # evaluation (network.py:141-199) — held-out arrays when the data
        # loader provided them (eval_arrays), else the training shard.
        with jax.named_scope("murmura.eval"):
            return evaluate(params, d["eval_x"], d["eval_y"], d["eval_mask"])

    init_agg_state = {
        k: np.asarray(v) for k, v in agg.init_state(n).items()
    }
    if dmtt is not None:
        init_agg_state.update(
            {k: np.asarray(v) for k, v in init_dmtt_state(n).items()}
        )
    if compression is not None:
        # Error-feedback residual (zeros) and/or the top-k reference
        # estimate, which adopts the protocol-known initial broadcast (a
        # real deployment sends full states once at setup) so round 0's
        # delta is already sparse.  Stored in the resident param dtype —
        # both shapes are [N, P] and round-stable, so donation aliases hold.
        clash = set(COMPRESS_STATE_KEYS) & set(init_agg_state)
        if clash:
            raise ValueError(
                f"aggregator '{agg.name}' carries state keys {sorted(clash)}"
                " reserved for the compressed exchange"
            )
        init_flat = np.asarray(jax.vmap(ravel)(init_params))
        init_agg_state.update(
            init_compress_state(compression, init_flat, init_flat.dtype)
        )
    if staleness is not None:
        # The payload cache + age stamps ride agg_state under the
        # reserved STALE_STATE_KEYS slice — same [N, P]/[N] shapes and
        # dtypes every round, so the scan carry, gang vmap, donation
        # aliases and durability snapshots all hold without special
        # cases (the COMPRESS_STATE_KEYS story).
        clash = set(STALE_STATE_KEYS) & set(init_agg_state)
        if clash:
            raise ValueError(
                f"aggregator '{agg.name}' carries state keys "
                f"{sorted(clash)} reserved for the bounded-staleness "
                "exchange"
            )
        leaf = jax.tree_util.tree_leaves(init_params)[0]
        # flat_dim, not model_dim: the cache row must match the (padded)
        # exchanged width so it shards over "param" with the broadcast.
        init_agg_state.update(
            init_stale_state(staleness, n, flat_dim, leaf.dtype)
        )
    if adaptive:
        # Adaptation state rides agg_state under the attack's reserved
        # ATTACK_STATE_KEYS slice — same shapes/dtypes every round, so the
        # scan carry, gang vmap, donation aliases and durability snapshots
        # all hold without special cases (the COMPRESS_STATE_KEYS story).
        clash = set(attack.state_keys) & set(init_agg_state)
        if clash:
            raise ValueError(
                f"aggregator '{agg.name}' carries state keys "
                f"{sorted(clash)} reserved for the adaptive attack"
            )
        init_agg_state.update(
            {
                k: np.asarray(v)
                for k, v in attack.init_attack_state(n).items()
            }
        )
    if pipeline:
        # The double-buffered pipeline stage rides agg_state under the
        # reserved PIPELINE_STATE_KEYS slice — same shapes/dtypes every
        # round, so the scan carry, gang vmap, donation aliases and
        # durability snapshots all hold without special cases (the
        # COMPRESS/STALE_STATE_KEYS story).  With staleness armed the
        # broadcast buffer is the stale cache (buffer reuse —
        # core/pipeline.pipeline_state_keys).
        clash = set(pipe_keys) & set(init_agg_state)
        if clash:
            raise ValueError(
                f"aggregator '{agg.name}' carries state keys "
                f"{sorted(clash)} reserved for the pipelined exchange"
            )
        leaf = jax.tree_util.tree_leaves(init_params)[0]
        init_agg_state.update(
            init_pipeline_state(
                n, flat_dim, leaf.dtype,
                sparse_offsets=sparse_offsets,
                stale=staleness is not None,
            )
        )

    return RoundProgram(
        train_step=train_round,
        eval_step=eval_step,
        init_params=init_params,
        init_agg_state=init_agg_state,
        data_arrays=data_arrays,
        num_nodes=n,
        model_dim=model_dim,
        evidential=evidential,
        faulted=faults is not None,
        hp_inputs=hp_inputs,
        sparse_offsets=sparse_offsets,
        compression=compression,
        adaptive_attack=adaptive,
        staleness=staleness,
        pipelined=pipeline,
        train_flat=train_flat,
        param_shards=param_shards,
        flat_dim=flat_dim,
    )


def build_multi_round(program: RoundProgram, chunk: int, eval_every: int):
    """Fuse ``chunk`` FL rounds into one ``lax.scan`` program.

    The SURVEY §7 end state: the round loop itself lives on the device and
    metrics come back as device-resident history arrays after the scan —
    one dispatch per ``chunk`` rounds instead of per round.  Evaluation runs
    under ``lax.cond`` only on rounds where ``(round + 1) % eval_every == 0``
    (cond executes a single branch, so skipped rounds pay zero eval FLOPs,
    same as the separately-dispatched path).

    Returns a function
        (params, agg_state, base_key, adj_stack[chunk, N, N], compromised,
         round0, data) -> (params', agg_state', rows)
    where ``rows`` is a [chunk, ...] metrics pytree: per-round ``agg_*``
    stats, eval metrics (zeros on unevaluated rounds), and an ``evaluated``
    flag the orchestrator uses to select history rows.  ``adj_stack`` holds
    the per-round adjacency (host-computed G^t for mobility; the static mask
    tiled otherwise); per-round RNG is ``fold_in(base_key, round)`` so a
    fused run consumes the same independent streams regardless of chunking.

    Faulted programs (``program.faulted``) additionally take a per-round
    ``alive_stack`` [chunk, N] after ``compromised`` — the fault-schedule
    twin of ``adj_stack``, riding the same scan xs.
    """
    as_struct = lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
    eval_struct = jax.eval_shape(
        program.eval_step,
        jax.tree_util.tree_map(as_struct, program.init_params),
        {k: as_struct(v) for k, v in program.data_arrays.items()},
    )

    def _body(carry, i, adj, alive, compromised, base_key, round0, data):
        params, agg_state = carry
        r = round0 + i
        key = jax.random.fold_in(base_key, r)
        step_args = [params, agg_state, key, adj, compromised]
        if alive is not None:
            step_args.append(alive)
        params, agg_state, m = program.train_step(
            *step_args, r.astype(jnp.float32), data,
        )
        do_eval = (r + 1) % eval_every == 0
        ev = jax.lax.cond(
            do_eval,
            lambda p: program.eval_step(p, data),
            lambda p: jax.tree_util.tree_map(
                lambda s: jnp.zeros(s.shape, s.dtype), eval_struct
            ),
            params,
        )
        rows = {**m, **ev, "evaluated": do_eval}
        return (params, agg_state), rows

    if program.faulted:
        def multi_round(params, agg_state, base_key, adj_stack, compromised, alive_stack, round0, data):  # murmura: traced
            def body(carry, xs):
                i, adj, alive = xs
                return _body(
                    carry, i, adj, alive, compromised, base_key, round0, data
                )

            (params, agg_state), rows = jax.lax.scan(
                body, (params, agg_state),
                (jnp.arange(chunk), adj_stack, alive_stack),
            )
            return params, agg_state, rows
    else:
        def multi_round(params, agg_state, base_key, adj_stack, compromised, round0, data):  # murmura: traced
            def body(carry, xs):
                i, adj = xs
                return _body(
                    carry, i, adj, None, compromised, base_key, round0, data
                )

            (params, agg_state), rows = jax.lax.scan(
                body, (params, agg_state), (jnp.arange(chunk), adj_stack)
            )
            return params, agg_state, rows

    return multi_round

"""Pipelined rounds: hide exchange/aggregation behind local training
(ISSUE 14; docs/PERFORMANCE.md "Pipelined rounds").

Every in-jit backend runs the round strictly serialized: train, then
exchange the broadcast, then aggregate — inside one fused scan step the
collectives sit on the critical path between the training matmuls and
the parameter update, so compression (PR 7) cut exchanged *bytes* but
not wall-clock.  The delayed-averaging line — "Improving Efficiency in
Large-Scale Decentralized Distributed Training" (arXiv:2002.01119) and
the async half of asynchronous quantized decentralized SGD
(arXiv:1910.12308, whose quantized half is PR 7 and whose staleness
half is PR 13) — shows convergence survives applying the *previous*
round's aggregation displacement while the current round trains.

This module implements that as a **double-buffered pipeline stage riding
the round program's carried state** under the reserved
:data:`PIPELINE_STATE_KEYS` (the ``STALE_STATE_KEYS`` pattern): because
it lives in ``agg_state``, the fused ``lax.scan`` carry, gang vmap,
MUR900 snapshot completeness and durability resume all cover it with no
special cases, and chunk boundaries need no explicit warm-up/drain —
the buffer simply rides the carry across dispatches.

Semantics (the docs/PERFORMANCE.md table; machine-checked by MUR120x,
analysis/pipeline.py).  Let ``Q_r = Train_r(P_r)`` be round ``r``'s
locally trained (post-quarantine-scrub) flat params and
``(B_r, A_r)`` the broadcast/adjacency pair the round *produces* —
post-attack, post-sentinel, post-codec, post-stale-fold: exactly what
the serialized program's aggregation would have consumed.  Then:

- serialized:  ``P_{r+1} = Agg(Q_r, B_r, A_r)``  (guards folded);
- pipelined:   ``P_{r+1} = Q_r + valid * (Agg(Q_{r-1}, B_{r-1},
  A_{r-1}) - Q_{r-1})`` — round ``r`` trains on params that already
  include round ``r-2``'s aggregation displacement, while round
  ``r-1``'s buffered exchange is aggregated *concurrently* with the
  training matmuls (no data dependence between the two stages; the
  program issues the aggregation's collectives on the buffered tensor
  before the training scan consumes params, so XLA's async dispatch is
  free to overlap them).

Round 0 is the warm-up: the buffer starts invalid (``pipe_valid`` 0),
the displacement is ``where``-gated to exactly zero, and
``P_1 = Q_0`` — pure local training.  There is no drain round: the last
round's broadcast is produced into the buffer and never aggregated
(visible as one un-consumed buffer in the final snapshot — a resumed
run aggregates it on its first round, which is why SIGKILL at any
boundary resumes byte-identically).

Scrub discipline: the sentinels run at *production* time, before the
buffer write — a quarantined or attack-scrubbed row never enters the
buffer, so the delayed aggregation can never replay a caught row even
though its verdict was computed one round before the aggregation runs
(the MUR1203 taint contract; the MUR1103 replay-hole discipline).

Buffer reuse (core/stale.py): with bounded staleness armed, the stale
fold's payload cache already stores exactly the post-fold broadcast the
buffer needs (``stale_cache`` after round ``r-1`` *is* ``B_{r-1}``), so
the pipeline reads its broadcast buffer from ``STALE_STATE_KEYS``
instead of carrying a duplicate [N, P] tensor — ``pipe_bcast`` exists
only in staleness-free builds.
"""

from typing import Dict, Optional, Tuple

import numpy as np

# Reserved round-program-level agg_state keys (the DMTT_STATE_KEYS /
# COMPRESS_STATE_KEYS / STALE_STATE_KEYS pattern, core/rounds.py):
# carried by the round step but never handed to the aggregation rule's
# state dict, and registered in durability/snapshot.
# RESERVED_AGG_STATE_KEY_GROUPS so the MUR900 snapshot-completeness
# bijection — and therefore SIGKILL/--resume with a populated pipeline
# buffer — covers them for free (MUR1200, analysis/pipeline.py).
ADJ_KEY = "pipe_adj"
BCAST_KEY = "pipe_bcast"
OWN_KEY = "pipe_own"
VALID_KEY = "pipe_valid"
PIPELINE_STATE_KEYS = (ADJ_KEY, BCAST_KEY, OWN_KEY, VALID_KEY)


def pipeline_state_keys(stale: bool) -> Tuple[str, ...]:
    """The PIPELINE_STATE_KEYS subset a build actually carries.

    With bounded staleness armed the broadcast buffer IS the stale
    fold's payload cache (``stale_cache`` holds the post-fold exchanged
    tensor the next round's delayed aggregation consumes), so
    ``pipe_bcast`` would be a byte-for-byte duplicate [N, P] tensor —
    it is dropped and the round program reads
    ``agg_state["stale_cache"]`` instead (module docstring).
    """
    if stale:
        return tuple(k for k in PIPELINE_STATE_KEYS if k != BCAST_KEY)
    return PIPELINE_STATE_KEYS


def init_pipeline_state(
    num_nodes: int,
    model_dim: int,
    dtype,
    *,
    sparse_offsets: Tuple[int, ...] = (),
    stale: bool = False,
) -> Dict[str, np.ndarray]:
    """Initial ``agg_state`` entries for a pipelined program.

    The buffer starts *invalid* (``pipe_valid`` 0): round 0's delayed
    aggregation runs on these placeholder values — a full base-like
    graph over the initial broadcast, so every rule's math is finite —
    and its displacement is ``where``-discarded, making warm-up exact
    (``P_1 = Q_0``) rather than approximately-zero (a multiplicative
    gate would propagate a hypothetical NaN through ``0 * nan``; the
    ``where`` is the same static-scrub contract MUR803 interval-checks
    on the fault sentinels).

    ``pipe_adj`` is stored **node-leading**: ``[N, N]`` dense, or
    ``[N, k]`` in sparse mode (the transpose of the round input's
    ``[k, N]`` edge mask) so the mesh's leading-axis sharding
    (parallel/mesh._shard_leading_axis) places it on the node axis like
    every other carried row.
    """
    init_flat = np.zeros((num_nodes, model_dim), dtype)
    if sparse_offsets:
        adj0 = np.ones((num_nodes, len(sparse_offsets)), np.float32)
    else:
        adj0 = np.ones((num_nodes, num_nodes), np.float32) - np.eye(
            num_nodes, dtype=np.float32
        )
    state = {
        ADJ_KEY: adj0,
        OWN_KEY: init_flat,
        VALID_KEY: np.zeros((), np.float32),
    }
    if not stale:
        state[BCAST_KEY] = init_flat.copy()
    return state


# ---------------------------------------------------------------------------
# The explicit one-round-delayed averaging reference (tests + the battery
# --pipeline pre-flight).
# ---------------------------------------------------------------------------


def run_delayed_reference(
    net,
    rounds: int,
    eval_every: int = 1,
):
    """Drive a SERIALIZED network's round program through the explicit
    one-round-delayed averaging recursion (module docstring) and return
    ``(params, history)`` — the independent implementation the pipelined
    program must match bit-for-bit on CPU.

    ``net`` must be a :class:`~murmura_tpu.core.network.Network` built
    WITHOUT ``exchange.pipeline`` (its ``train_step`` is the serialized
    round, its ``train_flat`` the training-only stage).  The driver runs,
    per round ``r``:

    1. ``own_r  = train_flat(P_r, ...)`` — the trained post-scrub flat
       params (a pure sub-computation of the serialized step);
    2. ``S_r, state' = train_step(P_r, state, ...)`` — the full
       serialized round, whose output IS the guarded aggregation of
       round ``r``'s exchange and whose state update IS the production
       sequence (codec EF, stale cache, rule state);
    3. ``P_{r+1} = own_r + disp``; ``disp`` then advances to
       ``ravel(S_r) - own_r`` for the next round (zero on round 0) —
       with the faulted builds' keep-mask applied exactly as the
       pipelined combine applies it.

    The recursion never touches the pipelined code path: steps 1-2 are
    the pre-existing serialized program, step 3 is four elementwise jnp
    ops — which is what makes a bit-for-bit match meaningful evidence
    that the fused double-buffered program computes one-round-delayed
    averaging and nothing else.
    """
    import jax
    import jax.numpy as jnp

    from murmura_tpu.core.network import record_round_metrics
    from murmura_tpu.ops.flatten import make_flatteners

    prog = net.program
    if prog.pipelined:
        raise ValueError(
            "run_delayed_reference drives the SERIALIZED round program "
            "through the delayed recursion; build the reference network "
            "without exchange.pipeline"
        )
    template = jax.tree_util.tree_map(lambda l: l[0], prog.init_params)
    ravel, unravel, _dim = make_flatteners(template)
    v_ravel = jax.jit(jax.vmap(ravel))
    v_unravel = jax.jit(jax.vmap(unravel))
    step = jax.jit(prog.train_step)
    tflat = jax.jit(prog.train_flat)
    ev = jax.jit(prog.eval_step)

    params = jax.tree_util.tree_map(jnp.asarray, prog.init_params)
    agg_state = {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()}
    d = {k: jnp.asarray(v) for k, v in prog.data_arrays.items()}
    comp = jnp.asarray(net.compromised)
    base_key = jax.random.PRNGKey(net.seed)

    from murmura_tpu.core.network import empty_history

    history = empty_history()
    disp = jnp.zeros_like(v_ravel(params))
    for r in range(rounds):
        key = jax.random.fold_in(base_key, r)
        ridx = jnp.asarray(float(r), jnp.float32)
        adj = jnp.asarray(net._adjacency_for_round(r))
        args = [params, agg_state, key, adj, comp]
        targs = [params, agg_state, key, adj, comp]
        alive = None
        if prog.faulted:
            alive = jnp.asarray(net._alive_for_round(r))
            args.append(alive)
            targs.append(alive)
        own, train_ok = tflat(*targs, ridx, d)
        s_params, agg_state, _m = step(*args, ridx, d)
        new_flat = own + disp
        if alive is not None:
            # nan_quarantine scrubbed own back to the pre-round value
            # and the serialized keep-guard froze those rows; own ==
            # pre_flat there, so the keep-mask reduces to discarding
            # the displacement — exactly the pipelined combine.
            keep = (alive > 0) & (train_ok > 0)
            new_flat = jnp.where(keep[:, None], new_flat, own)
        disp = v_ravel(s_params) - own
        params = v_unravel(new_flat)
        if (r + 1) % eval_every == 0:
            metrics = jax.device_get(ev(params, d))
            record_round_metrics(
                history, r + 1, metrics, net.compromised,
                prog.evidential, net.attack is not None,
            )
    return params, history


# ---------------------------------------------------------------------------
# Composition manifest (murmura_tpu/levers.py; `murmura check --compose`).
# The single source of truth for this lever's cross-feature verdicts —
# guard sites in config/schema.py and utils/factories.py cite
# refusal_reason() so user-facing messages and the analyzer's grid can
# never drift apart (MUR1400).
# ---------------------------------------------------------------------------
from murmura_tpu.levers import LeverManifest, composes, refuses

LEVER_MANIFEST = LeverManifest(
    name="pipeline",
    module="murmura_tpu.core.pipeline",
    state_keys_group="PIPELINE_STATE_KEYS",
    stage="murmura.pipeline",
    verdicts={
        "adaptive": refuses(
            "exchange.pipeline does not compose with attack.adaptive: "
            "the acceptance feedback would observe round r-1's "
            "aggregation after round r's production already ran, "
            "changing the closed loop's timing semantics — run "
            "adaptive experiments serialized"
        ),
        "compression": composes(),
        "dmtt": refuses(
            "exchange.pipeline does not compose with dmtt (claim "
            "verification gates each round's exchange between "
            "production and aggregation; delaying the aggregation "
            "would verify claims against a different round's graph)"
        ),
        "faults": composes(),
        "mobility": composes(),
    },
)

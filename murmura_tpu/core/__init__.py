"""Core runtime: the jitted round program and Network orchestrator
(reference: murmura/core/)."""

from murmura_tpu.core.network import Network
from murmura_tpu.core.rounds import RoundProgram, build_round_program

__all__ = ["Network", "RoundProgram", "build_round_program"]

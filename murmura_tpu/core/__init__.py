"""Core runtime: the jitted round program and Network orchestrator
(reference: murmura/core/)."""

from murmura_tpu.core.gang import GangMember, GangNetwork
from murmura_tpu.core.network import Network
from murmura_tpu.core.rounds import RoundProgram, build_round_program

__all__ = [
    "GangMember",
    "GangNetwork",
    "Network",
    "RoundProgram",
    "build_round_program",
]

"""Gang-batched multi-seed execution — vmap the round program over an
experiment axis (ISSUE 5; docs/PERFORMANCE.md).

The paper's evaluation is a grid: every (rule x attack x topology) cell is
re-run across seeds, yet one network per process pays the full trace/compile
(~40 s on the bench scenario) for seconds of rounds, and a small-N round
leaves the device mostly idle.  A *gang* stacks S independent experiments —
differing in seed, and optionally in traced scalar hyperparameters (lr,
attack intensity) — into leading-axis-``[S, ...]`` inputs and ``jax.vmap``s
the existing round program (:func:`core.rounds.build_round_program` /
:func:`core.rounds.build_multi_round`) over that axis: ONE compile and one
saturated device program cover the whole sweep.

Design invariants (each machine-checked):

- **Parity** — a gang member's history is byte-identical on CPU to the
  single run with that member's seed (tests/test_gang.py), because every
  member's inputs are built by the very same per-member
  ``build_round_program`` call a single run would make, and the batched
  program applies identical math per member.  The attack's compromised
  *placement* is pinned across members (attacks close over a static
  compromised set — the gaussian scatter matrix); a single run reproduces a
  member exactly by pinning ``attack.params.seed`` to the gang's base seed.
- **No new collectives** — vmapping the round program must not introduce
  communication the single-run program lacks (``murmura check --ir``
  MUR500).
- **Bucketed compiles** — the gang pads S to the next power of two and
  masks padding members out of recording, so growing S within a bucket
  reuses the compiled executable: zero recompiles (MUR501), the same trick
  the alive/adjacency value-inputs use for churn (MUR302).

When gang loses: resident memory is S x a single run's (params, optimizer
state, data all gain the seed axis) — at large models or large N, prefer
fewer members per gang over spilling HBM.  Shape-affecting knobs
(num_nodes, batch_size, model size, krum's selection count) cannot vary
inside a gang; they change the traced program and belong in separate
sweeps.
"""

import time
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from murmura_tpu.core.network import (
    effective_adjacency,
    effective_alive,
    effective_edge_mask,
    empty_history,
    record_round_metrics,
    sanitizer_scope,
)
from murmura_tpu.core.rounds import RoundProgram


def next_bucket(size: int) -> int:
    """Smallest power of two >= size — the gang's compile-shape bucket."""
    if size < 1:
        raise ValueError(f"gang size must be >= 1, got {size}")
    b = 1
    while b < size:
        b *= 2
    return b


@dataclass(frozen=True)
class GangMember:
    """One experiment of the gang: a seed plus optional traced-scalar
    hyperparameter overrides (values the compiled program takes as inputs,
    so every member rides one jit)."""

    seed: int
    lr: Optional[float] = None
    attack_scale: Optional[float] = None

    @property
    def label(self) -> str:
        parts = [f"seed_{self.seed}"]
        if self.lr is not None:
            parts.append(f"lr_{self.lr:g}")
        if self.attack_scale is not None:
            parts.append(f"atk_{self.attack_scale:g}")
        return "-".join(parts)


def resolve_members(config, seeds: Optional[Sequence[int]] = None) -> List[GangMember]:
    """The gang's member list from ``config.sweep`` (or an explicit seed
    list — the CLI ``--seeds`` override / ``murmura run --seeds N`` sugar).

    ``noise_std`` member overrides are resolved here into the program-level
    ``attack_scale`` multiplier (scale = noise_std / the configured gaussian
    noise_std), so the round program needs only one knob.
    """
    def _distinct(members: List[GangMember]) -> List[GangMember]:
        # Member labels key the sweep output JSON and the per-member
        # telemetry run dirs — a duplicate would silently collapse one
        # member's results onto another's, so every member source (the
        # --seeds CLI list included) fails loud instead.
        labels = [m.label for m in members]
        if len(labels) != len(set(labels)):
            raise ValueError(
                f"sweep members are not distinct (labels: {labels}) — two "
                "identical members would just duplicate work"
            )
        return members

    if seeds is not None:
        return _distinct([GangMember(seed=int(s)) for s in seeds])
    sweep = config.sweep
    if sweep is None:
        raise ValueError("config has no sweep block and no explicit seeds")
    if sweep.seeds is not None:
        return _distinct([GangMember(seed=int(s)) for s in sweep.seeds])
    if sweep.num_seeds is not None:
        base = config.experiment.seed
        return [GangMember(seed=base + i) for i in range(sweep.num_seeds)]
    p = config.attack.params
    base_noise = float(p.get("noise_std", p.get("std", 10.0)))
    members = []
    for m in sweep.members:
        scale = m.attack_scale
        if m.noise_std is not None:
            if base_noise <= 0:
                raise ValueError(
                    "sweep member noise_std override needs a positive "
                    "attack.params.noise_std to scale against"
                )
            scale = m.noise_std / base_noise
        members.append(GangMember(
            seed=int(m.seed if m.seed is not None else config.experiment.seed),
            lr=m.lr,
            attack_scale=scale,
        ))
    return _distinct(members)


def gang_hp_inputs(members: Sequence[GangMember]) -> Tuple[str, ...]:
    """Which scalar hyperparameters the gang's program must take as inputs
    (``build_round_program(hp_inputs=...)``).  Seed-only gangs lift none —
    the traced program stays byte-identical to a single run's."""
    hp = []
    if any(m.lr is not None for m in members):
        hp.append("lr")
    if any(m.attack_scale is not None for m in members):
        hp.append("attack_scale")
    return tuple(hp)


def _stack_trees(trees: Sequence[Any], indices: Sequence[int]) -> Any:
    """Stack member pytrees along a new leading axis in ``indices`` order
    (the bucket-padding order: real members then replicas of member 0)."""
    picked = [trees[i] for i in indices]
    return jax.tree_util.tree_map(
        lambda *leaves: np.stack([np.asarray(l) for l in leaves]), *picked
    )


def _check_member_compatible(progs: Sequence[RoundProgram], members) -> None:
    """Fail loud when member programs are not gang-batchable.

    The gang runs member 0's traced function over everyone's inputs, so
    every static property the trace bakes in — shapes, dtypes, the batch
    schedule's max step count — must agree, or a member would silently
    train differently than its single run (a parity violation worse than
    an error)."""
    base = progs[0]
    base_shapes = {
        k: (v.shape, str(np.asarray(v).dtype))
        for k, v in base.data_arrays.items()
    }
    for i, prog in enumerate(progs[1:], start=1):
        label = members[i].label
        if prog.num_nodes != base.num_nodes or prog.model_dim != base.model_dim:
            raise ValueError(
                f"gang member {label}: num_nodes/model_dim mismatch with "
                "member 0 — members must share the network and model shape"
            )
        shapes = {
            k: (v.shape, str(np.asarray(v).dtype))
            for k, v in prog.data_arrays.items()
        }
        if shapes != base_shapes:
            diff = sorted(
                k for k in set(shapes) | set(base_shapes)
                if shapes.get(k) != base_shapes.get(k)
            )
            raise ValueError(
                f"gang member {label}: data arrays differ from member 0's "
                f"in {diff} — per-seed partitions must produce identical "
                "shapes to share one compiled program (pin "
                "training.max_samples or use an equal-shard partitioner)"
            )
        for k in ("steps", "eff_batch"):
            if int(prog.data_arrays[k].max()) != int(base.data_arrays[k].max()):
                raise ValueError(
                    f"gang member {label}: static batch schedule "
                    f"(max {k}) differs from member 0's — the traced scan "
                    "length would silently truncate this member's training; "
                    "equalize per-node sample counts across seeds"
                )


class GangNetwork:
    """Orchestrates S stacked experiments over one vmapped round program.

    The gang twin of :class:`core.network.Network`: same history schema,
    same RNG discipline (round r runs with ``fold_in(PRNGKey(member_seed),
    r)`` per member), same fused-dispatch semantics — but every device
    program carries a leading ``[B]`` experiment axis (B = the padded
    bucket) and history/telemetry fan out per member.

    Args:
        program: member 0's RoundProgram (the gang's traced function).
        member_programs: every member's RoundProgram — their init state and
            data arrays are the gang's stacked inputs.
        members: the resolved member list (seeds + hp overrides).
        topology / mobility / fault_schedule: shared across members — their
            seeds are independent of the experiment seed by construction
            (topology.seed / mobility.seed / faults.seed).
        backend: ``simulation`` (one device) or ``tpu`` (gang laid onto a
            2-D ("seed", "nodes") mesh — parallel/mesh.py).
        telemetry_writers: optional per-member TelemetryWriter list (one
            manifest per member, ``<run_dir>/<member label>``).
    """

    def __init__(
        self,
        program: RoundProgram,
        member_programs: Sequence[RoundProgram],
        members: Sequence[GangMember],
        topology,
        attack=None,
        mobility=None,
        fault_schedule=None,
        backend: str = "simulation",
        mesh=None,
        num_devices: Optional[int] = None,
        donate: bool = True,
        bucket: bool = True,
        base_lr: float = 0.01,
        recompile_guard: bool = False,
        transfer_guard: bool = False,
        telemetry_writers: Optional[Sequence] = None,
        retain_init: bool = False,
        min_batch: int = 1,
    ):
        if len(member_programs) != len(members):
            raise ValueError("one RoundProgram per member required")
        _check_member_compatible(member_programs, members)
        if program.sparse:
            from murmura_tpu.topology.sparse import SparseTopology

            # Sparse exchange mode (topology/sparse.py): the gang's adj
            # input is the member-shared [k, N] edge mask, exactly like a
            # single run's — it rides in_axes=None so nothing here is
            # mode-specific beyond the per-round mask source below.  A
            # node-SHARDED gang mesh is still rejected at the factory
            # (the [k, N] layout needs edge_mask_sharding plumbing).
            if not isinstance(topology, SparseTopology):
                raise ValueError(
                    "the gang's round program was built with "
                    "sparse_offsets but the topology is not a "
                    "SparseTopology"
                )
            if mobility is not None:
                raise ValueError(
                    "sparse exchange mode does not compose with mobility"
                )
        self.program = program
        self.members = list(members)
        self.gang_size = len(members)
        # min_batch pre-grows the compile shape (serve/daemon.py: a bucket
        # built at full capacity admits tenants value-only — the shape
        # never changes, so admission never recompiles).
        self.batch = (
            next_bucket(max(self.gang_size, min_batch))
            if bucket else self.gang_size
        )
        self.topology = topology
        self.attack = attack
        self.mobility = mobility
        self.fault_schedule = fault_schedule
        self.backend = backend
        self.recompile_guard = recompile_guard
        self.transfer_guard = transfer_guard
        self._tracker = None
        self.last_compile_report: Optional[List] = None
        self._warmed: set = set()
        self.telemetry = list(telemetry_writers or [])
        if self.telemetry and len(self.telemetry) != self.gang_size:
            raise ValueError("one telemetry writer per member required")

        n = program.num_nodes
        if topology.num_nodes != n:
            raise ValueError(
                f"Topology has {topology.num_nodes} nodes, gang stack has {n}"
            )

        # Bucket padding: replicate member 0 into the tail slots.  Padding
        # members execute (their cost is the price of the stable compile
        # shape) but are never recorded and never see a telemetry writer.
        self._indices = list(range(self.gang_size)) + [0] * (
            self.batch - self.gang_size
        )

        # Per-member compromised masks are identical by construction (the
        # attack placement is pinned across the gang — module docstring),
        # but stack them anyway: the program takes the mask as an input,
        # and a future per-member threat model only needs this array.
        if attack is not None:
            comp = attack.compromised.astype(np.float32)
        else:
            comp = np.zeros(n, dtype=np.float32)
        self.compromised = comp
        self._comp_stack = np.stack([comp for _ in self._indices])

        stack = lambda get: _stack_trees(  # noqa: E731
            [get(p) for p in member_programs], self._indices
        )
        init_params_host = stack(lambda p: p.init_params)
        init_agg_host = stack(lambda p: p.init_agg_state)
        # retain_init keeps the stacked host-side init arrays alive so
        # reset_run() can rebuild fresh device state without the member
        # programs (the frontier's stage loop — value-only resets over one
        # warm compiled program).  Off by default: normal sweeps should
        # not hold a second host copy of [B, N, P] params.
        self._init_params_host = init_params_host if retain_init else None
        self._init_agg_host = init_agg_host if retain_init else None
        self._base_lr = base_lr
        self.params = jax.tree_util.tree_map(jnp.asarray, init_params_host)
        self.agg_state = {
            k: jnp.asarray(v) for k, v in init_agg_host.items()
        }
        data = stack(lambda p: p.data_arrays)
        # Per-member hyperparameter inputs overwrite the stacked defaults.
        if "lr" in program.hp_inputs:
            data["hp_lr"] = np.asarray(
                [
                    members[i].lr if members[i].lr is not None else base_lr
                    for i in self._indices
                ],
                np.float32,
            )
        if "attack_scale" in program.hp_inputs:
            data["hp_attack_scale"] = np.asarray(
                [
                    members[i].attack_scale
                    if members[i].attack_scale is not None
                    else 1.0
                    for i in self._indices
                ],
                np.float32,
            )
        self._data = {k: jnp.asarray(v) for k, v in data.items()}
        # Per-member base keys: round r always runs with fold_in(base_s, r),
        # exactly the single-run stream for that member's seed.
        self._rng = jnp.stack(
            [jax.random.PRNGKey(members[i].seed) for i in self._indices]
        )
        self._fold_in = jax.jit(
            jax.vmap(jax.random.fold_in, in_axes=(0, None))
        )

        # --- the vmapped programs ------------------------------------------
        # The experiment axis is data-parallel by construction: members
        # share the shape family and the adjacency/alive inputs (seed-
        # independent), so adj/alive/round ride unbatched (in_axes=None) —
        # less resident memory and no per-member copies of [N, N] masks.
        if program.faulted:
            step_axes = (0, 0, 0, None, 0, None, None, 0)
        else:
            step_axes = (0, 0, 0, None, 0, None, 0)
        vstep = jax.vmap(program.train_step, in_axes=step_axes)
        veval = jax.vmap(program.eval_step, in_axes=(0, 0))

        if backend == "tpu":
            from jax.sharding import NamedSharding, PartitionSpec as P

            from murmura_tpu.parallel.mesh import (
                gang_adj_stack_sharding,
                gang_node_sharding,
                make_gang_mesh,
                make_gang_param_mesh,
                shard_gang_eval_step,
                shard_gang_step,
            )

            if mesh is None:
                if getattr(program, "param_shards", 1) > 1:
                    # The sharding x sweep lift (ISSUE 16): the gang
                    # mesh grows a "param" role so the [S, N, P] stacked
                    # state shards its trailing flat axis too.
                    mesh = make_gang_param_mesh(
                        self.batch, n, program.param_shards, num_devices
                    )
                else:
                    mesh = make_gang_mesh(self.batch, n, num_devices)
            self.mesh = mesh
            self._step = shard_gang_step(
                vstep, program, self.batch, mesh, donate=donate
            )
            self._eval = shard_gang_eval_step(veval, program, self.batch, mesh)
            self._adj_stack_s = gang_adj_stack_sharding(mesh)
            self._node_rows_s = gang_node_sharding(mesh)
            self._gang2d_s = NamedSharding(mesh, P("seed", "nodes"))
            self._member_s = NamedSharding(mesh, P("seed"))
            self._repl_s = NamedSharding(mesh, P())
        else:
            self.mesh = None
            donate_argnums = (0, 1) if donate else ()
            self._step = jax.jit(vstep, donate_argnums=donate_argnums)
            self._eval = jax.jit(veval)
            self._adj_stack_s = None
            self._node_rows_s = self._gang2d_s = None
            self._member_s = self._repl_s = None
        self._donate = donate
        self._fused_cache: Dict[Any, Any] = {}
        self._place_resident_state()
        # The compromised stack never changes across rounds: staged onto
        # its device layout once, not per dispatch.
        self._comp_dev = self._stage(self._comp_stack, self._gang2d_s)

        self.histories: List[Dict[str, List[Any]]] = [
            empty_history() for _ in range(self.gang_size)
        ]
        self._last_stats: List[Dict[str, np.ndarray]] = [
            {} for _ in range(self.gang_size)
        ]
        self.round_times: List[float] = []
        self.current_round = 0
        # Graceful degradation (durability/dispatch.py; docs/ROBUSTNESS.md):
        # a member marked dead keeps computing (its vmap lane cannot be
        # carved out of the compiled program — the same reason padding
        # members execute) but its history FREEZES at the failure round
        # and its telemetry surfaces the degradation, while survivors
        # continue unperturbed.  The alive-mask trick, one level up.
        self.member_active: List[bool] = [True] * self.gang_size

    # ------------------------------------------------------------------

    def _place_resident_state(self) -> None:
        """Pre-place the stacked state on the gang mesh (tpu backend,
        single host) — the gang twin of Network._place_resident_state."""
        if self.mesh is None or jax.process_count() > 1:
            return
        from murmura_tpu.parallel.mesh import (
            _shard_gang_leading,
            mesh_param_shards,
        )

        flat_dim = None
        if mesh_param_shards(self.mesh) > 1:
            flat_dim = getattr(
                self.program, "flat_dim", self.program.model_dim
            )
        place = lambda tree: jax.device_put(  # noqa: E731
            tree, _shard_gang_leading(tree, self.mesh, flat_dim)
        )
        self.params = place(self.params)
        self.agg_state = place(self.agg_state)
        self._data = place(self._data)

    def _stage(self, value, sharding=None):
        if sharding is None or self.mesh is None or jax.process_count() > 1:
            return jnp.asarray(value)
        return jax.device_put(value, sharding)

    def _adjacency_for_round(self, round_idx: int) -> np.ndarray:
        """Member-shared per-round adjacency (the Network helper — the
        topology/mobility/fault seeds are member-independent).  Sparse
        programs take the [k, N] edge mask where dense ones take the
        [N, N] matrix, exactly like a single run's dispatch loop."""
        if self.program.sparse:
            return effective_edge_mask(
                self.topology, self.fault_schedule, round_idx
            )
        return effective_adjacency(
            self.topology, self.mobility, self.fault_schedule, round_idx
        )

    def _alive_for_round(self, round_idx: int) -> np.ndarray:
        return effective_alive(
            self.fault_schedule, self.program.num_nodes, round_idx
        )

    def _sanitizer_scope(self):
        """The shared :func:`core.network.sanitizer_scope` (recompile /
        transfer guards) over this orchestrator."""
        return sanitizer_scope(self)

    # ------------------------------------------------------------------

    def train(
        self,
        rounds: int,
        verbose: bool = False,
        eval_every: int = 1,
        rounds_per_dispatch: int = 1,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 0,
    ) -> List[Dict[str, List[Any]]]:
        """Run the gang for ``rounds`` FL rounds; returns per-member
        histories (``self.histories``).

        ``checkpoint_dir``/``checkpoint_every`` snapshot the FULL stacked
        gang state — every member's params/agg_state/rng lane plus every
        per-member history — through the same durable path single runs
        use (durability/snapshot.py), so an interrupted sweep resumes all
        S members byte-identically (`murmura sweep --resume`).
        """
        from murmura_tpu.analysis.sanitizers import CompileTracker

        # Independent of the recompile guard: a passive process-wide
        # baseline so every member's manifest carries the compiles this
        # train() call paid (the metrics fold's `counter="compiles"`).
        compile_probe = CompileTracker()
        try:
            with self._sanitizer_scope():
                if rounds_per_dispatch > 1:
                    self._train_fused(
                        rounds, verbose, eval_every, rounds_per_dispatch,
                        checkpoint_dir, checkpoint_every,
                    )
                else:
                    self._train_rounds(
                        rounds, verbose, eval_every, checkpoint_dir,
                        checkpoint_every,
                    )
        finally:
            compiled = compile_probe.total
            for s, t in enumerate(self.telemetry):
                if t is not None:
                    if compiled:
                        t.add_counters({"compiles": compiled})
                    t.finalize(history=self.histories[s])
        return self.histories

    def _step_args(self, keys, adj, round_value, alive=None):
        args = [
            self.params,
            self.agg_state,
            keys,
            self._stage(adj, self._node_rows_s),
            self._comp_dev,
            self._stage(np.asarray(round_value, np.float32), self._repl_s),
            self._data,
        ]
        if self.program.faulted:
            args.insert(5, self._stage(alive, self._node_rows_s))
        return args

    def _train_rounds(
        self, rounds, verbose, eval_every, checkpoint_dir=None,
        checkpoint_every=0,
    ) -> None:
        last_saved = -1
        for _ in range(rounds):
            round_idx = self.current_round
            t0 = time.perf_counter()
            warmup = "step" not in self._warmed
            if self._tracker is not None:
                self._tracker.begin(f"gang round {round_idx}")
            adj = self._adjacency_for_round(round_idx)
            keys = self._stage(
                self._fold_in(
                    self._rng, jnp.asarray(np.asarray(round_idx, np.uint32))
                ),
                self._member_s,
            )
            args = self._step_args(
                keys, adj, round_idx,
                alive=self._alive_for_round(round_idx)
                if self.program.faulted else None,
            )
            self.params, self.agg_state, agg_metrics = self._step(*args)
            self._warmed.add("step")
            self.current_round = round_idx + 1
            if self.current_round % eval_every == 0:
                if self._tracker is not None:
                    self._tracker.mark(allow=warmup)
                warmup = "eval" not in self._warmed
                metrics = {**self._eval(self.params, self._data), **agg_metrics}
                self._warmed.add("eval")
                self._record_all(self.current_round, jax.device_get(metrics), verbose)
            if self._tracker is not None:
                self._tracker.end(allow=warmup)
            wall = time.perf_counter() - t0
            self.round_times.append(wall)
            self._emit_phase_times(round_idx, "gang_per_round", wall)
            if (
                checkpoint_dir
                and checkpoint_every
                and self.current_round % checkpoint_every == 0
            ):
                self.save_checkpoint(checkpoint_dir)
                last_saved = self.current_round
        if checkpoint_dir and rounds > 0 and self.current_round != last_saved:
            self.save_checkpoint(checkpoint_dir)

    def _fused_step(self, chunk: int, eval_every: int):
        key = (chunk, eval_every)
        if key not in self._fused_cache:
            from murmura_tpu.core.rounds import build_multi_round

            fn = build_multi_round(self.program, chunk, eval_every)
            if self.program.faulted:
                axes = (0, 0, 0, None, 0, None, None, 0)
            else:
                axes = (0, 0, 0, None, 0, None, 0)
            vfn = jax.vmap(fn, in_axes=axes)
            if self.mesh is not None:
                from murmura_tpu.parallel.mesh import shard_gang_multi_round

                self._fused_cache[key] = shard_gang_multi_round(
                    vfn, self.program, self.batch, self.mesh,
                    donate=self._donate,
                )
            else:
                donate_argnums = (0, 1) if self._donate else ()
                self._fused_cache[key] = jax.jit(
                    vfn, donate_argnums=donate_argnums
                )
        return self._fused_cache[key]

    def _train_fused(
        self, rounds, verbose, eval_every, chunk, checkpoint_dir=None,
        checkpoint_every=0,
    ) -> None:
        done = 0
        while done < rounds:
            k = min(chunk, rounds - done)
            step = self._fused_step(k, eval_every)
            round0 = self.current_round
            t0 = time.perf_counter()
            program_key = ("fused", k, eval_every)
            if self._tracker is not None:
                self._tracker.begin(f"gang rounds {round0}..{round0 + k - 1}")
            adj_stack = self._stage(
                np.stack(
                    [self._adjacency_for_round(round0 + i) for i in range(k)]
                ),
                self._adj_stack_s,
            )
            args = [
                self.params,
                self.agg_state,
                self._stage(self._rng, self._member_s),
                adj_stack,
                self._comp_dev,
                self._stage(np.asarray(round0, np.int32), self._repl_s),
                self._data,
            ]
            if self.program.faulted:
                args.insert(
                    5,
                    self._stage(
                        np.stack(
                            [self._alive_for_round(round0 + i) for i in range(k)]
                        ),
                        self._adj_stack_s,
                    ),
                )
            self.params, self.agg_state, rows = step(*args)
            rows = jax.device_get(rows)
            chunk_warmup = program_key not in self._warmed
            self._warmed.add(program_key)
            self.current_round = round0 + k
            elapsed = time.perf_counter() - t0
            self.round_times.extend([elapsed / k] * k)
            done += k
            for i in range(k):
                self._emit_phase_times(
                    round0 + i, "gang_fused", elapsed / k, chunk=k
                )
                # rows leaves are [B, chunk, ...]; "evaluated" is the same
                # unbatched cadence flag broadcast over the gang axis.
                if np.asarray(rows["evaluated"])[0, i]:
                    self._record_all(
                        round0 + i + 1,
                        {
                            m: v[:, i]
                            for m, v in rows.items()
                            if m != "evaluated"
                        },
                        verbose,
                    )
            if self._tracker is not None:
                self._tracker.end(allow=chunk_warmup)
            crossed_cadence = checkpoint_every and (
                self.current_round // checkpoint_every
                > round0 // checkpoint_every
            )
            if checkpoint_dir and (crossed_cadence or done >= rounds):
                self.save_checkpoint(checkpoint_dir)

    # ------------------------------------------------------------------
    # durability (durability/snapshot.py): the gang snapshots through the
    # same fsync'd path single runs use; every section carries the full
    # padded [B, ...] stack so a restore is value-only into the warm
    # compiled program (padding lanes replicate member 0's trajectory
    # exactly, so saving them costs bytes but buys bit-exactness).

    def save_checkpoint(self, directory: str) -> None:
        from murmura_tpu.durability.snapshot import save_run_snapshot

        t0 = time.perf_counter()
        save_run_snapshot(directory, self)
        for t in self.telemetry:
            if t is not None:
                t.checkpoint_event(
                    self.current_round, time.perf_counter() - t0,
                    action="save", path=str(directory),
                )

    def restore_checkpoint(self, directory: str) -> int:
        """Restore the full gang; returns the round to continue from."""
        from murmura_tpu.durability.snapshot import restore_run_snapshot

        t0 = time.perf_counter()
        round_num = restore_run_snapshot(directory, self)
        for t in self.telemetry:
            if t is not None:
                t.checkpoint_event(
                    round_num, time.perf_counter() - t0,
                    action="restore", path=str(directory),
                )
                t.emit(
                    "run_resumed", round=round_num, path=str(directory),
                    run_id=t.run_id,
                )
        return round_num

    def _durability_history(self):
        return {
            "gang_members": self.histories,
            "labels": [m.label for m in self.members],
        }

    def _durability_set_history(self, history) -> None:
        if not isinstance(history, dict) or "gang_members" not in history:
            raise ValueError(
                "snapshot carries no gang history — it was written by a "
                "single run; resume it with `murmura run --resume` instead"
            )
        labels = history.get("labels")
        ours = [m.label for m in self.members]
        if labels != ours:
            raise ValueError(
                f"gang snapshot members {labels} != this gang's {ours} — "
                "resuming into a different member set would misattribute "
                "every lane; rebuild with the sweep that wrote the snapshot"
            )
        self.histories = history["gang_members"]

    def _durability_extra_state(self):
        meta: Dict[str, Any] = {
            "gang": {
                "batch": self.batch,
                "gang_size": self.gang_size,
                "member_active": list(self.member_active),
                # Duplicated from the history payload so the member-set
                # identity check can run PRE-mutation (validate hook).
                "labels": [m.label for m in self.members],
            }
        }
        run_ids = [
            t.run_id if t is not None else None for t in self.telemetry
        ]
        if any(r is not None for r in run_ids):
            meta["telemetry_run_ids"] = run_ids
        return {}, meta

    def _durability_validate_extra(self, arrays, meta) -> None:
        gm = meta.get("gang")
        if gm is None:
            raise ValueError(
                "snapshot carries no gang section — it was written by a "
                "single run; resume it with `murmura run --resume` instead"
            )
        if int(gm["batch"]) != self.batch:
            raise ValueError(
                f"gang snapshot batch {gm['batch']} != this gang's "
                f"{self.batch} — the stacked state shapes cannot match"
            )
        labels = gm.get("labels")
        ours = [m.label for m in self.members]
        if labels is not None and labels != ours:
            # Same member count/batch but a different seed list has
            # identical stacked shapes — the shape guard cannot catch it,
            # and this must refuse BEFORE any lane is overwritten.
            raise ValueError(
                f"gang snapshot members {labels} != this gang's {ours} — "
                "resuming into a different member set would misattribute "
                "every lane; rebuild with the sweep that wrote the snapshot"
            )

    def _durability_restore_extra(self, arrays, meta) -> None:
        gm = meta["gang"]
        active = gm.get("member_active")
        if active is not None and len(active) == self.gang_size:
            self.member_active = [bool(a) for a in active]

    def reset_run(
        self,
        members: Sequence[GangMember],
        member_programs: Optional[Sequence[RoundProgram]] = None,
        telemetry_writers: Optional[Sequence] = None,
    ) -> None:
        """Value-only reset for a fresh run over the SAME warm compiled
        programs — zero recompiles on the next train().

        Two modes:

        - **Stage reset** (``member_programs=None`` — the `murmura
          frontier` stage loop): params/agg_state/RNG/histories return
          to round 0 from the retained host init arrays.  Constraints,
          each fail-loud: the gang must have been built with
          ``retain_init=True``, the new member list must be
          slot-for-slot the same seeds (data shards and init params
          were built per ORIGINAL seed), and only traced-input
          overrides (lr / attack_scale) may differ.
        - **Re-tenanting** (``member_programs`` given — the `murmura
          serve` admission path, docs/ROBUSTNESS.md "Serving"): each
          lane is spliced host-side with a NEW member's init params /
          agg state / data shards / RNG base from its own
          ``build_round_program`` output.  New seeds are allowed
          (the programs carry the per-seed values); the member count
          may be anything in ``1..batch`` (padding lanes replicate
          member 0, exactly like construction); duplicate labels are
          allowed (serve tenants are identified by submission id, not
          label).  The compiled executables are untouched — the new
          programs contribute VALUES only and are never traced, so
          every admitted tenant still runs member 0's traced math,
          which ``_check_member_compatible`` requires to be
          gang-batchable with the template's.
        """
        if member_programs is not None:
            self._admit_members(members, member_programs, telemetry_writers)
            return
        if telemetry_writers is not None:
            raise ValueError(
                "reset_run(telemetry_writers=...) is only meaningful on "
                "the re-tenanting path (member_programs given) — a stage "
                "reset keeps the gang's writers"
            )
        if self._init_params_host is None:
            raise ValueError(
                "reset_run() needs the gang built with retain_init=True "
                "(the stacked host init arrays are the reset source)"
            )
        members = list(members)
        if len(members) != self.gang_size:
            raise ValueError(
                f"reset_run got {len(members)} members for a gang of "
                f"{self.gang_size} — the bucket shape must not change "
                "(that is the whole point of the reset)"
            )
        for i, (old, new) in enumerate(zip(self.members, members)):
            if new.seed != old.seed:
                raise ValueError(
                    f"reset_run member {i} changes seed {old.seed} -> "
                    f"{new.seed} — data shards and init params were "
                    "built per original seed; only lr/attack_scale may "
                    "vary across stages"
                )
        labels = [m.label for m in members]
        if len(labels) != len(set(labels)):
            raise ValueError(
                f"reset_run members are not distinct (labels: {labels})"
            )
        self.members = members
        if "lr" in self.program.hp_inputs:
            self._data["hp_lr"] = jnp.asarray(np.asarray(
                [
                    members[i].lr if members[i].lr is not None
                    else self._base_lr
                    for i in self._indices
                ],
                np.float32,
            ))
        if "attack_scale" in self.program.hp_inputs:
            self._data["hp_attack_scale"] = jnp.asarray(np.asarray(
                [
                    members[i].attack_scale
                    if members[i].attack_scale is not None
                    else 1.0
                    for i in self._indices
                ],
                np.float32,
            ))
        self.params = jax.tree_util.tree_map(
            jnp.asarray, self._init_params_host
        )
        self.agg_state = {
            k: jnp.asarray(v) for k, v in self._init_agg_host.items()
        }
        self._rng = jnp.stack(
            [jax.random.PRNGKey(members[i].seed) for i in self._indices]
        )
        self._place_resident_state()
        self.histories = [empty_history() for _ in range(self.gang_size)]
        self._last_stats = [{} for _ in range(self.gang_size)]
        self.round_times = []
        self.current_round = 0
        self.member_active = [True] * self.gang_size

    def _admit_members(
        self,
        members: Sequence[GangMember],
        member_programs: Sequence[RoundProgram],
        telemetry_writers: Optional[Sequence],
    ) -> None:
        """The re-tenanting half of :meth:`reset_run` (serve/daemon.py):
        splice a new generation of tenants into the warm bucket's lanes
        — values only, the compiled [B, ...] executables never change
        shape (B = self.batch is fixed at construction; min_batch
        pre-grows it to the bucket's capacity)."""
        members = list(members)
        progs = list(member_programs)
        if len(progs) != len(members):
            raise ValueError("one RoundProgram per admitted member required")
        if not 1 <= len(members) <= self.batch:
            raise ValueError(
                f"cannot admit {len(members)} members into a bucket of "
                f"batch {self.batch} — the compiled shape is fixed; a "
                "larger tenant set needs a bigger bucket (a new compile)"
            )
        # The admitted programs are value sources for member 0's traced
        # math — the same batchability contract construction enforces.
        # The slot-0 member in the probe list is unused by the checker.
        _check_member_compatible(
            [self.program, *progs], [self.members[0], *members]
        )
        self.members = members
        self.gang_size = len(members)
        self._indices = list(range(self.gang_size)) + [0] * (
            self.batch - self.gang_size
        )
        stack = lambda get: _stack_trees(  # noqa: E731
            [get(p) for p in progs], self._indices
        )
        init_params_host = stack(lambda p: p.init_params)
        init_agg_host = stack(lambda p: p.init_agg_state)
        if self._init_params_host is not None:
            # Keep the stage-reset source coherent with the new tenants
            # (a frontier-style reset after an admission must reset to
            # the ADMITTED generation's init, not a stale one's).
            self._init_params_host = init_params_host
            self._init_agg_host = init_agg_host
        self.params = jax.tree_util.tree_map(jnp.asarray, init_params_host)
        self.agg_state = {
            k: jnp.asarray(v) for k, v in init_agg_host.items()
        }
        data = stack(lambda p: p.data_arrays)
        if "lr" in self.program.hp_inputs:
            data["hp_lr"] = np.asarray(
                [
                    members[i].lr if members[i].lr is not None
                    else self._base_lr
                    for i in self._indices
                ],
                np.float32,
            )
        if "attack_scale" in self.program.hp_inputs:
            data["hp_attack_scale"] = np.asarray(
                [
                    members[i].attack_scale
                    if members[i].attack_scale is not None
                    else 1.0
                    for i in self._indices
                ],
                np.float32,
            )
        self._data = {k: jnp.asarray(v) for k, v in data.items()}
        self._rng = jnp.stack(
            [jax.random.PRNGKey(members[i].seed) for i in self._indices]
        )
        self._place_resident_state()
        if telemetry_writers is not None:
            self.telemetry = list(telemetry_writers)
        if self.telemetry and len(self.telemetry) != self.gang_size:
            raise ValueError(
                f"{len(self.telemetry)} telemetry writers for "
                f"{self.gang_size} admitted members — pass one writer per "
                "member (or an empty list) when re-tenanting"
            )
        self.histories = [empty_history() for _ in range(self.gang_size)]
        self._last_stats = [{} for _ in range(self.gang_size)]
        self.round_times = []
        self.current_round = 0
        self.member_active = [True] * self.gang_size

    def freeze_member(self, member: int, reason: str) -> None:
        """Gracefully degrade one member's lane: recording stops (its
        history freezes at the current round), survivors continue, and
        the degradation is surfaced as a ``backend_degraded`` telemetry
        event.  The lane's compute continues — a vmap lane cannot be
        carved out of the compiled program, exactly like the padding
        members — so freezing never perturbs the surviving members'
        numbers.  Idempotent."""
        if not 0 <= member < self.gang_size:
            raise ValueError(
                f"member {member} out of range for gang of {self.gang_size}"
            )
        if not self.member_active[member]:
            return
        self.member_active[member] = False
        t = self.telemetry[member] if self.telemetry else None
        if t is not None:
            t.emit(
                "backend_degraded",
                member=self.members[member].label,
                reason=reason,
                round=self.current_round,
            )

    # ------------------------------------------------------------------

    def _emit_phase_times(self, round_idx, mode, wall_s, **extra) -> None:
        if self.program.pipelined:
            # The pipelined critical-path marker, mirrored from
            # Network._phase_overlap so gang members' reports render the
            # same critical-path decomposition as single runs.
            extra.setdefault("overlap", "pipelined")
        for t in self.telemetry:
            if t is not None:
                t.phase_times(
                    round_idx, mode, wall_s, gang=self.gang_size, **extra
                )

    def _record_all(self, round_num: int, metrics, verbose: bool) -> None:
        """Fan one evaluated round's [B, ...] metrics out to the per-member
        histories (padding members are dropped).  Uses the same
        record_round_metrics the single-run orchestrator uses, so a member
        row is byte-identical to its single run's."""
        in_deg = None
        if any(t is not None for t in self.telemetry):
            # The effective adjacency is member-shared — compute its
            # in-degree once per recorded round, not once per member.
            mask = np.asarray(self._adjacency_for_round(round_num - 1))
            if self.program.sparse:
                in_deg = self.topology.in_degree_from_edge_mask(mask)
            else:
                in_deg = mask.sum(axis=0)
        for s in range(self.gang_size):
            if not self.member_active[s]:
                # Frozen lane (freeze_member): the member's history stays
                # at its failure round; its compute still ran (vmap lane),
                # like a padding member's.
                continue
            member_metrics = {
                k: np.asarray(v)[s] for k, v in metrics.items()
            }
            self._last_stats[s] = record_round_metrics(
                self.histories[s], round_num, member_metrics,
                self.compromised, self.program.evidential,
                self.attack is not None,
            )
            t = self.telemetry[s] if self.telemetry else None
            if t is not None:
                t.round_event(
                    round_num, member_metrics, in_degree=in_deg,
                )
        if verbose:
            accs = np.asarray(metrics["accuracy"])[: self.gang_size]
            line = ", ".join(
                f"{self.members[s].label}={accs[s].mean():.4f}"
                for s in range(self.gang_size)
            )
            print(f"Round {round_num}: {line}", flush=True)

    def get_node_statistics(self, member: int = 0) -> Dict[int, Dict[str, Any]]:
        """Per-node aggregator statistics of one gang member."""
        n = self.program.num_nodes
        return {
            i: {k: float(v[i]) for k, v in self._last_stats[member].items()}
            for i in range(n)
        }


# ---------------------------------------------------------------------------
# Composition manifest (murmura_tpu/levers.py; `murmura check --compose`).
# The single source of truth for this lever's cross-feature verdicts —
# guard sites in config/schema.py and utils/factories.py cite
# refusal_reason() so user-facing messages and the analyzer's grid can
# never drift apart (MUR1400).
# ---------------------------------------------------------------------------
from murmura_tpu.levers import LeverManifest, composes, refuses

LEVER_MANIFEST = LeverManifest(
    name="sweep",
    module="murmura_tpu.core.gang",
    mesh_axes=("seed",),
    verdicts={
        "adaptive": composes(),
        "compression": composes(),
        "dmtt": composes(),
        "faults": composes(),
        "mobility": composes(),
        "pipeline": composes(),
        "population": refuses(
            "population does not compose with sweep (gang batching) "
            "yet — run cohort-streaming experiments unganged"
        ),
        # Lifted (ISSUE 16): the gang mesh grew a "param" role —
        # make_gang_param_mesh lays ("seed", "nodes", "param") and the
        # [S, N, P] stacked state shards on its trailing axis.
        "sharding": composes(),
        "sparse": composes(
            tpu_backend=(
                "sparse topologies (exponential/one_peer) are not "
                "gang-batchable on backend: tpu yet (the gang mesh "
                "lacks the [k, N] edge-mask sharding layout) — use "
                "backend: simulation for sparse gangs, or run sparse "
                "tpu experiments unganged"
            ),
        ),
        "staleness": composes(),
    },
)

"""Bounded-staleness gossip: the stale-tolerant exchange layer (ISSUE 13;
docs/ROBUSTNESS.md "Bounded staleness").

Every in-jit backend runs strictly synchronous rounds: a neighbor whose
payload misses the round — a straggler, a crashed node, a dropped link —
is simply masked out of the adjacency, so under churn the effective graph
thins and learning is gated on the slowest healthy path.  The
asynchronous quantized decentralized SGD line (arXiv:1910.12308, whose
quantized half is PR 7's codec) and delayed-averaging schemes
(arXiv:2002.01119) show convergence survives *bounded* delay: a receiver
may aggregate a neighbor's round-``(r - a)`` payload for small ``a``
instead of dropping the edge.

This module implements that as a **payload cache riding the round
program's carried state** under the reserved :data:`STALE_STATE_KEYS`
(the ``COMPRESS_STATE_KEYS`` pattern): because it lives in ``agg_state``,
the fused ``lax.scan`` carry, gang vmap, MUR900 snapshot completeness and
durability resume all cover it with no special cases.

Semantics (the docs/ROBUSTNESS.md table; machine-checked by MUR110x,
analysis/staleness.py):

- ``stale_cache`` [N, P] holds each sender's last broadcast that was
  **delivered** — it cleared the NaN/attack sentinels and reached at
  least one live receiver; ``stale_age`` [N] counts rounds since.
- A sender whose round-``r`` payload is *not* delivered (straggling,
  crashed, isolated by link drops, quarantined, scrubbed) has its
  base-topology in-edges re-added with weight
  ``discount ** age`` for every alive receiver, **provided** the cached
  payload is no older than ``max_staleness`` AND the sender was not
  scrubbed/quarantined *this round* — a caught row must not survive via
  its cached copy (the replay hole adaptive attackers would otherwise
  exploit; MUR1103 taint-kills it).
- Ages past ``max_staleness`` degrade to today's drop-the-edge behavior.

Granularity: the cache is **sender-granular** — one payload version per
sender per round, because every aggregation rule consumes the exchange as
a per-sender ``[N, P]`` tensor (aggregation/base.py) and no rule's math
can rank two versions of the same neighbor in one round.  Delivery is
therefore inferred from the folded adjacency itself (a sender with zero
live out-edges did not deliver), which yields the *relayed-gossip*
reading of per-edge link drops: a link-dropped edge whose sender still
reached some receiver stays dropped for the round (the fresh version did
not cross this edge and the cache may be newer than what this edge last
carried), while a fully-disrupted sender's last delivered payload — which
by construction exists somewhere in the network — is served to every
alive base-graph receiver.  This is exactly the jitted twin of the ZMQ
backend's deadline semantics with a bounded redelivery window: the
straggler schedule becomes a *delay* model (the payload lands next round
at age 1) instead of a pure drop.

Discount weighting: mean-family rules (fedavg, BALANCE/UBAR blends,
evidential trust) honor the fractional re-added weight directly;
selection rules (krum, median, trimmed mean) treat any positive weight as
a full candidate — a candidate cannot be 0.8-selected — so for them
``staleness_discount`` only controls nothing vs something.

Pipeline buffer reuse (ISSUE 14; core/pipeline.py): the cache-advance
invariant below — after the fold, ``stale_cache`` holds EXACTLY the
post-fold broadcast receivers aggregated this round — is what lets
pipelined rounds (``exchange.pipeline``) use this cache as their
broadcast buffer: round r+1's delayed aggregation reads the cache
before round r+1's fold advances it, getting round r's served payload
byte-for-byte, so a staleness-composed pipelined build carries no
duplicate ``pipe_bcast`` tensor (core/pipeline.pipeline_state_keys).
"""

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

# Reserved round-program-level agg_state keys (the DMTT_STATE_KEYS /
# COMPRESS_STATE_KEYS pattern, core/rounds.py): carried by the round step
# but never handed to the aggregation rule's state dict, and registered
# in durability/snapshot.RESERVED_AGG_STATE_KEY_GROUPS so the MUR900
# snapshot-completeness bijection — and therefore SIGKILL/--resume with a
# populated cache — covers them for free (MUR1100, analysis/staleness.py).
CACHE_KEY = "stale_cache"
AGE_KEY = "stale_age"
STALE_STATE_KEYS = (AGE_KEY, CACHE_KEY)


@dataclass(frozen=True)
class StalenessSpec:
    """Trace-time bounded-staleness spec (config: ``exchange:``).

    Static under trace — the staleness bound, discount and the base
    exchange graph are program structure; everything data-dependent (the
    cache, ages, which edges are stale this round) is traced values, so
    rounds never recompile across staleness variation (MUR1101).

    ``base_mask`` is the UNFAULTED exchange graph the re-added edges are
    drawn from: the static ``[N, N]`` topology mask (dense mode, zero
    diagonal) or the static all-active ``[k, N]`` edge mask (sparse
    exponential mode).  Staleness therefore requires a static topology —
    mobility's per-round G^t and one_peer's round-varying mask have no
    trace-time base graph (config/schema.py rejects them loudly).
    """

    max_staleness: int
    discount: float = 1.0
    base_mask: Optional[np.ndarray] = field(default=None, repr=False)

    def __post_init__(self):
        if self.max_staleness < 1:
            raise ValueError(
                f"max_staleness must be >= 1 to arm the stale exchange "
                f"(0 disables it at the config layer), got "
                f"{self.max_staleness}"
            )
        if not 0.0 < self.discount <= 1.0:
            raise ValueError(
                f"staleness_discount must be in (0, 1], got {self.discount}"
            )

    @property
    def age_cap(self) -> float:
        """Saturation value for the age counter: one past the bound is
        already "expired", so ages stay small exact integers in float32
        regardless of run length."""
        return float(self.max_staleness + 1)


def init_stale_state(
    spec: Optional[StalenessSpec], num_nodes: int, model_dim: int, dtype
) -> Dict[str, np.ndarray]:
    """Initial ``agg_state`` entries for a stale-enabled program.

    The cache starts empty (zeros) with every age at the expired sentinel
    ``max_staleness + 1``: an edge disrupted before its sender ever
    delivered degrades to the drop-the-edge behavior — round 0 has no
    payload to replay.
    """
    if spec is None:
        return {}
    return {
        CACHE_KEY: np.zeros((num_nodes, model_dim), dtype),
        AGE_KEY: np.full((num_nodes,), spec.age_cap, np.float32),
    }


def make_stale_fold(
    spec: StalenessSpec,
    sparse_offsets: Tuple[int, ...] = (),
    audit: bool = False,
):
    """Build the traced staleness fold for one round program.

    ``audit`` (telemetry.audit_taps — a trace-time constant, like the
    rules' ``ctx.audit``) additionally emits the per-node
    ``tap_stale_used`` / ``tap_stale_age`` stats.

    Returns ``fold(bcast, adj, state, recv_ok, scrub_ok) ->
    (bcast_eff, adj_eff, state_updates, stats)`` where:

    - ``bcast`` is the round's exchanged [N, P] tensor (post-attack,
      post-sentinel, post-codec-decode — finite by construction);
    - ``adj`` is the fully-folded adjacency ([N, N], or the [k, N] edge
      mask in sparse mode) with every fault already applied;
    - ``state`` holds the :data:`STALE_STATE_KEYS` entries;
    - ``recv_ok`` is the [N] RECEIVER eligibility mask — re-added edges
      must mirror the fresh folds' receiver side, so dead AND
      quarantined receivers (whose fresh edges were zeroed both ways)
      get no stale in-edges;
    - ``scrub_ok`` is the [N] product of this round's SENDER sentinel
      verdicts (1 = clean; 0 = quarantined or attack-scrubbed) — the
      gate that taint-kills a caught row's cached copy (MUR1103).

    All decisions are per-round *values* over [N]/[k, N] tensors: dense
    mode adds only elementwise math and one adjacency column sum; sparse
    mode only rolls of [N] rows (boundary ppermutes on a sharded node
    axis) — the stale program's traced collective inventory equals the
    drop-sync faulted program's (MUR1102).
    """
    sparse_offsets = tuple(int(o) for o in sparse_offsets)
    sparse = bool(sparse_offsets)
    base = np.asarray(spec.base_mask, dtype=np.float32)
    if sparse:
        if base.ndim != 2 or base.shape[0] != len(sparse_offsets):
            raise ValueError(
                f"sparse staleness base mask must be [k, N] with k = "
                f"{len(sparse_offsets)} offsets, got {base.shape}"
            )
    else:
        if base.ndim != 2 or base.shape[0] != base.shape[1]:
            raise ValueError(
                f"dense staleness base mask must be square [N, N], got "
                f"{base.shape}"
            )
        if np.diagonal(base).any():
            raise ValueError(
                "dense staleness base mask must have a zero diagonal "
                "(MUR301: re-added edges must never include self-loops)"
            )
    base_c = jnp.asarray(base)
    max_staleness = float(spec.max_staleness)
    age_cap = spec.age_cap
    discount = float(spec.discount)
    log_discount = float(np.log(discount)) if discount < 1.0 else 0.0

    def _sender_view(vec):  # murmura: traced
        """[k, N] sender-side view of a [N] node flag (the rounds.py
        helper): row j holds vec[(i + offsets[j]) % N] at column i."""
        return jnp.stack([jnp.roll(vec, -o) for o in sparse_offsets])

    def _sender_out_degree(adj):  # murmura: traced
        """[N] live out-edge count per SENDER under the folded adjacency:
        dense column sums, or rolls of the [k, N] edge rows back onto the
        sender index (aggregation/base.circulant_in_degree's construction
        — ppermute-only on a sharded node axis)."""
        if sparse:
            return sum(
                jnp.roll(adj[j].astype(jnp.float32), o)
                for j, o in enumerate(sparse_offsets)
            )
        return adj.sum(axis=0)

    def fold(bcast, adj, state, recv_ok, scrub_ok):  # murmura: traced
        # Static shape guard (trace-time, zero runtime cost): the base
        # mask's N axis must match this program's node axis — a [k, 1]
        # or wrong-N mask would silently BROADCAST against the [N] node
        # flags below and re-add edges of a different graph.
        n = recv_ok.shape[0]
        if base_c.shape[-1] != n:
            raise ValueError(
                f"staleness base mask covers {base_c.shape[-1]} nodes "
                f"but this program's node axis is {n}"
            )
        cache = state[CACHE_KEY]
        age = state[AGE_KEY].astype(jnp.float32)

        # Delivery inference: a sender with at least one live out-edge
        # put its payload in the network this round (the relay reading —
        # module docstring); zero live out-edges means straggle, death,
        # quarantine, scrub, or total link isolation, all of which the
        # preceding folds expressed as a zeroed column.
        deliver = (_sender_out_degree(adj) > 0).astype(jnp.float32)
        age_new = jnp.where(
            deliver > 0, 0.0, jnp.minimum(age + 1.0, age_cap)
        )
        # Usable = stale (not delivering) AND within the bound AND not
        # caught by a sentinel this round.  The scrub gate is the replay
        # hole's plug: a quarantined/scrubbed row's CACHED copy is
        # withheld for the round exactly like its fresh one (MUR1103
        # taint-kills the path).
        usable = (
            (1.0 - deliver)
            * scrub_ok
            * (age_new <= max_staleness).astype(jnp.float32)
        )
        if discount < 1.0:
            w_sender = usable * jnp.exp(age_new * log_discount)
        else:
            w_sender = usable

        # Re-added edges: base-graph in-edges of stale senders, gated by
        # receiver liveness.  Columns of delivering senders carry
        # w_sender = 0, so the sum never double-counts a live edge and a
        # link-dropped edge of a delivering sender stays dropped.
        if sparse:
            readd = base_c * recv_ok[None, :] * _sender_view(w_sender)
        else:
            readd = base_c * recv_ok[:, None] * w_sender[None, :]
        adj_eff = adj + readd

        # One payload version per sender: fresh rows pass through, stale
        # rows substitute the cached copy.  The cache then advances to
        # exactly what receivers could aggregate this round, so the
        # served representation and the stored one never diverge — the
        # invariant the pipelined rounds' buffer reuse relies on (module
        # docstring; core/pipeline.py reads this cache as pipe_bcast).
        fresh = deliver[:, None] > 0
        bcast_eff = jnp.where(fresh, bcast, cache.astype(bcast.dtype))
        updates = {
            CACHE_KEY: bcast_eff.astype(cache.dtype),
            AGE_KEY: age_new,
        }

        used = (readd > 0).astype(jnp.float32)
        # "Expired" counts AGE expiry only: the cached payload is older
        # than the bound (a round-0 cold cache reads as infinitely old,
        # which is the same operator fact).  Scrub-withheld senders are
        # NOT expired — their cache is fresh enough, just quarantined
        # for the round — and counting them here would over-report
        # cache expiry under attack (agg_stale_expired / the
        # bench_breakdown manifest are read as the age signal).
        expired = (
            (1.0 - deliver)
            * scrub_ok
            * (age_new > max_staleness).astype(jnp.float32)
        )
        if sparse:
            used_in = used.sum(axis=0)  # per-receiver stale in-edges
            expired_edges = (
                base_c * recv_ok[None, :] * _sender_view(expired)
            )
        else:
            used_in = used.sum(axis=1)
            expired_edges = base_c * recv_ok[:, None] * expired[None, :]
        stats = {
            "stale_used": used.sum(),
            "stale_expired": (expired_edges > 0).astype(jnp.float32).sum(),
        }
        if audit:
            # Per-node taps (telemetry.audit_taps): WHICH receivers
            # aggregated stale rows and HOW old each served sender's
            # payload was — elementwise over node-local rows plus the
            # same column-sum/roll shapes as the delivery inference, so
            # no collectives are added (MUR400/MUR1102).  The age tap is
            # gated on the sender actually having a re-added edge: a
            # usable cache nobody was eligible to receive (every
            # base-graph receiver dead/quarantined) was NOT served, and
            # the report's histogram documents 0 = fresh or unserved.
            served = (_sender_out_degree(used) > 0).astype(jnp.float32)
            stats["tap_stale_used"] = used_in
            stats["tap_stale_age"] = age_new * usable * served
        return bcast_eff, adj_eff, updates, stats

    return fold


# ---------------------------------------------------------------------------
# Composition manifest (murmura_tpu/levers.py; `murmura check --compose`).
# The single source of truth for this lever's cross-feature verdicts —
# guard sites in config/schema.py and utils/factories.py cite
# refusal_reason() so user-facing messages and the analyzer's grid can
# never drift apart (MUR1400).
# ---------------------------------------------------------------------------
from murmura_tpu.levers import LeverManifest, composes, refuses

LEVER_MANIFEST = LeverManifest(
    name="staleness",
    module="murmura_tpu.core.stale",
    state_keys_group="STALE_STATE_KEYS",
    stage="murmura.stale",
    verdicts={
        "adaptive": composes(),
        "compression": composes(),
        "dmtt": refuses(
            "bounded staleness does not compose with dmtt (the "
            "exchange graph is trust-gated per round; a cached row "
            "would bypass the round's claim verification)"
        ),
        # Staleness is DEFINED over the fault model: without it the
        # cache is dead state, so the dependency is a constraint tag.
        "faults": composes(
            requires_faults=(
                "exchange.max_staleness requires faults.enabled: true "
                "— without the fault model nothing ever misses a "
                "round, so the stale cache would be dead state in "
                "every program"
            ),
        ),
        "mobility": refuses(
            "bounded staleness does not compose with mobility: an "
            "edge leaving G^t is topology change, not a fault, and "
            "the re-add layer needs a static base graph baked at "
            "trace time"
        ),
        "pipeline": composes(),
        "population": refuses(
            "bounded staleness does not compose with population "
            "(the payload cache is per-slot [N, P] carried state; "
            "cohort swaps reassign node slots, so a cached row would "
            "be served into the wrong user's stream — the "
            "compression carried-state rationale)"
        ),
        "sharding": composes(),
        "sparse": composes(
            one_peer=(
                "bounded staleness does not compose with the one_peer "
                "topology (its active offset varies per round as mask "
                "values, so there is no static base edge mask to "
                "re-add from); use the exponential sparse family or a "
                "dense topology"
            ),
        ),
    },
)

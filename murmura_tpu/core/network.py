"""Network orchestrator (reference: murmura/core/network.py:16-312).

Drives the jitted round step across rounds, maintains the reference's
history schema (network.py:47-58), and exposes per-node aggregator
statistics (network.py:201-210).  The same orchestrator serves both the
``simulation`` backend (single device) and the ``tpu`` backend (node axis
sharded over a mesh) — only the compilation of the step differs.
"""

import contextlib
import time
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from murmura_tpu.attacks.base import Attack
from murmura_tpu.core.rounds import RoundProgram
from murmura_tpu.topology.base import Topology
from murmura_tpu.topology.dynamic import MobilityModel


def effective_adjacency(
    topology, mobility, fault_schedule, round_idx: int
) -> np.ndarray:
    """One round's effective [N, N] adjacency: mobility G^t (or the static
    mask) with the fault-schedule masks folded in host-side.  Shared by
    the single-run orchestrator and the gang dispatch path (core/gang.py)
    so the fold-in semantics cannot drift between them."""
    if mobility is not None:
        adj = mobility.adjacency_at(round_idx).astype(np.float32)
    else:
        adj = topology.mask()
    if fault_schedule is not None:
        # adj * alive_i * alive_j * link_mask * straggler columns —
        # folded host-side so the compiled program only ever sees a
        # differently-valued adjacency input.
        adj = fault_schedule.masked_adjacency(adj, round_idx)
    return adj


def effective_edge_mask(topology, fault_schedule, round_idx: int) -> np.ndarray:
    """One round's effective [k, N] sparse edge mask (topology/sparse.py):
    the SparseTopology schedule (static all-ones / one_peer single-offset)
    with the fault-schedule masks folded in host-side — the sparse twin of
    :func:`effective_adjacency`, consumed by round programs built with
    ``sparse_offsets``.  O(k·N) host work per round, never O(N^2)."""
    mask = topology.edge_mask(round_idx)
    if fault_schedule is not None:
        mask = fault_schedule.masked_edge_mask(
            mask, topology.offsets, round_idx
        )
    return mask


def effective_alive(fault_schedule, num_nodes: int, round_idx: int) -> np.ndarray:
    """[N] float32 alive mask for a faulted program's extra input (shared
    single-run/gang helper, see :func:`effective_adjacency`)."""
    if fault_schedule is not None:
        return fault_schedule.alive_at(round_idx)
    return np.ones(num_nodes, dtype=np.float32)


@contextlib.contextmanager
def sanitizer_scope(owner):
    """Arm the opt-in runtime sanitizers around one train() call.

    ``owner`` (Network or GangNetwork — one shared contract) provides
    ``transfer_guard``/``recompile_guard`` flags and receives ``_tracker``
    during the scope plus ``last_compile_report`` on exit.

    ``tpu.transfer_guard``: jax.transfer_guard("disallow") over the round
    loop — the loop's deliberate transfers are explicit (jnp.asarray /
    device_put / device_get) and pass; implicit traffic raises.
    ``tpu.recompile_guard``: a CompileTracker the round loops bracket each
    round with; post-warmup compiles raise RecompileError.
    """
    with contextlib.ExitStack() as stack:
        if owner.transfer_guard:
            from murmura_tpu.analysis.sanitizers import transfer_sanitizer

            stack.enter_context(transfer_sanitizer())
        if owner.recompile_guard:
            from murmura_tpu.analysis.sanitizers import track_compiles

            owner._tracker = stack.enter_context(track_compiles())
        try:
            yield
        finally:
            if owner._tracker is not None:
                owner.last_compile_report = list(owner._tracker.per_round)
            owner._tracker = None


def empty_history() -> Dict[str, List[Any]]:
    """The reference's history schema (network.py:47-58) — shared by the
    single-run orchestrator and the gang dispatch path (core/gang.py) so
    the two cannot drift."""
    return {
        "round": [],
        "mean_accuracy": [],
        "std_accuracy": [],
        "mean_loss": [],
        "honest_accuracy": [],
        "compromised_accuracy": [],
        "mean_vacuity": [],
        "mean_entropy": [],
        "mean_strength": [],
    }


def record_round_metrics(
    history: Dict[str, List[Any]],
    round_num: int,
    metrics: Dict[str, np.ndarray],
    compromised: np.ndarray,
    evidential: bool,
    has_attack: bool,
) -> Dict[str, np.ndarray]:
    """Append one evaluated round to ``history``; returns the round's raw
    per-node ``agg_*`` stats (the ``get_node_statistics`` source).

    This is the single source of truth for how device metrics become
    history floats — the gang-parity contract (a gang member's history is
    byte-identical to its single run, tests/test_gang.py) rides on both
    paths sharing it.
    """
    acc = np.asarray(metrics["accuracy"])
    loss = np.asarray(metrics["loss"])
    comp = np.asarray(compromised) > 0

    history["round"].append(round_num)
    history["mean_accuracy"].append(float(acc.mean()))
    history["std_accuracy"].append(float(acc.std()))
    history["mean_loss"].append(float(loss.mean()))
    if has_attack and comp.any():
        history["honest_accuracy"].append(float(acc[~comp].mean()))
        history["compromised_accuracy"].append(float(acc[comp].mean()))
    if evidential:
        history["mean_vacuity"].append(float(np.asarray(metrics["vacuity"]).mean()))
        history["mean_entropy"].append(float(np.asarray(metrics["entropy"]).mean()))
        history["mean_strength"].append(
            float(np.asarray(metrics["strength"]).mean())
        )

    last_stats = {
        k[len("agg_"):]: np.asarray(v)
        for k, v in metrics.items()
        if k.startswith("agg_")
    }
    # Per-round rule statistics (acceptance rates, thresholds, trust...)
    # accumulate in the history under their agg_ keys — the reference
    # buries these in aggregator-internal lists surfaced only via
    # get_statistics() (e.g. balance.py:46-53).
    for k, v in last_stats.items():
        arr = np.asarray(v, dtype=np.float64)
        history.setdefault(f"agg_{k}", []).append(
            float(arr.mean()) if arr.ndim else float(arr)
        )
    return last_stats


class Network:
    """Orchestrates decentralized FL over a compiled round program."""

    def __init__(
        self,
        program: RoundProgram,
        topology: Topology,
        attack: Optional[Attack] = None,
        mobility: Optional[MobilityModel] = None,
        backend: str = "simulation",
        mesh=None,
        seed: int = 42,
        donate: bool = True,
        profile_dir: Optional[str] = None,
        recompile_guard: bool = False,
        transfer_guard: bool = False,
        fault_schedule=None,
        telemetry=None,
    ):
        self.program = program
        self.topology = topology
        self.attack = attack
        self.mobility = mobility
        self.backend = backend
        self.seed = seed
        self.profile_dir = profile_dir
        # Operational fault model (faults/schedule.py): per-round alive and
        # link masks fold into the adjacency input and the faulted
        # program's alive argument — values only, no recompiles (the same
        # trick the compromised mask and mobility G^t already use).
        self.fault_schedule = fault_schedule
        # Telemetry (telemetry/writer.py, docs/OBSERVABILITY.md): when a
        # writer is attached, the round loops emit phase_times / round /
        # memory / checkpoint events and each train() call re-finalizes
        # the run manifest.  None (default) leaves every loop byte-for-byte
        # on its pre-telemetry path — histories and compiled programs are
        # identical (tested, tests/test_telemetry.py).
        self.telemetry = telemetry
        self._profile_window_active = False
        # round_idx -> host in-degree of the round's effective adjacency,
        # captured as a byproduct of the dispatch loop's own adjacency
        # computation so _record's round events never re-run the mobility
        # G^t / fault masking (O(N^2) host work) inside the timed window.
        self._in_degree_cache: Dict[int, np.ndarray] = {}
        if fault_schedule is not None and not program.faulted:
            raise ValueError(
                "A fault schedule was supplied but the round program was "
                "built without faults (build_round_program(faults=...)); "
                "the alive mask would silently never reach the round step"
            )
        # Opt-in runtime sanitizers (tpu.recompile_guard / tpu.transfer_guard;
        # analysis/sanitizers.py).  Backend-independent: the simulation
        # backend exercises them in CI where no chip is at stake.
        self.recompile_guard = recompile_guard
        self.transfer_guard = transfer_guard
        self._tracker = None
        # (label, compiles) per round bracket from the last guarded train()
        # — diagnostics for tests and post-mortems.
        self.last_compile_report: Optional[List] = None
        # Programs that have already executed once (and thus compiled):
        # "step", "eval", ("fused", chunk, eval_every).  A compile in any
        # later round is a post-warmup recompile and fails the guard.
        self._warmed: set = set()

        n = program.num_nodes
        if topology.num_nodes != n:
            raise ValueError(
                f"Topology has {topology.num_nodes} nodes, data/model stack has {n}"
            )
        if program.sparse:
            from murmura_tpu.topology.sparse import SparseTopology

            if not isinstance(topology, SparseTopology):
                raise ValueError(
                    "the round program was built with sparse_offsets but "
                    "the topology is not a SparseTopology — the program's "
                    "adjacency input is a [k, N] edge mask only a sparse "
                    "topology can produce"
                )
            if tuple(topology.offsets) != tuple(program.sparse_offsets):
                raise ValueError(
                    f"sparse topology offsets {tuple(topology.offsets)} != "
                    f"round program offsets {tuple(program.sparse_offsets)}"
                )
            if mobility is not None:
                raise ValueError(
                    "sparse exchange mode does not compose with mobility "
                    "(G^t is a dense per-round graph)"
                )

        self.compromised = (
            attack.compromised.astype(np.float32)
            if attack is not None
            else np.zeros(n, dtype=np.float32)
        )

        if backend == "tpu":
            from murmura_tpu.parallel.mesh import (
                adj_stack_sharding,
                make_shardings,
                shard_eval_step,
                shard_step,
            )

            if mesh is None:
                from murmura_tpu.parallel.mesh import make_mesh

                mesh = make_mesh()
            self.mesh = mesh
            self._step = shard_step(program.train_step, program, mesh, donate=donate)
            self._eval = shard_eval_step(program.eval_step, program, mesh)
            self._node_s, self._repl = make_shardings(mesh)
            if program.sparse:
                # Sparse adjacency inputs carry the node axis SECOND
                # ([k, N] per-round mask, [chunk, k, N] fused stack).
                from murmura_tpu.parallel.mesh import (
                    edge_mask_sharding,
                    sparse_adj_stack_sharding,
                )

                self._adj_s = edge_mask_sharding(mesh)
                self._adj_stack_s = sparse_adj_stack_sharding(mesh)
            else:
                self._adj_s = self._node_s
                self._adj_stack_s = adj_stack_sharding(mesh)
        else:
            self.mesh = None
            donate_argnums = (0, 1) if donate else ()
            self._step = jax.jit(program.train_step, donate_argnums=donate_argnums)
            self._eval = jax.jit(program.eval_step)
            self._node_s = self._repl = None
            self._adj_s = self._adj_stack_s = None
        if transfer_guard and jax.process_count() > 1:
            raise ValueError(
                "tpu.transfer_guard is single-host only: multi-host "
                "resident state cannot be explicitly pre-placed with "
                "jax.device_put, so the guard would flag the legitimate "
                "cross-process staging"
            )

        # Mutable run state
        self.params = program.init_params
        self.agg_state = {k: jnp.asarray(v) for k, v in program.init_agg_state.items()}
        self._data = {k: jnp.asarray(v) for k, v in program.data_arrays.items()}
        self._place_resident_state()
        # Base key; round r always runs with fold_in(base, r), so the stream
        # is a pure function of (seed, round) — identical across per-round
        # and fused dispatch, any rounds_per_dispatch chunking, and
        # checkpoint resume points.
        self._rng = jax.random.PRNGKey(seed)
        # Jitted so its internal constants compile into the program instead
        # of landing as per-round implicit host->device transfers (eager
        # fold_in stages them eagerly and trips tpu.transfer_guard).
        self._fold_in = jax.jit(jax.random.fold_in)
        # Deferred-quiesce scalar fetch (see _train_rounds): built once here
        # so repeated defer_metrics train() calls reuse one compile cache
        # instead of paying a fresh XLA compile per call.
        self._first_scalar = jax.jit(
            lambda tree: jax.tree_util.tree_leaves(tree)[0].ravel()[0]
        )

        # History schema parity (reference: network.py:47-58)
        self.history: Dict[str, List[Any]] = empty_history()
        self._last_stats: Dict[str, np.ndarray] = {}
        self._donate = donate
        self._fused_cache: Dict[Any, Any] = {}
        self.round_times: List[float] = []
        # Persistent round counter: schedules (BALANCE/trust tightening,
        # evidential-loss annealing) and the mobility G^t keep advancing
        # across successive train() calls and checkpoint resumes.
        self.current_round = 0

    def _place_resident_state(self) -> None:
        """Explicitly place params/agg_state/data on the mesh (tpu backend,
        single host).

        Without this the first sharded jit call reshards every single-device
        input implicitly — a device-to-device transfer per buffer that (a)
        trips tpu.transfer_guard and (b) repeats after every checkpoint
        restore.  Multi-host placement stays with the jit staging path
        (device_put cannot target non-addressable devices).
        """
        if self._node_s is None or jax.process_count() > 1:
            return
        from murmura_tpu.parallel.mesh import (
            _shard_leading_axis,
            mesh_param_shards,
            state_sharding_specs,
        )

        if self.mesh is not None and mesh_param_shards(self.mesh) > 1:
            # Param-sharded placement: [N, flat_dim] leaves (the stale
            # cache, pipeline buffers, EF residual) land column-split
            # over the "param" axis — the layout the jit expects, so the
            # first call (and every restore) stays reshard-free.
            flat_dim = self.program.flat_dim or self.program.model_dim
            place = lambda tree: jax.device_put(  # noqa: E731
                tree, state_sharding_specs(tree, self.mesh, flat_dim)
            )
            self.params = place(self.params)
            self.agg_state = place(self.agg_state)
            self._data = jax.device_put(
                self._data,
                _shard_leading_axis(self._data, self._node_s, self._repl),
            )
            return
        place = lambda tree: jax.device_put(  # noqa: E731
            tree, _shard_leading_axis(tree, self._node_s, self._repl)
        )
        self.params = place(self.params)
        self.agg_state = place(self.agg_state)
        self._data = place(self._data)

    def _stage(self, value, sharding):
        """Stage one loop input explicitly: plain device transfer off-mesh,
        ``jax.device_put`` to the target sharding on the tpu backend (jit
        would otherwise reshard implicitly — see _place_resident_state).

        Multi-host keeps the jit ``in_shardings`` staging path: device_put
        to a non-addressable sharding is a blocking cross-process broadcast
        collective per call (and unsupported on some backends), which would
        cost more per round than the implicit reshard it avoids.
        """
        if sharding is None or jax.process_count() > 1:
            return jnp.asarray(value)
        return jax.device_put(value, sharding)

    def _adjacency_for_round(self, round_idx: int) -> np.ndarray:
        if self.program.sparse:
            mask = effective_edge_mask(
                self.topology, self.fault_schedule, round_idx
            )
            if self.telemetry is not None:
                self._in_degree_cache[round_idx] = (
                    self.topology.in_degree_from_edge_mask(mask)
                )
            return mask
        adj = effective_adjacency(
            self.topology, self.mobility, self.fault_schedule, round_idx
        )
        if self.telemetry is not None:
            self._in_degree_cache[round_idx] = np.asarray(adj).sum(axis=0)
        return adj

    def _alive_for_round(self, round_idx: int) -> np.ndarray:
        """[N] float32 alive mask for a faulted program's extra input."""
        return effective_alive(
            self.fault_schedule, self.program.num_nodes, round_idx
        )

    def exchange_cost_analysis(self) -> Dict[str, float]:
        """Analytic per-round exchange accounting (docs/PERFORMANCE.md).

        ``exchange_bytes_per_round`` is edges x the bytes of the
        representation that actually crosses an edge — the full [P] row in
        the resident dtype, or the compressed payload (int8 blocks+scales /
        top-k values+indices) when the program was built with a
        ``compression`` spec.  The bench's compression variants emit this
        next to the measured ``cost{flops,bytes,mfu}`` line so the bytes
        reduction is committed, attributable history (the MUR206 ethos),
        not a claim.
        """
        import jax.numpy as _jnp

        p = self.program.model_dim
        leaf = jax.tree_util.tree_leaves(self.program.init_params)[0]
        itemsize = _jnp.dtype(leaf.dtype).itemsize
        if self.program.sparse:
            edges = float(
                np.asarray(
                    effective_edge_mask(
                        self.topology, self.fault_schedule, self.current_round
                    )
                ).sum()
            )
        else:
            edges = float(
                np.asarray(
                    effective_adjacency(
                        self.topology, self.mobility, self.fault_schedule,
                        self.current_round,
                    )
                ).sum()
            )
        comp = self.program.compression
        uncompressed = float(p * itemsize)
        payload = (
            float(comp.payload_bytes(p, itemsize))
            if comp is not None
            else uncompressed
        )
        return {
            "edges": edges,
            "payload_bytes_per_edge": payload,
            "uncompressed_bytes_per_edge": uncompressed,
            "exchange_bytes_per_round": edges * payload,
            "uncompressed_exchange_bytes_per_round": edges * uncompressed,
            "exchange_bytes_reduction": (
                uncompressed / payload if payload else None
            ),
        }

    def _step_compiled(self):
        """AOT-compile the train step on the shapes ``train`` runs.

        Memoized so :meth:`step_cost_analysis` and
        :meth:`step_memory_analysis` (and any future AOT introspection)
        share one compile — the jit cache is keyed on the same shapes, so
        ``train`` afterwards still hits it and nothing executes here.
        """
        compiled = getattr(self, "_aot_compiled", None)
        if compiled is not None:
            return compiled
        args = [
            self.params,
            self.agg_state,
            jax.random.PRNGKey(0),
            jnp.asarray(self._adjacency_for_round(self.current_round)),
            jnp.asarray(self.compromised),
            jnp.asarray(0.0, dtype=jnp.float32),
            self._data,
        ]
        if self.program.faulted:
            args.insert(5, jnp.asarray(self._alive_for_round(self.current_round)))
        compiled = self._step.lower(*args).compile()
        self._aot_compiled = compiled
        return compiled

    def step_cost_analysis(self) -> Dict[str, float]:
        """XLA cost analysis of the compiled train step (flops, bytes).

        Uses the AOT path on the same shapes ``train`` runs, so the compile
        cache is hit and nothing executes.  Basis for the bench's MFU
        estimate (flops/round x rounds/sec / peak chip flops) and the
        runtime twin of the per-aggregator budget sweep
        (``murmura check --ir``, analysis/budgets.py — which also owns the
        cross-version result normalization used here).  Covers the
        per-round program only — eval is compiled separately and runs on the
        ``eval_every`` cadence, so its flops are not part of a round.
        """
        from murmura_tpu.analysis.budgets import normalize_cost_analysis

        return normalize_cost_analysis(self._step_compiled().cost_analysis())

    def step_memory_analysis(self) -> Dict[str, float]:
        """XLA memory analysis of the compiled train step (bytes).

        Runtime twin of the MUR1500 memory-budget sweep (``murmura check
        --memory``, analysis/memory.py — which owns the cross-version
        normalization used here).  Shares the AOT compile with
        :meth:`step_cost_analysis`, so asking for both costs one compile.
        ``peak_bytes`` is the static accounting identity
        arguments + outputs - aliased + temporaries + generated code; on
        backends whose ``memory_analysis()`` lacks a field it contributes
        zero rather than failing.
        """
        from murmura_tpu.analysis.memory import normalize_memory_analysis

        return normalize_memory_analysis(
            self._step_compiled().memory_analysis()
        )

    def train(
        self,
        rounds: int,
        verbose: bool = False,
        eval_every: int = 1,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 0,
        defer_metrics: bool = False,
        rounds_per_dispatch: int = 1,
    ) -> Dict[str, List[Any]]:
        """Run the FL rounds (reference: network.py:60-94).

        Evaluation is a separately compiled program run only on rounds that
        are recorded (``eval_every``) — unlike the reference, whose loop
        evaluates every round (network.py:141-199), skipped-eval rounds pay
        zero eval FLOPs here.

        Args:
            checkpoint_dir: if set, write a checkpoint after every
                ``checkpoint_every`` rounds (and at the end). No reference
                counterpart — the reference keeps all state in memory.
            defer_metrics: keep per-round metrics on device and record them
                only after the last round.  Removes the host sync from the
                round loop so XLA queues rounds back-to-back (throughput
                mode — history is identical, per-round ``round_times``
                become dispatch times rather than wall round times).
                Only meaningful for per-round dispatch: with
                ``rounds_per_dispatch > 1`` the fused scan already fetches
                metrics once per chunk, so ``defer_metrics`` is ignored
                (a warning is emitted).
            rounds_per_dispatch: fuse this many rounds into one
                ``lax.scan`` program (core.rounds.build_multi_round) — the
                round loop lives on the device and history comes back as
                stacked arrays per chunk.  Eval still runs only on the
                ``eval_every`` cadence (``lax.cond`` inside the scan).
                Checkpoints land on chunk boundaries.  1 = per-round
                dispatch (default).
        """
        from murmura_tpu.analysis.sanitizers import CompileTracker

        profile = self.profile_dir is not None
        if profile:
            jax.profiler.start_trace(self.profile_dir)
        # Passive compile accounting independent of the recompile guard:
        # the manifest's `compiles` counter feeds the offline metrics fold
        # (telemetry/metrics.py), so a scrape can surface recompile churn
        # without arming the raising sanitizer.
        compile_probe = CompileTracker()
        try:
            with self._sanitizer_scope():
                if rounds_per_dispatch > 1:
                    if defer_metrics:
                        import warnings

                        warnings.warn(
                            "defer_metrics is ignored when rounds_per_dispatch > 1: "
                            "the fused scan already syncs metrics once per chunk",
                            stacklevel=2,
                        )
                    self._train_fused(
                        rounds, verbose, eval_every, checkpoint_dir,
                        checkpoint_every, rounds_per_dispatch,
                    )
                else:
                    self._train_rounds(
                        rounds, verbose, eval_every, checkpoint_dir,
                        checkpoint_every, defer_metrics,
                    )
        finally:
            if profile:
                jax.profiler.stop_trace()
            # Close a still-open telemetry profile window (the run may end
            # mid-window) and commit the manifest: each train() call
            # re-finalizes, so the manifest is always the latest complete
            # view even across checkpoint/resume segments.
            self._profile_window_stop(self.current_round, force=True)
            if self.telemetry is not None:
                compiled = compile_probe.total
                if compiled:
                    self.telemetry.add_counters({"compiles": compiled})
                self.telemetry.finalize(history=self.history)
        return self.history

    # ------------------------------------------------------------------
    # telemetry hooks (telemetry/writer.py; docs/OBSERVABILITY.md)

    def _profile_window_start(self, round_idx: int, span: int = 1) -> None:
        """Open the telemetry profiler window at its scheduled round.

        Skipped while the legacy whole-train trace (``tpu.profile_dir``)
        is active — jax.profiler traces do not nest.  On the fused path
        this is called at chunk boundaries with ``span`` = chunk size, so
        the window opens at the first chunk OVERLAPPING it — a start round
        strictly inside a chunk must not be skipped (the rounds
        [round_idx, round_idx + span) dispatch as one program; containment
        of round_idx alone would miss it).
        """
        t = self.telemetry
        if (
            t is None
            or not t.profile_rounds
            or self._profile_window_active
            or self.profile_dir is not None
        ):
            return
        end = t.profile_start_round + t.profile_rounds
        if round_idx < end and round_idx + span > t.profile_start_round:
            trace_dir = t.profile_dir or str(t.run_dir / "trace")
            jax.profiler.start_trace(trace_dir)
            self._profile_window_active = True
            t.emit(
                "profile", status="started", round=round_idx,
                trace_dir=trace_dir,
            )

    def _profile_window_stop(self, next_round: int, force: bool = False) -> None:
        t = self.telemetry
        if t is None or not self._profile_window_active:
            return
        if force or next_round >= t.profile_start_round + t.profile_rounds:
            jax.profiler.stop_trace()
            self._profile_window_active = False
            t.emit(
                "profile", status="stopped", round=next_round - 1,
                trace_dir=t.profile_dir or str(t.run_dir / "trace"),
            )

    def _phase_overlap(self) -> Dict[str, str]:
        """Extra phase_times fields describing in-dispatch concurrency.

        A pipelined program (exchange.pipeline) runs train and the
        delayed exchange+aggregate concurrently inside every dispatch:
        the recorded wall time is the round's CRITICAL PATH, and the
        per-phase named_scope brackets overlap in profiler-trace time —
        summing them would double-count the hidden exchange.  The
        ``overlap`` marker lets ``murmura report`` render a
        critical-path decomposition instead (telemetry/report.py);
        serialized programs emit no marker, keeping their phase_times
        records byte-identical to previous releases (pinned by
        tests/test_pipeline.py).
        """
        if self.program.pipelined:
            return {"overlap": "pipelined"}
        return {}

    def _sanitizer_scope(self):
        """The shared :func:`sanitizer_scope` over this orchestrator."""
        return sanitizer_scope(self)

    def _fused_step(self, chunk: int, eval_every: int):
        """Compiled fused multi-round program, cached per (chunk, cadence)."""
        key = (chunk, eval_every)
        if key not in self._fused_cache:
            from murmura_tpu.core.rounds import build_multi_round

            fn = build_multi_round(self.program, chunk, eval_every)
            if self.backend == "tpu":
                from murmura_tpu.parallel.mesh import shard_multi_round

                self._fused_cache[key] = shard_multi_round(
                    fn, self.program, self.mesh, donate=self._donate
                )
            else:
                donate_argnums = (0, 1) if self._donate else ()
                self._fused_cache[key] = jax.jit(
                    fn, donate_argnums=donate_argnums
                )
        return self._fused_cache[key]

    def _train_fused(
        self, rounds, verbose, eval_every, checkpoint_dir, checkpoint_every,
        chunk,
    ) -> None:
        comp = self._stage(self.compromised, self._node_s)
        done = 0
        while done < rounds:
            k = min(chunk, rounds - done)
            step = self._fused_step(k, eval_every)
            round0 = self.current_round
            self._profile_window_start(round0, span=k)
            t0 = time.perf_counter()
            program_key = ("fused", k, eval_every)
            if self._tracker is not None:
                self._tracker.begin(f"rounds {round0}..{round0 + k - 1}")
            adj_stack = self._stage(
                np.stack(
                    [self._adjacency_for_round(round0 + i) for i in range(k)]
                ),
                self._adj_stack_s,
            )
            step_args = [
                self.params,
                self.agg_state,
                self._stage(self._rng, self._repl),
                adj_stack,
                comp,
                self._stage(np.asarray(round0, np.int32), self._repl),
                self._data,
            ]
            if self.program.faulted:
                # Per-round alive masks ride the scan like the adj stack.
                step_args.insert(
                    5,
                    self._stage(
                        np.stack(
                            [self._alive_for_round(round0 + i) for i in range(k)]
                        ),
                        self._adj_stack_s,
                    ),
                )
            self.params, self.agg_state, rows = step(*step_args)
            rows = jax.device_get(rows)
            chunk_warmup = program_key not in self._warmed
            self._warmed.add(program_key)
            self.current_round = round0 + k
            # Keep round_times in per-round units across dispatch modes:
            # one amortized entry per round, not one per chunk (the chunk
            # runs as a single device program, so per-round wall times
            # inside it are not observable).
            elapsed = time.perf_counter() - t0
            self.round_times.extend([elapsed / k] * k)
            done += k
            if self.telemetry is not None:
                # One amortized phase_times record per round — per-round
                # wall times inside a single device dispatch are not
                # observable, so the chunk's elapsed/k is the honest unit
                # (same semantics as round_times; mode records the split).
                for i in range(k):
                    self.telemetry.phase_times(
                        round0 + i, "fused", elapsed / k, chunk=k,
                        **self._phase_overlap(),
                    )
                self.telemetry.memory_event(self.current_round - 1)
                self._profile_window_stop(self.current_round)
            for i in range(k):
                if rows["evaluated"][i]:
                    self._record(
                        round0 + i + 1,
                        {
                            m: v[i]
                            for m, v in rows.items()
                            if m != "evaluated"
                        },
                        verbose,
                    )
            # After the bookkeeping: a guard raise must leave
            # current_round/history aligned with the already-advanced
            # (donated) params, or a catch-and-checkpoint caller would
            # record k-rounds-stale metadata beside the new state.
            if self._tracker is not None:
                self._tracker.end(allow=chunk_warmup)
            crossed_cadence = checkpoint_every and (
                self.current_round // checkpoint_every > round0 // checkpoint_every
            )
            if checkpoint_dir and (crossed_cadence or done >= rounds):
                self.save_checkpoint(checkpoint_dir)

    def _train_rounds(
        self, rounds, verbose, eval_every, checkpoint_dir, checkpoint_every,
        defer_metrics=False,
    ) -> None:
        comp = self._stage(self.compromised, self._node_s)
        last_saved = -1
        pending: List[Any] = []
        for _ in range(rounds):
            round_idx = self.current_round
            self._profile_window_start(round_idx)
            t0 = time.perf_counter()
            warmup = "step" not in self._warmed
            if self._tracker is not None:
                self._tracker.begin(f"round {round_idx}")
            adj = self._stage(self._adjacency_for_round(round_idx), self._adj_s)
            # 0-d numpy staging: scalar conversions from numpy ARRAYS are
            # explicit transfers (transfer_guard-clean); Python/numpy
            # scalars would be implicit and trip the sanitizer.
            step_key = self._stage(
                self._fold_in(
                    self._rng, jnp.asarray(np.asarray(round_idx, np.uint32))
                ),
                self._repl,
            )
            step_args = [
                self.params,
                self.agg_state,
                step_key,
                adj,
                comp,
                self._stage(np.asarray(round_idx, np.float32), self._repl),
                self._data,
            ]
            if self.program.faulted:
                step_args.insert(
                    5, self._stage(self._alive_for_round(round_idx), self._node_s)
                )
            self.params, self.agg_state, agg_metrics = self._step(*step_args)
            self._warmed.add("step")
            self.current_round = round_idx + 1
            if self.current_round % eval_every == 0:
                # Close the step phase before eval runs: eval's own warmup
                # must not whitelist a post-warmup step recompile landing
                # in the same round (and vice versa).
                if self._tracker is not None:
                    self._tracker.mark(allow=warmup)
                warmup = "eval" not in self._warmed
                metrics = {**self._eval(self.params, self._data), **agg_metrics}
                self._warmed.add("eval")
                if defer_metrics:
                    pending.append((self.current_round, metrics))
                else:
                    metrics = jax.device_get(metrics)
                    self._record(self.current_round, metrics, verbose)
            if self._tracker is not None:
                self._tracker.end(allow=warmup)
            wall = time.perf_counter() - t0
            self.round_times.append(wall)
            if self.telemetry is not None:
                self.telemetry.phase_times(
                    round_idx, "per_round", wall,
                    evaluated=bool(self.current_round % eval_every == 0),
                    deferred=bool(defer_metrics),
                    **self._phase_overlap(),
                )
                self.telemetry.memory_event(round_idx)
                self._profile_window_stop(self.current_round)
            if (
                checkpoint_dir
                and checkpoint_every
                and self.current_round % checkpoint_every == 0
            ):
                self._drain_pending(pending, verbose)  # checkpointed history
                self.save_checkpoint(checkpoint_dir)   # must be complete
                last_saved = self.current_round
        self._drain_pending(pending, verbose)
        if defer_metrics and rounds > 0:
            # Quiesce: in deferred mode the only host syncs are the drained
            # metrics, which cover rounds only up to the last eval — any
            # later rounds are still in flight when the loop exits (and this
            # environment's block_until_ready does not block).  Fetching one
            # scalar that depends on the final params makes train() return
            # only after every dispatched round has executed, so wall-clock
            # timing around a deferred train() call is honest.
            if jax.process_count() == 1:
                # Jitted: eager [0]-indexing stages its slice start as an
                # implicit scalar transfer and trips tpu.transfer_guard.
                jax.device_get(self._first_scalar(self.params))
            else:
                # Multi-host: params are sharded across non-addressable
                # devices, so a scalar fetch would raise; block on the
                # sharded tree instead (real TPU runtimes do block here).
                jax.block_until_ready(self.params)
        if checkpoint_dir and rounds > 0 and self.current_round != last_saved:
            self.save_checkpoint(checkpoint_dir)

    def _drain_pending(self, pending: List[Any], verbose: bool) -> None:
        for round_num, metrics in pending:
            self._record(round_num, jax.device_get(metrics), verbose)
        pending.clear()

    def save_checkpoint(self, directory: str) -> None:
        """Snapshot the complete run state to ``directory``
        (durability/snapshot.py over the fsync'd utils/checkpoint.py
        path)."""
        from murmura_tpu.durability.snapshot import save_run_snapshot

        t0 = time.perf_counter()
        save_run_snapshot(directory, self)
        if self.telemetry is not None:
            self.telemetry.checkpoint_event(
                self.current_round, time.perf_counter() - t0,
                action="save", path=str(directory),
            )

    def restore_checkpoint(self, directory: str) -> int:
        """Restore run state; returns the round to continue from.

        Value-only into the (possibly warm) compiled program — zero extra
        compiles, donation-safe (restored buffers are fresh).  Emits a
        ``run_resumed`` telemetry event so a resumed run is visible in
        the event stream it APPENDS to (the writer must have been opened
        with ``resume=True`` — factories.build_network_from_config does
        this automatically when a checkpoint exists).
        """
        from murmura_tpu.durability.snapshot import restore_run_snapshot

        t0 = time.perf_counter()
        round_num = restore_run_snapshot(directory, self)
        if self.telemetry is not None:
            self.telemetry.checkpoint_event(
                round_num, time.perf_counter() - t0,
                action="restore", path=str(directory),
            )
            self.telemetry.emit(
                "run_resumed", round=round_num, path=str(directory),
                run_id=self.telemetry.run_id,
            )
        return round_num

    # ------------------------------------------------------------------
    # durability hooks (durability/snapshot.py): what a complete snapshot
    # of THIS orchestrator carries beyond the base sections.  Subclasses
    # (PopulationNetwork, and the gang twin in core/gang.py) override.

    def _durability_history(self):
        """The json-able history section of a snapshot."""
        return self.history

    def _durability_set_history(self, history) -> None:
        self.history = history

    def _durability_extra_state(self):
        """(arrays, meta) extra sections; the base orchestrator carries
        the telemetry run id (stable across resumes — writer.py) and, for
        param-sharded programs, the shard count (gather-on-save makes the
        *values* layout-free, but the flat PAD is a function of the shard
        count, so a different-shard restore must refuse loudly instead of
        loading a wrong-width cache row)."""
        meta = {}
        if self.telemetry is not None:
            meta["telemetry_run_id"] = self.telemetry.run_id
        if self.program.param_shards > 1:
            meta["param_shards"] = int(self.program.param_shards)
        return {}, meta

    def _durability_validate_extra(self, arrays, meta) -> None:
        """Pure pre-restore validation, called BEFORE any live state is
        mutated — raise to refuse the snapshot.  A gang snapshot carries
        its member data in extra_meta with NO extra arrays, and flax's
        from_bytes would happily load its [S, ...]-stacked leaves into a
        single run — so refuse on meta keys too, symmetric with the
        gang/population guards."""
        foreign = sorted(set(arrays) | ({"gang", "population"} & set(meta)))
        if foreign:
            raise ValueError(
                f"snapshot carries extra sections {foreign} this "
                "orchestrator does not understand — it was written by a "
                "population/gang run; rebuild with the matching config"
            )
        snap_shards = int(meta.get("param_shards", 1))
        ours = int(self.program.param_shards)
        if snap_shards != ours:
            # The flat pad is shards-dependent (ops/flatten.padded_dim),
            # so even when two shard counts happen to produce the same
            # padded width, a cross-shard restore is a different program
            # family — refuse loudly, symmetric with the gang/population
            # identity guards (satellite: restoring a 4-shard snapshot
            # into a 2-shard mesh must refuse, not silently reshard).
            raise ValueError(
                f"snapshot was written by a param-sharded run with "
                f"tpu.param_shards={snap_shards} but this run has "
                f"param_shards={ours} — the flat pad (and the mesh "
                "layout the cache rows restore into) is a function of "
                "the shard count; rebuild with the matching "
                "tpu.param_shards"
            )

    def _durability_restore_extra(self, arrays, meta) -> None:
        """Apply orchestrator-specific sections after the base restore;
        validation already happened in ``_durability_validate_extra``."""

    def _record(self, round_num: int, metrics: Dict[str, np.ndarray], verbose: bool):
        acc = np.asarray(metrics["accuracy"])
        last_stats = record_round_metrics(
            self.history, round_num, metrics, self.compromised,
            self.program.evidential, self.attack is not None,
        )

        if self.telemetry is not None:
            # Per-node arrays of the recorded round (accuracy, agg_* rule
            # stats, agg_tap_* audit taps) plus the host-side in-degree of
            # the round's effective adjacency — the sender-side context
            # `murmura report` turns tap counts into rejection counts
            # with.  The in-degree was cached when the dispatch loop built
            # the round's adjacency; the fallback recompute only fires for
            # out-of-band _record calls (none today).
            in_deg = self._in_degree_cache.pop(round_num - 1, None)
            # Unrecorded rounds (eval_every > 1) never pop their entries;
            # prune everything at or below the recorded round so the cache
            # stays O(eval_every), not O(total rounds).
            self._in_degree_cache = {
                r: v for r, v in self._in_degree_cache.items()
                if r >= round_num
            }
            if in_deg is None:
                # Re-running the round's adjacency build repopulates the
                # cache with the mode-correct in-degree (dense column sums
                # or the sparse edge-mask roll sums).
                self._adjacency_for_round(round_num - 1)
                in_deg = self._in_degree_cache.pop(round_num - 1)
            self.telemetry.round_event(
                round_num,
                {k: np.asarray(v) for k, v in metrics.items()},
                in_degree=in_deg,
            )
        self._last_stats = last_stats

        if verbose:
            comp = self.compromised > 0
            line = f"Round {round_num}: Mean Accuracy = {acc.mean():.4f} ± {acc.std():.4f}"
            print(line, flush=True)
            if self.attack is not None and comp.any():
                print(
                    f"  Honest: {acc[~comp].mean():.4f}, "
                    f"Compromised: {acc[comp].mean():.4f}",
                    flush=True,
                )
            if self.program.evidential:
                print(
                    f"  Uncertainty: Vacuity={np.asarray(metrics['vacuity']).mean():.4f}, "
                    f"Entropy={np.asarray(metrics['entropy']).mean():.4f}, "
                    f"Strength={np.asarray(metrics['strength']).mean():.2f}",
                    flush=True,
                )

    def get_node_statistics(self) -> Dict[int, Dict[str, Any]]:
        """Per-node aggregator statistics (reference: network.py:201-210)."""
        n = self.program.num_nodes
        return {
            i: {k: float(v[i]) for k, v in self._last_stats.items()}
            for i in range(n)
        }

"""Passive metrics monitor (reference: murmura/distributed/monitor.py:6-175).

PULL-only collector: its death cannot affect training.  Metrics are buffered
keyed by (round, node); complete rounds flush in order; a hard deadline
(t_start + rounds*duration + 2*duration) forces a partial flush of whatever
arrived.  Produces a history dict with the same schema as Network.train
(reference: monitor.py:49-59 vs network.py:47-58).
"""

import time
from typing import Any, Dict, List, Optional, Set

import numpy as np

from murmura_tpu.config.schema import Config
from murmura_tpu.distributed.endpoints import Endpoints
from murmura_tpu.distributed.messaging import MsgType, decode, unpack_obj
from murmura_tpu.telemetry.schema import MONITOR_KNOWN_KEYS


class Monitor:
    def __init__(
        self,
        config: Config,
        run_id: str,
        t_start: float,
        compromised_ids: Optional[Set[int]] = None,
    ):
        self.config = config
        self.run_id = run_id
        self.endpoints = Endpoints(config.distributed, run_id)
        self.t_start = t_start
        self.num_nodes = config.topology.num_nodes
        self.rounds = config.experiment.rounds
        self.round_duration = config.distributed.round_duration_s
        self.compromised = compromised_ids or set()

        self.history: Dict[str, List[Any]] = {
            "round": [],
            "mean_accuracy": [],
            "std_accuracy": [],
            "mean_loss": [],
            "honest_accuracy": [],
            "compromised_accuracy": [],
            "mean_vacuity": [],
            "mean_entropy": [],
            "mean_strength": [],
        }
        self._buffer: Dict[int, Dict[int, dict]] = {}
        self._flushed_through = -1
        # Per-node CUMULATIVE operational counters (node_process.py emits
        # the running totals on every frame; last frame wins), folded into
        # the telemetry manifest at the end of the run.
        self._node_counters: Dict[int, Dict[str, float]] = {}
        # telemetry.enabled: the monitor owns the run manifest for the
        # distributed backend (the same writer/schema the in-process
        # orchestrator uses — telemetry/writer.py).  Built lazily in run()
        # so construction stays socket- and filesystem-free for tests.
        self._telemetry = None

    def run(self) -> Dict[str, List[Any]]:
        import zmq

        from murmura_tpu.utils.factories import build_telemetry_writer

        self._telemetry = build_telemetry_writer(self.config, run_id=self.run_id)

        ctx = zmq.Context()
        sock = ctx.socket(zmq.PULL)
        sock.bind(self.endpoints.monitor_bind())
        poller = zmq.Poller()
        poller.register(sock, zmq.POLLIN)

        hard_deadline = (
            self.t_start + self.rounds * self.round_duration + 2 * self.round_duration
        )
        try:
            while time.monotonic() < hard_deadline:
                if self._flushed_through >= self.rounds - 1:
                    break
                events = dict(poller.poll(200))
                if sock in events:
                    msg_type, sender, _round, payload = decode(sock.recv_multipart())
                    if msg_type == MsgType.METRICS:
                        self._ingest(unpack_obj(payload))
                self._flush_complete()
            self._flush_partial()
        finally:
            sock.close()
            ctx.term()
            self._finalize_telemetry()
        return self.history

    def _finalize_telemetry(self) -> None:
        """Fold node counters + history into the one run manifest."""
        if self._telemetry is None:
            return
        for counters in self._node_counters.values():
            self._telemetry.add_counters(counters)
        self._telemetry.finalize(history=self.history)
        self._telemetry.close()

    # ------------------------------------------------------------------

    def _ingest(self, metrics: dict) -> None:
        r = int(metrics.get("round", -1))
        n = int(metrics.get("node", -1))
        if r < 0 or r >= self.rounds or n < 0:
            return
        # Cumulative counters are captured at ingest (last frame wins), so
        # they survive even when the round itself never flushes — a node's
        # final totals must not depend on its last round completing.
        counters = metrics.get("counters")
        if isinstance(counters, dict):
            self._node_counters[n] = {
                k: float(v)
                for k, v in counters.items()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            }
        self._buffer.setdefault(r, {})[n] = metrics

    def _flush_complete(self) -> None:
        """Flush rounds in order while fully reported (monitor.py:81-108)."""
        while True:
            nxt = self._flushed_through + 1
            if nxt >= self.rounds or len(self._buffer.get(nxt, {})) < self.num_nodes:
                return
            self._record_round(nxt, self._buffer.pop(nxt))
            self._flushed_through = nxt

    def _flush_partial(self) -> None:
        """Hard deadline passed: flush incomplete rounds in order
        (monitor.py:110-128).

        Rounds with zero buffered messages between flushed ones get a NaN
        row (reporting_nodes=0) instead of being skipped over, so
        ``history['round']`` stays gap-free and index-aligned (round-4
        advisor: advancing past a wholly-unreported round left a silent
        hole, unlike the all-skipped case which already records NaNs).
        """
        reported = [r for r in self._buffer if self._buffer[r]]
        if not reported:
            self._buffer.clear()
            return
        # Clamp to the configured horizon: one corrupt METRICS frame with
        # a huge round tag must not drive an unbounded NaN-row loop.
        last = min(max(reported), self.rounds - 1)
        for r in range(self._flushed_through + 1, last + 1):
            self._record_round(r, self._buffer.get(r, {}))
            self._flushed_through = r
        self._buffer.clear()

    def _record_round(self, round_idx: int, per_node: Dict[int, dict]) -> None:
        rows = [m for m in per_node.values() if not m.get("skipped")]
        # Per-round overrun visibility (reference keeps skipped metrics
        # flagged rather than dropped — node_process.py:278-281).
        self.history.setdefault("skipped_nodes", []).append(
            len(per_node) - len(rows)
        )
        # Degradation visibility: how many nodes reported this round at all.
        # A crashed/stalled node shows up as reporting_nodes < num_nodes on
        # every partial-flushed round (the reference only logs the missing
        # set inside each node's stdout — node_process.py:259-269).
        self.history.setdefault("reporting_nodes", []).append(len(per_node))
        # Forward-compat: metric keys this monitor version does not know
        # (a newer node build, an experimental probe) are forwarded under
        # extra.* — into the history AND the manifest event stream —
        # instead of silently dropped (the pre-telemetry _ingest behavior;
        # regression-tested in tests/test_distributed.py).  The union with
        # already-recording extra.* lists keeps every such list appended on
        # EVERY flushed round (None when nobody reported the key), so
        # extra columns stay index-aligned with 'round' from the first
        # round the key appears — including gap/all-skipped rounds.
        extra_keys = sorted(
            ({k for m in per_node.values() for k in m}
             - set(MONITOR_KNOWN_KEYS))
            | {
                k[len("extra."):] for k in self.history
                if k.startswith("extra.")
            }
        )
        for k in extra_keys:
            vals = {n: m[k] for n, m in per_node.items() if k in m}
            nums = [
                float(v) for v in vals.values()
                if isinstance(v, (int, float)) and not isinstance(v, bool)
            ]
            self.history.setdefault(f"extra.{k}", []).append(
                float(np.mean(nums)) if nums else None
            )
            if vals and self._telemetry is not None:
                self._telemetry.emit(
                    "extra", round=round_idx + 1, key=k,
                    values={str(n): v for n, v in vals.items()},
                )
        if self._telemetry is not None:
            self._telemetry.emit(
                "round",
                round=round_idx + 1,
                nodes={
                    str(n): {
                        k: v for k, v in m.items()
                        if k not in ("counters",)
                    }
                    for n, m in per_node.items()
                },
            )
        if not rows:
            # Every node overran its training window: keep the round visible
            # with NaN metrics instead of silently producing an empty
            # history (round-2 verdict weak #5).  Every list that has been
            # recording (uncertainty, agg_*) gets a NaN too so history
            # columns stay index-aligned with 'round'.
            self.history["round"].append(round_idx + 1)
            self.history["mean_accuracy"].append(float("nan"))
            self.history["std_accuracy"].append(float("nan"))
            self.history["mean_loss"].append(float("nan"))
            if self.compromised:
                self.history["honest_accuracy"].append(float("nan"))
                self.history["compromised_accuracy"].append(float("nan"))
            for k, lst in self.history.items():
                if (k.startswith("agg_") or k.startswith("mean_v")
                        or k in ("mean_entropy", "mean_strength")) and lst:
                    lst.append(float("nan"))
            return
        accs = np.array([m.get("accuracy", 0.0) for m in rows])
        losses = np.array([m.get("loss", 0.0) for m in rows])
        self.history["round"].append(round_idx + 1)
        self.history["mean_accuracy"].append(float(accs.mean()))
        self.history["std_accuracy"].append(float(accs.std()))
        self.history["mean_loss"].append(float(losses.mean()))

        if self.compromised:
            honest = [
                m.get("accuracy", 0.0)
                for m in rows
                if not m.get("compromised", False)
            ]
            comp = [
                m.get("accuracy", 0.0) for m in rows if m.get("compromised", False)
            ]
            # NaN placeholders keep every history list index-aligned with
            # 'round' even when a partial flush lost one class's reports.
            self.history["honest_accuracy"].append(
                float(np.mean(honest)) if honest else float("nan")
            )
            self.history["compromised_accuracy"].append(
                float(np.mean(comp)) if comp else float("nan")
            )

        vacs = [m["vacuity"] for m in rows if "vacuity" in m]
        if vacs:
            self.history["mean_vacuity"].append(float(np.mean(vacs)))
            self.history["mean_entropy"].append(
                float(np.mean([m["entropy"] for m in rows]))
            )
            self.history["mean_strength"].append(
                float(np.mean([m["strength"] for m in rows]))
            )

        # Per-round aggregator statistics, mean over reporting nodes — same
        # agg_<key> schema the simulation/tpu history records
        # (core/network.py), so the two backends' histories stay comparable.
        agg_keys = sorted({k for m in rows for k in m.get("stats", {})})
        for k in agg_keys:
            vals = [
                float(np.asarray(m["stats"][k], dtype=np.float64).mean())
                for m in rows
                if k in m.get("stats", {})
            ]
            self.history.setdefault(f"agg_{k}", []).append(float(np.mean(vals)))

"""Per-node worker process (reference: murmura/distributed/node_process.py:8-364).

Socket layout: one PULL bind (receives from neighbors), lazy PUSH per
neighbor, one PUSH to the monitor.  Round protocol: sleep until
t_start + k*round_duration -> local train (honest only) -> overrun check ->
attack own outgoing state -> PUSH to current neighbors -> PULL until all
expected arrived or deadline (aggregate with whatever arrived) -> aggregate
-> evaluate -> PUSH metrics.  Round sync is the system clock; there are no
control messages.
"""

import os
import time
from typing import Dict, List, Optional

import numpy as np

from murmura_tpu.config.schema import Config
from murmura_tpu.distributed.endpoints import Endpoints
from murmura_tpu.distributed.messaging import (
    MsgType,
    decode,
    encode,
    pack_obj,
    pack_state,
    unpack_state,
)


def _force_cpu_jax() -> None:
    """Child processes must not contend for the single-tenant TPU; local
    training in the ZMQ backend runs on CPU (the tpu backend is the device
    path).

    The env mutation alone is NOT enough: jax captures JAX_PLATFORMS when
    it is imported, and the package import (``python -m murmura_tpu`` /
    a spawned worker) happens before this runs.  jax.config.update works
    as long as no backend has initialized yet — same technique as
    tests/conftest.py.  Without it, workers on a machine with a wedged
    TPU plugin hang inside device init instead of training on CPU.
    """
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


class NodeProcess:
    """One FL node in its own OS process."""

    def __init__(
        self,
        config: Config,
        node_id: int,
        run_id: str,
        t_start: float,
        compromised_ids: List[int],
        host: Optional[str] = None,
        resume: bool = False,
    ):
        self.config = config
        self.node_id = node_id
        self.run_id = run_id
        self.t_start = t_start
        self.compromised_ids = set(compromised_ids)
        self.host = host
        self.is_compromised = node_id in self.compromised_ids
        # Crash recovery (faults.enabled): a respawned process restores its
        # last per-node checkpoint and rejoins at the wall-clock-current
        # round instead of replaying from round 0.
        self.resume = resume
        self.start_round = 0

        self.endpoints = Endpoints(config.distributed, run_id)
        self.rounds = config.experiment.rounds
        self.round_duration = config.distributed.round_duration_s

        self.node = None
        self.attack = None
        self.mobility = None
        self.fault_schedule = None
        self.static_neighbors: List[int] = []
        self._ctx = None
        self._pull = None
        self._push: Dict[int, object] = {}
        self._monitor_push = None
        # Telemetry counters (docs/OBSERVABILITY.md): operational events
        # that were previously only visible as per-process stdout lines.
        # Ride every METRICS frame under the known 'counters' key; the
        # Monitor folds them into the run manifest (a pre-telemetry
        # monitor drops the unknown key harmlessly — forward-compat).
        self._counters: Dict[str, float] = {
            "send_retries": 0.0,
            "send_failures": 0.0,
            "reconnects": 0.0,
            "rounds_skipped": 0.0,
            "nonfinite_drops": 0.0,
            "checkpoint_saves": 0.0,
            "checkpoint_s": 0.0,
        }

    # ------------------------------------------------------------------

    def run(self) -> None:
        """Entry point inside the child process (reference: node_process.py:111-124)."""
        _force_cpu_jax()
        from murmura_tpu.utils.factories import apply_compilation_cache
        from murmura_tpu.utils.seed import set_seed

        apply_compilation_cache(self.config)
        # per-node seeding (node_process.py:113)
        set_seed(self.config.experiment.seed + self.node_id)
        self._build_node()
        if self.resume:
            self._restore_node_checkpoint()
            # Rejoin at the wall-clock-current round: round k occupies
            # [t_start + k*dur, t_start + (k+1)*dur).  Scheduled-dead
            # rounds between boot and recovery are self-skipped below.
            self.start_round = max(
                0,
                int((time.monotonic() - self.t_start) // self.round_duration),
            )
        self._setup_sockets()
        try:
            self._run_all_rounds()
        finally:
            self._teardown()

    # ------------------------------------------------------------------

    def _build_node(self) -> None:
        """Factories + full dataset load in every process, then subset
        (reference behavior: node_process.py:333-364)."""
        from murmura_tpu.aggregation import build_aggregator
        from murmura_tpu.data.registry import build_federated_data
        from murmura_tpu.distributed.local import LocalNode
        from murmura_tpu.topology.generators import create_topology
        from murmura_tpu.utils.factories import (
            build_attack,
            build_fault_schedule,
            build_mobility,
            resolve_model,
        )

        cfg = self.config
        # Same deterministic schedule every process reconstructs from the
        # seed — dead peers are excluded from expected-neighbor sets
        # without any control messages (faults/schedule.py).
        self.fault_schedule = build_fault_schedule(cfg)
        data = build_federated_data(
            cfg.data.adapter,
            cfg.data.params,
            num_nodes=cfg.topology.num_nodes,
            seed=cfg.experiment.seed,
            max_samples=cfg.training.max_samples,
        )
        # Shared model construction: wearables input_dim auto-sync + the
        # fail-fast data/model shape check, same as the in-process backends.
        model = resolve_model(cfg, data)
        x, y = data.get_client_data(self.node_id)
        # Only pass separate eval arrays when a real test split exists;
        # otherwise LocalNode aliases its training shard (no second device
        # copy of the same data).
        eval_x = eval_y = None
        if data.x_test is not None:
            eval_x, eval_y = data.get_client_eval_data(self.node_id)

        self.mobility = build_mobility(cfg)
        if self.mobility is None:
            topo = create_topology(
                cfg.topology.type,
                num_nodes=cfg.topology.num_nodes,
                p=cfg.topology.p,
                k=cfg.topology.k,
                seed=cfg.topology.seed,
            )
            self.static_neighbors = topo.neighbors[self.node_id]
            max_deg = max(len(ns) for ns in topo.neighbors)
        else:
            max_deg = cfg.topology.num_nodes - 1

        self.attack = build_attack(cfg)

        from murmura_tpu.ops.flatten import model_dimension
        import jax

        model_dim = model_dimension(jax.eval_shape(model.init, jax.random.PRNGKey(0)))
        agg_params = dict(cfg.aggregation.params)
        if cfg.aggregation.algorithm == "evidential_trust":
            probe_size = int(agg_params.get("max_eval_samples", 100))
        else:
            probe_size = cfg.training.batch_size
        agg = build_aggregator(
            cfg.aggregation.algorithm, agg_params, model_dim=model_dim,
            total_rounds=cfg.experiment.rounds,
        )

        self.node = LocalNode(
            node_id=self.node_id,
            model=model,
            agg=agg,
            x=x,
            y=y,
            eval_x=eval_x,
            eval_y=eval_y,
            max_neighbors=max_deg,
            local_epochs=cfg.training.local_epochs,
            batch_size=cfg.training.batch_size,
            lr=cfg.training.lr,
            total_rounds=cfg.experiment.rounds,
            probe_size=probe_size,
            annealing_rounds=max(1, cfg.experiment.rounds // 2),
            seed=cfg.experiment.seed + self.node_id,
        )

    def _setup_sockets(self) -> None:
        """PULL bind + PUSH to monitor; neighbor PUSH sockets are lazy
        (reference: node_process.py:130-155)."""
        import zmq

        self._ctx = zmq.Context()
        self._pull = self._ctx.socket(zmq.PULL)
        self._pull.bind(self.endpoints.node_bind(self.node_id, self.host))
        self._monitor_push = self._ctx.socket(zmq.PUSH)
        self._monitor_push.setsockopt(zmq.LINGER, 2000)
        self._monitor_push.connect(self.endpoints.monitor_connect())

    def _push_to(self, neighbor_id: int):
        import zmq

        if neighbor_id not in self._push:
            sock = self._ctx.socket(zmq.PUSH)
            sock.setsockopt(zmq.LINGER, 2000)
            sock.connect(self.endpoints.node_connect(neighbor_id))
            self._push[neighbor_id] = sock
        return self._push[neighbor_id]

    def _teardown(self) -> None:
        for sock in self._push.values():
            sock.close()
        if self._pull is not None:
            self._pull.close()
        if self._monitor_push is not None:
            self._monitor_push.close()
        if self._ctx is not None:
            self._ctx.term()

    # ------------------------------------------------------------------

    def current_neighbors(self, round_idx: int) -> List[int]:
        """Static topology or mobility G^t (reference: node_process.py:292-323)."""
        if self.mobility is not None:
            return self.mobility.neighbors_at(round_idx)[self.node_id]
        return list(self.static_neighbors)

    def _scheduled_dead(self, round_idx: int) -> bool:
        return (
            self.fault_schedule is not None
            and self.fault_schedule.alive_at(round_idx)[self.node_id] <= 0
        )

    def _run_all_rounds(self) -> None:
        for k in range(self.start_round, self.rounds):
            target = self.t_start + k * self.round_duration
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if self._scheduled_dead(k):
                # Self-enforced crash window: a dead process neither
                # trains nor reports (reporting_nodes drops — the
                # monitor's degradation telemetry).  Self-enforcement
                # keeps multi-machine runs (no FaultInjector parent)
                # honoring the schedule, and gives a respawned process a
                # boot round before its scheduled recovery.  With the
                # injector armed this is belt-and-suspenders: the process
                # is normally SIGKILLed before it gets here.
                continue
            self._execute_round(k)
            if self.fault_schedule is not None:
                self._save_node_checkpoint(k)

    @property
    def _is_colluder(self) -> bool:
        """Colluding attacks (ALIE, IPM) estimate population statistics
        from the coalition's own benign states on this backend."""
        return (
            self.attack is not None
            and self.attack.name in ("alie", "ipm")
            and self.is_compromised
        )

    def _execute_round(self, round_idx: int) -> None:
        """One wall-clock round (reference: node_process.py:193-247)."""
        deadline = self.t_start + (round_idx + 1) * self.round_duration
        # 0. already past this round's deadline (a previous round's
        # training overran the whole window, or a recovery boot landed
        # late): publish the SKIPPED frame so the monitor stays
        # index-aligned, instead of training into the next window and
        # silently advancing.
        if time.monotonic() >= deadline:
            print(
                f"[node {self.node_id}] round {round_idx}: round window "
                "already elapsed; skipping",
                flush=True,
            )
            self._send_metrics(round_idx, skipped=True)
            return
        neighbors = self.current_neighbors(round_idx)
        if self.fault_schedule is not None:
            # Re-resolve the expected-neighbor set from the schedule:
            # no waiting out the full deadline on a known-dead peer or a
            # dropped link.  Symmetric link masks keep sender and receiver
            # expectations consistent without communication.
            alive = self.fault_schedule.alive_at(round_idx)
            link = self.fault_schedule.link_mask_at(round_idx)
            neighbors = [
                j for j in neighbors
                if alive[j] > 0 and link[self.node_id, j] > 0
            ]

        # 1. local training (honest only — node_process.py:205-207).
        # ALIE/IPM colluders ALSO train: their benign states are the
        # coalition sample the papers' estimators run on (alie.py module
        # docstring); the benign result never leaves the coalition.
        faults = self.config.faults if self.config.faults.enabled else None
        pre_flat = None
        if faults is not None and faults.nan_quarantine:
            # Pre-round snapshot: a divergent (non-finite) local step rolls
            # back to this instead of poisoning the exchange — the ZMQ twin
            # of the in-jit sentinel (core/rounds.py, docs/ROBUSTNESS.md).
            pre_flat = self.node.get_flat_state()
        t_train0 = time.monotonic()
        if not self.is_compromised or self._is_colluder:
            self.node.local_train(round_idx)

        # 1b. straggler realization: the schedule's boolean becomes an
        # actual delay — (factor-1) x the measured training time, capped
        # just past the round window.  Deliberately WEAKER than the jitted
        # backends' model (which drops a straggler's outgoing column
        # unconditionally): here the delay is physical, so whether the
        # update misses the delivery deadline depends on real timing —
        # a 2x slowdown that still fits the window delivers on time, as
        # it would in production (docs/ROBUSTNESS.md).
        if (
            self.fault_schedule is not None
            and self.fault_schedule.straggler_at(round_idx)[self.node_id]
        ):
            train_time = time.monotonic() - t_train0
            delay = min(
                (self.fault_schedule.straggler_factor - 1.0) * train_time,
                max(0.0, deadline - time.monotonic()) + 0.5,
            )
            if delay > 0:
                print(
                    f"[node {self.node_id}] round {round_idx}: straggling "
                    f"{delay:.2f}s (factor "
                    f"{self.fault_schedule.straggler_factor})",
                    flush=True,
                )
                time.sleep(delay)

        # 2. overrun check: skip exchange if training blew the window
        # (node_process.py:210-218)
        if time.monotonic() >= deadline:
            print(
                f"[node {self.node_id}] round {round_idx}: training overran "
                "the round window; skipping exchange",
                flush=True,
            )
            self._send_metrics(round_idx, skipped=True)
            return

        # 2b. numerical sentinel (faults.nan_quarantine): a non-finite
        # post-training state quarantines this node for the round — params
        # roll back to the pre-round snapshot and the exchange is skipped
        # (neighbors degrade via the normal deadline semantics; they ALSO
        # drop non-finite arrivals in _collect_states as defense in depth).
        flat = self.node.get_flat_state()
        if (
            faults is not None
            and self.node_id in faults.nan_inject_nodes
            and round_idx >= faults.nan_inject_from_round
        ):
            # Deterministic divergence injection for chaos testing, same
            # semantics as the jitted backends' nan_inject_nodes.
            flat = np.full_like(flat, np.nan)
        if pre_flat is not None and not np.isfinite(flat).all():
            print(
                f"[node {self.node_id}] round {round_idx}: non-finite local "
                "update quarantined; rolling back to the pre-round state",
                flush=True,
            )
            self.node.set_flat_state(pre_flat)
            self._send_metrics(round_idx, skipped=False)
            return

        # 3. attack own outgoing state (node_process.py:221-225).
        # ALIE/IPM colluders first exchange benign states within the
        # coalition; neighbor MODEL_STATEs arriving during that window are
        # buffered and handed to the collection in step 5.
        prebuffered: Dict[int, np.ndarray] = {}
        if self._is_colluder:
            out_flat, prebuffered = self._colluding_state(
                flat, round_idx, deadline
            )
        else:
            out_flat = self._attacked_state(flat, round_idx)

        # 4. PUSH to current neighbors (node_process.py:227-232)
        payload = pack_state(out_flat)
        for nid in neighbors:
            self._send_to(
                nid, encode(MsgType.MODEL_STATE, self.node_id, payload, round_idx)
            )

        # 5. collect neighbor states until expected or deadline
        # (node_process.py:249-276)
        received = self._collect_states(
            set(neighbors), round_idx, deadline, prebuffered=prebuffered
        )

        # 6. aggregate with whatever arrived (partial OK)
        if received:
            self.node.aggregate_with_neighbors(received, round_idx)

        # 7. evaluate + metrics to monitor
        self._send_metrics(round_idx, skipped=False)

    def _reject_nonfinite(self, sender: int, state: np.ndarray) -> bool:
        """Receiver-side sentinel (faults.nan_quarantine): drop a neighbor
        state carrying non-finite values before it reaches any rule math
        (0 * nan == nan in every Gram/matmul path) — defense in depth
        behind the sender-side rollback, and the only line of defense
        against a peer running without the sentinel."""
        if (
            self.config.faults.enabled
            and self.config.faults.nan_quarantine
            and not np.isfinite(state).all()
        ):
            print(
                f"[node {self.node_id}] dropped non-finite state from "
                f"{sender}",
                flush=True,
            )
            self._counters["nonfinite_drops"] += 1
            return True
        return False

    def _send_to(self, neighbor_id: int, frames, attempts: int = 3) -> bool:
        """Send with exponential-backoff reconnect.

        A PUSH socket wedged by a peer restart (stale IPC inode, refused
        TCP connect at send time) raises; dropping the cached socket and
        reconnecting fresh is the recovery — ZMQ re-resolves the endpoint.
        Failure after the retry budget degrades to the round's
        partial-aggregation semantics (the peer just misses this state).
        """
        delay = 0.05
        for attempt in range(attempts):
            try:
                self._push_to(neighbor_id).send_multipart(frames, copy=False)
                return True
            except Exception as e:
                print(
                    f"[node {self.node_id}] push to {neighbor_id} failed "
                    f"(attempt {attempt + 1}/{attempts}): {e}",
                    flush=True,
                )
                self._counters["send_retries"] += 1
                self._counters["reconnects"] += 1
                sock = self._push.pop(neighbor_id, None)
                if sock is not None:
                    try:
                        sock.close(linger=0)
                    except Exception:  # pragma: no cover - teardown races
                        pass
                if attempt + 1 < attempts:
                    time.sleep(delay)
                    delay *= 2
        self._counters["send_failures"] += 1
        return False

    def _attacked_state(self, flat: np.ndarray, round_idx: int) -> np.ndarray:
        if self.attack is None or not self.is_compromised:
            return flat
        import jax
        import jax.numpy as jnp

        key = jax.random.fold_in(
            jax.random.PRNGKey(self.config.experiment.seed + 7919), round_idx
        )
        key = jax.random.fold_in(key, self.node_id)
        out = self.attack.apply(
            jnp.asarray(flat)[None, :], jnp.ones((1,)), key, round_idx
        )
        return np.asarray(out[0], dtype=np.float32)

    def _colluding_state(
        self, flat: np.ndarray, round_idx: int, deadline: float
    ) -> tuple:
        """Coalition-estimated colluding vector — ALIE's mu - z*sigma
        (Baruch et al.) or IPM's -epsilon*mu (Xie et al.), both estimated
        from the corrupted workers' own benign states, which is the
        papers' construction (module docstrings of attacks/alie.py and
        attacks/ipm.py have the omniscient-vs-estimated distinction).

        Protocol: push own benign state to every other colluder
        (COLLUDE_STATE), collect theirs until half the remaining round
        window is spent, then broadcast the colluding vector over whatever
        coalition sample arrived (always >= the own state — the same
        partial-collect degradation the model exchange uses).  Neighbor
        MODEL_STATEs arriving early are buffered and returned for step 5.
        """
        import zmq
        peers = sorted(self.compromised_ids - {self.node_id})
        if self.fault_schedule is not None:
            # Dead colluders can neither contribute nor receive: shrink
            # the coalition instead of burning half the round window
            # waiting on them.
            alive = self.fault_schedule.alive_at(round_idx)
            peers = [p for p in peers if alive[p] > 0]
        payload = pack_state(flat)
        for nid in peers:
            self._send_to(
                nid,
                encode(MsgType.COLLUDE_STATE, self.node_id, payload, round_idx),
            )

        coalition: Dict[int, np.ndarray] = {self.node_id: np.asarray(flat)}
        prebuffered: Dict[int, np.ndarray] = {}
        # Leave at least half the remaining window for the real exchange.
        sub_deadline = min(
            deadline, time.monotonic() + 0.5 * max(0.0, deadline - time.monotonic())
        )
        poller = zmq.Poller()
        poller.register(self._pull, zmq.POLLIN)
        while set(peers) - set(coalition) and time.monotonic() < sub_deadline:
            timeout_ms = max(1, int((sub_deadline - time.monotonic()) * 1000))
            events = dict(poller.poll(min(timeout_ms, 200)))
            if self._pull not in events:
                continue
            msg_type, sender, msg_round, data = decode(self._pull.recv_multipart())
            if msg_round != round_idx:
                continue  # straggler from an earlier round window
            if msg_type == MsgType.COLLUDE_STATE and sender in peers:
                state = unpack_state(data)
                if not self._reject_nonfinite(sender, state):
                    coalition[sender] = state
            elif msg_type == MsgType.MODEL_STATE:
                state = unpack_state(data)
                if not self._reject_nonfinite(sender, state):
                    prebuffered[sender] = state
        missing = set(peers) - set(coalition)
        if missing:
            print(
                f"[node {self.node_id}] {self.attack.name}: coalition "
                f"sample {len(coalition)}/{len(peers) + 1} "
                f"(missing {sorted(missing)})",
                flush=True,
            )
        sample = np.stack(list(coalition.values()))
        p = self.config.attack.params
        if self.attack.name == "ipm":
            from murmura_tpu.attacks.ipm import ipm_vector, resolve_ipm_epsilon

            out = ipm_vector(sample, resolve_ipm_epsilon(p.get("epsilon")))
        else:
            from murmura_tpu.attacks.alie import (
                colluding_vector,
                resolve_alie_z,
            )

            out = colluding_vector(
                sample,
                resolve_alie_z(
                    self.config.topology.num_nodes,
                    len(self.compromised_ids),
                    p.get("z"),
                ),
            )
        return out, prebuffered

    def _collect_states(
        self,
        expected: set,
        round_idx: int,
        deadline: float,
        prebuffered: Optional[Dict[int, np.ndarray]] = None,
    ) -> Dict[int, np.ndarray]:
        import zmq

        received: Dict[int, np.ndarray] = {
            s: v for s, v in (prebuffered or {}).items() if s in expected
        }
        poller = zmq.Poller()
        poller.register(self._pull, zmq.POLLIN)
        while expected - set(received) and time.monotonic() < deadline:
            timeout_ms = max(1, int((deadline - time.monotonic()) * 1000))
            events = dict(poller.poll(min(timeout_ms, 200)))
            if self._pull in events:
                msg_type, sender, msg_round, payload = decode(
                    self._pull.recv_multipart()
                )
                # round tag drops stragglers from earlier round windows
                if (
                    msg_type == MsgType.MODEL_STATE
                    and sender in expected
                    and msg_round == round_idx
                ):
                    state = unpack_state(payload)
                    if self._reject_nonfinite(sender, state):
                        expected = expected - {sender}
                        continue
                    received[sender] = state
        missing = expected - set(received)
        if missing:
            print(
                f"[node {self.node_id}] deadline: aggregating with "
                f"{len(received)}/{len(expected)} neighbors (missing {sorted(missing)})",
                flush=True,
            )
        return received

    # ------------------------------------------------------------------
    # crash-recovery checkpoints (faults.enabled runs)

    def _save_node_checkpoint(self, round_idx: int) -> None:
        """Atomically snapshot this node's state after a completed round.

        Flat params + RNG key + per-node ('node'-kind) aggregator state;
        per-edge trust is deliberately not persisted — a recovered peer
        re-earns link trust, which is the conservative (Byzantine-safe)
        choice.  fsync'd write + os.replace so a crash mid-save leaves the
        previous checkpoint intact (utils/checkpoint.py semantics).
        """
        import io

        from murmura_tpu.utils.checkpoint import durable_replace

        t0 = time.monotonic()
        path = self.endpoints.node_checkpoint_path(self.node_id)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        payload = {
            "flat": self.node.get_flat_state(),
            "rng": np.asarray(self.node.rng),
            "round": np.int64(round_idx),
        }
        for k, v in getattr(self.node, "_node_state", {}).items():
            payload[f"node_state.{k}"] = np.asarray(v)
        buf = io.BytesIO()
        np.savez(buf, **payload)
        durable_replace(
            os.path.dirname(path), os.path.basename(path), buf.getvalue()
        )
        self._counters["checkpoint_saves"] += 1
        self._counters["checkpoint_s"] += time.monotonic() - t0

    def _restore_node_checkpoint(self) -> Optional[int]:
        """Restore the last checkpoint; returns its round or None."""
        import jax.numpy as jnp

        path = self.endpoints.node_checkpoint_path(self.node_id)
        if not os.path.exists(path):
            print(
                f"[node {self.node_id}] resume requested but no checkpoint "
                f"at {path}; rejoining from the initial model",
                flush=True,
            )
            return None
        with np.load(path) as data:
            self.node.set_flat_state(data["flat"])
            self.node.rng = jnp.asarray(data["rng"])
            for k in list(getattr(self.node, "_node_state", {})):
                key = f"node_state.{k}"
                if key in data:
                    self.node._node_state[k] = np.asarray(data[key])
            restored = int(data["round"])
        print(
            f"[node {self.node_id}] restored checkpoint from round "
            f"{restored}",
            flush=True,
        )
        return restored

    def _send_metrics(self, round_idx: int, skipped: bool) -> None:
        metrics = {"round": round_idx, "node": self.node_id, "skipped": skipped}
        if skipped:
            self._counters["rounds_skipped"] += 1
        else:
            metrics.update(self.node.evaluate())
            metrics["stats"] = self.node.get_aggregator_statistics()
        metrics["compromised"] = self.is_compromised
        # Cumulative operational counters ride every frame: the monitor
        # folds the LAST value per node into the manifest, so losing any
        # individual frame loses nothing (each frame carries the totals).
        metrics["counters"] = dict(self._counters)
        try:
            self._monitor_push.send_multipart(
                encode(MsgType.METRICS, self.node_id, pack_obj(metrics), round_idx)
            )
        except Exception as e:  # pragma: no cover
            print(f"[node {self.node_id}] metrics push failed: {e}", flush=True)


def run_single_node(
    config: Config,
    node_id: int,
    t_start: float,
    run_id: str,
    host: Optional[str] = None,
    resume: bool = False,
) -> None:
    """Multi-machine worker entry (reference: cli.py:143-208).  The operator
    copies run_id/t_start printed by the head node; t_start must be valid on
    this machine's monotonic clock."""
    # Strip the TPU plugin env BEFORE importing anything jax-backed —
    # build_attack pulls in the factories module, which imports jax.
    _force_cpu_jax()
    if not 0 <= node_id < config.topology.num_nodes:
        raise ValueError(
            f"--node-id {node_id} out of range for "
            f"topology.num_nodes={config.topology.num_nodes}"
        )
    from murmura_tpu.utils.factories import build_attack

    attack = build_attack(config)
    compromised = sorted(attack.get_compromised_nodes()) if attack else []
    NodeProcess(
        config,
        node_id=node_id,
        run_id=run_id,
        t_start=t_start,
        compromised_ids=compromised,
        host=host,
        resume=resume,
    ).run()

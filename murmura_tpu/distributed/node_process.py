"""Per-node worker process (reference: murmura/distributed/node_process.py:8-364).

Socket layout: one PULL bind (receives from neighbors), lazy PUSH per
neighbor, one PUSH to the monitor.  Round protocol: sleep until
t_start + k*round_duration -> local train (honest only) -> overrun check ->
attack own outgoing state -> PUSH to current neighbors -> PULL until all
expected arrived or deadline (aggregate with whatever arrived) -> aggregate
-> evaluate -> PUSH metrics.  Round sync is the system clock; there are no
control messages.
"""

import os
import time
from typing import Dict, List, Optional

import numpy as np

from murmura_tpu.config.schema import Config
from murmura_tpu.distributed.endpoints import Endpoints
from murmura_tpu.distributed.messaging import (
    MsgType,
    decode,
    encode,
    pack_obj,
    pack_state,
    unpack_state,
)


def _force_cpu_jax() -> None:
    """Child processes must not contend for the single-tenant TPU; local
    training in the ZMQ backend runs on CPU (the tpu backend is the device
    path).

    The env mutation alone is NOT enough: jax captures JAX_PLATFORMS when
    it is imported, and the package import (``python -m murmura_tpu`` /
    a spawned worker) happens before this runs.  jax.config.update works
    as long as no backend has initialized yet — same technique as
    tests/conftest.py.  Without it, workers on a machine with a wedged
    TPU plugin hang inside device init instead of training on CPU.
    """
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")


class NodeProcess:
    """One FL node in its own OS process."""

    def __init__(
        self,
        config: Config,
        node_id: int,
        run_id: str,
        t_start: float,
        compromised_ids: List[int],
        host: Optional[str] = None,
    ):
        self.config = config
        self.node_id = node_id
        self.run_id = run_id
        self.t_start = t_start
        self.compromised_ids = set(compromised_ids)
        self.host = host
        self.is_compromised = node_id in self.compromised_ids

        self.endpoints = Endpoints(config.distributed, run_id)
        self.rounds = config.experiment.rounds
        self.round_duration = config.distributed.round_duration_s

        self.node = None
        self.attack = None
        self.mobility = None
        self.static_neighbors: List[int] = []
        self._ctx = None
        self._pull = None
        self._push: Dict[int, object] = {}
        self._monitor_push = None

    # ------------------------------------------------------------------

    def run(self) -> None:
        """Entry point inside the child process (reference: node_process.py:111-124)."""
        _force_cpu_jax()
        from murmura_tpu.utils.factories import apply_compilation_cache
        from murmura_tpu.utils.seed import set_seed

        apply_compilation_cache(self.config)
        # per-node seeding (node_process.py:113)
        set_seed(self.config.experiment.seed + self.node_id)
        self._build_node()
        self._setup_sockets()
        try:
            self._run_all_rounds()
        finally:
            self._teardown()

    # ------------------------------------------------------------------

    def _build_node(self) -> None:
        """Factories + full dataset load in every process, then subset
        (reference behavior: node_process.py:333-364)."""
        from murmura_tpu.aggregation import build_aggregator
        from murmura_tpu.data.registry import build_federated_data
        from murmura_tpu.distributed.local import LocalNode
        from murmura_tpu.topology.generators import create_topology
        from murmura_tpu.utils.factories import (
            build_attack,
            build_mobility,
            resolve_model,
        )

        cfg = self.config
        data = build_federated_data(
            cfg.data.adapter,
            cfg.data.params,
            num_nodes=cfg.topology.num_nodes,
            seed=cfg.experiment.seed,
            max_samples=cfg.training.max_samples,
        )
        # Shared model construction: wearables input_dim auto-sync + the
        # fail-fast data/model shape check, same as the in-process backends.
        model = resolve_model(cfg, data)
        x, y = data.get_client_data(self.node_id)
        # Only pass separate eval arrays when a real test split exists;
        # otherwise LocalNode aliases its training shard (no second device
        # copy of the same data).
        eval_x = eval_y = None
        if data.x_test is not None:
            eval_x, eval_y = data.get_client_eval_data(self.node_id)

        self.mobility = build_mobility(cfg)
        if self.mobility is None:
            topo = create_topology(
                cfg.topology.type,
                num_nodes=cfg.topology.num_nodes,
                p=cfg.topology.p,
                k=cfg.topology.k,
                seed=cfg.topology.seed,
            )
            self.static_neighbors = topo.neighbors[self.node_id]
            max_deg = max(len(ns) for ns in topo.neighbors)
        else:
            max_deg = cfg.topology.num_nodes - 1

        self.attack = build_attack(cfg)

        from murmura_tpu.ops.flatten import model_dimension
        import jax

        model_dim = model_dimension(jax.eval_shape(model.init, jax.random.PRNGKey(0)))
        agg_params = dict(cfg.aggregation.params)
        if cfg.aggregation.algorithm == "evidential_trust":
            probe_size = int(agg_params.get("max_eval_samples", 100))
        else:
            probe_size = cfg.training.batch_size
        agg = build_aggregator(
            cfg.aggregation.algorithm, agg_params, model_dim=model_dim,
            total_rounds=cfg.experiment.rounds,
        )

        self.node = LocalNode(
            node_id=self.node_id,
            model=model,
            agg=agg,
            x=x,
            y=y,
            eval_x=eval_x,
            eval_y=eval_y,
            max_neighbors=max_deg,
            local_epochs=cfg.training.local_epochs,
            batch_size=cfg.training.batch_size,
            lr=cfg.training.lr,
            total_rounds=cfg.experiment.rounds,
            probe_size=probe_size,
            annealing_rounds=max(1, cfg.experiment.rounds // 2),
            seed=cfg.experiment.seed + self.node_id,
        )

    def _setup_sockets(self) -> None:
        """PULL bind + PUSH to monitor; neighbor PUSH sockets are lazy
        (reference: node_process.py:130-155)."""
        import zmq

        self._ctx = zmq.Context()
        self._pull = self._ctx.socket(zmq.PULL)
        self._pull.bind(self.endpoints.node_bind(self.node_id, self.host))
        self._monitor_push = self._ctx.socket(zmq.PUSH)
        self._monitor_push.setsockopt(zmq.LINGER, 2000)
        self._monitor_push.connect(self.endpoints.monitor_connect())

    def _push_to(self, neighbor_id: int):
        import zmq

        if neighbor_id not in self._push:
            sock = self._ctx.socket(zmq.PUSH)
            sock.setsockopt(zmq.LINGER, 2000)
            sock.connect(self.endpoints.node_connect(neighbor_id))
            self._push[neighbor_id] = sock
        return self._push[neighbor_id]

    def _teardown(self) -> None:
        for sock in self._push.values():
            sock.close()
        if self._pull is not None:
            self._pull.close()
        if self._monitor_push is not None:
            self._monitor_push.close()
        if self._ctx is not None:
            self._ctx.term()

    # ------------------------------------------------------------------

    def current_neighbors(self, round_idx: int) -> List[int]:
        """Static topology or mobility G^t (reference: node_process.py:292-323)."""
        if self.mobility is not None:
            return self.mobility.neighbors_at(round_idx)[self.node_id]
        return list(self.static_neighbors)

    def _run_all_rounds(self) -> None:
        for k in range(self.rounds):
            target = self.t_start + k * self.round_duration
            delay = target - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            self._execute_round(k)

    @property
    def _is_colluder(self) -> bool:
        """Colluding attacks (ALIE, IPM) estimate population statistics
        from the coalition's own benign states on this backend."""
        return (
            self.attack is not None
            and self.attack.name in ("alie", "ipm")
            and self.is_compromised
        )

    def _execute_round(self, round_idx: int) -> None:
        """One wall-clock round (reference: node_process.py:193-247)."""
        deadline = self.t_start + (round_idx + 1) * self.round_duration
        neighbors = self.current_neighbors(round_idx)

        # 1. local training (honest only — node_process.py:205-207).
        # ALIE/IPM colluders ALSO train: their benign states are the
        # coalition sample the papers' estimators run on (alie.py module
        # docstring); the benign result never leaves the coalition.
        if not self.is_compromised or self._is_colluder:
            self.node.local_train(round_idx)

        # 2. overrun check: skip exchange if training blew the window
        # (node_process.py:210-218)
        if time.monotonic() >= deadline:
            print(
                f"[node {self.node_id}] round {round_idx}: training overran "
                "the round window; skipping exchange",
                flush=True,
            )
            self._send_metrics(round_idx, skipped=True)
            return

        # 3. attack own outgoing state (node_process.py:221-225).
        # ALIE/IPM colluders first exchange benign states within the
        # coalition; neighbor MODEL_STATEs arriving during that window are
        # buffered and handed to the collection in step 5.
        flat = self.node.get_flat_state()
        prebuffered: Dict[int, np.ndarray] = {}
        if self._is_colluder:
            out_flat, prebuffered = self._colluding_state(
                flat, round_idx, deadline
            )
        else:
            out_flat = self._attacked_state(flat, round_idx)

        # 4. PUSH to current neighbors (node_process.py:227-232)
        payload = pack_state(out_flat)
        for nid in neighbors:
            try:
                self._push_to(nid).send_multipart(
                    encode(MsgType.MODEL_STATE, self.node_id, payload, round_idx),
                    copy=False,
                )
            except Exception as e:  # pragma: no cover - socket teardown races
                print(f"[node {self.node_id}] push to {nid} failed: {e}", flush=True)

        # 5. collect neighbor states until expected or deadline
        # (node_process.py:249-276)
        received = self._collect_states(
            set(neighbors), round_idx, deadline, prebuffered=prebuffered
        )

        # 6. aggregate with whatever arrived (partial OK)
        if received:
            self.node.aggregate_with_neighbors(received, round_idx)

        # 7. evaluate + metrics to monitor
        self._send_metrics(round_idx, skipped=False)

    def _attacked_state(self, flat: np.ndarray, round_idx: int) -> np.ndarray:
        if self.attack is None or not self.is_compromised:
            return flat
        import jax
        import jax.numpy as jnp

        key = jax.random.fold_in(
            jax.random.PRNGKey(self.config.experiment.seed + 7919), round_idx
        )
        key = jax.random.fold_in(key, self.node_id)
        out = self.attack.apply(
            jnp.asarray(flat)[None, :], jnp.ones((1,)), key, round_idx
        )
        return np.asarray(out[0], dtype=np.float32)

    def _colluding_state(
        self, flat: np.ndarray, round_idx: int, deadline: float
    ) -> tuple:
        """Coalition-estimated colluding vector — ALIE's mu - z*sigma
        (Baruch et al.) or IPM's -epsilon*mu (Xie et al.), both estimated
        from the corrupted workers' own benign states, which is the
        papers' construction (module docstrings of attacks/alie.py and
        attacks/ipm.py have the omniscient-vs-estimated distinction).

        Protocol: push own benign state to every other colluder
        (COLLUDE_STATE), collect theirs until half the remaining round
        window is spent, then broadcast the colluding vector over whatever
        coalition sample arrived (always >= the own state — the same
        partial-collect degradation the model exchange uses).  Neighbor
        MODEL_STATEs arriving early are buffered and returned for step 5.
        """
        import zmq
        peers = sorted(self.compromised_ids - {self.node_id})
        payload = pack_state(flat)
        for nid in peers:
            try:
                self._push_to(nid).send_multipart(
                    encode(MsgType.COLLUDE_STATE, self.node_id, payload, round_idx),
                    copy=False,
                )
            except Exception as e:  # pragma: no cover - socket teardown races
                print(
                    f"[node {self.node_id}] collude push to {nid} failed: {e}",
                    flush=True,
                )

        coalition: Dict[int, np.ndarray] = {self.node_id: np.asarray(flat)}
        prebuffered: Dict[int, np.ndarray] = {}
        # Leave at least half the remaining window for the real exchange.
        sub_deadline = min(
            deadline, time.monotonic() + 0.5 * max(0.0, deadline - time.monotonic())
        )
        poller = zmq.Poller()
        poller.register(self._pull, zmq.POLLIN)
        while set(peers) - set(coalition) and time.monotonic() < sub_deadline:
            timeout_ms = max(1, int((sub_deadline - time.monotonic()) * 1000))
            events = dict(poller.poll(min(timeout_ms, 200)))
            if self._pull not in events:
                continue
            msg_type, sender, msg_round, data = decode(self._pull.recv_multipart())
            if msg_round != round_idx:
                continue  # straggler from an earlier round window
            if msg_type == MsgType.COLLUDE_STATE and sender in peers:
                coalition[sender] = unpack_state(data)
            elif msg_type == MsgType.MODEL_STATE:
                prebuffered[sender] = unpack_state(data)
        missing = set(peers) - set(coalition)
        if missing:
            print(
                f"[node {self.node_id}] {self.attack.name}: coalition "
                f"sample {len(coalition)}/{len(peers) + 1} "
                f"(missing {sorted(missing)})",
                flush=True,
            )
        sample = np.stack(list(coalition.values()))
        p = self.config.attack.params
        if self.attack.name == "ipm":
            from murmura_tpu.attacks.ipm import ipm_vector, resolve_ipm_epsilon

            out = ipm_vector(sample, resolve_ipm_epsilon(p.get("epsilon")))
        else:
            from murmura_tpu.attacks.alie import (
                colluding_vector,
                resolve_alie_z,
            )

            out = colluding_vector(
                sample,
                resolve_alie_z(
                    self.config.topology.num_nodes,
                    len(self.compromised_ids),
                    p.get("z"),
                ),
            )
        return out, prebuffered

    def _collect_states(
        self,
        expected: set,
        round_idx: int,
        deadline: float,
        prebuffered: Optional[Dict[int, np.ndarray]] = None,
    ) -> Dict[int, np.ndarray]:
        import zmq

        received: Dict[int, np.ndarray] = {
            s: v for s, v in (prebuffered or {}).items() if s in expected
        }
        poller = zmq.Poller()
        poller.register(self._pull, zmq.POLLIN)
        while expected - set(received) and time.monotonic() < deadline:
            timeout_ms = max(1, int((deadline - time.monotonic()) * 1000))
            events = dict(poller.poll(min(timeout_ms, 200)))
            if self._pull in events:
                msg_type, sender, msg_round, payload = decode(
                    self._pull.recv_multipart()
                )
                # round tag drops stragglers from earlier round windows
                if (
                    msg_type == MsgType.MODEL_STATE
                    and sender in expected
                    and msg_round == round_idx
                ):
                    received[sender] = unpack_state(payload)
        missing = expected - set(received)
        if missing:
            print(
                f"[node {self.node_id}] deadline: aggregating with "
                f"{len(received)}/{len(expected)} neighbors (missing {sorted(missing)})",
                flush=True,
            )
        return received

    def _send_metrics(self, round_idx: int, skipped: bool) -> None:
        metrics = {"round": round_idx, "node": self.node_id, "skipped": skipped}
        if not skipped:
            metrics.update(self.node.evaluate())
            metrics["stats"] = self.node.get_aggregator_statistics()
        metrics["compromised"] = self.is_compromised
        try:
            self._monitor_push.send_multipart(
                encode(MsgType.METRICS, self.node_id, pack_obj(metrics), round_idx)
            )
        except Exception as e:  # pragma: no cover
            print(f"[node {self.node_id}] metrics push failed: {e}", flush=True)


def run_single_node(
    config: Config,
    node_id: int,
    t_start: float,
    run_id: str,
    host: Optional[str] = None,
) -> None:
    """Multi-machine worker entry (reference: cli.py:143-208).  The operator
    copies run_id/t_start printed by the head node; t_start must be valid on
    this machine's monotonic clock."""
    # Strip the TPU plugin env BEFORE importing anything jax-backed —
    # build_attack pulls in the factories module, which imports jax.
    _force_cpu_jax()
    if not 0 <= node_id < config.topology.num_nodes:
        raise ValueError(
            f"--node-id {node_id} out of range for "
            f"topology.num_nodes={config.topology.num_nodes}"
        )
    from murmura_tpu.utils.factories import build_attack

    attack = build_attack(config)
    compromised = sorted(attack.get_compromised_nodes()) if attack else []
    NodeProcess(
        config,
        node_id=node_id,
        run_id=run_id,
        t_start=t_start,
        compromised_ids=compromised,
        host=host,
    ).run()

"""Single-machine launcher for the ZMQ backend
(reference: murmura/distributed/runner.py:33-213).

Computes a shared t_start = monotonic() + startup_grace, prints run_id +
t_start for multi-machine operators, spawns the monitor first and then one
process per node (picklable module-level entry points), joins the monitor
for the history, and terminates stragglers.
"""

import multiprocessing as mp
import uuid
from typing import Any, Dict, List

from murmura_tpu.config.schema import Config
from murmura_tpu.distributed.endpoints import Endpoints


def _monitor_main(config: Config, run_id: str, t_start: float,
                  compromised: List[int], queue) -> None:
    from murmura_tpu.distributed.monitor import Monitor

    history = Monitor(
        config, run_id, t_start, compromised_ids=set(compromised)
    ).run()
    queue.put(history)


def _node_main(config: Config, node_id: int, run_id: str, t_start: float,
               compromised: List[int], resume: bool = False) -> None:
    from murmura_tpu.distributed.node_process import NodeProcess

    # DMTT configs get the trust-protocol process (reference: runner.py:88-103)
    if config.dmtt is not None:
        from murmura_tpu.dmtt.node_process import DMTTNodeProcess

        cls = DMTTNodeProcess
    else:
        cls = NodeProcess
    cls(
        config,
        node_id=node_id,
        run_id=run_id,
        t_start=t_start,
        compromised_ids=compromised,
        resume=resume,
    ).run()


class DistributedRunner:
    """Launches monitor + N node processes on this machine.

    ``run()`` is ``start()`` + ``wait()``.  The split exists so callers can
    reach the spawned processes mid-run — the fault-injection test SIGKILLs
    a node between rounds and asserts the survivors degrade per the
    deadline semantics (reference: node_process.py:249-276).
    """

    def __init__(self, config: Config):
        self.config = config
        self.node_procs: List[Any] = []
        self.t_start: float = 0.0
        self._monitor = None
        self._queue = None
        # Fault-injection state (config.faults.enabled with churn): the
        # injector thread SIGKILLs scheduled nodes mid-round and respawns
        # them (resume-from-checkpoint) at their scheduled recovery.
        self.injector = None
        self._ctx = None
        self._run_id = None
        self._compromised: List[int] = []

    def run(self) -> Dict[str, List[Any]]:
        self.start()
        return self.wait()

    def start(self) -> None:
        import importlib.util
        import os

        from murmura_tpu.utils.factories import build_attack

        if self.config.dmtt is not None:
            # Fail fast in the parent rather than letting every child die
            # and the monitor idle until its hard deadline.
            if importlib.util.find_spec("murmura_tpu.dmtt.node_process") is None:
                raise RuntimeError(
                    "config.dmtt is set but the DMTT protocol module is not "
                    "available in this build"
                )

        # Same fail-fast principle for data/model wiring: a mismatch would
        # otherwise crash all N children with raw tracebacks while the head
        # idles on monitor.join for the full time budget.  resolve_model
        # raises ConfigError with the config-level explanation.
        # max_samples=32 keeps the head's throwaway stacking cheap — the
        # shape check only needs one sample's dimensionality.
        from murmura_tpu.data.registry import build_federated_data
        from murmura_tpu.utils.factories import resolve_model

        resolve_model(
            self.config,
            build_federated_data(
                self.config.data.adapter,
                self.config.data.params,
                num_nodes=self.config.topology.num_nodes,
                seed=self.config.experiment.seed,
                max_samples=min(32, self.config.training.max_samples or 32),
            ),
        )

        # Children must boot clean of the single-tenant TPU plugin: the axon
        # sitecustomize registers at interpreter start (before any code in
        # the child runs), so strip the trigger env for the spawn window —
        # spawn inherits os.environ.  ZMQ-backend local training is a CPU
        # path by design.  The parent's env is restored afterwards so later
        # simulation/tpu runs in the same process are unaffected.
        saved_env = {
            k: os.environ.get(k) for k in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS")
        }
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"

        cfg = self.config
        attack = build_attack(cfg)
        compromised = sorted(attack.get_compromised_nodes()) if attack else []

        run_id = uuid.uuid4().hex[:8]
        endpoints = Endpoints(cfg.distributed, run_id)
        endpoints.ensure_dirs()

        import time

        t_start = time.monotonic() + cfg.distributed.startup_grace_s
        print(
            f"[runner] run_id={run_id} t_start={t_start:.3f} "
            f"(grace {cfg.distributed.startup_grace_s}s) — pass these to "
            "`murmura_tpu run-node` on other machines",
            flush=True,
        )

        ctx = mp.get_context("spawn")
        self._ctx = ctx
        self._run_id = run_id
        self._compromised = compromised
        self._queue = ctx.Queue()
        self._monitor = ctx.Process(
            target=_monitor_main,
            args=(cfg, run_id, t_start, compromised, self._queue),
            daemon=False,
        )
        self._monitor.start()

        self.t_start = t_start
        self.node_procs = []
        for node_id in range(cfg.topology.num_nodes):
            p = ctx.Process(
                target=_node_main,
                args=(cfg, node_id, run_id, t_start, compromised),
                daemon=False,
            )
            p.start()
            self.node_procs.append(p)

        # All children are spawned; restore the parent's env.
        for k, v in saved_env.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v

        from murmura_tpu.utils.factories import build_fault_schedule

        schedule = build_fault_schedule(cfg)
        if schedule is not None and cfg.faults.crash_prob > 0:
            from murmura_tpu.faults.injector import FaultInjector

            self.injector = FaultInjector(
                schedule,
                rounds=cfg.experiment.rounds,
                round_duration=cfg.distributed.round_duration_s,
                t_start=t_start,
                kill=self._kill_node,
                respawn=self._respawn_node,
            )
            self.injector.start()

    def _kill_node(self, node_id: int) -> None:
        """SIGKILL a node's current process (FaultInjector callback)."""
        import os
        import signal

        p = self.node_procs[node_id]
        if p.is_alive():
            os.kill(p.pid, signal.SIGKILL)

    def _respawn_node(self, node_id: int) -> None:
        """Start a fresh resume-from-checkpoint process for a recovering
        node (FaultInjector callback).  Same TPU-env strip/restore dance as
        start(): spawn inherits os.environ at process creation (there is no
        per-Process env with multiprocessing, and the axon sitecustomize
        registers at interpreter start, before any child code could strip
        it).  Runs on the injector watcher thread, so a host that embeds
        DistributedRunner and touches JAX_PLATFORMS/PALLAS_AXON_POOL_IPS on
        another thread mid-run can observe the brief strip window; the CLI
        single-run path cannot."""
        import os

        old = self.node_procs[node_id]
        if old.is_alive():  # pragma: no cover - schedule/kill race
            return
        saved_env = {
            k: os.environ.get(k) for k in ("PALLAS_AXON_POOL_IPS", "JAX_PLATFORMS")
        }
        os.environ.pop("PALLAS_AXON_POOL_IPS", None)
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            p = self._ctx.Process(
                target=_node_main,
                args=(self.config, node_id, self._run_id, self.t_start,
                      self._compromised, True),
                daemon=False,
            )
            p.start()
            self.node_procs[node_id] = p
        finally:
            for k, v in saved_env.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v

    def wait(self) -> Dict[str, List[Any]]:
        cfg = self.config
        history: Dict[str, List[Any]] = {}
        try:
            # generous join: rounds * duration + grace + hard-deadline margin
            budget = (
                cfg.distributed.startup_grace_s
                + (cfg.experiment.rounds + 3) * cfg.distributed.round_duration_s
                + 60.0
            )
            self._monitor.join(timeout=budget)
            if self._monitor.is_alive():
                self._monitor.terminate()
            while not self._queue.empty():
                history = self._queue.get_nowait()
        finally:
            if self.injector is not None:
                self.injector.stop()
            for p in self.node_procs:
                p.join(timeout=5.0)
            for p in self.node_procs:
                if p.is_alive():
                    p.terminate()
        if cfg.telemetry.enabled:
            # The Monitor process owns the manifest (one writer per run);
            # the runner only points the operator at it.
            from murmura_tpu.utils.factories import default_telemetry_dir

            print(
                f"[runner] telemetry run written to "
                f"{default_telemetry_dir(cfg)} — render with "
                "`murmura report <dir>`",
                flush=True,
            )
        return history

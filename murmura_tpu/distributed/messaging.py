"""Wire format (reference: murmura/distributed/messaging.py:11-78).

2-frame multipart: header = struct("!Bii") (1-byte MsgType + 4-byte sender
id + 4-byte round tag), then the payload.  The round tag lets receivers drop
stale messages that arrive after their round's deadline — the reference's
untagged states can be mistaken for the next round's broadcast.  Model
states travel as flattened float32 parameter vectors serialized with numpy
(the reference ships full torch state dicts via torch.save — flat vectors
are both smaller and exactly what the aggregation rules consume);
metrics/claims use pickle.
"""

import io
import pickle
import struct
from enum import IntEnum
from typing import Any, Tuple

import numpy as np

_HEADER = struct.Struct("!Bii")


class MsgType(IntEnum):
    MODEL_STATE = 1
    METRICS = 2
    TOPO_CLAIM = 3
    # Intra-coalition benign-state exchange for the ALIE colluding attack
    # (attackers coordinate out-of-band by construction — Baruch et al.).
    COLLUDE_STATE = 4


def pack_state(flat: np.ndarray) -> bytes:
    """Serialize a flat float32 parameter vector."""
    buf = io.BytesIO()
    np.save(buf, np.asarray(flat, dtype=np.float32), allow_pickle=False)
    return buf.getvalue()


def unpack_state(payload: bytes) -> np.ndarray:
    return np.load(io.BytesIO(payload), allow_pickle=False)


def pack_obj(obj: Any) -> bytes:
    """Serialize metrics / topology claims."""
    return pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)


def unpack_obj(payload: bytes) -> Any:
    return pickle.loads(payload)


def encode(
    msg_type: MsgType, sender: int, payload: bytes, round_idx: int
) -> Tuple[bytes, bytes]:
    """Build the 2-frame multipart message."""
    return _HEADER.pack(int(msg_type), sender, round_idx), payload


def decode(frames) -> Tuple[MsgType, int, int, bytes]:
    """Parse a received multipart message -> (type, sender, round, payload)."""
    if len(frames) != 2:
        raise ValueError(f"Expected 2 frames, got {len(frames)}")
    msg_type, sender, round_idx = _HEADER.unpack(frames[0])
    return MsgType(msg_type), sender, round_idx, frames[1]

"""ZeroMQ multi-process backend (reference: murmura/distributed/).

Retained for capability parity as the non-TPU multi-machine path (SURVEY.md
§5 north star: "alongside the existing simulation and ZMQ-distributed
backends").  One OS process per FL node plus a passive monitor; round
boundaries are wall-clock (t_start + k * round_duration_s) with no control
messages; fault tolerance is deadline-based partial aggregation
(reference: murmura/distributed/node_process.py:8-12, 249-276).

The TPU backend replaces all of this with mesh collectives (parallel/mesh.py);
this package exists so experiments that need share-nothing processes (e.g.
real multi-machine deployments without TPU interconnect) keep working.
"""

from murmura_tpu.distributed.endpoints import Endpoints
from murmura_tpu.distributed.messaging import MsgType, encode, decode

__all__ = ["Endpoints", "MsgType", "encode", "decode"]

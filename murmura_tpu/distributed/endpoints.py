"""Socket address computation (reference: murmura/distributed/endpoints.py:31-69).

IPC (single machine): per-run directories under ipc_dir so concurrent runs
never collide.  TCP (multi-machine): node i binds base_port + i; per-node
host overrides via node_hosts.
"""

import os
from typing import Optional

from murmura_tpu.config.schema import DistributedConfig


class Endpoints:
    """Resolves bind/connect addresses for nodes and the monitor."""

    MONITOR_ID = -1

    def __init__(self, cfg: DistributedConfig, run_id: str):
        self.cfg = cfg
        self.run_id = run_id

    # -- IPC ----------------------------------------------------------------

    def _ipc_path(self, name: str) -> str:
        return os.path.join(self.cfg.ipc_dir, self.run_id, name)

    def ensure_dirs(self) -> None:
        if self.cfg.transport == "ipc":
            os.makedirs(os.path.join(self.cfg.ipc_dir, self.run_id), exist_ok=True)

    def node_checkpoint_path(self, node_id: int) -> str:
        """Per-node crash-recovery checkpoint (faults.enabled runs).

        Lives under the run's ipc_dir regardless of transport — it is a
        LOCAL path on whichever machine hosts the node, which is exactly
        the durability a restarted process on the same machine needs.
        """
        return self._ipc_path(f"node_{node_id}.ckpt.npz")

    # -- addresses ----------------------------------------------------------

    def node_bind(self, node_id: int, host: Optional[str] = None) -> str:
        """Address node_id's PULL socket binds on."""
        if self.cfg.transport == "ipc":
            return f"ipc://{self._ipc_path(f'node_{node_id}')}"
        bind_host = host or "0.0.0.0"
        return f"tcp://{bind_host}:{self.cfg.base_port + node_id}"

    def node_connect(self, node_id: int) -> str:
        """Address peers use to PUSH to node_id."""
        if self.cfg.transport == "ipc":
            return f"ipc://{self._ipc_path(f'node_{node_id}')}"
        host = (self.cfg.node_hosts or {}).get(node_id, self.cfg.host)
        return f"tcp://{host}:{self.cfg.base_port + node_id}"

    def monitor_bind(self) -> str:
        if self.cfg.transport == "ipc":
            return f"ipc://{self._ipc_path('monitor')}"
        return f"tcp://0.0.0.0:{self.cfg.coordinator_pull_port}"

    def monitor_connect(self) -> str:
        if self.cfg.transport == "ipc":
            return f"ipc://{self._ipc_path('monitor')}"
        return f"tcp://{self.cfg.host}:{self.cfg.coordinator_pull_port}"

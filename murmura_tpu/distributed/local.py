"""Single-node runtime for the ZMQ backend.

One process owns one FL node (reference: murmura/core/node.py:14-252 held by
murmura/distributed/node_process.py).  Training/eval are small jitted CPU
programs; aggregation reuses the SAME pure vectorized rules as the
simulation/tpu backends by building a fixed-size mini-network tensor —
slot 0 is this node, slots 1..M-1 hold the neighbor states that arrived
before the round deadline (missing neighbors are masked out of the
adjacency row, reproducing the reference's partial-aggregation semantics,
node_process.py:259-269).  A fixed M = 1 + max_degree keeps shapes static so
nothing recompiles as the arrival set varies round to round.

Known tradeoff: reusing the square network-wide rules means the mini network
computes all M rows of the cheap O(P)-per-entry math (distances, trust
updates) although only row 0 is consumed — an O(degree) overhead per process
accepted to keep one implementation of every rule.  The expensive part does
NOT pay that tax: probe-based rules (UBAR stage 2, evidential trust, DMTT
scoring) receive this node's probe batch with a leading dim of 1, so each of
the M models is forwarded once (reference per-node cost, ubar.py:152-202)
rather than M^2 times.
"""

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from murmura_tpu.aggregation.base import AggContext, AggregatorDef
from murmura_tpu.models.core import Model
from murmura_tpu.ops.flatten import make_flatteners
from murmura_tpu.ops.losses import (
    evidential_loss,
    masked_cross_entropy,
    uncertainty_metrics,
)


class LocalNode:
    """One FL peer: local SGD, masked eval, rule-based aggregation."""

    def __init__(
        self,
        node_id: int,
        model: Model,
        agg: AggregatorDef,
        x: np.ndarray,
        y: np.ndarray,
        *,
        eval_x: Optional[np.ndarray] = None,
        eval_y: Optional[np.ndarray] = None,
        max_neighbors: int,
        local_epochs: int = 1,
        batch_size: int = 64,
        lr: float = 0.01,
        total_rounds: int = 20,
        probe_size: Optional[int] = None,
        annealing_rounds: int = 10,
        lambda_weight: float = 0.1,
        seed: int = 42,
    ):
        self.node_id = node_id
        self.model = model
        self.agg = agg
        self.total_rounds = total_rounds
        self.mini_n = 1 + max_neighbors

        n_samples = len(y)
        self.x = jnp.asarray(x)
        self.y = jnp.asarray(y, jnp.int32)
        self.n_samples = n_samples
        # Held-out evaluation arrays (round 3); default = training shard,
        # matching the reference (murmura/core/network.py:289-294) and the
        # simulation/tpu backends' eval_arrays fallback.
        self._eval_x = self.x if eval_x is None else jnp.asarray(eval_x)
        self._eval_y = self.y if eval_y is None else jnp.asarray(eval_y, jnp.int32)
        # reference batch rule (network.py:278-287)
        self.eff_batch = int(min(batch_size, max(2, n_samples)))
        self.steps = n_samples // self.eff_batch if n_samples > self.eff_batch else 1
        self.local_epochs = local_epochs
        self.lr = lr
        self.evidential = model.evidential
        self.num_classes = model.num_classes
        self.annealing_rounds = annealing_rounds
        self.lambda_weight = lambda_weight

        self.rng = jax.random.PRNGKey(seed)
        self.params = model.init(jax.random.PRNGKey(seed))
        self._ravel, self._unravel, self.model_dim = make_flatteners(self.params)

        p_size = int(min(n_samples, probe_size or self.eff_batch))
        self._probe_x = self.x[:p_size]
        self._probe_y = self.y[:p_size]
        self._probe_mask = jnp.ones((p_size,), jnp.float32)

        # Per-rule carried state, projected per AggregatorDef.state_kind.
        template = agg.init_state(self.mini_n)
        unknown = [k for k in template if agg.state_kind.get(k) not in ("node", "edge")]
        if unknown:
            raise ValueError(
                f"Aggregator '{agg.name}' carries state keys {unknown} without a "
                "state_kind annotation — the distributed backend cannot project "
                "them per-neighbor and would silently reset them every round"
            )
        self._node_state = {
            k: np.asarray(v[0]) for k, v in template.items()
            if agg.state_kind.get(k) == "node"
        }
        self._edge_state: Dict[str, Dict[int, np.ndarray]] = {
            k: {} for k, v in template.items() if agg.state_kind.get(k) == "edge"
        }
        self._state_template = {k: np.asarray(v) for k, v in template.items()}

        self._train_fn = jax.jit(self._build_train_fn())
        self._eval_fn = jax.jit(self._build_eval_fn())
        self._agg_fn = jax.jit(self._build_agg_fn())
        self._probe_eval_fn = jax.jit(self._build_probe_eval_fn())
        self._last_stats: Dict[str, float] = {}

    # ------------------------------------------------------------------
    # training
    # ------------------------------------------------------------------

    def _build_train_fn(self):
        model = self.model
        n, b, steps = self.n_samples, self.eff_batch, self.steps
        evidential, num_classes = self.evidential, self.num_classes
        annealing, lam_w = self.annealing_rounds, self.lambda_weight
        lr, epochs = self.lr, self.local_epochs

        def loss_fn(params, xb, yb, key, round_idx):
            out = model.apply(params, xb, key, True)
            if evidential:
                lam = jnp.minimum(1.0, round_idx / max(1, annealing)) * lam_w
                return evidential_loss(out, yb, jnp.ones(xb.shape[0]), num_classes, lam)
            loss, _ = masked_cross_entropy(out, yb, jnp.ones(xb.shape[0]))
            return loss

        grad_fn = jax.grad(loss_fn)

        def train(params, key, round_idx):
            def epoch(params, ekey):
                pkey, skey = jax.random.split(ekey)
                perm = jax.random.permutation(pkey, n)

                def step(params, t):
                    pos = (t * b + jnp.arange(b)) % n
                    idx = perm[pos]
                    g = grad_fn(
                        params, self.x[idx], self.y[idx],
                        jax.random.fold_in(skey, t), round_idx,
                    )
                    return jax.tree_util.tree_map(
                        lambda p, gg: p - lr * gg, params, g
                    ), None

                params, _ = jax.lax.scan(step, params, jnp.arange(steps))
                return params, None

            params, _ = jax.lax.scan(epoch, params, jax.random.split(key, epochs))
            return params

        return train

    def _build_eval_fn(self):
        model = self.model
        evidential = self.evidential
        ex, ey = self._eval_x, self._eval_y

        def evaluate(params):
            out = model.apply(params, ex, None, False)
            mask = jnp.ones((ex.shape[0],), jnp.float32)
            if evidential:
                unc = uncertainty_metrics(out)
                probs = unc["probs"]
                nll = -jnp.log(
                    jnp.take_along_axis(probs, ey[:, None], axis=-1)[:, 0] + 1e-10
                )
                acc = (jnp.argmax(out, -1) == ey).mean()
                return {
                    "loss": nll.mean(),
                    "accuracy": acc,
                    "vacuity": unc["vacuity"].mean(),
                    "entropy": unc["entropy"].mean(),
                    "strength": unc["strength"].mean(),
                }
            loss, acc = masked_cross_entropy(out, ey, mask)
            return {"loss": loss, "accuracy": acc}

        return evaluate

    def _build_probe_eval_fn(self):
        """Score an arbitrary flat state on this node's probe data — DMTT
        model-compatibility scoring (reference: murmura/dmtt/
        node_process.py:309-363)."""
        model = self.model
        evidential = self.evidential
        unravel = self._unravel

        def probe_eval(flat):
            params = unravel(flat)
            out = model.apply(params, self._probe_x, None, False)
            acc = (jnp.argmax(out, -1) == self._probe_y).mean()
            if evidential:
                vac = uncertainty_metrics(out)["vacuity"].mean()
            else:
                vac = jnp.zeros(())
            return {"accuracy": acc, "vacuity": vac}

        return probe_eval

    # ------------------------------------------------------------------
    # aggregation via the shared vectorized rules
    # ------------------------------------------------------------------

    def _build_agg_fn(self):
        m = self.mini_n
        agg = self.agg
        ctx = AggContext(
            apply_fn=self.model.apply,
            unravel=self._unravel,
            # Leading dim 1 = single evaluator: probe-based rules evaluate
            # each of the M models ONCE on this node's batch (O(M) forwards)
            # and broadcast the metric row, instead of the M x M cross-eval
            # a tiled [M, B, ...] layout would cost.  Only row 0 of the mini
            # network is consumed, and all rows are identical either way.
            probe_x=self._probe_x[None],
            probe_y=self._probe_y[None],
            probe_mask=self._probe_mask[None],
            evidential=self.evidential,
            num_classes=self.num_classes,
            total_rounds=self.total_rounds,
        )

        def aggregate(own_flat, neighbor_flats, neighbor_mask, round_idx, state):
            # mini network: slot 0 = self, slots 1.. = neighbors
            flats = jnp.concatenate([own_flat[None], neighbor_flats], axis=0)
            adj = jnp.zeros((m, m), jnp.float32)
            adj = adj.at[0, 1:].set(neighbor_mask)
            adj = adj.at[1:, 0].set(neighbor_mask)
            new_flat, new_state, stats = agg.aggregate(
                flats, flats, adj, round_idx, state, ctx
            )
            row_stats = {k: v[0] for k, v in stats.items()}
            return new_flat[0], new_state, row_stats

        return aggregate

    def _mini_state(self, neighbor_ids: List[int]) -> Dict[str, jnp.ndarray]:
        state = {}
        for k, template in self._state_template.items():
            arr = np.array(template)
            kind = self.agg.state_kind.get(k)
            if kind == "node":
                arr[0] = self._node_state[k]
            elif kind == "edge":
                for slot, nid in enumerate(neighbor_ids, start=1):
                    if nid in self._edge_state[k]:
                        arr[0, slot] = self._edge_state[k][nid]
            state[k] = jnp.asarray(arr)
        return state

    def _store_state(self, state, neighbor_ids: List[int]) -> None:
        for k in self._state_template:
            kind = self.agg.state_kind.get(k)
            arr = np.asarray(state[k])
            if kind == "node":
                self._node_state[k] = arr[0]
            elif kind == "edge":
                for slot, nid in enumerate(neighbor_ids, start=1):
                    self._edge_state[k][nid] = arr[0, slot]

    # ------------------------------------------------------------------
    # public API (reference Node surface: core/node.py:59-252)
    # ------------------------------------------------------------------

    def local_train(self, round_idx: int) -> None:
        self.rng, key = jax.random.split(self.rng)
        self.params = self._train_fn(
            self.params, key, jnp.asarray(round_idx, jnp.float32)
        )

    def get_flat_state(self) -> np.ndarray:
        return np.asarray(self._ravel(self.params), dtype=np.float32)

    def set_flat_state(self, flat: np.ndarray) -> None:
        self.params = self._unravel(jnp.asarray(flat))

    def evaluate(self) -> Dict[str, float]:
        return {k: float(v) for k, v in self._eval_fn(self.params).items()}

    def probe_eval_flat(self, flat: np.ndarray) -> Dict[str, float]:
        """Accuracy + vacuity of a neighbor's flat state on local probe data."""
        out = self._probe_eval_fn(jnp.asarray(flat))
        return {k: float(v) for k, v in out.items()}

    def aggregate_with_neighbors(
        self, neighbor_states: Dict[int, np.ndarray], round_num: int
    ) -> None:
        """Aggregate own params with the received subset (partial OK)."""
        neighbor_ids = sorted(neighbor_states)[: self.mini_n - 1]
        flats = np.zeros((self.mini_n - 1, self.model_dim), np.float32)
        mask = np.zeros((self.mini_n - 1,), np.float32)
        for slot, nid in enumerate(neighbor_ids):
            flats[slot] = neighbor_states[nid]
            mask[slot] = 1.0
        state = self._mini_state(neighbor_ids)
        new_flat, new_state, stats = self._agg_fn(
            self._ravel(self.params),
            jnp.asarray(flats),
            jnp.asarray(mask),
            jnp.asarray(float(round_num)),
            state,
        )
        self.params = self._unravel(new_flat)
        self._store_state(new_state, neighbor_ids)
        self._last_stats = {k: float(v) for k, v in stats.items()}

    def get_aggregator_statistics(self) -> Dict[str, float]:
        return dict(self._last_stats)

"""Sketchguard: Count-Sketch compressed filtering
(reference: murmura/aggregation/sketchguard.py:13-274).

Filtering decisions run on [sketch_size] Count-Sketch compressions of the
flattened states (what would travel on the wire — sketchguard.py:126-155);
aggregation itself is BALANCE-style on the full states (sketchguard.py:236-261).
The adaptive threshold boosts by 1.5x when the mean of the last 3 acceptance
rates drops below 0.3 (attack detection — sketchguard.py:189-204); that
3-round window is this rule's carried state.
"""

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from murmura_tpu.aggregation.balance import accept_with_closest_fallback
from murmura_tpu.aggregation.base import (
    AggContext,
    AggregatorDef,
    InfluenceDecl,
    blend_with_own,
    circulant_masked_mean,
    circulant_neighbor_distances,
    masked_neighbor_mean,
    pairwise_l2_distances,
)
from murmura_tpu.ops.sketch import count_sketch, make_sketch_tables


def make_sketchguard(
    model_dim: int,
    sketch_size: int = 1000,
    gamma: float = 2.0,
    kappa: float = 1.0,
    alpha: float = 0.5,
    min_neighbors: int = 1,
    network_seed: int = 42,
    attack_detection_window: int = 5,
    exchange_offsets: Optional[Sequence[int]] = None,
    sparse_exchange: bool = False,
    **_params,
) -> AggregatorDef:
    hash_np, sign_np = make_sketch_tables(model_dim, sketch_size, network_seed)
    hash_table = jnp.asarray(hash_np)
    sign_table = jnp.asarray(sign_np)
    offsets = None if exchange_offsets is None else [int(o) for o in exchange_offsets]
    if sparse_exchange and offsets is None:
        raise ValueError("sparse_exchange requires exchange_offsets")

    # The reference keeps a deque(maxlen=attack_detection_window) of
    # acceptance rates but its threshold logic only reads the last 3
    # (sketchguard.py:64, 197-201); a window < 3 therefore disables the
    # attack factor entirely.  We carry the full window for parity.
    window = max(1, int(attack_detection_window))

    def init_state(num_nodes: int):
        return {
            # rolling acceptance-rate history, most recent last
            "acc_window": np.zeros((num_nodes, window), dtype=np.float32),
            "window_len": np.zeros((num_nodes,), dtype=np.int32),
        }

    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        sketch_one = lambda v: count_sketch(v, hash_table, sign_table, sketch_size)
        own_sk = jax.vmap(sketch_one)(own)
        bcast_sk = jax.vmap(sketch_one)(bcast)

        own_sk_norm = jnp.sqrt(jnp.sum(own_sk * own_sk, axis=-1))

        lambda_t = round_idx / jnp.maximum(1, ctx.total_rounds)
        time_factor = gamma * jnp.exp(-kappa * lambda_t)
        # Attack detection: boost threshold when the mean of the last 3
        # acceptance rates dropped below 0.3, once >= 3 rounds are in the
        # window (sketchguard.py:195-201).
        window_active = (state["window_len"] >= 3) & (window >= 3)
        recent = state["acc_window"][:, -3:].mean(axis=1)
        attack_factor = jnp.where(window_active & (recent < 0.3), 1.5, 1.0)
        threshold = time_factor * attack_factor * own_sk_norm

        if sparse_exchange:
            # Sparse exchange mode: the distance filter itself runs in
            # *circulant* sketch space — [k, N] per-offset sketch distances
            # via rolls instead of the [N, N] pairwise matrix — so nothing
            # O(N^2) is ever materialized and the whole rule stays
            # ppermute-only (the 'sparse' collectives declaration below).
            # The direct elementwise norm differs from the Gram-identity
            # path in f32 rounding, so sparse-vs-circulant parity for this
            # rule is allclose, not byte-exact.
            edge_b = adj > 0  # [k, N]
            d_k = circulant_neighbor_distances(
                own_sk, bcast_sk, offsets
            )  # [k, N]
            accept_k_b = edge_b & (d_k <= threshold[None, :])
            count = accept_k_b.sum(axis=0)
            closest = jnp.argmin(jnp.where(edge_b, d_k, jnp.inf), axis=0)
            has_any = edge_b.any(axis=0)
            fallback = (
                ((count < min_neighbors) & has_any)[None, :]
                & (jnp.arange(len(offsets))[:, None] == closest[None, :])
                & edge_b
            )
            accept_k = (accept_k_b | fallback).astype(own.dtype)
            neighbor_avg = circulant_masked_mean(bcast, accept_k, offsets)
            has_accepted = accept_k.sum(axis=0) > 0
            new_flat = blend_with_own(own, neighbor_avg, has_accepted, alpha)

            degree = jnp.maximum(adj.sum(axis=0), 1.0)
            acc_rate = accept_k.sum(axis=0) / degree
            new_state = {
                "acc_window": jnp.concatenate(
                    [state["acc_window"][:, 1:], acc_rate[:, None]], axis=1
                ),
                "window_len": jnp.minimum(state["window_len"] + 1, window),
            }
            stats = {
                "acceptance_rate": acc_rate,
                "threshold": threshold,
                "compression_ratio": jnp.full(
                    (own.shape[0],), model_dim / sketch_size, dtype=own.dtype
                ),
            }
            return new_flat, new_state, stats

        sk_dist = pairwise_l2_distances(own_sk, bcast_sk)
        accepted = accept_with_closest_fallback(sk_dist, adj, threshold, min_neighbors)

        if offsets is not None:
            # The filter ran in cheap sketch space ([N, S]); only the
            # full-state mean is heavy. On a circulant graph the accepted
            # mask is nonzero only at the k offsets — extract those columns
            # and accumulate rolled copies instead of an [N, N] @ [N, P]
            # gather (tpu.exchange: ppermute).
            n = own.shape[0]
            cols = (
                jnp.arange(n)[None, :] + jnp.asarray(offsets)[:, None]
            ) % n  # [k, N]
            accept_k = accepted[jnp.arange(n)[None, :], cols]  # [k, N]
            neighbor_avg = circulant_masked_mean(bcast, accept_k, offsets)
        else:
            neighbor_avg = masked_neighbor_mean(bcast, accepted)
        has_accepted = accepted.sum(axis=1) > 0
        new_flat = blend_with_own(own, neighbor_avg, has_accepted, alpha)

        degree = jnp.maximum(adj.sum(axis=1), 1.0)
        acc_rate = accepted.sum(axis=1) / degree
        new_state = {
            "acc_window": jnp.concatenate(
                [state["acc_window"][:, 1:], acc_rate[:, None]], axis=1
            ),
            "window_len": jnp.minimum(state["window_len"] + 1, window),
        }
        stats = {
            "acceptance_rate": acc_rate,
            "threshold": threshold,
            "compression_ratio": jnp.full(
                (own.shape[0],), model_dim / sketch_size, dtype=own.dtype
            ),
        }
        return new_flat, new_state, stats

    return AggregatorDef(
        name="sketchguard",
        aggregate=aggregate,
        init_state=init_state,
        state_kind={"acc_window": "node", "window_len": "node"},
        # MUR202: the distance filter runs in dense *sketch* space ([N, S],
        # S << P) by design, so even the circulant mode gathers/reduces the
        # small sketches — only the heavy [N, P] mean must stay ppermute.
        # The sparse mode filters in *circulant* sketch space instead
        # (rolled per-offset distances), so it is ppermute-only (MUR601).
        collectives={
            "dense": {"all_gather", "all_reduce"},
            "circulant": {"all_gather", "all_reduce", "ppermute"},
            "sparse": {"ppermute"},
        },
        # MUR800: BALANCE-style distance filtering in sketch space — the
        # accept set is data-dependent and spans the whole neighborhood on
        # benign inputs; declared unbounded (the BALANCE rationale).
        influence=InfluenceDecl(
            "unbounded",
            note="sketch-space distance accept-filter: benign inputs "
            "accept every neighbor; exclusion is data-dependent",
        ),
    )

"""Multi-Krum selection (reference: murmura/aggregation/krum.py:8-75).

Per node i over candidates {i} ∪ N(i) (m = 1 + degree, c expected Byzantine):
- requires c < (m-2)/2, else fall back to own state (krum.py:49-52);
- score(j) = sum of the (m - c - 2) smallest distances from j to the other
  candidates (krum.py:64-71); winner = argmin score (krum.py:73-75).

TPU shape: two global distance matrices (bcast-bcast and own-bcast) feed
every node's selection.  Candidate i in node i's view is its *own* true
state (krum.py:45: ``[own_state] + neighbors``), so the entries involving
the self candidate are swapped to the own-state distances.

Each node gathers only its candidate block out of the shared [N, N]
matrices: candidate indices [N, m] (self first, then neighbors) index a
[m, m] pair block per node, so the per-node working set is O(N·m²) with
m = max_candidates instead of the O(N³) that sorting full per-node [N, N]
copies under vmap materializes (round-2 verdict weak #4).  ``max_candidates``
is injected by the factories as max-degree+1 for static topologies; the
default m = N is the dense fallback for dynamic graphs (mobility/DMTT).

On circulant graphs (tpu.exchange: ppermute) the dense Gram disappears
entirely: see ``aggregate_circulant`` below — O(k·N·P) delta vectors, the
O(degree) exchange the other five rules already have.
"""

from typing import Optional, Sequence

import jax
import jax.numpy as jnp

from murmura_tpu.aggregation.base import (
    AggContext,
    AggregatorDef,
    InfluenceDecl,
    candidate_indices,
    circulant_in_degree,
    circulant_masked_mean,
    circulant_neighbor_distances,
    pairwise_l2_distances,
)


def make_krum(
    num_compromised: int = 0,
    max_candidates: int = None,
    exchange_offsets: Optional[Sequence[int]] = None,
    sparse_exchange: bool = False,
    pallas: bool = False,
    **_params,
) -> AggregatorDef:
    c = int(num_compromised)
    mc = None if max_candidates is None else int(max_candidates)
    offsets = None if exchange_offsets is None else [int(o) for o in exchange_offsets]
    if sparse_exchange and offsets is None:
        raise ValueError("sparse_exchange requires exchange_offsets")
    pallas = bool(pallas)  # ops/pallas_agg.py fused distance kernels

    def aggregate_circulant(own, bcast, adj, round_idx, state, ctx: AggContext):
        """O(degree) Krum for circulant graphs (tpu.exchange: ppermute).

        Every candidate-pair distance on a circulant graph is one entry of
        a shared "delta vector": for candidates at offsets o_a, o_b from
        node i, ``||bcast[i+o_a] - bcast[i+o_b]||`` equals
        ``B_d[i + min(o_a, o_b)]`` with ``B_d[j] = ||bcast_j - bcast_{j+d}||``
        and d = |o_b - o_a|.  So the whole selection needs only
        |deltas| + k rolled elementwise norms — O(k·N·P) work and O(k·N)
        memory versus the dense path's O(N²·P) Gram matmul and [N, N]
        matrices — and each roll lowers to boundary collective-permutes on
        a sharded node axis.
        """
        n = own.shape[0]
        k = len(offsets)
        m = k + 1  # self + full circulant degree at every node
        # The Krum constraint (krum.py:49-52) holds or fails identically at
        # every node of a degree-regular graph — a static Python bool, not
        # a traced fallback.  Scores are computed either way so the
        # krum_score stat matches the dense path's (which reports the
        # argmin score even when the constraint forces the own state).
        # Sparse exchange mode: ``adj`` is the [k, N] edge mask, the
        # candidate count varies per node (one_peer schedules, fault-
        # dropped links), so validity/constraint/trim depth become traced
        # per-node values — with an all-ones mask every formula below
        # reduces bit-exactly to the static circulant path (appending
        # +0.0 terms and where(True, ...) selections are exact).
        ok = c < (m - 2) / 2

        own_d = circulant_neighbor_distances(
            own, bcast, offsets, pallas=pallas
        )  # [k, N]
        deltas = sorted(
            {abs(o2 - o1) for o1 in offsets for o2 in offsets if o1 != o2}
        )
        bcast_d = circulant_neighbor_distances(
            bcast, bcast, deltas, pallas=pallas
        )  # [D, N]
        didx = {d: i for i, d in enumerate(deltas)}

        # [m, m, N] candidate-pair distances per node, assembled from the
        # delta vectors with cheap [N] rolls (m is a small static constant).
        rows = []
        for a in range(m):
            cols = []
            for b in range(m):
                if a == b:
                    cols.append(jnp.full((n,), jnp.inf, own_d.dtype))
                elif a == 0 or b == 0:
                    cols.append(own_d[max(a, b) - 1])
                else:
                    o_a, o_b = offsets[a - 1], offsets[b - 1]
                    v = bcast_d[didx[abs(o_b - o_a)]]
                    cols.append(jnp.roll(v, -min(o_a, o_b)))
            rows.append(jnp.stack(cols))
        pair = jnp.stack(rows)  # [m, m, N]

        if sparse_exchange:
            valid = jnp.concatenate(
                [jnp.ones((1, n), adj.dtype), adj], axis=0
            ) > 0  # [m, N]: self always a candidate
            m_i = valid.sum(axis=0)  # [N] traced candidate counts
            pair_valid = valid[:, None, :] & valid[None, :, :]
            masked = jnp.where(pair_valid, pair, jnp.inf)
            num_closest = jnp.maximum(1, m_i - c - 2)  # [N]
            ranked = jnp.sort(masked, axis=1)
            take = (
                jnp.arange(m)[None, :, None] < num_closest[None, None, :]
            )
            scores = jnp.where(
                take & jnp.isfinite(ranked), ranked, 0.0
            ).sum(axis=1)  # [m, N]
            scores = jnp.where(valid, scores, jnp.inf)
            w = jnp.argmin(scores, axis=0)
            best = jnp.min(scores, axis=0)
            # Per-node constraint: too few candidates => own state.
            w = jnp.where(c < (m_i - 2) / 2, w, 0)
        else:
            num_closest = max(1, m - c - 2)
            ranked = jnp.sort(pair, axis=1)
            scores = ranked[:, :num_closest, :].sum(axis=1)  # [m, N]
            w = jnp.argmin(scores, axis=0)  # [N] candidate position
            best = jnp.min(scores, axis=0)

            if not ok:
                w = jnp.zeros((n,), w.dtype)  # every node keeps own state
        accept_k = (w[None, :] == jnp.arange(1, m)[:, None]).astype(own.dtype)
        neighbor_sel = circulant_masked_mean(bcast, accept_k, offsets)
        selected_own = w == 0
        new_flat = jnp.where(selected_own[:, None], own, neighbor_sel)
        offs = jnp.asarray([0] + offsets)
        stats = {
            "selected_index": (jnp.arange(n) + offs[w]) % n,
            "krum_score": best,
            "selected_own": selected_own.astype(jnp.float32),
        }
        if ctx.audit:
            # Sender-side audit taps via rolls only: accept_k[o_idx, i]
            # says receiver i selected its neighbor at offsets[o_idx], so
            # selected_by[s] = sum_o accept_k[o_idx, (s - o) % n] — each
            # roll lowers to boundary ppermutes on a sharded node axis,
            # keeping the circulant inventory ppermute-only (MUR400).
            stats["tap_selected_by"] = sum(
                jnp.roll(accept_k[i].astype(jnp.float32), o)
                for i, o in enumerate(offsets)
            )
            if sparse_exchange:
                stats["tap_considered_by"] = circulant_in_degree(adj, offsets)
            else:
                stats["tap_considered_by"] = jnp.full(
                    (n,), float(len(offsets))
                )
        return new_flat, state, stats

    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        n = own.shape[0]
        m_cap = n if mc is None else min(mc, n)
        d_bcast = pairwise_l2_distances(bcast, pallas=pallas)
        d_own = pairwise_l2_distances(
            own, bcast, pallas=pallas
        )  # [i, j] = ||own_i - bcast_j||

        cand_idx, valid = candidate_indices(adj, m_cap)  # [N, m] each
        pair_eye = jnp.eye(m_cap, dtype=bool)

        def select_for_node(node_idx, ci, vi):
            # [m, m] candidate-pair distances; entries involving the self
            # candidate (position 0) use the own-state distance row.
            d = d_bcast[ci][:, ci]
            own_d = d_own[node_idx, ci]  # [m]: ||own_i - bcast_{c_j}||
            is_self = ci == node_idx
            d = jnp.where(is_self[:, None], own_d[None, :], d)
            d = jnp.where(is_self[None, :], own_d[:, None], d)

            m = vi.sum()
            num_closest = jnp.maximum(1, m - c - 2)
            pair_valid = vi[None, :] & vi[:, None] & ~pair_eye
            masked = jnp.where(pair_valid, d, jnp.inf)
            ranked = jnp.sort(masked, axis=-1)
            take = jnp.arange(m_cap)[None, :] < num_closest
            scores = jnp.where(
                take & jnp.isfinite(ranked), ranked, 0.0
            ).sum(-1)
            scores = jnp.where(vi, scores, jnp.inf)
            w = jnp.argmin(scores)
            ok = c < (m - 2) / 2  # Krum constraint (krum.py:49-52)
            return jnp.where(ok, ci[w], node_idx), scores[w]

        winners, best_scores = jax.vmap(select_for_node)(
            jnp.arange(n), cand_idx, valid
        )
        # Winner index == self means "own state"; otherwise take the broadcast.
        # Row selection stays a gather: a one-hot matmul would be faster on
        # TPU (same pathology as the attack's old scatter) but 0*inf = NaN
        # propagates any single non-finite Byzantine broadcast to EVERY
        # node's output, breaking exactly the isolation Krum exists for.
        selected_own = winners == jnp.arange(n)
        new_flat = jnp.where(selected_own[:, None], own, bcast[winners])
        stats = {
            "selected_index": winners,
            "krum_score": best_scores,
            "selected_own": selected_own.astype(jnp.float32),
        }
        if ctx.audit:
            # Sender-side audit taps: how many peers picked node i's
            # broadcast as their Krum winner (self-selections excluded),
            # and how many had it as a candidate at all (its in-degree
            # under the round's effective adjacency — faults included).
            # ``murmura report`` turns considered - selected into the
            # per-node rejection counts.  The column sums reduce across
            # the sharded node axis, which lowers to the all_reduce the
            # dense inventory already declares (MUR400).
            node_ids = jnp.arange(n)
            picked = (winners[:, None] == node_ids[None, :]) & (
                ~selected_own[:, None]
            )
            stats["tap_selected_by"] = picked.astype(jnp.float32).sum(axis=0)
            stats["tap_considered_by"] = adj.astype(jnp.float32).sum(axis=0)
        return new_flat, state, stats

    return AggregatorDef(
        name="krum",
        aggregate=aggregate if offsets is None else aggregate_circulant,
        # MUR202: the dense Gram/selection gathers; the O(degree) circulant
        # selection must stay boundary ppermutes (the north-star invariant).
        collectives={
            "dense": {"all_gather", "all_reduce"},
            "circulant": {"ppermute"},
        },
        # Compressed exchange: the circulant path touches the broadcast
        # only through the shared roll kernels, which move the int8
        # payload (MUR700).
        quantized_exchange=offsets is not None,
        # MUR800: the output row is the single Krum winner (argmin score,
        # gathered / one-hot-mean-selected) or the node's own state — at
        # most ONE neighbor's values ever enter a node's parameters,
        # regardless of how the scores were computed (score dataflow is
        # selection influence, excluded by the analyzer's semantics).
        influence=InfluenceDecl(
            "bounded",
            bound=lambda k: 1,
            note="single Krum winner: at most one neighbor's state is "
            "ever adopted; scores only decide which",
        ),
    )

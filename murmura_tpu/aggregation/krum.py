"""Multi-Krum selection (reference: murmura/aggregation/krum.py:8-75).

Per node i over candidates {i} ∪ N(i) (m = 1 + degree, c expected Byzantine):
- requires c < (m-2)/2, else fall back to own state (krum.py:49-52);
- score(j) = sum of the (m - c - 2) smallest distances from j to the other
  candidates (krum.py:64-71); winner = argmin score (krum.py:73-75).

TPU shape: two global distance matrices (bcast-bcast and own-bcast) feed
every node's selection; per-node candidate masks + rank masks replace the
reference's Python sorts.  Candidate i in node i's view is its *own* true
state (krum.py:45: ``[own_state] + neighbors``), so row/col i of the
distance matrix is swapped to the own-state version under the vmap.
"""

import jax
import jax.numpy as jnp

from murmura_tpu.aggregation.base import (
    AggContext,
    AggregatorDef,
    pairwise_l2_distances,
)


def make_krum(num_compromised: int = 0, **_params) -> AggregatorDef:
    c = int(num_compromised)

    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        n = own.shape[0]
        d_bcast = pairwise_l2_distances(bcast)
        d_own = pairwise_l2_distances(own, bcast)  # [i, j] = ||own_i - bcast_j||
        eye = jnp.eye(n, dtype=bool)
        adj_b = adj.astype(bool)

        def select_for_node(cand_row, node_idx):
            # Node node_idx's candidate-pair distances: candidate node_idx is
            # the own state, others are broadcasts.
            is_own_row = jnp.arange(n)[:, None] == node_idx
            is_own_col = jnp.arange(n)[None, :] == node_idx
            d = jnp.where(is_own_row, d_own[node_idx][None, :], d_bcast)
            d = jnp.where(is_own_col, d_own[node_idx][:, None], d)

            m = cand_row.sum()
            num_closest = jnp.maximum(1, m - c - 2)
            pair_valid = cand_row[None, :] & cand_row[:, None] & ~eye
            masked = jnp.where(pair_valid, d, jnp.inf)
            ranked = jnp.sort(masked, axis=-1)
            take = jnp.arange(n)[None, :] < num_closest
            scores = jnp.where(
                take & jnp.isfinite(ranked), ranked, 0.0
            ).sum(-1)
            scores = jnp.where(cand_row, scores, jnp.inf)
            winner = jnp.argmin(scores)
            ok = c < (m - 2) / 2  # Krum constraint (krum.py:49-52)
            return jnp.where(ok, winner, node_idx), scores[winner]

        cand = adj_b | eye
        winners, best_scores = jax.vmap(select_for_node)(cand, jnp.arange(n))
        # Winner index == self means "own state"; otherwise take the broadcast.
        selected_own = winners == jnp.arange(n)
        new_flat = jnp.where(selected_own[:, None], own, bcast[winners])
        stats = {
            "selected_index": winners,
            "krum_score": best_scores,
            "selected_own": selected_own.astype(jnp.float32),
        }
        return new_flat, state, stats

    return AggregatorDef(name="krum", aggregate=aggregate)

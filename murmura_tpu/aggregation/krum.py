"""Multi-Krum selection (reference: murmura/aggregation/krum.py:8-75).

Per node i over candidates {i} ∪ N(i) (m = 1 + degree, c expected Byzantine):
- requires c < (m-2)/2, else fall back to own state (krum.py:49-52);
- score(j) = sum of the (m - c - 2) smallest distances from j to the other
  candidates (krum.py:64-71); winner = argmin score (krum.py:73-75).

TPU shape: two global distance matrices (bcast-bcast and own-bcast) feed
every node's selection.  Candidate i in node i's view is its *own* true
state (krum.py:45: ``[own_state] + neighbors``), so the entries involving
the self candidate are swapped to the own-state distances.

Each node gathers only its candidate block out of the shared [N, N]
matrices: candidate indices [N, m] (self first, then neighbors) index a
[m, m] pair block per node, so the per-node working set is O(N·m²) with
m = max_candidates instead of the O(N³) that sorting full per-node [N, N]
copies under vmap materializes (round-2 verdict weak #4).  ``max_candidates``
is injected by the factories as max-degree+1 for static topologies; the
default m = N is the dense fallback for dynamic graphs (mobility/DMTT).
"""

import jax
import jax.numpy as jnp

from murmura_tpu.aggregation.base import (
    AggContext,
    AggregatorDef,
    pairwise_l2_distances,
)


def make_krum(
    num_compromised: int = 0, max_candidates: int = None, **_params
) -> AggregatorDef:
    c = int(num_compromised)
    mc = None if max_candidates is None else int(max_candidates)

    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        n = own.shape[0]
        m_cap = n if mc is None else min(mc, n)
        d_bcast = pairwise_l2_distances(bcast)
        d_own = pairwise_l2_distances(own, bcast)  # [i, j] = ||own_i - bcast_j||

        # Candidate order per node: self first (rank 2), neighbors (rank 1),
        # non-candidates last.  argsort is stable, so neighbor indices come
        # out ascending and truncation at m_cap is deterministic.
        rank = adj + 2.0 * jnp.eye(n, dtype=adj.dtype)
        cand_idx = jnp.argsort(-rank, axis=1)[:, :m_cap]  # [N, m]
        valid = jnp.take_along_axis(rank, cand_idx, axis=1) > 0.0  # [N, m]
        pair_eye = jnp.eye(m_cap, dtype=bool)

        def select_for_node(node_idx, ci, vi):
            # [m, m] candidate-pair distances; entries involving the self
            # candidate (position 0) use the own-state distance row.
            d = d_bcast[ci][:, ci]
            own_d = d_own[node_idx, ci]  # [m]: ||own_i - bcast_{c_j}||
            is_self = ci == node_idx
            d = jnp.where(is_self[:, None], own_d[None, :], d)
            d = jnp.where(is_self[None, :], own_d[:, None], d)

            m = vi.sum()
            num_closest = jnp.maximum(1, m - c - 2)
            pair_valid = vi[None, :] & vi[:, None] & ~pair_eye
            masked = jnp.where(pair_valid, d, jnp.inf)
            ranked = jnp.sort(masked, axis=-1)
            take = jnp.arange(m_cap)[None, :] < num_closest
            scores = jnp.where(
                take & jnp.isfinite(ranked), ranked, 0.0
            ).sum(-1)
            scores = jnp.where(vi, scores, jnp.inf)
            w = jnp.argmin(scores)
            ok = c < (m - 2) / 2  # Krum constraint (krum.py:49-52)
            return jnp.where(ok, ci[w], node_idx), scores[w]

        winners, best_scores = jax.vmap(select_for_node)(
            jnp.arange(n), cand_idx, valid
        )
        # Winner index == self means "own state"; otherwise take the broadcast.
        selected_own = winners == jnp.arange(n)
        new_flat = jnp.where(selected_own[:, None], own, bcast[winners])
        stats = {
            "selected_index": winners,
            "krum_score": best_scores,
            "selected_own": selected_own.astype(jnp.float32),
        }
        return new_flat, state, stats

    return AggregatorDef(name="krum", aggregate=aggregate)

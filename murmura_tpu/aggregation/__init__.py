"""Byzantine-resilient aggregation rules, vectorized over the node axis
(reference: murmura/aggregation/)."""

from typing import Any, Dict

from murmura_tpu.aggregation.base import (
    AggContext,
    AggregatorDef,
    InfluenceDecl,
    masked_neighbor_mean,
    pairwise_l2_distances,
)
from murmura_tpu.aggregation.fedavg import make_fedavg
from murmura_tpu.aggregation.krum import make_krum
from murmura_tpu.aggregation.balance import make_balance
from murmura_tpu.aggregation.sketchguard import make_sketchguard
from murmura_tpu.aggregation.ubar import make_ubar
from murmura_tpu.aggregation.evidential_trust import make_evidential_trust
from murmura_tpu.aggregation.robust_stats import (
    make_coordinate_median,
    make_geometric_median,
    make_trimmed_mean,
)

AGGREGATORS = {
    "fedavg": make_fedavg,
    "krum": make_krum,
    "balance": make_balance,
    "sketchguard": make_sketchguard,
    "ubar": make_ubar,
    "evidential_trust": make_evidential_trust,
    # Beyond reference parity: the classic coordinate-wise robust rules.
    "median": make_coordinate_median,
    "trimmed_mean": make_trimmed_mean,
    "geometric_median": make_geometric_median,
}


def build_aggregator(
    algorithm: str, params: Dict[str, Any], model_dim: int = 0, total_rounds: int = 20
) -> AggregatorDef:
    """Build a rule from config, injecting derived params the way the
    reference factory does (murmura/utils/factories.py:83-88: sketchguard
    gets model_dim; schedule-based rules use total_rounds via AggContext)."""
    algo = algorithm.lower()
    if algo not in AGGREGATORS:
        raise ValueError(f"Unknown aggregation algorithm: {algorithm}")
    params = dict(params or {})
    params.pop("total_rounds", None)  # carried via AggContext instead
    if algo == "krum" and "f" in params:
        # Reference configs name the Byzantine tolerance "f"
        # (examples/configs/uci_har_byzantine.yaml).
        f = params.pop("f")
        if "num_compromised" in params and params["num_compromised"] != f:
            raise ValueError(
                f"krum config supplies both f={f} and "
                f"num_compromised={params['num_compromised']} with different "
                "values; they are aliases — set exactly one"
            )
        params.setdefault("num_compromised", f)
    if algo == "sketchguard":
        params.setdefault("model_dim", model_dim)
    return AGGREGATORS[algo](**params)


__all__ = [
    "AggContext",
    "AggregatorDef",
    "InfluenceDecl",
    "AGGREGATORS",
    "build_aggregator",
    "make_fedavg",
    "make_krum",
    "make_balance",
    "make_sketchguard",
    "make_ubar",
    "make_evidential_trust",
    "make_coordinate_median",
    "make_geometric_median",
    "make_trimmed_mean",
    "pairwise_l2_distances",
    "masked_neighbor_mean",
]

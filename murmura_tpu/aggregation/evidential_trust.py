"""Evidential Trust-Aware aggregation — the CCGrid'26 paper algorithm
(reference: murmura/aggregation/evidential_trust.py:25-469).

Per neighbor j evaluated on node i's local validation samples:
    trust = (1 - vacuity) * (w_a * accuracy + (1 - w_a))        (:289-293)
    * exp(-(vacuity - tau_u)) penalty when vacuity > tau_u       (:296-302)
    clipped to [0, 1]                                            (:305)
EMA smoothing trust_t = momentum*new + (1-momentum)*old          (:318-342)
Tightening threshold tau(t) = clip(tau_base*(1 - gamma*exp(-kappa t/T)),
    0.05, tau_base)                                              (:344-381)
Accepted = trust >= tau(t); none accepted -> own state (:191-192); else
trust-normalized neighbor mean blended with own via self_weight (:194-212).

Carried state: the per-edge smoothed trust matrix [N, N] and a seen mask —
the reference's ``_smoothed_trust`` dict (:112-113) vectorized.
The per-neighbor deepcopy+load_state_dict evaluation loop (:236-260) becomes
one batched cross-evaluation (aggregation/probe.py).

Documented deviation — evidence-inflation guard: the reference's trust
computation rewards *overconfident* Byzantine states: Gaussian noise on
parameters yields enormous softplus evidence, hence vacuity ~ 0 and trust
~ (1-0)*(w_a*acc + 1-w_a) ~ 0.55, and with the reference's torch models the
noised BatchNorm running_var goes negative, making vacuity NaN and
``max(0.0, min(1.0, nan))`` evaluate to trust = 1.0 for the attacker
(reproduced empirically against the reference at
murmura/aggregation/evidential_trust.py:303-305).  The paper's own training
loss includes a KL term precisely to punish spurious evidence inflation, so
this implementation extends that intent to cross-evaluation: neighbors whose
mean Dirichlet strength exceeds ``strength_guard_factor`` x the *median*
neighbor strength (honest-majority robust statistic) or whose metrics are
non-finite receive zero trust.  Disable with ``strength_guard: false`` for
strict reference parity.
"""

from typing import Optional, Sequence

import jax.numpy as jnp
import numpy as np

from murmura_tpu.aggregation.base import (
    AggContext,
    AggregatorDef,
    InfluenceDecl,
    circulant_in_degree,
    circulant_weighted_sum,
    masked_neighbor_mean,
)
from murmura_tpu.aggregation.probe import (
    circulant_probe_eval,
    evidential_trust_metric,
    pairwise_probe_eval,
)


def make_evidential_trust(
    vacuity_threshold: float = 0.5,
    accuracy_weight: float = 0.5,
    trust_threshold: float = 0.3,
    self_weight: float = 0.5,
    use_adaptive_trust: bool = True,
    trust_momentum: float = 0.7,
    use_tightening_threshold: bool = True,
    gamma: float = 0.5,
    kappa: float = 1.0,
    min_neighbors: int = 1,
    max_eval_samples: int = 100,
    track_statistics: bool = True,
    strength_guard: bool = True,
    strength_guard_factor: float = 10.0,
    exchange_offsets: Optional[Sequence[int]] = None,
    sparse_exchange: bool = False,
    **_params,
) -> AggregatorDef:
    offsets = None if exchange_offsets is None else [int(o) for o in exchange_offsets]
    if sparse_exchange and offsets is None:
        raise ValueError("sparse_exchange requires exchange_offsets")

    def init_state(num_nodes: int):
        return {
            "smoothed_trust": np.zeros((num_nodes, num_nodes), dtype=np.float32),
            "trust_seen": np.zeros((num_nodes, num_nodes), dtype=np.float32),
        }

    def _trust_from_metrics(vacuity, accuracy):
        base_trust = (1.0 - vacuity) * (
            accuracy_weight * accuracy + (1.0 - accuracy_weight)
        )
        penalty = jnp.where(
            vacuity > vacuity_threshold,
            jnp.exp(-(vacuity - vacuity_threshold)),
            1.0,
        )
        return jnp.clip(base_trust * penalty, 0.0, 1.0)

    def _current_threshold(round_idx, total_rounds):
        if not use_tightening_threshold:
            return jnp.asarray(trust_threshold)
        lambda_t = round_idx / jnp.maximum(1, total_rounds)
        decay = jnp.exp(-kappa * lambda_t)
        return jnp.clip(
            trust_threshold * (1.0 - gamma * decay), 0.05, trust_threshold
        )

    def aggregate_circulant(own, bcast, adj, round_idx, state, ctx: AggContext):
        """O(degree) path (tpu.exchange: ppermute): k x N probe forwards and
        per-offset trust columns of the [N, N] smoothed-trust state, which
        keeps its dense layout for checkpoint/statistics parity."""
        n = own.shape[0]
        k = len(offsets)
        cols = (
            jnp.arange(n)[None, :] + jnp.asarray(offsets)[:, None]
        ) % n  # [k, N]
        rows = jnp.arange(n)[None, :]

        metrics = circulant_probe_eval(
            bcast, offsets, ctx, evidential_trust_metric
        )  # [k, N] each
        vacuity = metrics["vacuity"]
        trust_new = _trust_from_metrics(vacuity, metrics["accuracy"])

        if strength_guard:
            strength = metrics["strength"]  # [k, N]
            order = jnp.sort(strength, axis=0)
            median = order[(k - 1) // 2][None, :]
            inflated = strength > strength_guard_factor * jnp.maximum(median, 1e-6)
            finite = (
                jnp.isfinite(trust_new)
                & jnp.isfinite(vacuity)
                & jnp.isfinite(strength)
            )
            trust_new = jnp.where(inflated | ~finite, 0.0, trust_new)

        # Sparse exchange mode: ``adj`` is the [k, N] edge mask — inactive
        # edges contribute no trust observation (state untouched), cannot
        # be accepted, and drop out of every masked statistic.  The [N, N]
        # smoothed-trust state keeps its dense layout (it is carried
        # aggregation state, O(N^2) *memory* but indexed O(k·N) per round;
        # documented exception to the MUR600 no-dense-operand set).
        edge_b = adj > 0 if sparse_exchange else None

        if use_adaptive_trust:
            seen = state["trust_seen"][rows, cols]  # [k, N]
            smoothed = (
                trust_momentum * trust_new
                + (1.0 - trust_momentum) * state["smoothed_trust"][rows, cols]
            )
            trust = jnp.where(seen > 0, smoothed, trust_new)
            if sparse_exchange:
                old_t = state["smoothed_trust"][rows, cols]
                new_state = {
                    "smoothed_trust": state["smoothed_trust"]
                    .at[rows, cols]
                    .set(jnp.where(edge_b, trust, old_t)),
                    "trust_seen": state["trust_seen"]
                    .at[rows, cols]
                    .set(jnp.where(edge_b, 1.0, seen)),
                }
            else:
                new_state = {
                    "smoothed_trust": state["smoothed_trust"].at[rows, cols].set(trust),
                    "trust_seen": state["trust_seen"].at[rows, cols].set(1.0),
                }
        else:
            trust = trust_new
            new_state = state

        current_threshold = _current_threshold(round_idx, ctx.total_rounds)
        accepted = trust >= current_threshold  # [k, N]
        if sparse_exchange:
            accepted = accepted & edge_b
        weights = jnp.where(accepted, trust, 0.0)
        total = weights.sum(axis=0)
        has_accepted = total > 0
        norm_w = weights / jnp.maximum(total, 1e-12)[None, :]

        # out_dtype: per-chunk accumulation stays at the promoted f32
        # precision, only the stored blend returns to the resident param
        # dtype (MUR201 — the exchanged [N, P] tensor must not upcast).
        neighbor_agg = circulant_weighted_sum(
            bcast, norm_w, offsets, out_dtype=own.dtype
        )
        blended = self_weight * own + (1.0 - self_weight) * neighbor_agg
        new_flat = jnp.where(has_accepted[:, None], blended, own)

        if sparse_exchange:
            edge_w = adj.astype(jnp.float32)
            deg = jnp.maximum(edge_w.sum(axis=0), 1.0)
            # Reduce through .mean + a k/deg rescale rather than a
            # multiply-sum: with an all-active mask the rescale is exactly
            # 1.0, so the stat is bit-identical to the static circulant
            # path's .mean(axis=0) (a fused multiply-sum accumulates in a
            # different order and drifts by an ulp).
            masked_mean = lambda m: (  # noqa: E731
                jnp.where(edge_b, m, 0.0).mean(axis=0) * (float(k) / deg)
            )
            stats = {
                "acceptance_rate": accepted.sum(axis=0) / deg,
                "mean_trust": masked_mean(trust),
                "mean_vacuity": masked_mean(vacuity),
                "mean_entropy": masked_mean(metrics["entropy"]),
                "threshold": jnp.broadcast_to(current_threshold, (n,)),
            }
        else:
            stats = {
                "acceptance_rate": accepted.sum(axis=0) / float(k),
                "mean_trust": trust.mean(axis=0),
                "mean_vacuity": vacuity.mean(axis=0),
                "mean_entropy": metrics["entropy"].mean(axis=0),
                "threshold": jnp.broadcast_to(current_threshold, (n,)),
            }
        if ctx.audit:
            # Sender-side taps via rolls only (ppermute stays the only
            # roll-added collective — MUR400): trust[o_idx, i] is receiver
            # i's trust of sender (i + o) % n.
            stats["tap_selected_by"] = sum(
                jnp.roll(accepted[i].astype(jnp.float32), o)
                for i, o in enumerate(offsets)
            )
            if sparse_exchange:
                in_deg = circulant_in_degree(adj, offsets)
                stats["tap_considered_by"] = in_deg
                stats["tap_trust_received"] = sum(
                    jnp.roll(
                        (trust * adj.astype(trust.dtype))[i].astype(
                            jnp.float32
                        ),
                        o,
                    )
                    for i, o in enumerate(offsets)
                ) / jnp.maximum(in_deg, 1.0)
            else:
                stats["tap_considered_by"] = jnp.full((n,), float(k))
                stats["tap_trust_received"] = sum(
                    jnp.roll(trust[i].astype(jnp.float32), o)
                    for i, o in enumerate(offsets)
                ) / float(k)
        return new_flat, new_state, stats

    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        if offsets is not None:
            return aggregate_circulant(own, bcast, adj, round_idx, state, ctx)
        adj_b = adj.astype(bool)

        # Phase 1: cross-evaluate all broadcast models on all nodes' probe
        # data — reusing the round's shared cross-eval when DMTT already ran
        # it with the evidential metric fields included.
        if ctx.probe_cross is not None and "entropy" in ctx.probe_cross:
            metrics = ctx.probe_cross
        else:
            metrics = pairwise_probe_eval(bcast, ctx, evidential_trust_metric)
        vacuity = metrics["vacuity"]  # [N_i, N_j]
        accuracy = metrics["accuracy"]

        trust_new = _trust_from_metrics(vacuity, accuracy)

        if strength_guard:
            # Evidence-inflation guard (see module docstring): a neighbor
            # whose Dirichlet strength dwarfs the median of the evaluated
            # neighborhood is overconfident garbage, not evidence.  The
            # median is the honest-majority robust center (c < N/2).
            strength = metrics["strength"]
            n = strength.shape[0]
            masked = jnp.where(adj_b, strength, jnp.inf)
            order = jnp.sort(masked, axis=1)
            deg = jnp.maximum(adj_b.sum(axis=1), 1)
            med_idx = jnp.clip((deg - 1) // 2, 0, n - 1)
            median = jnp.take_along_axis(order, med_idx[:, None], axis=1)  # [N,1]
            inflated = strength > strength_guard_factor * jnp.maximum(median, 1e-6)
            finite = (
                jnp.isfinite(trust_new) & jnp.isfinite(vacuity) & jnp.isfinite(strength)
            )
            trust_new = jnp.where(inflated | ~finite, 0.0, trust_new)

        # EMA smoothing; first observation of an edge uses the raw value
        # (evidential_trust.py:330-337).
        if use_adaptive_trust:
            seen = state["trust_seen"]
            smoothed = (
                trust_momentum * trust_new
                + (1.0 - trust_momentum) * state["smoothed_trust"]
            )
            trust = jnp.where(seen > 0, smoothed, trust_new)
            new_state = {
                "smoothed_trust": jnp.where(adj_b, trust, state["smoothed_trust"]),
                "trust_seen": jnp.where(adj_b, 1.0, seen),
            }
        else:
            trust = trust_new
            new_state = state

        # Phase 2: tightening threshold + filtering.
        current_threshold = _current_threshold(round_idx, ctx.total_rounds)
        accepted = adj_b & (trust >= current_threshold)
        weights = jnp.where(accepted, trust, 0.0)
        total = weights.sum(axis=1)
        has_accepted = total > 0

        # Phase 3: trust-normalized neighbor mean + personalization blend.
        # masked_neighbor_mean owns the dtype discipline (MUR201): bf16
        # matmul operands with f32 accumulation, normalized by the SAME
        # cast weights the matmul uses (normalizing first and casting after
        # would scale rows by sum(w)/sum(bf16(w)) != 1), stored back in the
        # resident param dtype.
        neighbor_agg = masked_neighbor_mean(bcast, weights)
        blended = self_weight * own + (1.0 - self_weight) * neighbor_agg
        new_flat = jnp.where(has_accepted[:, None], blended, own)

        degree = jnp.maximum(adj.sum(axis=1), 1.0)
        masked = lambda m: (m * adj).sum(axis=1) / degree
        stats = {
            "acceptance_rate": accepted.sum(axis=1) / degree,
            "mean_trust": masked(trust),
            "mean_vacuity": masked(vacuity),
            "mean_entropy": masked(metrics["entropy"]),
            "threshold": jnp.broadcast_to(current_threshold, degree.shape),
        }
        if ctx.audit:
            # Receiver-side taps only on the dense path: the untapped dense
            # evidential program lowers WITHOUT an all_reduce (its probe
            # cross-eval is vmapped, not a Gram matmul), so a sender-side
            # column sum would add a collective the untapped program does
            # not have — exactly what MUR400 forbids (taps must observe,
            # never communicate).  Row reductions are node-local.  The
            # circulant path keeps the sender-side view (rolls are already
            # its ppermutes); dense sender-side rejection analysis comes
            # from krum/balance/ubar or the circulant exchange.
            stats["tap_accepted"] = accepted.astype(jnp.float32).sum(axis=1)
            stats["tap_considered"] = adj.astype(jnp.float32).sum(axis=1)
        return new_flat, new_state, stats

    return AggregatorDef(
        name="evidential_trust",
        aggregate=aggregate,
        init_state=init_state,
        needs_probe=True,
        state_kind={"smoothed_trust": "edge", "trust_seen": "edge"},
        # MUR202: the dense trust probe cross-evaluates exchanged states
        # (vmapped forwards GSPMD decomposes into gather/all-to-all over
        # the small probe batches).  The circulant mode still gathers the
        # [N, N] *edge-indexed* smoothed-trust state (its scatter/gather is
        # O(N*k), not O(N*P)) — only the heavy [N, P] blend must stay
        # ppermute.
        collectives={
            "dense": {"all_gather", "all_reduce", "all_to_all"},
            "circulant": {"all_gather", "all_reduce", "ppermute"},
        },
        # MUR800: the trust-weighted blend normalizes over every accepted
        # neighbor (and the trust normalizer couples them), so all
        # neighbors' values reach the output when all are trusted — the
        # benign case.  Exclusion (trust < threshold, the strength guard)
        # is data-dependent; declared unbounded.
        influence=InfluenceDecl(
            "unbounded",
            note="trust-normalized mean over accepted neighbors: benign "
            "inputs trust everyone; exclusion is data-dependent",
        ),
    )

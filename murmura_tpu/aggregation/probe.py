"""Batched cross-evaluation of every node's model on every node's probe data.

This replaces the reference's most expensive pattern: per neighbor, deep-copy
a module, load_state_dict, and loop batches (ubar.py:175-188,
evidential_trust.py:236-260, dmtt/node_process.py:309-363).  Here the gathered
[N, P] tensor is already on-device, so "evaluate neighbor j on my data" is a
batched forward: for each parameter row j, one forward over ALL nodes' probe
batches at once ([N*B] samples — one big MXU-friendly matmul), scanned over j
to bound memory at O(N * B * K) per step.
"""

from typing import Callable, Dict

import jax
import jax.numpy as jnp
import numpy as np

from murmura_tpu.aggregation.base import AggContext


def pairwise_probe_eval(
    flat: jnp.ndarray,
    ctx: AggContext,
    metric_fn: Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], Dict[str, jnp.ndarray]],
) -> Dict[str, jnp.ndarray]:
    """Evaluate model j on node i's probe batch for all (i, j).

    Args:
        flat: [N, P] gathered flattened params.
        ctx: aggregation context with probe_x [N, B, ...], probe_y [N, B],
            probe_mask [N, B].
        metric_fn: (outputs [B, K], y [B], mask [B]) -> dict of scalar metrics.

    Returns:
        dict of [N, N] arrays, entry [i, j] = metric of model j on node i's data.

    ``probe_x`` may carry a leading dim of 1 — a single evaluator whose
    metrics broadcast to every row.  The ZMQ LocalNode uses this: its
    mini-network consumes only row 0, so evaluating one probe batch per
    model (O(M) forwards) replaces the M x M cross-eval of the tiled
    layout while producing identical rows.
    """
    n = flat.shape[0]
    n_eval, b = ctx.probe_x.shape[:2]
    xs = ctx.probe_x.reshape((n_eval * b,) + ctx.probe_x.shape[2:])

    def eval_one_model(flat_j: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        params = ctx.unravel(flat_j)
        outputs = ctx.apply_fn(params, xs, None, False)  # [n_eval*B, K]
        outputs = outputs.reshape(n_eval, b, -1)
        return jax.vmap(metric_fn)(outputs, ctx.probe_y, ctx.probe_mask)

    # scan over models j -> dict of [N_j, n_eval]; transpose to [n_eval, N_j].
    per_j = jax.lax.map(eval_one_model, flat)
    return {k: jnp.broadcast_to(v.T, (n, n)) for k, v in per_j.items()}


def circulant_probe_eval(
    bcast: jnp.ndarray,
    offsets,
    ctx: AggContext,
    metric_fn: Callable[[jnp.ndarray, jnp.ndarray, jnp.ndarray], Dict[str, jnp.ndarray]],
) -> Dict[str, jnp.ndarray]:
    """Evaluate each node's k circulant neighbors on its own probe batch.

    The O(degree) counterpart of :func:`pairwise_probe_eval` for
    tpu.exchange: ppermute — k x N probe forwards instead of N x N, with the
    neighbor states materialized per offset by a circular shift.

    Returns:
        dict of [k, N] arrays, entry [o, i] = metric of the model of node
        (i + offsets[o]) % N evaluated on node i's probe data.
    """
    if not offsets:
        raise ValueError(
            "circulant_probe_eval needs at least one offset: an empty "
            "offset list means a circulant graph with no neighbors, so "
            "there is no cross-eval to compute (check the topology's "
            "circulant_offsets() wiring)"
        )

    def eval_one(flat_j, x_i, y_i, m_i):
        params = ctx.unravel(flat_j)
        outputs = ctx.apply_fn(params, x_i, None, False)
        return metric_fn(outputs, y_i, m_i)

    # Serialize the offsets so only ONE rolled [N, P] copy is live at a
    # time: an unconstrained Python-unrolled loop lets XLA schedule all k
    # rolls concurrently — the 256-node OOM class the chunked kernels in
    # base.py exist for.  The shifts stay STATIC (a traced shift under
    # lax.map would lower to a [2N, P] concat + dynamic_slice and defeat
    # node-axis sharding); ordering is imposed by gating each roll's input
    # on the previous offset's metrics via optimization_barrier.  The probe
    # forwards dominate the cost, so losing cross-offset parallelism is
    # free.  The shift op is backend-dependent (ctx.node_axis_sharded):
    # on ONE device a static-index row gather — jnp.roll's slice+concat
    # lowering pads the [o, P] wrap-around slice up to 128x (1.56 GB of
    # pure padding per offset at 256 nodes, the UBAR OOM) while a
    # constant-index gather pads nothing; on a SHARDED node axis jnp.roll
    # — it lowers to boundary collective-permutes (O(degree) ICI traffic)
    # where the gather would lower to a full all-gather (verified on an
    # 8-device mesh HLO: roll = 6 collective-permutes / 0 all-gathers,
    # take = 0 / 3).
    per_offset = []
    gate = bcast
    for o in offsets:
        if ctx.node_axis_sharded:
            rolled = jnp.roll(gate, -o, axis=0)
        else:
            idx = jnp.asarray(np.roll(np.arange(gate.shape[0]), -o))
            rolled = jnp.take(gate, idx, axis=0)
        m = jax.vmap(eval_one)(rolled, ctx.probe_x, ctx.probe_y, ctx.probe_mask)
        gate = jax.lax.optimization_barrier(
            (bcast, jax.tree_util.tree_leaves(m)[0])
        )[0]
        per_offset.append(m)
    return {
        key: jnp.stack([m[key] for m in per_offset]) for key in per_offset[0]
    }


def ce_loss_metric(outputs, y, mask):
    """Masked mean CE loss (UBAR stage-2 probe — ubar.py:204-222)."""
    logp = jax.nn.log_softmax(outputs, axis=-1)
    nll = -jnp.take_along_axis(logp, y[:, None], axis=-1)[:, 0]
    return {"loss": (nll * mask).sum() / jnp.maximum(mask.sum(), 1.0)}


def accuracy_vacuity_metric(outputs, y, mask):
    """Masked accuracy + zero vacuity for softmax models — the DMTT model
    score path when the model has no evidential head
    (murmura/dmtt/node_process.py:333-363: u_bar stays 0 for softmax)."""
    denom = jnp.maximum(mask.sum(), 1.0)
    acc = ((jnp.argmax(outputs, -1) == y).astype(jnp.float32) * mask).sum() / denom
    return {"accuracy": acc, "vacuity": jnp.zeros(())}


def combined_probe_metric(evidential: bool):
    """One metric covering every probe consumer in a round, so the N x N
    cross-eval is computed once and shared: DMTT model scoring needs
    accuracy/vacuity, UBAR stage 2 needs the CE loss, evidential trust needs
    vacuity/entropy/strength.  Forward passes dominate the cross-eval cost;
    emitting extra reductions per pass is free by comparison."""
    base = evidential_trust_metric if evidential else accuracy_vacuity_metric

    def metric(outputs, y, mask):
        out = base(outputs, y, mask)
        out.update(ce_loss_metric(outputs, y, mask))
        return out

    return metric


def evidential_trust_metric(outputs, y, mask):
    """Masked accuracy + mean vacuity of Dirichlet outputs
    (evidential_trust.py:249-287)."""
    denom = jnp.maximum(mask.sum(), 1.0)
    s = outputs.sum(-1)
    k = outputs.shape[-1]
    vacuity = ((k / s) * mask).sum() / denom
    acc = ((jnp.argmax(outputs, -1) == y).astype(jnp.float32) * mask).sum() / denom
    entropy_per = -(
        (outputs / outputs.sum(-1, keepdims=True))
        * jnp.log(outputs / outputs.sum(-1, keepdims=True) + 1e-10)
    ).sum(-1)
    entropy = (entropy_per * mask).sum() / denom
    strength = (s * mask).sum() / denom
    return {"accuracy": acc, "vacuity": vacuity, "entropy": entropy,
            "strength": strength}

"""UBAR: two-stage Byzantine-resilient aggregation
(reference: murmura/aggregation/ubar.py:15-271).

Stage 1 — distance shortlist: keep max(min_neighbors, floor(rho * degree))
closest neighbors by L2 (ubar.py:114-150).
Stage 2 — loss probe: keep shortlisted neighbors whose loss on one local
training batch is <= own loss; fallback to the best-loss candidate when none
pass (ubar.py:152-202).  Output alpha*own + (1-alpha)*mean (ubar.py:224-249).

TPU shape: stage 2's per-neighbor load_state_dict loop becomes one batched
cross-evaluation of the gathered [N, P] tensor (see aggregation/probe.py);
the own-loss baseline is the vmapped diagonal over the true own states.
"""

from typing import Optional, Sequence

import jax.numpy as jnp

from murmura_tpu.aggregation.base import (
    AggContext,
    AggregatorDef,
    InfluenceDecl,
    blend_with_own,
    circulant_in_degree,
    circulant_masked_mean,
    circulant_neighbor_distances,
    masked_neighbor_mean,
    pairwise_l2_distances,
    rank_mask,
    self_probe_metrics,
)
from murmura_tpu.aggregation.probe import (
    ce_loss_metric,
    circulant_probe_eval,
    pairwise_probe_eval,
)


def make_ubar(
    rho: float = 0.4,
    alpha: float = 0.5,
    min_neighbors: int = 1,
    exchange_offsets: Optional[Sequence[int]] = None,
    sparse_exchange: bool = False,
    pallas: bool = False,
    **_params,
) -> AggregatorDef:
    offsets = None if exchange_offsets is None else [int(o) for o in exchange_offsets]
    if sparse_exchange and offsets is None:
        raise ValueError("sparse_exchange requires exchange_offsets")
    pallas = bool(pallas)  # ops/pallas_agg.py fused distance kernels

    def aggregate_circulant(own, bcast, adj, round_idx, state, ctx: AggContext):
        """O(degree) path (tpu.exchange: ppermute): distances, the stage-2
        loss probe (k x N forwards instead of N x N), and the accepted mean
        all run over k rolled copies."""
        n = own.shape[0]
        k = len(offsets)

        # Stage 1: rho * degree closest neighbors.  On the static circulant
        # path the degree is the compile-time constant k; in sparse
        # exchange mode ``adj`` is the [k, N] edge mask and the per-node
        # degree (and therefore the shortlist size) is a traced value —
        # the floor runs in f32 instead of Python float, which agrees with
        # int(rho * k) for every non-pathological (rho, k).
        d_nk = circulant_neighbor_distances(
            own, bcast, offsets, pallas=pallas
        ).T  # [N, k]
        if sparse_exchange:
            edge_b = adj.T > 0  # [N, k] receiver-side active-edge mask
            deg = adj.sum(axis=0)  # [N]
            num_select = jnp.maximum(
                min_neighbors, jnp.floor(rho * deg).astype(jnp.int32)
            )
            shortlist = rank_mask(d_nk, edge_b, num_select)  # [N, k]
        else:
            num_select = max(min_neighbors, int(rho * k))
            shortlist = rank_mask(
                d_nk, jnp.ones_like(d_nk, dtype=bool),
                jnp.full((n,), num_select, jnp.int32),
            )  # [N, k]

        # Stage 2: loss probe per offset.
        losses = circulant_probe_eval(bcast, offsets, ctx, ce_loss_metric)[
            "loss"
        ].T  # [N, k]
        own_loss = self_probe_metrics(own, ctx, ce_loss_metric)["loss"]
        passed = shortlist & (losses <= own_loss[:, None])

        shortlist_losses = jnp.where(shortlist, losses, jnp.inf)
        best = jnp.argmin(shortlist_losses, axis=1)  # [N] offset index
        fallback = (
            jnp.arange(k)[None, :] == best[:, None]
        ) & shortlist
        none_passed = ~passed.any(axis=1)
        accepted = jnp.where(
            (none_passed & shortlist.any(axis=1))[:, None], fallback, passed
        ).astype(own.dtype)  # [N, k]

        neighbor_avg = circulant_masked_mean(bcast, accepted.T, offsets)
        has_accepted = accepted.sum(axis=1) > 0
        new_flat = blend_with_own(own, neighbor_avg, has_accepted, alpha)

        shortlist_count = jnp.maximum(shortlist.sum(axis=1).astype(own.dtype), 1.0)
        stage1_denom = (
            jnp.maximum(deg, 1.0) if sparse_exchange else float(k)
        )
        stats = {
            "stage1_acceptance_rate": shortlist.sum(axis=1) / stage1_denom,
            "stage2_acceptance_rate": accepted.sum(axis=1) / shortlist_count,
            "own_loss": own_loss,
        }
        if ctx.audit:
            # Sender-side taps via rolls only (ppermute-clean, MUR400):
            # accepted[i, o_idx] = receiver i accepted sender (i + o) % n.
            stats["tap_selected_by"] = sum(
                jnp.roll(accepted[:, i].astype(jnp.float32), o)
                for i, o in enumerate(offsets)
            )
            if sparse_exchange:
                stats["tap_considered_by"] = circulant_in_degree(adj, offsets)
            else:
                stats["tap_considered_by"] = jnp.full(
                    (own.shape[0],), float(k)
                )
        return new_flat, state, stats

    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        if offsets is not None:
            return aggregate_circulant(own, bcast, adj, round_idx, state, ctx)
        n = own.shape[0]
        adj_b = adj.astype(bool)
        degree = adj.sum(axis=1)

        # Stage 1: rho * degree closest neighbors (ubar.py:133-139).
        dist = pairwise_l2_distances(own, bcast, pallas=pallas)
        num_select = jnp.maximum(min_neighbors, (rho * degree).astype(jnp.int32))
        shortlist = rank_mask(dist, adj_b, num_select)

        # Stage 2: loss probe on one local batch (ubar.py:152-202).  Reuse
        # the round's shared cross-eval when another consumer (DMTT) already
        # ran the N x N forward sweep.
        if ctx.probe_cross is not None and "loss" in ctx.probe_cross:
            losses = ctx.probe_cross["loss"]  # [N_i, N_j]
        else:
            losses = pairwise_probe_eval(bcast, ctx, ce_loss_metric)["loss"]
        own_loss = self_probe_metrics(own, ctx, ce_loss_metric)["loss"]  # [N]
        passed = shortlist & (losses <= own_loss[:, None])

        # Fallback: best-loss shortlisted candidate when none pass
        # (ubar.py:195-197).
        shortlist_losses = jnp.where(shortlist, losses, jnp.inf)
        best = jnp.argmin(shortlist_losses, axis=1)
        fallback = jnp.zeros_like(passed).at[jnp.arange(n), best].set(True) & shortlist
        has_shortlist = shortlist.any(axis=1)
        none_passed = ~passed.any(axis=1)
        accepted = jnp.where(
            (none_passed & has_shortlist)[:, None], fallback, passed
        ).astype(own.dtype)

        neighbor_avg = masked_neighbor_mean(bcast, accepted)
        has_accepted = accepted.sum(axis=1) > 0
        new_flat = blend_with_own(own, neighbor_avg, has_accepted, alpha)

        deg_safe = jnp.maximum(degree, 1.0)
        shortlist_count = jnp.maximum(shortlist.sum(axis=1).astype(own.dtype), 1.0)
        stats = {
            "stage1_acceptance_rate": shortlist.sum(axis=1) / deg_safe,
            "stage2_acceptance_rate": accepted.sum(axis=1) / shortlist_count,
            "own_loss": own_loss,
        }
        if ctx.audit:
            # Sender-side taps: who passed the loss probe, per sender
            # (column sums lower to the declared all_reduce — MUR400).
            stats["tap_selected_by"] = accepted.astype(jnp.float32).sum(axis=0)
            stats["tap_considered_by"] = adj.astype(jnp.float32).sum(axis=0)
        return new_flat, state, stats

    return AggregatorDef(
        name="ubar",
        aggregate=aggregate,
        needs_probe=True,
        # MUR202: the dense mode cross-evaluates exchanged states (vmapped
        # probe forwards GSPMD decomposes into gather/all-to-all over the
        # small probe batches); the circulant mode is rolls ONLY — probe
        # data stays node-local, so even the stage-2 loss probe must lower
        # to boundary ppermutes.
        collectives={
            "dense": {"all_gather", "all_reduce", "all_to_all"},
            "circulant": {"ppermute"},
        },
        # MUR800: stage 1 is a STRUCTURAL cap — rank_mask keeps exactly
        # max(min_neighbors, floor(rho*degree)) closest neighbors, and
        # stage 2 (loss probe + best-loss fallback) only ever shrinks that
        # shortlist.  No output coordinate can mix values from more
        # neighbors than the stage-1 shortlist size.
        influence=InfluenceDecl(
            "bounded",
            bound=lambda k: max(min_neighbors, int(rho * k)),
            note=f"stage-1 distance shortlist caps accepted neighbors at "
            f"max({min_neighbors}, floor({rho}*degree)); stage 2 only "
            "shrinks it",
        ),
    )

"""BALANCE: adaptive distance filtering
(reference: murmura/aggregation/balance.py:13-185).

threshold_i(t) = gamma * exp(-kappa * t/T) * ||own_i||  (balance.py:82-89);
accept neighbors with L2 distance <= threshold (balance.py:108-131);
fallback-accept the closest neighbor when fewer than min_neighbors pass
(balance.py:133-135); output alpha*own + (1-alpha)*mean(accepted), own state
when nothing accepted (balance.py:140-175).
"""

import jax.numpy as jnp

from murmura_tpu.aggregation.base import (
    AggContext,
    AggregatorDef,
    blend_with_own,
    masked_neighbor_mean,
    pairwise_l2_distances,
)


def accept_with_closest_fallback(
    dist: jnp.ndarray,
    adj: jnp.ndarray,
    threshold: jnp.ndarray,
    min_neighbors: int,
) -> jnp.ndarray:
    """Accepted-neighbor mask with the BALANCE closest-neighbor fallback.

    Args:
        dist: [N, N] own-to-broadcast distances (diagonal ignored).
        adj: [N, N] 0/1 adjacency.
        threshold: [N] per-node acceptance thresholds.
        min_neighbors: fallback trigger (reference default 1, balance.py:133).

    Returns:
        [N, N] float mask of accepted neighbors.
    """
    adj_b = adj.astype(bool)
    accepted = adj_b & (dist <= threshold[:, None])
    count = accepted.sum(axis=1)
    has_any_neighbor = adj_b.any(axis=1)
    masked = jnp.where(adj_b, dist, jnp.inf)
    closest = jnp.argmin(masked, axis=1)
    fallback_row = (
        jnp.zeros_like(accepted).at[jnp.arange(adj.shape[0]), closest].set(True)
    )
    use_fallback = (count < min_neighbors) & has_any_neighbor
    accepted = jnp.where(use_fallback[:, None], accepted | fallback_row, accepted)
    return accepted.astype(dist.dtype)


def make_balance(
    gamma: float = 2.0,
    kappa: float = 1.0,
    alpha: float = 0.5,
    min_neighbors: int = 1,
    **_params,
) -> AggregatorDef:
    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        lambda_t = round_idx / jnp.maximum(1, ctx.total_rounds)
        own_norm = jnp.sqrt(jnp.sum(own * own, axis=-1))
        threshold = gamma * jnp.exp(-kappa * lambda_t) * own_norm

        dist = pairwise_l2_distances(own, bcast)
        accepted = accept_with_closest_fallback(dist, adj, threshold, min_neighbors)

        neighbor_avg = masked_neighbor_mean(bcast, accepted)
        has_accepted = accepted.sum(axis=1) > 0
        new_flat = blend_with_own(own, neighbor_avg, has_accepted, alpha)

        degree = jnp.maximum(adj.sum(axis=1), 1.0)
        stats = {
            "acceptance_rate": accepted.sum(axis=1) / degree,
            "threshold": threshold,
        }
        return new_flat, state, stats

    return AggregatorDef(name="balance", aggregate=aggregate)

"""BALANCE: adaptive distance filtering
(reference: murmura/aggregation/balance.py:13-185).

threshold_i(t) = gamma * exp(-kappa * t/T) * ||own_i||  (balance.py:82-89);
accept neighbors with L2 distance <= threshold (balance.py:108-131);
fallback-accept the closest neighbor when fewer than min_neighbors pass
(balance.py:133-135); output alpha*own + (1-alpha)*mean(accepted), own state
when nothing accepted (balance.py:140-175).
"""

from typing import Optional, Sequence

import jax.numpy as jnp

from murmura_tpu.aggregation.base import (
    AggContext,
    AggregatorDef,
    InfluenceDecl,
    blend_with_own,
    circulant_in_degree,
    circulant_masked_mean,
    circulant_neighbor_distances,
    masked_neighbor_mean,
    pairwise_l2_distances,
)


def accept_with_closest_fallback(
    dist: jnp.ndarray,
    adj: jnp.ndarray,
    threshold: jnp.ndarray,
    min_neighbors: int,
) -> jnp.ndarray:
    """Accepted-neighbor mask with the BALANCE closest-neighbor fallback.

    Args:
        dist: [N, N] own-to-broadcast distances (diagonal ignored).
        adj: [N, N] 0/1 adjacency.
        threshold: [N] per-node acceptance thresholds.
        min_neighbors: fallback trigger (reference default 1, balance.py:133).

    Returns:
        [N, N] float mask of accepted neighbors.
    """
    adj_b = adj.astype(bool)
    accepted = adj_b & (dist <= threshold[:, None])
    count = accepted.sum(axis=1)
    has_any_neighbor = adj_b.any(axis=1)
    masked = jnp.where(adj_b, dist, jnp.inf)
    closest = jnp.argmin(masked, axis=1)
    fallback_row = (
        jnp.zeros_like(accepted).at[jnp.arange(adj.shape[0]), closest].set(True)
    )
    use_fallback = (count < min_neighbors) & has_any_neighbor
    accepted = jnp.where(use_fallback[:, None], accepted | fallback_row, accepted)
    return accepted.astype(dist.dtype)


def make_balance(
    gamma: float = 2.0,
    kappa: float = 1.0,
    alpha: float = 0.5,
    min_neighbors: int = 1,
    exchange_offsets: Optional[Sequence[int]] = None,
    sparse_exchange: bool = False,
    pallas: bool = False,
    **_params,
) -> AggregatorDef:
    offsets = None if exchange_offsets is None else [int(o) for o in exchange_offsets]
    if sparse_exchange and offsets is None:
        raise ValueError("sparse_exchange requires exchange_offsets")
    pallas = bool(pallas)  # ops/pallas_agg.py fused distance kernels

    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        lambda_t = round_idx / jnp.maximum(1, ctx.total_rounds)
        own_norm = jnp.sqrt(jnp.sum(own * own, axis=-1))
        threshold = gamma * jnp.exp(-kappa * lambda_t) * own_norm

        if offsets is not None:
            # O(degree) circulant path (tpu.exchange: ppermute): distances,
            # thresholding, closest-fallback, and the accepted mean all over
            # k rolled copies instead of [N, N] tensors.
            d_k = circulant_neighbor_distances(
                own, bcast, offsets, pallas=pallas
            )  # [k, N]
            if sparse_exchange:
                # Sparse exchange mode: ``adj`` is the [k, N] edge mask —
                # inactive edges are excluded from acceptance, the closest-
                # neighbor fallback, and the degree normalizer (all-ones
                # masks reproduce the static circulant path bit-for-bit).
                edge_b = adj > 0
                accept_k = edge_b & (d_k <= threshold[None, :])
                count = accept_k.sum(axis=0)
                closest = jnp.argmin(
                    jnp.where(edge_b, d_k, jnp.inf), axis=0
                )
                has_any = edge_b.any(axis=0)
                fallback = (
                    ((count < min_neighbors) & has_any)[None, :]
                    & (
                        jnp.arange(len(offsets))[:, None]
                        == closest[None, :]
                    )
                    & edge_b
                )
                degree = jnp.maximum(adj.sum(axis=0), 1.0).astype(own.dtype)
            else:
                accept_k = d_k <= threshold[None, :]
                count = accept_k.sum(axis=0)
                closest = jnp.argmin(d_k, axis=0)  # offset index per node
                fallback = (count < min_neighbors)[None, :] & (
                    jnp.arange(len(offsets))[:, None] == closest[None, :]
                )
                degree = jnp.full(
                    (own.shape[0],), float(len(offsets)), own.dtype
                )
            accept_k = (accept_k | fallback).astype(own.dtype)
            neighbor_avg = circulant_masked_mean(bcast, accept_k, offsets)
            accepted_count = accept_k.sum(axis=0)
            if ctx.audit:
                # Sender-side taps via rolls only (ppermute-clean, MUR400):
                # accept_k[o_idx, i] = receiver i accepted its neighbor at
                # offsets[o_idx], i.e. sender (i + o) % n.
                tap_selected_by = sum(
                    jnp.roll(accept_k[i].astype(jnp.float32), o)
                    for i, o in enumerate(offsets)
                )
                if sparse_exchange:
                    tap_considered_by = circulant_in_degree(adj, offsets)
                else:
                    tap_considered_by = jnp.full(
                        (own.shape[0],), float(len(offsets))
                    )
        else:
            dist = pairwise_l2_distances(own, bcast, pallas=pallas)
            accepted = accept_with_closest_fallback(
                dist, adj, threshold, min_neighbors
            )
            neighbor_avg = masked_neighbor_mean(bcast, accepted)
            accepted_count = accepted.sum(axis=1)
            degree = jnp.maximum(adj.sum(axis=1), 1.0)
            if ctx.audit:
                # Sender-side taps: column sums over the acceptance mask —
                # the cross-shard reduction lowers to the all_reduce the
                # dense inventory already declares (MUR400).
                tap_selected_by = accepted.astype(jnp.float32).sum(axis=0)
                tap_considered_by = adj.astype(jnp.float32).sum(axis=0)

        new_flat = blend_with_own(own, neighbor_avg, accepted_count > 0, alpha)
        stats = {
            "acceptance_rate": accepted_count / degree,
            "threshold": threshold,
        }
        if ctx.audit:
            stats["tap_selected_by"] = tap_selected_by
            stats["tap_considered_by"] = tap_considered_by
        return new_flat, state, stats

    return AggregatorDef(
        name="balance",
        aggregate=aggregate,
        # MUR202: dense distance filter + accepted mean gather; circulant
        # path is rolls only.
        collectives={
            "dense": {"all_gather", "all_reduce"},
            "circulant": {"ppermute"},
        },
        # Compressed exchange: the circulant path touches the broadcast
        # only through the shared roll kernels, which move the int8
        # payload (MUR700).
        quantized_exchange=offsets is not None,
        # MUR800: the distance filter is data-dependent — on benign inputs
        # every neighbor passes the threshold and the accepted mean spans
        # the whole neighborhood.  The cap exists only under attack, which
        # a static cardinality bound cannot promise; declared unbounded.
        influence=InfluenceDecl(
            "unbounded",
            note="distance-threshold accept-filter: benign inputs accept "
            "every neighbor; exclusion is data-dependent, not structural",
        ),
    )

"""Aggregation rule interface and shared kernels.

The reference's ``Aggregator.aggregate(node_id, own_state, neighbor_states,
round_num)`` (murmura/aggregation/base.py:20-49) runs once per node per round
over Python dicts.  Here a rule is one pure function over the whole network:

    aggregate(own[N, P], bcast[N, P], adj[N, N], round_idx, state, ctx)
        -> (new_flat[N, P], new_state, stats)

- ``own`` holds each node's true state; ``bcast`` holds the states as
  broadcast (post-attack).  The two differ only on compromised rows — the
  reference aggregates with the node's own true state while neighbors see
  the attacked snapshot (murmura/core/network.py:108-135, node.py:214-252);
- ``adj`` is the 0/1 adjacency mask of the gathered neighbor tensor;
- ``state`` carries cross-round per-rule memory (EMA trust, acceptance
  windows) that the reference keeps as Python attributes
  (e.g. evidential_trust.py:112-113, sketchguard.py:61-64);
- ``stats`` are per-node arrays replacing ``get_statistics()`` scalars.

Everything is traced — the rule compiles into the jitted round step.
"""

from dataclasses import dataclass, field
from typing import (
    Any,
    Callable,
    Collection,
    Dict,
    FrozenSet,
    Mapping,
    Optional,
    Tuple,
)

import jax
import jax.numpy as jnp

from murmura_tpu.ops.compress import Int8Blocks

Stats = Dict[str, jnp.ndarray]
AggState = Dict[str, jnp.ndarray]

# Canonical names of the communication primitives a lowered aggregation
# program may contain (the vocabulary of ``AggregatorDef.collectives`` and
# of the MUR202 collective-inventory check, analysis/ir.py).  They mirror
# the XLA HLO ops GSPMD emits when the node axis is sharded: the dense
# rules' gathered [N, P] reads become ``all_gather``/``all_reduce``; the
# circulant rules' ``jnp.roll`` becomes boundary ``ppermute``
# (collective-permute); vmapped probe sweeps may add ``all_to_all``.
COLLECTIVE_NAMES = frozenset(
    {"all_gather", "all_reduce", "ppermute", "all_to_all", "reduce_scatter"}
)


@dataclass(frozen=True)
class AggContext:
    """Per-round context handed to aggregation rules.

    Attributes:
        apply_fn: single-model forward (params, x, key, train) -> outputs.
        unravel: flat [P] -> params pytree.
        probe_x/probe_y/probe_mask: per-node probe batches [N, B, ...] used by
            loss-probe rules (UBAR stage 2 — ubar.py:152-202) and trust
            evaluation (evidential_trust.py:214-316).
        evidential: whether apply_fn outputs Dirichlet alphas.
        num_classes: output arity (for losses).
        total_rounds: T for threshold schedules.
        probe_cross: optional precomputed [N, N] cross-eval metric dict
            (probe.combined_probe_metric output) — set when another consumer
            in the same round step (DMTT) already paid for the N x N forward
            sweep, so probe-based rules reuse instead of recompute.
    """

    apply_fn: Callable = None
    unravel: Callable = None
    probe_x: Optional[jnp.ndarray] = None
    probe_y: Optional[jnp.ndarray] = None
    probe_mask: Optional[jnp.ndarray] = None
    evidential: bool = False
    num_classes: int = 0
    total_rounds: int = 1
    probe_cross: Optional[Dict[str, jnp.ndarray]] = None
    # True when the round step runs with the node axis sharded over a mesh
    # (tpu.num_devices > 1): circulant shift lowerings differ — jnp.roll
    # becomes boundary collective-permutes (O(degree) communication, the
    # point of tpu.exchange: ppermute) while a static-index gather would
    # lower to an all-gather; on ONE device the roles reverse (roll's
    # wrap-around slice pads up to 128x, a gather pads nothing).
    node_axis_sharded: bool = False
    # telemetry.audit_taps: rules additionally surface per-node decision
    # tensors (tap_* stats — who selected/accepted whom this round) riding
    # the normal stats/history output path.  Trace-time static; the tapped
    # program must add NO collectives beyond the rule's declared inventory
    # (circulant taps use rolls, dense taps use axis reductions already in
    # the declared set) and NO recompiles across rounds — both are
    # machine-checked contracts (`murmura check --ir` MUR400/MUR402).
    audit: bool = False


@dataclass(frozen=True)
class InfluenceDecl:
    """Declared Byzantine influence contract of a rule (``murmura check
    --flow``, MUR800-802 — analysis/flow.py).

    The flow analyzer seeds each exchanged broadcast row with a distinct
    taint label and propagates *value* dataflow through the rule's jaxpr
    (selection dataflow — comparisons, sort permutations, gather indices,
    ``where`` predicates — is excluded by construction: it decides WHICH
    finite values are chosen, and the finiteness precondition is
    discharged separately by the MUR803 scrub-dominance check).  The
    resulting per-output-coordinate taint cardinality is the number of
    distinct neighbors whose broadcast VALUES can enter that coordinate.

    ``kind="bounded"`` declares a cap: ``bound(k)`` maps the per-node
    neighbor count ``k`` (non-self candidates; self is always excluded
    from the cardinality) to the maximum labels any single output
    coordinate may carry — e.g. Krum's single winner (1), the
    coordinate-wise median's middle pair, the trimmed mean's kept
    interior.  MUR800 fails when the analyzed cardinality exceeds it.

    ``kind="unbounded"`` is an explicit admission that every neighbor's
    value can reach the output (fedavg's mean) or that the cap is
    data-dependent and vanishes on benign inputs (BALANCE/UBAR-style
    accept-filters admit everything when nothing looks hostile; the
    geometric median downweights but never excludes).  ``note`` says why
    — it doubles as runtime documentation (``murmura report`` prints it
    next to the observed audit-tap rejection counts).

    Declaring nothing is itself a finding (MUR801): every registered rule
    must state its influence contract, exactly as it must state its
    collective inventory.
    """

    kind: str  # "bounded" | "unbounded"
    bound: Optional[Callable[[int], int]] = None
    note: str = ""

    def __post_init__(self):
        if self.kind not in ("bounded", "unbounded"):
            raise ValueError(
                f"influence kind must be 'bounded' or 'unbounded', got "
                f"{self.kind!r}"
            )
        if self.kind == "bounded" and self.bound is None:
            raise ValueError("bounded influence declarations need a bound()")
        if self.kind == "unbounded" and self.bound is not None:
            raise ValueError(
                "unbounded influence declarations must not carry a bound()"
            )

    def describe(self, k: Optional[int] = None) -> str:
        """Human-readable contract line (the `murmura report` rendering)."""
        if self.kind == "unbounded":
            base = "unbounded"
        elif k is None:
            base = "bounded"
        else:
            base = f"bounded: <= {self.bound(k)} of {k} neighbors per coordinate"
        return f"{base} — {self.note}" if self.note else base


@dataclass(frozen=True)
class AggregatorDef:
    """A named aggregation rule with optional carried state.

    ``state_kind`` maps each carried-state key to its indexing scheme:
    'node' = leading axis is the node id (e.g. acceptance windows), 'edge' =
    [N, N] directed-edge matrix (e.g. smoothed trust).  The ZMQ distributed
    backend uses this to project the stacked state onto one process's view.

    ``collectives`` declares the rule's communication contract: for each
    exchange mode ('dense' = gathered [N, P] adjacency masking, 'circulant'
    = tpu.exchange: ppermute rolls) the set of :data:`COLLECTIVE_NAMES`
    the lowered SPMD program is allowed to contain.  ``murmura check --ir``
    (MUR202, analysis/ir.py) compiles each rule over a sharded node axis
    and fails on any collective outside the declaration — a stray
    ``all_gather`` on the circulant path is a finding at check time, not a
    silent O(N) ICI regression on the chip.  ``None`` means undeclared,
    itself a finding for registered rules.
    """

    name: str
    aggregate: Callable[
        [jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, AggState, AggContext],
        Tuple[jnp.ndarray, AggState, Stats],
    ]
    init_state: Callable[[int], AggState] = field(default=lambda num_nodes: {})
    needs_probe: bool = False
    state_kind: Dict[str, str] = field(default_factory=dict)
    collectives: Optional[Mapping[str, Collection[str]]] = None
    # True when this rule's exchange consumes the broadcast exclusively
    # through the shared circulant kernels below, which accept the int8
    # compressed payload (ops/compress.Int8Blocks) in place of the float
    # tensor — the rolls then move int8 + per-block scales through the
    # boundary ppermutes instead of a dequantized [*, P] float operand
    # (compressed exchange, MUR700).  Rules that run arbitrary math over
    # the broadcast (probe forwards, sketch tables) keep False and receive
    # the receiver-side dequantized tensor from core/rounds.py.
    quantized_exchange: bool = False
    # Declared Byzantine influence contract (see :class:`InfluenceDecl`):
    # how many distinct neighbors' broadcast VALUES may enter any single
    # output coordinate.  ``murmura check --flow`` verifies the analyzed
    # taint cardinality against it per exchange mode (MUR800), requires
    # every registered rule to declare one (MUR801), and pins the analyzed
    # result's parity across dense/circulant/sparse/compressed modes
    # (MUR802).  None = undeclared, itself a finding for registered rules.
    influence: Optional[InfluenceDecl] = None

    def declared_collectives(self, circulant) -> Optional[FrozenSet[str]]:
        """Allowed collective set for one exchange mode (``None`` =
        undeclared).  The hook the IR analyzer calls; values must be drawn
        from :data:`COLLECTIVE_NAMES`.  ``circulant`` is the legacy bool
        (dense/circulant) or a mode string; mode ``"sparse"`` (the [k, N]
        edge-mask engine) inherits the circulant declaration unless a rule
        declares a tighter ``"sparse"`` set — the sparse path IS the
        circulant machinery with mask weights (MUR601)."""
        if self.collectives is None:
            return None
        if isinstance(circulant, str):
            mode = circulant
        else:
            mode = "circulant" if circulant else "dense"
        if mode == "sparse" and "sparse" not in self.collectives:
            mode = "circulant"
        return frozenset(self.collectives.get(mode, ()))


# ---------------------------------------------------------------------------
# Shared kernels
# ---------------------------------------------------------------------------


def pairwise_l2_distances(
    a: jnp.ndarray, b: Optional[jnp.ndarray] = None, pallas: bool = False
) -> jnp.ndarray:
    """L2 distance matrix D[i, j] = ||a_i - b_j|| via one Gram matmul.

    With ``b=None`` this is the all-pairs matrix over one tensor. The
    reference instead recomputes per-pair distances inside each node's
    Python loop (krum.py:54-62, balance.py:99-106).

    Numerics: the rows are centered on the mean of ``a`` before the Gram
    identity.  Late in training all nodes' parameter vectors cluster around
    a common point with norms orders of magnitude larger than their pairwise
    distances; without centering, sq_a + sq_b - 2ab cancels catastrophically
    in float32 and Krum's small-distance ranking degrades to rounding noise.
    Centering leaves distances unchanged and shrinks the norms to the
    cluster scale.
    """
    same = b is None
    in_dtype = a.dtype
    a32 = a.astype(jnp.float32)
    b32 = a32 if same else b.astype(jnp.float32)
    center = jnp.mean(a32, axis=0, keepdims=True)
    a32 = a32 - center
    b32 = a32 if same else b32 - center
    if pallas:
        # Fused streamed kernel (ops/pallas_agg.py): Gram matmul + norms +
        # combination in one pass over the centered operands.  None =
        # shapes outside the kernel envelope; fall through to the lax path.
        from murmura_tpu.ops import pallas_agg

        d2p = pallas_agg.pairwise_sq_distances(a32, b32)
        if d2p is not None:
            return jnp.sqrt(jnp.maximum(d2p, 0.0))
    # Squared norms and the final combination accumulate in f32 regardless
    # of input dtype: with bf16 params (tpu.param_dtype) a bf16 reduction
    # would quantize the small post-centering distances the selection ranks
    # on.  The Gram matmul itself keeps bf16 *inputs* with f32 accumulation
    # (preferred_element_type) — the MXU-native mode — rather than f32
    # operands, which would double the memory-bound matmul's HBM reads.
    sq_a = jnp.sum(a32 * a32, axis=-1)
    sq_b = sq_a if same else jnp.sum(b32 * b32, axis=-1)
    if in_dtype == jnp.bfloat16:
        da, db = a32.astype(in_dtype), b32.astype(in_dtype)
    else:
        da, db = a32, b32
    d2 = (
        sq_a[:, None]
        + sq_b[None, :]
        - 2.0 * jnp.dot(da, db.T, preferred_element_type=jnp.float32)
    )
    return jnp.sqrt(jnp.maximum(d2, 0.0))


# Per-rolled-copy HBM budget for the circulant kernels.  A full-width
# jnp.roll of the stacked [N, P] states materializes ~k copies at once
# (XLA schedules the Python-unrolled offsets concurrently), and the
# wrap-around slices ([1..k, P]) pick up a 32-128x tile-padding expansion
# at large N — the 25 GB OOM the 256-node north-star program hit on a
# 15.75 GB v5e chip.  Chunking the parameter axis caps the rolled working
# set at this budget while leaving small-N programs (one chunk) with the
# exact unchunked computation.  The P axis is never sharded (the node
# axis is the mesh axis — parallel/mesh.py), so dynamic-slicing it is
# GSPMD-safe and rolls on axis 0 still lower to collective-permutes.
_CIRCULANT_CHUNK_BYTES = 256 * 1024 * 1024


def _p_chunk_len(n: int, p: int, itemsize: int, floor: int = 4) -> int:
    """Chunk length along P so one [N, chunk] rolled copy stays in budget.

    The default budget floor is the f32 itemsize even for bf16 inputs:
    every circulant kernel accumulates its chunk in float32 (distance
    reduces, weighted sums), and XLA materializes the per-copy f32 upcast
    of a rolled *float* operand — sizing by itemsize=2 would double the
    chunk and hand back the OOM headroom the 256-node north-star run
    depends on.

    Compressed-exchange callers pass ``floor=1``: the rolled copies of an
    int8 payload stay int8 (the dequantizing convert feeds straight into
    the subtract/FMA chain — there is no standalone f32 copy per roll), so
    sizing the exchange chunk by the ≥4-byte float assumption would cut
    the chunk 4x and quadruple the ppermute count for no memory benefit.

    Param-axis sharding (parallel/mesh.py ``param_axis_scope``): under an
    active param-sharded trace scope the budget is SHARD-LOCAL — a
    [N, chunk] rolled copy is resident at chunk/shards columns per
    device, so the admissible chunk scales UP by the shard count.  That
    keeps programs the sharded budget can hold entirely UNCHUNKED, which
    matters more than it reads: a chunk loop's traced-start
    dynamic-slices on the column axis cannot be proven shard-aligned by
    GSPMD, so any chunking under a sharded P degrades to column
    all-gathers (MUR1300's subject).  Programs still too large for the
    scaled budget keep the loop with chunks aligned to whole shard-local
    widths — documented degradation; add shards (or use the dense Gram
    rules) instead.  ``p`` values the shard count does not divide fall
    back to the unsharded accounting via ``active_param_shards(p)``.
    """
    from murmura_tpu.parallel.mesh import active_param_shards

    shards = active_param_shards(p)
    cap = _CIRCULANT_CHUNK_BYTES // max(1, n * max(itemsize, floor))
    chunk = max(1, min(p, cap * shards))
    if shards > 1 and chunk < p:
        # Align the (rare) still-chunked case to whole shard-local
        # widths: nchunks = ceil(p/chunk) grows until it divides the
        # shard count's column grid (bounded scan, trace-time only).
        p_local = p // shards
        chunk = max(p_local, (chunk // p_local) * p_local)
    return chunk


def _p_chunked_accumulate(arrays, chunk_fn, acc_init, p: int, chunk: int):
    """Reduce ``chunk_fn`` over [*, c]-slices of ``arrays`` along axis 1.

    Runs floor(p/chunk) full chunks under a fori_loop (one buffer of
    rolled temps live at a time; the carry is the small accumulator) and
    one statically-shaped tail outside it, so no padding of P is needed.
    """
    nfull = p // chunk

    def body(i, acc):
        cs = [
            jax.lax.dynamic_slice(a, (0, i * chunk), (a.shape[0], chunk))
            for a in arrays
        ]
        return acc + chunk_fn(*cs)

    acc = acc_init
    if nfull:
        acc = jax.lax.fori_loop(0, nfull, body, acc)
    if p - nfull * chunk:
        acc = acc + chunk_fn(*[a[:, nfull * chunk :] for a in arrays])
    return acc


def _p_chunked_map(arrays, chunk_fn, out_dtype, p: int, chunk: int):
    """Assemble ``chunk_fn`` over [*, c]-slices of ``arrays`` into [N, p].

    The map-flavored sibling of :func:`_p_chunked_accumulate`: full chunks
    run under a fori_loop whose carry is the output buffer (XLA aliases
    while-loop carries in place, so the only full-size array is the output
    itself), and the remainder is a statically-shaped tail update.

    A statically-unrolled formulation (chunks barrier-chained, output via
    one concatenate) was measured WORSE on the 256-node program: XLA's
    buffer assignment kept every chunk's slice + rolled temps in distinct
    live allocations (40.4 GB vs this formulation's 17.2 GB).  The while
    carry costs {0,1}-layout conversion copies at the loop boundary, but
    that is the cheaper failure mode.  On a single device, very large
    N*P circulant programs should prefer the dense allgather rules
    anyway — see the geometric-median Gram path and PERFORMANCE.md.
    """
    n = arrays[0].shape[0]
    nfull = p // chunk

    def body(i, out):
        cs = [
            jax.lax.dynamic_slice(a, (0, i * chunk), (a.shape[0], chunk))
            for a in arrays
        ]
        return jax.lax.dynamic_update_slice(
            out, chunk_fn(*cs).astype(out_dtype), (0, i * chunk)
        )

    out = jnp.zeros((n, p), out_dtype)
    if nfull:
        out = jax.lax.fori_loop(0, nfull, body, out)
    if p - nfull * chunk:
        tail = nfull * chunk
        out = jax.lax.dynamic_update_slice(
            out,
            chunk_fn(*[a[:, tail:] for a in arrays]).astype(out_dtype),
            (0, tail),
        )
    return out


def _quantized_pad_own(own, p_pad: int) -> jnp.ndarray:
    """Float own-side operand padded (with exact zeros) to the payload's
    block-padded width — the int8 codec's zero padding dequantizes to
    exact zeros, so both sides' padded columns are inert."""
    own32 = own.astype(jnp.float32)
    if own32.shape[1] == p_pad:
        return own32
    return jnp.pad(own32, ((0, 0), (0, p_pad - own32.shape[1])))


def _quantized_circulant_d2(own, qb: Int8Blocks, offsets) -> jnp.ndarray:
    """[k, N] squared neighbor distances over a compressed broadcast.

    Each roll moves the int8 payload + the [*, C] scale rows (boundary
    ppermutes of the COMPRESSED representation on a sharded node axis —
    MUR700); dequantization fuses into the subtract/square/reduce chain,
    so HBM serves int8 too.  Chunking runs in whole quant blocks so the
    scales slice consistently with the payload, sized with ``floor=1``
    (the compressed-itemsize rationale on :func:`_p_chunk_len`).
    """
    n = qb.num_nodes
    blk, nblocks, p_pad = qb.block, qb.num_blocks, qb.padded_p
    own_is_q = isinstance(own, Int8Blocks)
    own_f = None if own_is_q else _quantized_pad_own(own, p_pad)

    def chunk_d2(b0, nb):
        qc = qb.slice_blocks(b0, nb)
        if own_is_q:
            oc = own.slice_blocks(b0, nb).dequantize_f32()
        else:
            oc = jax.lax.dynamic_slice(own_f, (0, b0 * blk), (n, nb * blk))
        return jnp.stack(
            [
                jnp.sum(
                    jnp.square(oc - qc.roll(-o).dequantize_f32()), axis=-1
                )
                for o in offsets
            ]
        )

    bpc = max(1, _p_chunk_len(n, p_pad, 1, floor=1) // blk)
    if bpc >= nblocks:
        return chunk_d2(0, nblocks)
    nfull = nblocks // bpc

    def body(i, acc):
        return acc + chunk_d2(i * bpc, bpc)

    acc = jax.lax.fori_loop(
        0, nfull, body, jnp.zeros((len(offsets), n), jnp.float32)
    )
    if nblocks - nfull * bpc:
        acc = acc + chunk_d2(nfull * bpc, nblocks - nfull * bpc)
    return acc


def _quantized_circulant_weighted_sum(
    qb: Int8Blocks, w_k: jnp.ndarray, offsets, out_dtype
) -> jnp.ndarray:
    """Compressed twin of :func:`circulant_weighted_sum`: the rolled
    operands are the int8 payload + scales, the f32 weight products
    accumulate per chunk, and only the [N, p] output materializes in
    ``out_dtype``."""
    n = qb.num_nodes
    blk, nblocks, p_pad = qb.block, qb.num_blocks, qb.padded_p
    out_dtype = qb.out_dtype if out_dtype is None else out_dtype

    def chunk_sum(b0, nb):
        qc = qb.slice_blocks(b0, nb)
        acc = jnp.zeros((n, nb * blk), jnp.float32)
        for idx, o in enumerate(offsets):
            acc = acc + w_k[idx][:, None] * qc.roll(-o).dequantize_f32()
        return acc

    bpc = max(1, _p_chunk_len(n, p_pad, 1, floor=1) // blk)
    if bpc >= nblocks:
        return chunk_sum(0, nblocks)[:, : qb.p].astype(out_dtype)
    nfull = nblocks // bpc
    out = jnp.zeros((n, p_pad), out_dtype)

    def body(i, out):
        return jax.lax.dynamic_update_slice(
            out, chunk_sum(i * bpc, bpc).astype(out_dtype), (0, i * bpc * blk)
        )

    out = jax.lax.fori_loop(0, nfull, body, out)
    if nblocks - nfull * bpc:
        out = jax.lax.dynamic_update_slice(
            out,
            chunk_sum(nfull * bpc, nblocks - nfull * bpc).astype(out_dtype),
            (0, nfull * bpc * blk),
        )
    return out[:, : qb.p]


def _quantized_circulant_candidate_map(
    own, qb: Int8Blocks, offsets, fn
) -> jnp.ndarray:
    """Compressed twin of :func:`circulant_candidate_map`: the candidate
    stack is assembled from rolled int8 payloads dequantized per chunk
    (the stack itself is f32 in registers/VMEM — only the reads are
    compressed), with the budget scaled by the stack height."""
    n = qb.num_nodes
    blk, nblocks, p_pad = qb.block, qb.num_blocks, qb.padded_p
    own_f = _quantized_pad_own(own, p_pad)
    out_dtype = qb.out_dtype

    def chunk_apply(b0, nb):
        qc = qb.slice_blocks(b0, nb)
        oc = jax.lax.dynamic_slice(own_f, (0, b0 * blk), (n, nb * blk))
        return fn(
            jnp.stack(
                [oc] + [qc.roll(-o).dequantize_f32() for o in offsets]
            )
        )

    # The f32 stack dominates the working set, so size by the float
    # accounting (floor=4) scaled by the stack height, in whole blocks.
    stack = len(offsets) + 1
    bpc = max(1, _p_chunk_len(n * stack, p_pad, 4) // blk)
    if bpc >= nblocks:
        return chunk_apply(0, nblocks)[:, : qb.p].astype(out_dtype)
    nfull = nblocks // bpc
    out = jnp.zeros((n, p_pad), out_dtype)

    def body(i, out):
        return jax.lax.dynamic_update_slice(
            out,
            chunk_apply(i * bpc, bpc).astype(out_dtype),
            (0, i * bpc * blk),
        )

    out = jax.lax.fori_loop(0, nfull, body, out)
    if nblocks - nfull * bpc:
        out = jax.lax.dynamic_update_slice(
            out,
            chunk_apply(nfull * bpc, nblocks - nfull * bpc).astype(out_dtype),
            (0, nfull * bpc * blk),
        )
    return out[:, : qb.p]


def circulant_neighbor_distances(
    own: jnp.ndarray, bcast: jnp.ndarray, offsets, pallas: bool = False
) -> jnp.ndarray:
    """[k, N] distances D[o, i] = ||own_i - bcast[(i+o) % N]|| via circular
    shifts — the O(degree) counterpart of the [N, N] pairwise matrix for
    circulant graphs (tpu.exchange: ppermute). Each roll lowers to
    boundary-slice collective-permutes on a sharded node axis, and the
    direct elementwise norm avoids the Gram-identity cancellation the dense
    path has to center against.  The squared-diff reduction runs in f32
    regardless of input dtype (XLA fuses the upcast into the reduce, no
    extra HBM pass): a bf16 accumulation over millions of terms would
    quantize the small distances the Byzantine selections rank on, same
    hazard :func:`pairwise_l2_distances` guards against.

    Large N*P runs P-chunked (see ``_CIRCULANT_CHUNK_BYTES``): the sum over
    P is associative, so partial sums over chunks accumulate in the same
    f32 precision and only the final sqrt changes position — identical up
    to f32 summation order.

    Compressed exchange (``bcast`` — or both operands — an
    :class:`Int8Blocks` payload) dispatches to the quantized twin so the
    rolls move the compressed representation (MUR700); ``pallas=True``
    routes plain float operands through the fused Pallas streaming kernel
    (ops/pallas_agg.py) when the shapes fit its envelope.
    """
    if isinstance(bcast, Int8Blocks):
        # own may be float (node-local, uncompressed) or Int8Blocks (the
        # krum delta-distance call passes the payload on both sides).
        return jnp.sqrt(_quantized_circulant_d2(own, bcast, offsets))
    if isinstance(own, Int8Blocks):
        raise TypeError(
            "circulant_neighbor_distances got a compressed own-side "
            "operand with an uncompressed broadcast — the quantized twin "
            "needs the rolled (broadcast) side compressed; quantize both "
            "or neither"
        )
    if pallas:
        from murmura_tpu.ops import pallas_agg

        d2p = pallas_agg.circulant_sq_distances(own, bcast, offsets)
        if d2p is not None:
            return jnp.sqrt(jnp.maximum(d2p, 0.0))
    n, p = bcast.shape

    def chunk_d2(oc, bc):
        return jnp.stack(
            [
                jnp.sum(
                    jnp.square(
                        (oc - jnp.roll(bc, -o, axis=0)).astype(jnp.float32)
                    ),
                    axis=-1,
                )
                for o in offsets
            ]
        )

    chunk = _p_chunk_len(n, p, bcast.dtype.itemsize)
    if chunk >= p:
        return jnp.sqrt(chunk_d2(own, bcast))
    d2 = _p_chunked_accumulate(
        [own, bcast],
        chunk_d2,
        jnp.zeros((len(offsets), n), jnp.float32),
        p,
        chunk,
    )
    return jnp.sqrt(d2)


def circulant_weighted_sum(
    bcast: jnp.ndarray, w_k: jnp.ndarray, offsets, out_dtype=None
) -> jnp.ndarray:
    """[N, P] per-offset weighted neighbor sum: sum_o w_k[o, i] * bcast[(i+o) % N].

    The shared memory-safe kernel behind the circulant masked mean, the
    fedavg roll path, evidential trust's weighted blend and the Weiszfeld
    recursion.  Large N*P runs P-chunked with the output assembled via
    dynamic_update_slice on the fori_loop carry (XLA aliases while-loop
    carries in place, so the only full-size buffers are ``bcast`` and the
    output).

    ``out_dtype`` narrows the OUTPUT buffer only — per-chunk accumulation
    still runs at the promoted precision (f32 for f32 weights over bf16
    states) and the cast happens once per chunk.  Callers that iterate on
    the result (geometric median) pass the resident param dtype here so a
    bf16 256-node program does not materialize f32 [N, P] buffers — the
    6.3 GB-per-copy OOM class.

    A compressed broadcast (:class:`Int8Blocks`) dispatches to the
    quantized twin: the rolls move int8 + scales (MUR700).
    """
    if isinstance(bcast, Int8Blocks):
        return _quantized_circulant_weighted_sum(bcast, w_k, offsets, out_dtype)
    n, p = bcast.shape
    acc_dtype = jnp.result_type(bcast.dtype, w_k.dtype)
    if out_dtype is None:
        out_dtype = acc_dtype

    def chunk_sum(bc):
        acc = jnp.zeros(bc.shape, acc_dtype)
        for idx, o in enumerate(offsets):
            acc = acc + w_k[idx][:, None] * jnp.roll(bc, -o, axis=0)
        return acc

    chunk = _p_chunk_len(n, p, bcast.dtype.itemsize)
    if chunk >= p:
        return chunk_sum(bcast).astype(out_dtype)
    return _p_chunked_map([bcast], chunk_sum, out_dtype, p, chunk)


def candidate_chunk_dispatch(own, bcast, chunk_apply, stack_height: int):
    """Shared P-chunking dispatch for candidate-stack reductions.

    ``chunk_apply(own_chunk, bcast_chunk) -> [N, c]`` must be
    coordinate-wise along the last axis.  The budget is scaled by
    ``stack_height`` (how many [N, c]-sized copies the stack materializes
    per chunk); small N*P runs the exact single-chunk computation.  Both
    the circulant and the dense candidate maps dispatch through here so
    the OOM-budget logic lives in one place.
    """
    n, p = bcast.shape
    chunk = _p_chunk_len(n * stack_height, p, bcast.dtype.itemsize)
    if chunk >= p:
        return chunk_apply(own, bcast)
    out_dtype = jax.eval_shape(
        chunk_apply,
        jax.ShapeDtypeStruct((n, 1), own.dtype),
        jax.ShapeDtypeStruct((n, 1), bcast.dtype),
    ).dtype
    return _p_chunked_map([own, bcast], chunk_apply, out_dtype, p, chunk)


def circulant_candidate_map(own, bcast, offsets, fn) -> jnp.ndarray:
    """Apply a coordinate-wise reduction over the circulant candidate stack.

    ``fn`` maps the stacked candidates ``[m, N, c]`` (own + one rolled
    broadcast per offset, any chunk width c) to ``[N, c]`` and must be
    coordinate-wise along the last axis (sorts/means over the candidate
    axis are; anything mixing P columns is not).  Large N*P runs P-chunked
    with the budget scaled by the stack height m, so the median and
    trimmed-mean circulant paths never materialize the full [m, N, P]
    tensor (the same OOM class ``_CIRCULANT_CHUNK_BYTES`` exists for).

    A compressed broadcast (:class:`Int8Blocks`) dispatches to the
    quantized twin: the stack is assembled from rolled int8 payloads.
    """
    if isinstance(bcast, Int8Blocks):
        return _quantized_circulant_candidate_map(own, bcast, offsets, fn)

    def chunk_apply(oc, bc):
        return fn(jnp.stack([oc] + [jnp.roll(bc, -o, axis=0) for o in offsets]))

    return candidate_chunk_dispatch(own, bcast, chunk_apply, len(offsets) + 1)


def circulant_masked_mean(
    bcast: jnp.ndarray, accept_k: jnp.ndarray, offsets
) -> jnp.ndarray:
    """Weighted neighbor mean from per-offset acceptance.

    Args:
        bcast: [N, P] broadcast states.
        accept_k: [k, N] accept weight for node i's neighbor at offset o.
    """
    # Normalize the small [k, N] weights up front (full f32 precision) and
    # pin out_dtype to the resident param dtype: per-chunk accumulation
    # still runs at the promoted f32 precision inside the shared kernel,
    # but no full-size f32 [N, P] accumulator or quotient is ever
    # materialized (the OOM class out_dtype exists for) and the exchanged
    # tensor never upcasts (MUR201).
    cnt = accept_k.sum(axis=0)
    w_norm = accept_k / jnp.maximum(cnt, 1e-12)[None, :]
    return circulant_weighted_sum(bcast, w_norm, offsets, out_dtype=bcast.dtype)


def circulant_in_degree(edge_k: jnp.ndarray, offsets) -> jnp.ndarray:
    """[N] sender in-degree under a [k, N] edge mask, via rolls only.

    ``edge_k[j, i]`` says receiver ``i`` reads sender ``(i + offsets[j])
    % N``, so sender ``s`` is read by receiver ``(s - o) % N`` — each term
    is one roll of a [N] row, which lowers to boundary ppermutes on a
    sharded node axis (the tap/degree helper of the sparse exchange mode;
    keeps MUR400/MUR601 inventories ppermute-only).
    """
    return sum(
        jnp.roll(edge_k[j].astype(jnp.float32), o)
        for j, o in enumerate(offsets)
    )


def candidate_indices(adj: jnp.ndarray, m_cap: int):
    """Per-node candidate ordering shared by the candidate-block rules.

    Rank self first (2), neighbors next (1), non-candidates last; argsort
    is stable so neighbor indices come out ascending and truncation at
    ``m_cap`` is deterministic (krum.py candidate blocks; robust_stats.py).

    Returns:
        (cand_idx [N, m], valid [N, m] bool).
    """
    n = adj.shape[0]
    rank = adj + 2.0 * jnp.eye(n, dtype=adj.dtype)
    cand_idx = jnp.argsort(-rank, axis=1)[:, :m_cap]
    valid = jnp.take_along_axis(rank, cand_idx, axis=1) > 0.0
    return cand_idx, valid


def masked_neighbor_mean(bcast: jnp.ndarray, weights: jnp.ndarray) -> jnp.ndarray:
    """Weighted neighbor mean per node: (W @ bcast) / row-sum, safe on empty rows.

    Dtype-stable by contract (MUR201): with bf16 resident params the matmul
    runs bf16-in/f32-accumulate (the MXU-native mode — f32 *operands* would
    double the memory-bound matmul's HBM reads) and the mean is cast back to
    the resident dtype, so the exchanged [N, P] tensor never upcasts.  Row
    totals are summed (in f32) from the SAME cast weights the matmul uses:
    normalizing a bf16-quantized numerator by the unquantized f32 total
    would scale every row by sum(w)/sum(bf16(w)) != 1 — a systematic bias
    applied to the parameters each round.
    """
    w = weights.astype(bcast.dtype)
    totals = w.sum(axis=1, keepdims=True, dtype=jnp.float32)
    acc = jnp.dot(w, bcast, preferred_element_type=jnp.float32)
    return (acc / jnp.maximum(totals, 1e-12)).astype(bcast.dtype)


def blend_with_own(
    own: jnp.ndarray,
    neighbor_avg: jnp.ndarray,
    has_neighbors: jnp.ndarray,
    alpha: float,
) -> jnp.ndarray:
    """alpha*own + (1-alpha)*neighbor_avg where any neighbor was accepted,
    else own (the BALANCE/Sketchguard/UBAR output form — balance.py:140-175)."""
    blended = alpha * own + (1.0 - alpha) * neighbor_avg
    return jnp.where(has_neighbors[:, None], blended, own)


def rank_mask(values: jnp.ndarray, valid: jnp.ndarray, k: jnp.ndarray) -> jnp.ndarray:
    """Boolean mask of the k smallest valid entries per row.

    Args:
        values: [..., M] scores (smaller = better).
        valid: [..., M] candidate mask.
        k: [...] per-row number to keep.
    """
    masked = jnp.where(valid, values, jnp.inf)
    order = jnp.argsort(masked, axis=-1)
    ranks = jnp.argsort(order, axis=-1)
    return valid & (ranks < k[..., None])


def self_probe_metrics(
    own: jnp.ndarray, ctx: AggContext, metric_fn: Callable
) -> Dict[str, jnp.ndarray]:
    """Evaluate each node's own params on its own probe batch (diagonal of the
    cross-eval), e.g. UBAR's own-loss baseline (ubar.py:174-176)."""

    def one(flat_i, x_i, y_i, m_i):
        params = ctx.unravel(flat_i)
        outputs = ctx.apply_fn(params, x_i, None, False)
        return metric_fn(outputs, y_i, m_i)

    n = own.shape[0]
    # A leading probe dim of 1 means "one shared evaluator batch" (the ZMQ
    # LocalNode mini-network) — broadcast it across the node axis.
    px, py, pm = (
        jnp.broadcast_to(a, (n,) + a.shape[1:]) if a.shape[0] == 1 and n != 1 else a
        for a in (ctx.probe_x, ctx.probe_y, ctx.probe_mask)
    )
    return jax.vmap(one)(own, px, py, pm)

"""Coordinate-wise robust statistics: median and trimmed mean.

No reference counterpart (murmura ships exactly six rules); these are the
two classic Byzantine-robust baselines from the distributed-SGD literature
(coordinate-wise median / trimmed mean, Yin et al. 2018) included beyond
parity because the stacked-[N, P] design makes them one sort apiece.

Per node i the candidate set is {own_i} ∪ {bcast_j : j ∈ N(i)} — same
candidate semantics as Krum (krum.py:45: the node's own *true* state plus
the neighbors' broadcasts).  Both rules gather an [N, m, P] candidate
tensor (m = max_candidates, injected by the factories as max-degree+1 on
static graphs, same as Krum's candidate blocks) and reduce along the
candidate axis, so the working set is O(N·m·P) — sized for sparse graphs;
on dense graphs m approaches N and the gather approaches the full
cross-product.
"""

from typing import Optional, Sequence

import jax.numpy as jnp

from murmura_tpu.aggregation.base import (
    AggContext,
    AggregatorDef,
    InfluenceDecl,
    candidate_chunk_dispatch,
    candidate_indices,
    circulant_candidate_map,
    circulant_neighbor_distances,
    circulant_weighted_sum,
    pairwise_l2_distances,
)
from murmura_tpu.ops.compress import Int8Blocks


def _dense_candidate_map(own, bcast, adj, m_cap, fn):
    """Apply a coordinate-wise reduction over the gathered candidate stack.

    ``fn`` maps (cand [N, m, c], valid [N, m]) -> [N, c] and must be
    coordinate-wise along the last axis (candidate ordering:
    base.candidate_indices, shared with Krum's candidate blocks; the self
    candidate takes the node's own true state).  Large N*m*P runs
    P-chunked on the shared machinery so the dense median/trimmed-mean
    never materialize the full [N, m, P] gather — 15.7 GB at 256 nodes
    bf16 with m = 5, the same OOM class the circulant candidate map
    chunks against.

    Returns:
        ([N, P] result, valid [N, m]) — valid is also returned so callers
        compute count stats without re-deriving the candidate set.
    """
    n = bcast.shape[0]
    cand_idx, valid = candidate_indices(adj, m_cap)
    is_self = cand_idx == jnp.arange(n)[:, None]

    def chunk_apply(oc, bc):
        cand = bc[cand_idx]  # [N, m, c]
        cand = jnp.where(is_self[:, :, None], oc[:, None, :], cand)
        return fn(cand, valid)

    result = candidate_chunk_dispatch(
        own, bcast, chunk_apply, int(cand_idx.shape[1])
    )
    return result, valid


def make_coordinate_median(
    max_candidates: Optional[int] = None,
    exchange_offsets: Optional[Sequence[int]] = None,
    sparse_exchange: bool = False,
    pallas: bool = False,
    **_params,
) -> AggregatorDef:
    """Coordinate-wise median over own + neighbor states.

    On circulant graphs (``tpu.exchange: ppermute``) the gather is replaced
    by k circular shifts stacked into a [k+1, N, P] candidate tensor with
    the sort over the small static leading axis — the same O(k·N·P) working
    set as the gathered path (every candidate is valid on a circulant
    graph, so no inf-padding is needed) and the same O(degree)
    boundary-ppermute communication win the other rules get.
    """
    mc = None if max_candidates is None else int(max_candidates)
    offsets = (
        None if exchange_offsets is None else [int(o) for o in exchange_offsets]
    )
    if sparse_exchange and offsets is None:
        raise ValueError("sparse_exchange requires exchange_offsets")

    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        n = own.shape[0]
        m_cap = n if mc is None else min(mc, n)

        def coord_median(cand, valid):
            cnt = valid.sum(axis=1)
            # Invalid candidates are +inf-padded and sort to the END, so
            # the median indices (cnt-1)//2 and cnt//2 address only the
            # first cnt (valid) rows.
            ranked = jnp.sort(
                jnp.where(valid[:, :, None], cand, jnp.inf), axis=1
            )
            lo = jnp.take_along_axis(
                ranked, ((cnt - 1) // 2)[:, None, None], axis=1
            )
            hi = jnp.take_along_axis(ranked, (cnt // 2)[:, None, None], axis=1)
            return (0.5 * (lo + hi))[:, 0, :]

        new_flat, valid = _dense_candidate_map(
            own, bcast, adj, m_cap, coord_median
        )
        cnt = valid.sum(axis=1)  # [N] >= 1 (self always valid)
        return new_flat, state, {"num_candidates": cnt.astype(jnp.float32)}

    def aggregate_circulant(own, bcast, adj, round_idx, state, ctx: AggContext):
        n = own.shape[0]
        m = len(offsets) + 1

        if sparse_exchange:
            # Sparse exchange mode: ``adj`` is the [k, N] edge mask;
            # masked-out candidates inf-pad to the END of the sort and the
            # median indices address only the first cnt valid rows (the
            # dense path's formula over the circulant stack).  All-ones
            # masks reproduce the static path bit-for-bit.
            valid = jnp.concatenate(
                [jnp.ones((1, n), adj.dtype), adj], axis=0
            ) > 0  # [m, N]
            cnt = valid.sum(axis=0)  # [N] >= 1 (self always valid)

            def coord_median(cand):  # [m, N, c] -> [N, c]
                ranked = jnp.sort(
                    jnp.where(valid[:, :, None], cand, jnp.inf), axis=0
                )
                lo = jnp.take_along_axis(
                    ranked, ((cnt - 1) // 2)[None, :, None], axis=0
                )
                hi = jnp.take_along_axis(
                    ranked, (cnt // 2)[None, :, None], axis=0
                )
                return (0.5 * (lo + hi))[0]

            new_flat = circulant_candidate_map(
                own, bcast, offsets, coord_median
            )
            return new_flat, state, {
                "num_candidates": cnt.astype(jnp.float32)
            }

        from murmura_tpu.ops import pallas_agg

        # Static trace-time predicate (shape/envelope facts only) — the
        # taint pass cannot see through the helper's array params.
        if (  # murmura: ignore[MUR001]
            pallas
            and not isinstance(bcast, Int8Blocks)
            and pallas_agg.candidate_select_supported(own, bcast, offsets)
        ):
            # Fused Pallas kernel (ops/pallas_agg.py): the candidate stack
            # is built, sorted, and reduced per VMEM-resident P-chunk —
            # the static path only (masked/sparse counts are traced).
            new_flat = pallas_agg.fused_candidate_select(
                own, bcast, offsets, median=True
            )
        else:
            def coord_median(cand):  # [m, N, c] -> [N, c], all valid
                ranked = jnp.sort(cand, axis=0)
                return 0.5 * (ranked[(m - 1) // 2] + ranked[m // 2])

            new_flat = circulant_candidate_map(
                own, bcast, offsets, coord_median
            )
        return new_flat, state, {
            "num_candidates": jnp.full((n,), float(m), jnp.float32)
        }

    return AggregatorDef(
        name="median",
        aggregate=aggregate if offsets is None else aggregate_circulant,
        # MUR202: candidate-stack rules — dense gathers the [N, P] stack,
        # the circulant stack is rolls only.
        collectives={
            "dense": {"all_gather", "all_reduce"},
            "circulant": {"ppermute"},
        },
        # Compressed exchange: the circulant candidate stacks read the
        # broadcast only through the shared roll kernels (MUR700).
        quantized_exchange=offsets is not None,
        # MUR800: each output coordinate is the middle element (odd
        # candidate count) or the mean of the middle pair (even) of the
        # sorted {self} ∪ neighbors stack — at most 1-2 neighbor values
        # per coordinate; which ones is selection influence.
        influence=InfluenceDecl(
            "bounded",
            bound=lambda k: 1 if (k + 1) % 2 else 2,
            note="coordinate-wise median: the middle element (or pair) of "
            "the sorted candidate stack",
        ),
    )


def make_trimmed_mean(
    trim_ratio: float = 0.2,
    max_candidates: Optional[int] = None,
    exchange_offsets: Optional[Sequence[int]] = None,
    sparse_exchange: bool = False,
    pallas: bool = False,
    **_params,
) -> AggregatorDef:
    """Coordinate-wise beta-trimmed mean: drop the floor(beta*cnt) smallest
    and largest values per coordinate, average the rest.

    The circulant path (``exchange_offsets``) mirrors the median's: with a
    constant candidate count m = k+1 the trim depth is static, so the keep
    window is a static slice of the sorted [m, N, P] stack rather than a
    masked sum.
    """
    beta = float(trim_ratio)
    if not 0.0 <= beta < 0.5:
        raise ValueError(f"trim_ratio must be in [0, 0.5), got {beta}")
    mc = None if max_candidates is None else int(max_candidates)
    offsets = (
        None if exchange_offsets is None else [int(o) for o in exchange_offsets]
    )
    if sparse_exchange and offsets is None:
        raise ValueError("sparse_exchange requires exchange_offsets")

    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        n = own.shape[0]
        m_cap = n if mc is None else min(mc, n)

        def coord_trimmed(cand, valid):
            cnt = valid.sum(axis=1)  # [N]
            trim = jnp.floor(beta * cnt).astype(cnt.dtype)  # [N]
            ranked = jnp.sort(
                jnp.where(valid[:, :, None], cand, jnp.inf), axis=1
            )
            pos = jnp.arange(valid.shape[1])[None, :]  # [1, m]
            keep = (pos >= trim[:, None]) & (
                pos < (cnt - trim)[:, None]
            )  # [N, m]
            kept = jnp.where(keep[:, :, None], ranked, 0.0).sum(axis=1)
            denom = jnp.maximum(cnt - 2 * trim, 1)[:, None].astype(own.dtype)
            return kept / denom

        new_flat, valid = _dense_candidate_map(
            own, bcast, adj, m_cap, coord_trimmed
        )
        cnt = valid.sum(axis=1)
        trim = jnp.floor(beta * cnt).astype(cnt.dtype)
        return new_flat, state, {
            "num_candidates": cnt.astype(jnp.float32),
            "trimmed_per_side": trim.astype(jnp.float32),
        }

    def aggregate_circulant(own, bcast, adj, round_idx, state, ctx: AggContext):
        n = own.shape[0]
        m = len(offsets) + 1

        if sparse_exchange:
            # Sparse exchange mode: per-node candidate counts / trim depth
            # become traced values from the [k, N] edge mask (the dense
            # path's keep-window formula over the circulant stack); an
            # all-ones mask reproduces the static slice bit-for-bit (the
            # zero-padded sum and the /denom match mean(axis=0) exactly).
            valid = jnp.concatenate(
                [jnp.ones((1, n), adj.dtype), adj], axis=0
            ) > 0  # [m, N]
            cnt = valid.sum(axis=0)  # [N]
            trim_i = jnp.floor(beta * cnt).astype(cnt.dtype)  # [N]

            def coord_trimmed(cand):  # [m, N, c] -> [N, c]
                ranked = jnp.sort(
                    jnp.where(valid[:, :, None], cand, jnp.inf), axis=0
                )
                pos = jnp.arange(m)[:, None, None]  # [m, 1, 1]
                keep = (pos >= trim_i[None, :, None]) & (
                    pos < (cnt - trim_i)[None, :, None]
                )
                kept = jnp.where(keep, ranked, 0.0).sum(axis=0)
                denom = jnp.maximum(cnt - 2 * trim_i, 1)[:, None]
                return kept / denom.astype(kept.dtype)

            new_flat = circulant_candidate_map(
                own, bcast, offsets, coord_trimmed
            )
            return new_flat, state, {
                "num_candidates": cnt.astype(jnp.float32),
                "trimmed_per_side": trim_i.astype(jnp.float32),
            }

        trim = int(beta * m)  # static: every node has exactly m candidates

        from murmura_tpu.ops import pallas_agg

        # Static trace-time predicate (shape/envelope facts only) — the
        # taint pass cannot see through the helper's array params.
        if (  # murmura: ignore[MUR001]
            pallas
            and not isinstance(bcast, Int8Blocks)
            and pallas_agg.candidate_select_supported(
                own, bcast, offsets, trim=trim
            )
        ):
            # Fused Pallas kernel: sort + trim + mean per VMEM chunk (the
            # static path only — sparse trim depths are traced).
            new_flat = pallas_agg.fused_candidate_select(
                own, bcast, offsets, trim=trim, median=False
            )
        else:
            def coord_trimmed(cand):  # [m, N, c] -> [N, c]
                ranked = jnp.sort(cand, axis=0)
                return ranked[trim : m - trim].mean(axis=0)  # m-2*trim >= 1

            new_flat = circulant_candidate_map(
                own, bcast, offsets, coord_trimmed
            )
        return new_flat, state, {
            "num_candidates": jnp.full((n,), float(m), jnp.float32),
            "trimmed_per_side": jnp.full((n,), float(trim), jnp.float32),
        }

    return AggregatorDef(
        name="trimmed_mean",
        aggregate=aggregate if offsets is None else aggregate_circulant,
        # MUR202: candidate-stack rules — dense gathers the [N, P] stack,
        # the circulant stack is rolls only.
        collectives={
            "dense": {"all_gather", "all_reduce"},
            "circulant": {"ppermute"},
        },
        # Compressed exchange: the circulant candidate stacks read the
        # broadcast only through the shared roll kernels (MUR700).
        quantized_exchange=offsets is not None,
        # MUR800: the tails are dropped, so each coordinate averages at
        # most m - 2*floor(beta*m) of the m = k+1 sorted candidates (one
        # of which may be the node's own state — the bound stays the
        # conservative interior size).
        influence=InfluenceDecl(
            "bounded",
            bound=lambda k: (k + 1) - 2 * int(beta * (k + 1)),
            note=f"beta-trimmed mean (beta={beta}): only the sorted "
            "interior is averaged; the trimmed tails never contribute",
        ),
    )


def make_geometric_median(
    max_iters: int = 8,
    smoothing: float = 1e-6,
    max_candidates: Optional[int] = None,
    exchange_offsets: Optional[Sequence[int]] = None,
    sparse_exchange: bool = False,
    pallas: bool = False,
    **_params,
) -> AggregatorDef:
    """Geometric median via smoothed Weiszfeld iterations (RFA,
    Pillutla et al. 2022) — beyond-parity robust rule #3.

    Unlike the coordinate-wise rules above, the geometric median is
    rotation-invariant and has a 1/2 breakdown point in the *vector* sense:
    the minimizer of sum_i ||z - x_i|| cannot be dragged arbitrarily far
    while a majority of candidates stay bounded.  Weiszfeld is a fixed
    small number of reweighted-mean steps — each iteration is one masked
    [N, m] distance reduce + one weighted mean over the shared candidate
    tensor, so the whole rule is O(max_iters · N·m·P), static control flow
    (``lax.fori_loop``), no data-dependent branches.

    On circulant graphs (``tpu.exchange: ppermute``) the candidate gather
    is replaced by k circular shifts of the broadcast tensor
    (``aggregate_circulant`` below): same O(k·N·P) working set, but the
    shifts lower to boundary collective-permutes on a sharded node axis —
    O(degree) communication instead of the all-gather.  The coordinate-wise
    rules above get the same treatment by stacking the shifts into a
    [k+1, N, P] candidate tensor and sorting over the static leading axis.

    The smoothing floor on the distances is the standard Weiszfeld guard
    (a candidate exactly at the current iterate would otherwise get an
    infinite weight).
    """
    iters = int(max_iters)
    if iters < 1:
        raise ValueError(f"max_iters must be >= 1, got {max_iters}")
    nu = float(smoothing)
    if not nu > 0.0:
        # nu floors the Weiszfeld distances; at 0 a candidate coincident
        # with the iterate yields inf/inf = NaN states.
        raise ValueError(f"smoothing must be > 0, got {smoothing}")
    mc = None if max_candidates is None else int(max_candidates)
    offsets = (
        None if exchange_offsets is None else [int(o) for o in exchange_offsets]
    )
    if sparse_exchange and offsets is None:
        raise ValueError("sparse_exchange requires exchange_offsets")

    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        from jax import lax

        n = own.shape[0]
        m_cap = n if mc is None else min(mc, n)
        # Gram-masked formulation: candidates never materialize as an
        # [N, m, P] tensor (31.5 GB at 256 nodes — un-runnable on a v5e).
        # Node i's candidates are {own_i} + its masked neighbors, so every
        # Weiszfeld step is one [N, N] distance matrix (one Gram matmul,
        # pairwise_l2_distances) + one [N, N] @ [N, P] weighted mean —
        # O(N^2 + N.P) memory at every N, and the matmuls read the big
        # tensors exactly once per iteration (memory-optimal on a single
        # device; the circulant path below serves sharded meshes).
        # ``max_candidates`` semantics are preserved by masking weights to
        # the same deterministic candidate set the capped tensor used.
        if m_cap < n:
            ci, cv = candidate_indices(adj, m_cap)  # [N, m] each
            nb_mask = (
                jnp.zeros((n, n), jnp.float32)
                .at[jnp.arange(n)[:, None], ci]
                .max(cv.astype(jnp.float32))
            )
            nb_mask = nb_mask * (1.0 - jnp.eye(n))  # self handled apart
        else:
            # Zero the diagonal locally rather than relying on the
            # generators' zero-diagonal invariant: the self candidate is
            # added apart (w_self), so a stray self-edge in adj would
            # double-count own_i in every Weiszfeld step.
            nb_mask = adj.astype(jnp.float32) * (1.0 - jnp.eye(n))
        cnt = 1.0 + nb_mask.sum(axis=1)  # [N], self always a candidate

        def weighted_mean(w_self, w_nb):
            # w_nb rows are masked; f32 weights, f32 accumulation
            # (preferred_element_type), bf16/f32 state operands as stored.
            acc = w_self[:, None] * own.astype(jnp.float32) + jnp.dot(
                w_nb, bcast, preferred_element_type=jnp.float32
            )
            tot = w_self + w_nb.sum(axis=1)
            return (acc / jnp.maximum(tot, 1e-30)[:, None]).astype(own.dtype)

        def distances(z):
            # d_self elementwise (f32 reduce — a bf16 accumulation over P
            # terms would quantize the distances the reweighting ranks on);
            # neighbor distances via one centered Gram matmul.
            d_self = jnp.sqrt(
                jnp.square((own - z).astype(jnp.float32)).sum(axis=-1)
            )  # [N]
            # Argument order matters inside the loop: pairwise centers by
            # the FIRST argument's mean, so passing the loop-invariant
            # bcast first lets XLA hoist its centered copy out of the
            # Weiszfeld iterations (z's cluster stays near bcast's, so the
            # cancellation guard is equally served); [j, i] -> transpose.
            d_nb = pairwise_l2_distances(bcast, z, pallas=pallas).T  # [N, N]
            return d_self, d_nb

        ones_n = jnp.ones((n,), jnp.float32)
        z0 = weighted_mean(ones_n, nb_mask)

        def body(_, z):
            d_self, d_nb = distances(z)
            return weighted_mean(
                1.0 / jnp.maximum(d_self, nu),
                nb_mask / jnp.maximum(d_nb, nu),
            )

        z = lax.fori_loop(0, iters, body, z0)
        d_self, d_nb = distances(z)
        w_self = 1.0 / jnp.maximum(d_self, nu)
        w_nb = nb_mask / jnp.maximum(d_nb, nu)
        tot = jnp.maximum(w_self + w_nb.sum(axis=1), 1e-30)
        stats = {
            "num_candidates": cnt,
            # Attack telemetry: how concentrated the final Weiszfeld
            # weights are.  A clean network keeps shares near 1/cnt; an
            # outlier-heavy neighborhood pushes the max share up as honest
            # candidates cluster and outliers are downweighted.
            "max_weight_share": jnp.maximum(w_self, w_nb.max(axis=1)) / tot,
            "mean_dist_to_gm": (d_self + (d_nb * nb_mask).sum(axis=1))
            / jnp.maximum(cnt, 1.0),
        }
        return z.astype(own.dtype), state, stats

    def aggregate_circulant(own, bcast, adj, round_idx, state, ctx: AggContext):
        """O(degree)-communication Weiszfeld for circulant graphs: node i's
        candidates are itself plus the k fixed-offset neighbors, so the
        candidate states are k rolled views of the broadcast tensor and
        every reduction in the recursion is over the small static k axis."""
        from jax import lax

        n = own.shape[0]
        k = len(offsets)
        # Sparse exchange mode: the [k, N] edge mask multiplies the
        # Weiszfeld weights, so masked-out candidates carry zero weight in
        # every recursion step (an all-ones mask is bit-exact: 1.0 / x ==
        # 1.0 / x and 1.0 * x == x).
        edge_w = adj.astype(jnp.float32) if sparse_exchange else None

        def weighted_mean(w_self, w_k):
            # circulant_weighted_sum promotes each w*roll product to f32
            # (f32 weights) chunk-by-chunk — the same upcast-then-multiply
            # the old [k, N, P] f32 stack did, without ever holding k
            # rolled copies.  The iterate is STORED in the resident param
            # dtype: all f32 [N, P] intermediates here live only inside
            # fused elementwise chains (registers), never as buffers —
            # a bf16 256-node program previously materialized three
            # 6.3 GB f32 copies (own upcast + two remat copies of z) and
            # OOM'd at 32.7 GB.
            acc = w_self[:, None] * own + circulant_weighted_sum(
                bcast, w_k, offsets, out_dtype=own.dtype
            )
            tot = w_self + w_k.sum(axis=0)
            return (acc / jnp.maximum(tot, 1e-30)[:, None]).astype(own.dtype)

        def distances(z):
            # f32 reduces, same rationale as the dense path (XLA fuses the
            # per-element upcast into the reduce); the neighbor distances
            # ride the shared P-chunked kernel.
            d_self = jnp.sqrt(
                jnp.square((own - z).astype(jnp.float32)).sum(axis=-1)
            )  # [N]
            d_k = circulant_neighbor_distances(
                z, bcast, offsets, pallas=pallas
            )  # [k, N]
            return d_self, d_k

        ones_k = edge_w if sparse_exchange else jnp.ones((k, n), jnp.float32)
        z0 = weighted_mean(jnp.ones((n,), jnp.float32), ones_k)

        def neighbor_weights(d_k):
            if sparse_exchange:
                return edge_w / jnp.maximum(d_k, nu)
            return 1.0 / jnp.maximum(d_k, nu)

        def body(_, z):
            d_self, d_k = distances(z)
            return weighted_mean(
                1.0 / jnp.maximum(d_self, nu), neighbor_weights(d_k)
            )

        z = lax.fori_loop(0, iters, body, z0)
        d_self, d_k = distances(z)
        w_self = 1.0 / jnp.maximum(d_self, nu)
        w_k = neighbor_weights(d_k)
        tot = jnp.maximum(w_self + w_k.sum(axis=0), 1e-30)
        if sparse_exchange:
            cnt = 1.0 + edge_w.sum(axis=0)
            mean_dist = (d_self + (d_k * edge_w).sum(axis=0)) / cnt
        else:
            cnt = jnp.full((n,), float(k + 1), jnp.float32)
            mean_dist = (d_self + d_k.sum(axis=0)) / float(k + 1)
        stats = {
            "num_candidates": cnt,
            "max_weight_share": jnp.maximum(w_self, w_k.max(axis=0)) / tot,
            "mean_dist_to_gm": mean_dist,
        }
        return z.astype(own.dtype), state, stats

    return AggregatorDef(
        name="geometric_median",
        aggregate=aggregate if offsets is None else aggregate_circulant,
        # MUR202: candidate-stack rules — dense gathers the [N, P] stack,
        # the circulant stack is rolls only.
        collectives={
            "dense": {"all_gather", "all_reduce"},
            "circulant": {"ppermute"},
        },
        # Compressed exchange: the circulant candidate stacks read the
        # broadcast only through the shared roll kernels (MUR700).
        quantized_exchange=offsets is not None,
        # MUR800: Weiszfeld reweights but never excludes — every candidate
        # keeps a strictly positive 1/max(d, nu) weight, so every
        # neighbor's values enter the iterate.  The robustness claim is
        # norm-bounded drag (1/2 breakdown point), not cardinality-bounded
        # influence, which the taint domain cannot express — declared
        # unbounded with that note.
        influence=InfluenceDecl(
            "unbounded",
            note="Weiszfeld weights are positive for every candidate; "
            "robustness is norm-bounded drag, not exclusion",
        ),
    )

"""FedAvg: equal-weight mean of own + neighbor states
(reference: murmura/aggregation/fedavg.py:19-42).

Vectorized over the whole network: own state plus one adjacency matmul over
the broadcast tensor, normalized by 1 + degree.

``exchange_offsets`` (tpu.exchange: ppermute): on a circulant graph the
adjacency matmul is a sum of fixed circular shifts; ``jnp.roll`` along the
sharded node axis lowers to boundary-slice collective-permutes over ICI —
O(degree) bytes per device instead of the all-gathered [N, P] tensor
(SURVEY.md §7 "use ppermute neighbor-only exchange for sparse topologies").
"""

from typing import Optional, Sequence

import jax.numpy as jnp

from murmura_tpu.aggregation.base import (
    AggContext,
    AggregatorDef,
    InfluenceDecl,
    circulant_weighted_sum,
)


def make_fedavg(
    exchange_offsets: Optional[Sequence[int]] = None,
    sparse_exchange: bool = False,
    **_params,
) -> AggregatorDef:
    offsets = None if exchange_offsets is None else [int(o) for o in exchange_offsets]
    if sparse_exchange and offsets is None:
        raise ValueError("sparse_exchange requires exchange_offsets")

    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        if sparse_exchange:
            # Sparse exchange mode (topology/sparse.py): ``adj`` is the
            # [k, N] per-offset edge mask, never [N, N]; its rows weight
            # the rolled neighbor sum directly, so inactive edges (one_peer
            # rounds, fault-dropped links) contribute nothing.  An all-ones
            # mask reproduces the circulant path bit-for-bit (1.0 * x is
            # exact).
            degree = adj.sum(axis=0)
        else:
            degree = adj.sum(axis=1)
        if offsets is not None:
            # roll(bcast, -o)[i] == bcast[(i+o) % N]: node i's neighbor at
            # circulant offset o; the shared kernel chunks P at large N*P.
            # f32 weights force f32 per-chunk accumulation over the k adds
            # (matching the dense branch's preferred_element_type) while
            # out_dtype keeps the stored sum — and any chunked [N, P]
            # buffer — in the resident param dtype.
            if sparse_exchange:
                w_k = adj.astype(jnp.float32)
            else:
                w_k = jnp.ones((len(offsets), own.shape[0]), jnp.float32)
            neighbor_sum = circulant_weighted_sum(
                bcast, w_k, offsets, out_dtype=own.dtype
            )
        else:
            # bf16 operands with f32 accumulation (MXU-native); an f32 adj
            # operand would promote the gathered [N, P] tensor before the
            # matmul and double its HBM reads (MUR201).
            neighbor_sum = jnp.dot(
                adj.astype(bcast.dtype), bcast,
                preferred_element_type=jnp.float32,
            )
        # The 1/(1+degree) weights stay f32; only the stored mean returns
        # to the resident param dtype so the exchange never upcasts.
        new_flat = ((own + neighbor_sum) / (1.0 + degree)[:, None]).astype(
            own.dtype
        )
        return new_flat, state, {"num_neighbors": degree}

    return AggregatorDef(
        name="fedavg",
        aggregate=aggregate,
        # MUR202 contract: the dense mean is one gathered matmul; the
        # circulant path must stay boundary ppermutes — an all_gather there
        # is the exact regression tpu.exchange: ppermute exists to avoid.
        collectives={
            "dense": {"all_gather", "all_reduce"},
            "circulant": {"ppermute"},
        },
        # Compressed exchange: the circulant path touches the broadcast
        # only through the shared roll kernels, which move the int8
        # payload (MUR700).
        quantized_exchange=offsets is not None,
        # MUR800: plain averaging has no Byzantine filter at all — every
        # neighbor's state enters the 1/(1+degree) mean.  Declared
        # unbounded on purpose: the flow analyzer must never be able to
        # "prove" fedavg robust.
        influence=InfluenceDecl(
            "unbounded",
            note="every neighbor's state enters the degree-normalized "
            "mean; a single Byzantine row moves it arbitrarily",
        ),
    )

"""FedAvg: equal-weight mean of own + neighbor states
(reference: murmura/aggregation/fedavg.py:19-42).

Vectorized over the whole network: own state plus one adjacency matmul over
the broadcast tensor, normalized by 1 + degree.
"""

import jax.numpy as jnp

from murmura_tpu.aggregation.base import AggContext, AggregatorDef


def make_fedavg(**_params) -> AggregatorDef:
    def aggregate(own, bcast, adj, round_idx, state, ctx: AggContext):
        degree = adj.sum(axis=1)
        new_flat = (own + adj @ bcast) / (1.0 + degree)[:, None]
        return new_flat, state, {"num_neighbors": degree}

    return AggregatorDef(name="fedavg", aggregate=aggregate)

"""Click CLI (reference: murmura/cli.py:34-308, a typer app; this
environment ships click, which typer wraps, so the commands are plain click).

Commands: ``run`` (simulation / tpu / distributed by config.backend),
``run-node`` (multi-machine ZMQ worker), ``list-components``.
"""

import json
from pathlib import Path
from typing import Optional

import click
from rich.console import Console
from rich.markup import escape
from rich.table import Table

from murmura_tpu.config import load_config
from murmura_tpu.utils.seed import set_seed

console = Console()


def _die_config_error(e: Exception) -> None:
    """Render a wiring-level ConfigError and exit (shared by every CLI
    path; escape(): error text may contain [bracketed] segments rich would
    otherwise swallow as markup tags)."""
    console.print(f"[bold red]Config error:[/bold red] {escape(str(e))}")
    raise SystemExit(1)


def _load_config_or_die(config_path: Path):
    """Load a config, rendering validation/parse failures as readable
    errors instead of raw tracebacks (a long-standing CLI friction)."""
    import pydantic
    import yaml

    try:
        return load_config(config_path)
    except pydantic.ValidationError as e:
        console.print(f"[bold red]Invalid config[/bold red] {config_path}:")
        for err in e.errors():
            loc = ".".join(str(p) for p in err["loc"]) or "<root>"
            console.print(f"  [yellow]{escape(loc)}[/yellow]: {escape(err['msg'])}")
        raise SystemExit(1)
    except (yaml.YAMLError, json.JSONDecodeError, ValueError) as e:
        # Malformed YAML/JSON or an unsupported file suffix.  escape():
        # error text may contain [bracketed] segments rich would otherwise
        # swallow as markup tags.
        console.print(
            f"[bold red]Cannot parse config[/bold red] {config_path}: "
            f"{escape(str(e))}"
        )
        raise SystemExit(1)


@click.group()
def app():
    """murmura_tpu: TPU-native decentralized federated learning."""


def _resolve_durability(config, checkpoint_dir, checkpoint_every, resume,
                        retries):
    """Merge the CLI durability flags over the config's ``durability:``
    block (explicit flag wins; ``None`` means "not given")."""
    d = config.durability
    if checkpoint_dir is None and d.checkpoint_dir is not None:
        checkpoint_dir = Path(d.checkpoint_dir)
    if checkpoint_every is None:
        checkpoint_every = d.checkpoint_every
    if resume is None:
        resume = d.resume
    if retries is None:
        retries = d.retries
    if resume and checkpoint_dir is None:
        raise click.UsageError("--resume requires --checkpoint-dir")
    if retries and checkpoint_dir is None:
        raise click.UsageError(
            "--retries requires --checkpoint-dir: a transient-failure "
            "retry restores from the last snapshot before re-dispatching "
            "(retrying consumed/donated buffers without a restore is "
            "never safe)"
        )
    if checkpoint_dir is not None and not resume:
        from murmura_tpu.utils.checkpoint import has_checkpoint

        if has_checkpoint(checkpoint_dir):
            # A fresh run would clobber the existing snapshot — and worse,
            # a retry before this run's first snapshot would silently
            # restore the STALE one and return the old run's history.
            raise click.UsageError(
                f"{checkpoint_dir} already holds a snapshot; pass --resume "
                "to continue that run, or point --checkpoint-dir at a "
                "clean directory"
            )
    return checkpoint_dir, checkpoint_every, resume, retries


def _train_with_retries(orchestrator, train, *, retries, config,
                        checkpoint_dir):
    """The shared retry envelope for `run` and `_run_sweep`:
    ``train()`` dispatches (computing remaining rounds itself, so a
    restored round counter is respected); on a classified-transient
    failure the orchestrator is restored from its last snapshot before
    re-dispatching — retrying consumed (donated) buffers without a
    restore is never safe, so an attempt with no snapshot to restore
    refuses loudly instead."""

    def _attempt(try_idx: int):
        if try_idx > 0:
            from murmura_tpu.utils.checkpoint import has_checkpoint

            if not has_checkpoint(checkpoint_dir):
                raise RuntimeError(
                    f"transient failure before the first snapshot landed "
                    f"in {checkpoint_dir} — nothing to restore, so a "
                    "retry is not donation-safe; rerun from scratch "
                    "(lower durability.checkpoint_every to shrink this "
                    "window)"
                )
            done = orchestrator.restore_checkpoint(str(checkpoint_dir))
            console.print(
                f"Retry {try_idx}: restored round [bold]{done}[/bold]"
            )
        return train()

    if not retries:
        return _attempt(0)
    from murmura_tpu.durability.dispatch import RetryPolicy, run_with_retry

    writers = orchestrator.telemetry
    if not isinstance(writers, (list, tuple)):
        writers = [writers]

    def _on_retry(exc, try_idx, delay):
        reason = f"{type(exc).__name__}: {exc}"[:300]
        console.print(
            f"[yellow]Transient failure ({escape(reason)}); "
            f"retry {try_idx}/{retries} in {delay:.1f}s[/yellow]"
        )
        for t in writers:
            if t is not None:
                t.emit(
                    "backend_degraded", reason=reason, retry=try_idx,
                    delay_s=round(delay, 2),
                    round=orchestrator.current_round,
                )

    return run_with_retry(
        _attempt,
        policy=RetryPolicy(
            max_retries=retries,
            base_delay_s=config.durability.retry_base_delay_s,
            max_delay_s=config.durability.retry_max_delay_s,
        ),
        on_retry=_on_retry,
    )


def _enforce_require_tpu(config, require_tpu_flag: bool) -> None:
    """The --require-tpu / durability.require_tpu / MURMURA_REQUIRE_TPU=1
    hard-fail: abort loudly instead of silently falling back to CPU."""
    from murmura_tpu.durability.dispatch import (
        BackendRequirementError,
        require_tpu,
        tpu_required,
    )

    if not (require_tpu_flag or tpu_required(config)):
        return
    try:
        require_tpu(
            source="--require-tpu" if require_tpu_flag
            else "durability.require_tpu/MURMURA_REQUIRE_TPU"
        )
    except BackendRequirementError as e:
        console.print(f"[bold red]{escape(str(e))}[/bold red]")
        raise SystemExit(2)


@app.command()
@click.argument("config_path", type=click.Path(exists=True, path_type=Path))
@click.option("--verbose/--quiet", "verbose", default=None, help="Override config verbosity")
@click.option("--output", "-o", type=click.Path(path_type=Path), default=None,
              help="Write history JSON here")
@click.option("--checkpoint-dir", type=click.Path(path_type=Path), default=None,
              help="Snapshot the complete run state here (simulation/tpu "
                   "backends; single runs, gangs and population streaming "
                   "alike — durability/snapshot.py). Default: "
                   "durability.checkpoint_dir")
@click.option("--checkpoint-every", type=int, default=None,
              help="Rounds between checkpoints (with --checkpoint-dir; "
                   "default: durability.checkpoint_every)")
@click.option("--resume/--no-resume", default=None,
              help="Resume from --checkpoint-dir if a snapshot exists "
                   "(byte-identical continuation, telemetry stream "
                   "appends; default: durability.resume)")
@click.option("--require-tpu", is_flag=True, default=False,
              help="Abort loudly unless the default JAX backend is a TPU "
                   "— replaces the silent CPU fallback. Env twin: "
                   "MURMURA_REQUIRE_TPU=1; config twin: "
                   "durability.require_tpu")
@click.option("--retries", type=int, default=None,
              help="Retry the training dispatch on classified-transient "
                   "errors (device/tunnel), restoring from the last "
                   "snapshot with exponential backoff + jitter. Requires "
                   "--checkpoint-dir. Default: durability.retries")
@click.option("--device", type=click.Choice(["cpu", "tpu"]), default=None,
              help="Force the JAX platform (reference: cli.py:37 device override)")
@click.option("--profile", "profile", is_flag=True, default=False,
              help="Capture a profiler trace (perfetto/xprof) for the "
                   "telemetry round window; with no telemetry.profile_rounds "
                   "configured the whole run is captured. Implies telemetry "
                   "(docs/OBSERVABILITY.md).")
@click.option("--seeds", "num_seeds", type=int, default=None,
              help="Gang-batch N seeds (experiment.seed .. +N-1) into one "
                   "vmapped program — sugar for `murmura sweep` with "
                   "num_seeds: N (docs/PERFORMANCE.md). 1 = normal run.")
def run(config_path: Path, verbose, output, checkpoint_dir, checkpoint_every,
        resume, require_tpu, retries, device, profile, num_seeds):
    """Run an experiment from a config file (reference: cli.py:34-60)."""
    if num_seeds is not None and num_seeds < 1:
        raise click.UsageError(
            f"--seeds must be >= 1 (got {num_seeds}); 1 = normal run, "
            "N > 1 gang-batches N seeds"
        )
    if num_seeds is not None and num_seeds > 1:
        if profile:
            raise click.UsageError(
                "--seeds (gang-batched execution) does not combine with "
                "--profile; profile a single run instead"
            )
        config = _load_config_or_die(config_path)
        if verbose is not None:
            config.experiment.verbose = verbose
        base = config.experiment.seed
        return _run_sweep(
            config, seeds=[base + i for i in range(num_seeds)],
            output=output, device=device, checkpoint_dir=checkpoint_dir,
            checkpoint_every=checkpoint_every, resume=resume,
            require_tpu=require_tpu, retries=retries,
        )
    if device is not None:
        # Must land before anything initializes the XLA backend.
        import jax

        jax.config.update("jax_platforms", device)
    config = _load_config_or_die(config_path)
    if verbose is not None:
        config.experiment.verbose = verbose
    checkpoint_dir, checkpoint_every, resume, retries = _resolve_durability(
        config, checkpoint_dir, checkpoint_every, resume, retries
    )
    _enforce_require_tpu(config, require_tpu)
    if profile:
        if config.backend == "distributed":
            raise click.UsageError(
                "--profile captures a device trace of the jitted round "
                "loop; backend: distributed trains on CPU worker "
                "processes (use the telemetry counters instead)"
            )
        config.telemetry.enabled = True
        if config.telemetry.profile_rounds == 0:
            config.telemetry.profile_rounds = config.experiment.rounds

    population_on = (
        config.population is not None and config.population.enabled
    )
    extra = ""
    if population_on:
        extra = (
            f", population={config.population.virtual_size} virtual users "
            f"/ {config.population.sampler} cohorts"
        )
    console.print(
        f"[bold cyan]murmura_tpu[/bold cyan] experiment "
        f"[bold]{config.experiment.name}[/bold] "
        f"(backend={config.backend}, nodes={config.topology.num_nodes}, "
        f"rounds={config.experiment.rounds}{extra})"
    )
    set_seed(config.experiment.seed)

    if config.backend == "distributed":
        if resume or checkpoint_dir is not None:
            raise click.UsageError(
                "--checkpoint-dir/--resume are not supported with "
                "backend: distributed (state lives in per-node processes)"
            )
        from murmura_tpu.distributed.runner import DistributedRunner
        from murmura_tpu.utils.factories import ConfigError

        try:
            history = DistributedRunner(config).run()
        except ConfigError as e:
            _die_config_error(e)
    else:
        from murmura_tpu.utils.factories import (
            ConfigError,
            build_network_from_config,
        )

        try:
            # checkpoint_dir (resume path) makes the telemetry stream
            # append exactly when a snapshot exists — a resumed run never
            # rotates its own events to *.prev (durability satellite).
            network = build_network_from_config(
                config,
                checkpoint_dir=(
                    str(checkpoint_dir) if resume and checkpoint_dir else None
                ),
            )
        except ConfigError as e:
            # Wiring-level config errors (data/model mismatch, unsupported
            # exchange mode, ...) — render the message, not the traceback.
            # Unexpected exceptions stay loud.
            _die_config_error(e)
        if resume:
            from murmura_tpu.utils.checkpoint import has_checkpoint

            if has_checkpoint(checkpoint_dir):
                done = network.restore_checkpoint(str(checkpoint_dir))
                console.print(f"Resumed from round [bold]{done}[/bold]")
            else:
                console.print(
                    f"[yellow]No checkpoint in {checkpoint_dir}; "
                    "starting from round 0[/yellow]"
                )

        history = _train_with_retries(
            network,
            lambda: network.train(
                rounds=max(
                    0, config.experiment.rounds - network.current_round
                ),
                verbose=config.experiment.verbose,
                checkpoint_dir=str(checkpoint_dir) if checkpoint_dir else None,
                checkpoint_every=checkpoint_every,
                rounds_per_dispatch=config.tpu.rounds_per_dispatch,
            ),
            retries=retries, config=config, checkpoint_dir=checkpoint_dir,
        )

    _display_results(history)
    if output is not None:
        output.parent.mkdir(parents=True, exist_ok=True)
        output.write_text(json.dumps(history, indent=2))
        console.print(f"History written to [bold]{output}[/bold]")
    if config.telemetry.enabled:
        from murmura_tpu.utils.factories import default_telemetry_dir

        console.print(
            f"Telemetry run written to "
            f"[bold]{default_telemetry_dir(config)}[/bold] — render it "
            "with `murmura report <dir>`"
        )
    return history


def _run_sweep(config, seeds, output, device, checkpoint_dir=None,
               checkpoint_every=None, resume=None, require_tpu=False,
               retries=None):
    """Shared gang-sweep driver (`murmura sweep` and `murmura run --seeds`):
    build the gang, optionally resume it from its durability snapshot,
    train (retry-wrapped like single runs), render the per-member summary,
    write per-member histories."""
    if device is not None:
        # Must land before anything initializes the XLA backend.
        import jax

        jax.config.update("jax_platforms", device)
    checkpoint_dir, checkpoint_every, resume, retries = _resolve_durability(
        config, checkpoint_dir, checkpoint_every, resume, retries
    )
    _enforce_require_tpu(config, require_tpu)
    from murmura_tpu.utils.factories import ConfigError, build_gang_from_config

    try:
        gang = build_gang_from_config(
            config, seeds=seeds,
            checkpoint_dir=(
                str(checkpoint_dir) if resume and checkpoint_dir else None
            ),
        )
    except ConfigError as e:
        _die_config_error(e)
    console.print(
        f"[bold cyan]murmura_tpu[/bold cyan] sweep "
        f"[bold]{config.experiment.name}[/bold] "
        f"(backend={config.backend}, nodes={config.topology.num_nodes}, "
        f"rounds={config.experiment.rounds}, "
        f"gang={gang.gang_size} member(s), batch={gang.batch})"
    )
    if resume:
        from murmura_tpu.utils.checkpoint import has_checkpoint

        if has_checkpoint(checkpoint_dir):
            done = gang.restore_checkpoint(str(checkpoint_dir))
            console.print(
                f"Resumed all {gang.gang_size} member(s) from round "
                f"[bold]{done}[/bold]"
            )
        else:
            console.print(
                f"[yellow]No checkpoint in {checkpoint_dir}; "
                "starting from round 0[/yellow]"
            )
    histories = _train_with_retries(
        gang,
        lambda: gang.train(
            rounds=max(0, config.experiment.rounds - gang.current_round),
            verbose=config.experiment.verbose,
            rounds_per_dispatch=config.tpu.rounds_per_dispatch,
            checkpoint_dir=str(checkpoint_dir) if checkpoint_dir else None,
            checkpoint_every=checkpoint_every,
        ),
        retries=retries, config=config, checkpoint_dir=checkpoint_dir,
    )

    table = Table(title="Sweep results (final round)")
    table.add_column("Member")
    table.add_column("Mean acc", justify="right")
    table.add_column("Std", justify="right")
    table.add_column("Loss", justify="right")
    for member, h in zip(gang.members, histories):
        if h["round"]:
            table.add_row(
                member.label,
                f"{h['mean_accuracy'][-1]:.4f}",
                f"{h['std_accuracy'][-1]:.4f}",
                f"{h['mean_loss'][-1]:.4f}",
            )
        else:
            table.add_row(member.label, "-", "-", "-")
    console.print(table)
    finals = [h["mean_accuracy"][-1] for h in histories if h["round"]]
    if finals:
        import numpy as np

        console.print(
            f"Across {len(finals)} member(s): mean accuracy "
            f"[bold green]{np.mean(finals):.4f}[/bold green] "
            f"± {np.std(finals):.4f}"
        )

    combined = {m.label: h for m, h in zip(gang.members, histories)}
    if output is not None:
        output.parent.mkdir(parents=True, exist_ok=True)
        output.write_text(json.dumps(combined, indent=2))
        console.print(f"Per-member histories written to [bold]{output}[/bold]")
    if config.telemetry.enabled:
        from murmura_tpu.utils.factories import default_telemetry_dir

        console.print(
            f"Per-member telemetry runs under "
            f"[bold]{default_telemetry_dir(config)}/<member>[/bold] — "
            "render one with `murmura report <dir>`"
        )
    return combined


@app.command()
@click.argument("config_path", type=click.Path(exists=True, path_type=Path))
@click.option("--seeds", "seeds", type=str, default=None,
              help="Comma-separated member seeds overriding the config's "
                   "sweep block (e.g. --seeds 1,2,3)")
@click.option("--verbose/--quiet", "verbose", default=None,
              help="Override config verbosity")
@click.option("--output", "-o", type=click.Path(path_type=Path), default=None,
              help="Write the per-member history JSON (one object keyed by "
                   "member label) here")
@click.option("--device", type=click.Choice(["cpu", "tpu"]), default=None,
              help="Force the JAX platform")
@click.option("--checkpoint-dir", type=click.Path(path_type=Path), default=None,
              help="Snapshot the FULL stacked gang state here (every "
                   "member's lane + history — durability/snapshot.py). "
                   "Default: durability.checkpoint_dir")
@click.option("--checkpoint-every", type=int, default=None,
              help="Rounds between checkpoints (with --checkpoint-dir; "
                   "default: durability.checkpoint_every)")
@click.option("--resume/--no-resume", default=None,
              help="Resume the whole gang from --checkpoint-dir if a "
                   "snapshot exists (all members continue byte-"
                   "identically; default: durability.resume)")
@click.option("--require-tpu", is_flag=True, default=False,
              help="Abort loudly unless the default JAX backend is a TPU")
@click.option("--retries", type=int, default=None,
              help="Retry the gang dispatch on classified-transient errors, "
                   "restoring all members from the last snapshot (requires "
                   "--checkpoint-dir; default: durability.retries)")
def sweep(config_path: Path, seeds, verbose, output, device, checkpoint_dir,
          checkpoint_every, resume, require_tpu, retries):
    """Gang-batched multi-seed execution (docs/PERFORMANCE.md).

    Stacks the sweep's member experiments — the config's ``sweep:`` block,
    or an explicit ``--seeds`` list — along a leading [S] axis and vmaps
    the round program over it: ONE XLA compile and one saturated device
    program cover the whole sweep.  Per-member histories are byte-identical
    on CPU to the corresponding single runs (`murmura check --ir` MUR500/
    MUR501 keep the gang collective- and recompile-clean).
    """
    config = _load_config_or_die(config_path)
    if verbose is not None:
        config.experiment.verbose = verbose
    seed_list = None
    if seeds is not None:
        try:
            seed_list = [int(s) for s in seeds.split(",") if s.strip()]
        except ValueError:
            raise click.UsageError(f"--seeds must be comma-separated ints, got {seeds!r}")
        if not seed_list:
            raise click.UsageError("--seeds parsed to an empty list")
    elif config.sweep is None:
        raise click.UsageError(
            "config has no sweep block; add one or pass --seeds 1,2,3"
        )
    return _run_sweep(
        config, seeds=seed_list, output=output, device=device,
        checkpoint_dir=checkpoint_dir, checkpoint_every=checkpoint_every,
        resume=resume, require_tpu=require_tpu, retries=retries,
    )


@app.command()
@click.argument("config_path", type=click.Path(exists=True, path_type=Path))
@click.option("--output", "-o", type=click.Path(path_type=Path),
              default=Path("frontier.json"), show_default=True,
              help="Write the frontier artifact (rule x attack x strength "
                   "curves + breaking points vs declared bounds) here")
@click.option("--device", type=click.Choice(["cpu", "tpu"]), default=None,
              help="Force the JAX platform")
@click.option("--require-tpu", is_flag=True, default=False,
              help="Abort loudly unless the default JAX backend is a TPU")
def frontier(config_path: Path, output, device, require_tpu):
    """Adversarial breaking-point search at gang speed
    (docs/ROBUSTNESS.md "The robustness frontier").

    For every (rule x adaptive attack x topology) cell of the config's
    ``frontier:`` grid (defaults cover krum/median/trimmed_mean/balance
    x adaptive-ALIE/bisection-gaussian x dense/sparse-exponential), runs
    an attack-strength x seed gang bucket with an outer successive-
    halving loop that re-aims the grid at the honest-accuracy cliff
    WITHOUT recompiling, then writes ``frontier.json`` charting each
    rule's empirical breaking point next to its MUR800 declared
    influence bound.  Render with `murmura report --frontier`.
    """
    if device is not None:
        # Must land before anything initializes the XLA backend.
        import jax

        jax.config.update("jax_platforms", device)
    config = _load_config_or_die(config_path)
    _enforce_require_tpu(config, require_tpu)
    from murmura_tpu.frontier import run_frontier, write_frontier
    from murmura_tpu.utils.factories import ConfigError

    f = config.frontier
    grid_desc = (
        f"{f.rules} x {f.attacks} x {f.topologies}" if f is not None
        else "default grid"
    )
    console.print(
        f"[bold cyan]murmura_tpu[/bold cyan] frontier "
        f"[bold]{config.experiment.name}[/bold] "
        f"(nodes={config.topology.num_nodes}, {escape(grid_desc)})"
    )
    try:
        artifact = run_frontier(
            config, progress=lambda s: console.print(f"[dim]{escape(s)}[/dim]")
        )
    except ConfigError as e:
        _die_config_error(e)
    path = write_frontier(artifact, output)
    console.print(f"Frontier artifact written to [bold]{path}[/bold]")
    from murmura_tpu.telemetry.report import render_frontier

    render_frontier(artifact, console=console)
    return artifact


@app.command()
@click.argument("config_path", type=click.Path(exists=True, path_type=Path))
@click.option("--output", "-o", type=click.Path(path_type=Path),
              default=Path("grid.json"), show_default=True,
              help="Write the cross-cell grid manifest here")
@click.option("--device", type=click.Choice(["cpu", "tpu"]), default=None,
              help="Force the JAX platform")
@click.option("--require-tpu", is_flag=True, default=False,
              help="Abort loudly unless the default JAX backend is a TPU")
@click.option("--plan-only", is_flag=True, default=False,
              help="Print the bucket plan (cells per compile-compatible "
                   "bucket) without executing anything")
def grid(config_path: Path, output, device, require_tpu, plan_only):
    """Run the config's rule x attack x topology x strength x seed grid
    through the compile-compatible scheduler (docs/ROBUSTNESS.md
    "Serving").

    Cells are partitioned into buckets by their traced jaxpr skeleton
    (the MUR203/MUR500 structural-equality key): cells share a bucket iff
    their programs are structurally equal, each bucket runs as ONE gang
    on the fused dispatch path — one compile per bucket, counted by
    CompileTracker and recorded in the manifest — and strength/seed
    become traced member inputs.  The full README grid (5 rules x
    gaussian x 5 strengths x 2 seeds = 50 cells) executes in 5 compiles.
    Render the manifest with `murmura report --grid`.
    """
    if device is not None:
        # Must land before anything initializes the XLA backend.
        import jax

        jax.config.update("jax_platforms", device)
    config = _load_config_or_die(config_path)
    _enforce_require_tpu(config, require_tpu)
    from murmura_tpu.serve.scheduler import plan_grid, run_grid, write_grid
    from murmura_tpu.utils.factories import ConfigError

    g = config.grid
    grid_desc = (
        f"{g.rules} x {g.attacks} x {g.topologies}" if g is not None
        else "default grid"
    )
    console.print(
        f"[bold cyan]murmura_tpu[/bold cyan] grid "
        f"[bold]{config.experiment.name}[/bold] "
        f"(nodes={config.topology.num_nodes}, {escape(grid_desc)})"
    )
    try:
        if plan_only:
            buckets = plan_grid(config)
            for b in buckets:
                console.print(
                    f"  bucket [bold]{b.key}[/bold] "
                    f"{b.rule} x {b.attack} x {b.topology}: "
                    f"{len(b.cells)} cells"
                )
            console.print(
                f"{sum(len(b.cells) for b in buckets)} cells in "
                f"{len(buckets)} buckets = {len(buckets)} compiles"
            )
            return
        artifact = run_grid(
            config, progress=lambda s: console.print(f"[dim]{escape(s)}[/dim]")
        )
    except ConfigError as e:
        _die_config_error(e)
    path = write_grid(artifact, output)
    console.print(
        f"Grid manifest written to [bold]{path}[/bold] "
        f"({artifact['total_cells']} cells, "
        f"{artifact['total_compiles']} compiles)"
    )
    from murmura_tpu.telemetry.report import render_grid

    render_grid(artifact, console=console)
    return artifact


@app.command()
@click.argument("config_path", type=click.Path(exists=True, path_type=Path))
@click.option("--device", type=click.Choice(["cpu", "tpu"]), default=None,
              help="Force the JAX platform")
@click.option("--require-tpu", is_flag=True, default=False,
              help="Abort loudly unless the default JAX backend is a TPU")
def serve(config_path: Path, device, require_tpu):
    """Crash-surviving multi-tenant experiment daemon
    (docs/ROBUSTNESS.md "Serving").

    Accepts experiment submissions over a local unix socket (`murmura
    submit`), multiplexes structurally-equal submissions onto warm
    compiled gang buckets (power-of-two growth via ``serve.capacity``;
    admissions are value-only splices — zero recompiles, MUR1601),
    checkpoints every tenant on the ``serve.checkpoint_every`` cadence,
    and survives SIGKILL: on restart every in-flight run resumes from
    its snapshot byte-identically (MUR1603).  State lives under
    ``serve.state_dir``; re-running this command over the same state
    dir IS the recovery path.
    """
    if device is not None:
        # Must land before anything initializes the XLA backend.
        import jax

        jax.config.update("jax_platforms", device)
    config = _load_config_or_die(config_path)
    _enforce_require_tpu(config, require_tpu)
    from murmura_tpu.serve.daemon import ServeDaemon
    from murmura_tpu.utils.factories import ConfigError

    try:
        daemon = ServeDaemon(config)
    except (ConfigError, ValueError) as e:
        _die_config_error(e)
    console.print(
        f"[bold cyan]murmura_tpu[/bold cyan] serve: listening on "
        f"[bold]{daemon.socket_path}[/bold] "
        f"(state_dir={daemon.state_dir}, capacity={daemon.capacity})"
    )
    try:
        daemon.serve_forever()
    except KeyboardInterrupt:
        daemon.close()
    console.print("murmura serve: stopped")


@app.command()
@click.argument("config_path", type=click.Path(exists=True, path_type=Path))
@click.option("--socket", "socket_path", required=True,
              type=click.Path(path_type=Path),
              help="The daemon's unix socket (serve.socket / "
                   "<state_dir>/daemon.sock)")
@click.option("--wait/--no-wait", default=False,
              help="Block until the submission reaches a terminal state "
                   "and print its final record")
@click.option("--poll-s", type=float, default=0.5, show_default=True,
              help="Status poll interval with --wait")
def submit(config_path: Path, socket_path, wait, poll_s):
    """Submit one experiment to a running `murmura serve` daemon.

    The submitted yaml is a plain single-experiment config (no sweep/
    frontier/grid/serve sections — the daemon owns multiplexing).
    Submissions whose configs differ only in seed / name / lr share one
    warm compiled bucket.  Socket-layer failures (a daemon mid-restart)
    are classified transient and retried with backoff
    (durability/dispatch.py).
    """
    import time as _time

    import yaml

    from murmura_tpu.serve.protocol import send_request

    with open(config_path, encoding="utf-8") as fh:
        raw = yaml.safe_load(fh)
    resp = send_request(str(socket_path), {"op": "submit", "config": raw})
    if not resp.get("ok"):
        console.print(f"[bold red]{escape(str(resp.get('error')))}[/bold red]")
        raise SystemExit(1)
    console.print(
        f"submitted [bold]{resp['id']}[/bold] "
        f"(bucket {resp['bucket']})"
    )
    if not wait:
        return resp
    while True:
        st = send_request(
            str(socket_path), {"op": "status", "id": resp["id"]},
        )
        sub = st.get("submission", {})
        if sub.get("state") in ("done", "failed", "evicted"):
            console.print(
                f"[bold]{resp['id']}[/bold] {sub['state']} "
                f"(final_accuracy={sub.get('final_accuracy')})"
            )
            if sub.get("state") != "done":
                raise SystemExit(1)
            return sub
        _time.sleep(poll_s)


@app.command("run-node")
@click.argument("config_path", type=click.Path(exists=True, path_type=Path))
@click.option("--node-id", type=int, required=True, help="This worker's node id")
@click.option("--t-start", type=float, required=True, help="Shared round-0 start time")
@click.option("--run-id", type=str, required=True, help="Run id from the head node")
@click.option("--host", type=str, default=None, help="This node's bind host")
@click.option("--resume/--no-resume", default=False,
              help="Rejoin a running experiment from this node's last "
                   "per-node checkpoint (faults.enabled crash recovery)")
def run_node(config_path: Path, node_id, t_start, run_id, host, resume):
    """Multi-machine ZMQ worker (reference: cli.py:143-208)."""
    from murmura_tpu.distributed.node_process import run_single_node
    from murmura_tpu.utils.factories import ConfigError

    config = _load_config_or_die(config_path)
    try:
        run_single_node(
            config, node_id=node_id, t_start=t_start, run_id=run_id, host=host,
            resume=resume,
        )
    except ConfigError as e:
        _die_config_error(e)


@app.command()
@click.argument(
    "paths", nargs=-1, type=click.Path(exists=True, path_type=Path)
)
@click.option(
    "--contracts/--no-contracts", default=True,
    help="Also run the cross-layer contract checks (registry/schema/test "
         "sync, topology zero-diagonal)",
)
@click.option(
    "--ir/--no-ir", "ir", default=None,
    help="Run the jaxpr/HLO IR contracts (MUR200-205) and AOT cost budgets "
         "(MUR206).  Default: on for the package check, off when explicit "
         "PATHS are given (the IR pass traces the live registry, not "
         "files).",
)
@click.option(
    "--flow/--no-flow", "flow", default=None,
    help="Run the jaxpr dataflow contracts (MUR800-804: per-neighbor "
         "influence bounds, scrub dominance, zero-free denominators).  "
         "Default: on for the package check, off when explicit PATHS are "
         "given (the flow pass traces the live registry, not files).",
)
@click.option(
    "--durability/--no-durability", "durability", default=None,
    help="Run the executable resume-determinism contract (MUR901/902: "
         "save→restore→replay byte-equality and zero-recompile restore "
         "per rule x exchange mode).  Compiles and runs tiny programs "
         "(~2 min on CPU).  Default: on for the package check, off when "
         "explicit PATHS are given.",
)
@click.option(
    "--adaptive/--no-adaptive", "adaptive", default=None,
    help="Run the adaptive-adversary contracts (MUR1000-1003: attack-"
         "state registry bijection, recompile-free adaptation, "
         "collective-inventory parity, feedback taint containment).  "
         "Compiles and runs tiny programs (~1 min on CPU).  Default: on "
         "for the package check, off when explicit PATHS are given.",
)
@click.option(
    "--staleness/--no-staleness", "staleness", default=None,
    help="Run the bounded-staleness contracts (MUR1100-1103: stale-state "
         "registry bijection, zero recompiles across staleness "
         "variation, collective-inventory parity with the drop-sync "
         "program, influence-bound/replay-hole taint runs over the "
         "staleness path).  Compiles and runs tiny programs (~1 min on "
         "CPU).  Default: on for the package check, off when explicit "
         "PATHS are given.",
)
@click.option(
    "--pipeline/--no-pipeline", "pipeline", default=None,
    help="Run the pipelined-rounds contracts (MUR1200-1203: pipeline-"
         "state registry bijection, zero recompiles across buffer "
         "swaps, collective-inventory parity with the serialized "
         "program, delayed-step influence/lagging-verdict taint runs).  "
         "Compiles and runs tiny programs (~1 min on CPU).  Default: on "
         "for the package check, off when explicit PATHS are given.",
)
@click.option(
    "--sharded/--no-sharded", "sharded", default=None,
    help="Run the param-axis sharding contracts (MUR1300-1303: sharded-P "
         "collective inventory — ppermute-only on 'nodes', one small "
         "psum over 'param' — zero recompiles across sharded rounds, "
         "shards=1 bit-parity with the unsharded program, sharded "
         "execution parity).  Compiles and runs tiny sharded programs "
         "(~1 min on CPU).  Default: on for the package check, off when "
         "explicit PATHS are given.",
)
@click.option(
    "--compose/--no-compose", "compose", default=None,
    help="Run the cross-feature composition grid (MUR1400-1403: lever-"
         "manifest/guard bijection with the executable refusal census, "
         "the generated pairwise grid — every declared-compatible pair "
         "builds, trains recompile-free and keeps collective-inventory "
         "parity — composed carried-state/stage-order parity, and "
         "flow-taint preservation on composed cells).  Compiles and "
         "runs one tiny composed program per compatible pair (~3 min "
         "on CPU).  Default: on for the package check, off when "
         "explicit PATHS are given.",
)
@click.option(
    "--memory/--no-memory", "memory", default=None,
    help="Run the static memory contracts (MUR1500-1503: committed "
         "memory_analysis() budgets per (rule x topology x feature) "
         "round-program cell against analysis/MEMORY.json, per-device "
         "peak ~P/shards across shards {1,2,4}, donation completeness "
         "per carried leaf, and the pipelined overlap-dependence "
         "proof).  AOT-compiles the full grid (~3 min on CPU; the "
         "compiles are shared across all four contracts).  Default: on "
         "for the package check, off when explicit PATHS are given.",
)
@click.option(
    "--serve/--no-serve", "serve_checks", default=None,
    help="Run the serving contracts (MUR1600-1603: bucket-key soundness "
         "— same scheduler bucket ⇔ structurally equal independently-"
         "traced jaxpr skeletons — zero recompiles across warm-bucket "
         "admissions, frozen-lane non-interference under eviction, "
         "daemon kill+recover resume completeness with byte-identical "
         "histories).  Compiles and runs tiny gangs plus an in-process "
         "daemon (~1 min on CPU).  Default: on for the package check, "
         "off when explicit PATHS are given.",
)
@click.option(
    "--observe/--no-observe", "observe_checks", default=None,
    help="Run the observability contracts (MUR1700-1703: metrics↔ledger "
         "parity — a daemon scrape equals an independent replay of the "
         "durable ledger + event streams — scrape non-interference "
         "(polling metrics/ping/list mid-generation causes zero "
         "recompiles and byte-identical tenant histories), trace-span "
         "well-formedness with phase_times reconciliation, and schema "
         "discipline — v2 events carry their migration note and v1 "
         "streams still render).  Compiles and runs in-process daemons "
         "(~1 min on CPU).  Default: on for the package check, off when "
         "explicit PATHS are given.",
)
@click.option(
    "--json", "as_json", is_flag=True, default=False,
    help="Emit findings (and budget-delta / flow-summary / "
         "compose-summary / memory-summary records) as JSON lines for "
         "editor/CI annotation instead of the greppable text format.",
)
@click.option(
    "--update-budgets", is_flag=True, default=False,
    help="Re-measure the AOT cost grid and rewrite analysis/BUDGETS.json; "
         "review the diff as perf history.",
)
@click.option(
    "--update-memory", is_flag=True, default=False,
    help="Re-measure the AOT memory grid and rewrite "
         "analysis/MEMORY.json; review the diff as residency history.",
)
def check(paths, contracts, ir, flow, durability, adaptive, staleness,
          pipeline, sharded, compose, memory, serve_checks, observe_checks,
          as_json, update_budgets, update_memory):
    """JAX-aware static analysis over PATHS (default: the installed
    murmura_tpu package).

    Runs the AST lint rules (MUR001-006: traced branches, host syncs,
    recompilation hazards, import-time allocation, dtype promotion), the
    cross-layer contract checks (MUR101-103), and — for the package check —
    the jaxpr/HLO IR contracts plus committed cost budgets (MUR200-206),
    the jaxpr dataflow contracts (MUR800-804: per-neighbor Byzantine
    influence bounds, NaN/attack scrub dominance, zero-free denominators),
    the durability contracts (MUR900 snapshot completeness via
    --contracts; MUR901/902 resume determinism via --durability), the
    adaptive-adversary contracts (MUR1000-1003 via --adaptive), the
    bounded-staleness contracts (MUR1100-1103 via --staleness), the
    pipelined-rounds contracts (MUR1200-1203 via --pipeline), the
    param-axis sharding contracts (MUR1300-1303 via --sharded), the
    cross-feature composition grid (MUR1400-1403 via --compose), the
    static memory contracts (MUR1500-1503 via --memory), the serving
    contracts (MUR1600-1603 via --serve), and the observability
    contracts (MUR1700-1703 via --observe).
    Exits non-zero when any finding survives suppression.  See
    docs/ANALYSIS.md for the rule catalogue and the
    ``# murmura: ignore[...]`` suppression syntax.
    """
    if update_budgets:
        from murmura_tpu.analysis import budgets

        path = budgets.update_budgets()
        console.print(
            f"Budgets rewritten to [bold]{path}[/bold] — review the diff "
            "as perf history"
        )
        return
    if update_memory:
        from murmura_tpu.analysis import memory as memory_mod

        path = memory_mod.update_memory()
        console.print(
            f"Memory budgets rewritten to [bold]{path}[/bold] — review "
            "the diff as residency history"
        )
        return
    from murmura_tpu.analysis import (
        format_findings,
        format_findings_json,
        run_check_detailed,
    )

    findings, records = run_check_detailed(
        list(paths) or None, contracts=contracts, ir=ir, flow=flow,
        durability=durability, adaptive=adaptive, staleness=staleness,
        pipeline=pipeline, sharded=sharded, compose=compose, memory=memory,
        serve=serve_checks, observe=observe_checks,
    )
    if as_json:
        out = format_findings_json(findings, records)
        if out:
            click.echo(out)
        if findings:
            raise SystemExit(1)
        return
    if findings:
        click.echo(format_findings(findings))
        console.print(
            f"[bold red]{len(findings)} finding(s)[/bold red] "
            "(see docs/ANALYSIS.md for rules and suppression)"
        )
        raise SystemExit(1)
    console.print("[bold green]murmura check: clean[/bold green]")


@app.command()
@click.argument(
    "run_dir", required=False, default=None,
    type=click.Path(exists=True, file_okay=False, path_type=Path),
)
@click.option(
    "--frontier", "frontier_path", default=None,
    type=click.Path(exists=True, dir_okay=False, path_type=Path),
    help="Render a frontier.json artifact (`murmura frontier`) instead of "
         "a telemetry run directory: empirical breaking point vs MUR800 "
         "declared influence bound per rule x attack x topology cell, "
         "plus each cell's honest-accuracy curve over attack strength.",
)
@click.option(
    "--grid", "grid_path", default=None,
    type=click.Path(exists=True, dir_okay=False, path_type=Path),
    help="Render a grid.json manifest (`murmura grid`) instead of a "
         "telemetry run directory: cells per compile-compatible bucket "
         "with per-bucket compile counts, and per-cell accuracy / "
         "phase-time accounting.",
)
@click.option(
    "--json", "as_json", is_flag=True, default=False,
    help="Emit the report as one JSON object (machine-readable; the same "
         "dict the tables render) instead of rich tables.",
)
@click.option(
    "--latest", "latest", is_flag=True, default=False,
    help="Report the newest run found under the current directory "
         "(telemetry_runs/, serve state dirs) instead of naming RUN_DIR — "
         "the `murmura runs` index picks it.",
)
@click.option(
    "--trace", "trace_path", default=None,
    type=click.Path(dir_okay=False, path_type=Path),
    help="Instead of tables, export the run's trace spans (submit→admit→"
         "generation→round, built from the event stream's wall-clock "
         "timestamps) as Chrome trace-event JSON — open in Perfetto "
         "(ui.perfetto.dev) or chrome://tracing.",
)
def report(run_dir: Optional[Path], frontier_path: Optional[Path],
           grid_path: Optional[Path], as_json: bool, latest: bool,
           trace_path: Optional[Path]):
    """Render a telemetry run directory (manifest.json + events.jsonl),
    or — with ``--frontier`` / ``--grid`` — a frontier artifact or a
    grid scheduler manifest.

    Works on any producer's output — a `murmura_tpu run` with
    ``telemetry.enabled``, a distributed run's Monitor-folded manifest, or
    a bench artifact (bench.py / bench_breakdown.py).  Sections: accuracy,
    robustness/rule statistics, time breakdown by dispatch mode,
    checkpoints, device memory, per-node audit taps (e.g. krum rejection
    counts), distributed counters.  See docs/OBSERVABILITY.md;
    docs/ROBUSTNESS.md for reading the frontier tables.
    """
    if frontier_path is not None:
        from murmura_tpu.frontier import (
            frontier_break_summary,
            load_frontier,
        )
        from murmura_tpu.telemetry.report import render_frontier

        try:
            artifact = load_frontier(frontier_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            console.print(f"[bold red]{escape(str(e))}[/bold red]")
            raise SystemExit(1)
        if as_json:
            click.echo(json.dumps({
                "grid": artifact.get("grid"),
                "summary": frontier_break_summary(artifact),
            }))
        else:
            render_frontier(artifact, console=console)
        return
    if grid_path is not None:
        from murmura_tpu.serve.scheduler import load_grid
        from murmura_tpu.telemetry.report import render_grid

        try:
            artifact = load_grid(grid_path)
        except (OSError, ValueError, json.JSONDecodeError) as e:
            console.print(f"[bold red]{escape(str(e))}[/bold red]")
            raise SystemExit(1)
        if as_json:
            click.echo(json.dumps({
                "grid": artifact.get("grid"),
                "buckets": artifact.get("buckets"),
                "total_cells": artifact.get("total_cells"),
                "total_compiles": artifact.get("total_compiles"),
            }))
        else:
            render_grid(artifact, console=console)
        return
    if run_dir is None and latest:
        from murmura_tpu.telemetry.registry import find_latest

        row = find_latest([Path(".")])
        if row is None:
            console.print(
                "[bold red]--latest: no telemetry runs found under the "
                "current directory[/bold red]"
            )
            raise SystemExit(1)
        run_dir = Path(row["path"])
        console.print(f"[dim]latest: {run_dir}[/dim]")
    if run_dir is None:
        console.print(
            "[bold red]murmura report needs a RUN_DIR (or --latest, "
            "--frontier <frontier.json> / --grid <grid.json>)[/bold red]"
        )
        raise SystemExit(1)
    if trace_path is not None:
        from murmura_tpu.telemetry.spans import write_chrome_trace

        try:
            n = write_chrome_trace(trace_path, [run_dir])
        except FileNotFoundError as e:
            console.print(f"[bold red]{escape(str(e))}[/bold red]")
            raise SystemExit(1)
        console.print(
            f"wrote [bold]{n}[/bold] trace span(s) to "
            f"[bold]{trace_path}[/bold] — open in Perfetto "
            "(ui.perfetto.dev) or chrome://tracing"
        )
        return
    from murmura_tpu.telemetry.report import build_report, render_report

    try:
        if as_json:
            rep = build_report(run_dir)
            rep.pop("manifest", None)  # the run dir already holds it
            click.echo(json.dumps(rep))
        else:
            render_report(run_dir, console=console)
    except FileNotFoundError as e:
        console.print(f"[bold red]{escape(str(e))}[/bold red]")
        raise SystemExit(1)


@app.command()
@click.argument("target", type=click.Path(exists=True, path_type=Path))
def metrics(target: Path):
    """Render a run's metrics as OpenMetrics text (ISSUE 19 leg 1).

    TARGET is either a running daemon's unix socket (the live
    ``{"op": "metrics"}`` scrape — read-only, recompile-free, MUR1701)
    or a telemetry run directory (the same registry folded offline from
    manifest.json + events.jsonl — batch and serve runs scrape
    identically).  Pipe to any OpenMetrics/Prometheus scraper, or diff
    two snapshots by eye.
    """
    import stat

    from murmura_tpu.telemetry.metrics import (
        MetricsRegistry,
        fold_run_events,
        render_openmetrics,
        scrape_socket,
    )

    if stat.S_ISSOCK(target.stat().st_mode):
        try:
            click.echo(scrape_socket(str(target)))
        except (OSError, RuntimeError) as e:
            console.print(f"[bold red]{escape(str(e))}[/bold red]")
            raise SystemExit(1)
        return
    if not target.is_dir():
        console.print(
            "[bold red]murmura metrics needs a daemon socket or a "
            "telemetry run directory[/bold red]"
        )
        raise SystemExit(1)
    reg = MetricsRegistry()
    fold_run_events(reg, target)
    click.echo(render_openmetrics(reg))


@app.command()
@click.option("--socket", "socket_path", required=True,
              type=click.Path(exists=True, path_type=Path),
              help="The daemon's unix socket (serve.socket / "
                   "<state_dir>/daemon.sock)")
@click.option("--interval", "interval_s", type=float, default=1.0,
              show_default=True, help="Refresh interval in seconds")
@click.option("--iterations", type=int, default=None,
              help="Stop after N refreshes (default: until Ctrl-C)")
def top(socket_path: Path, interval_s: float, iterations):
    """Live daemon dashboard off the read-only ops (ISSUE 19 leg 2).

    Refreshes a tenant table (state / round progress / accuracy / mean
    round time), warm-bucket occupancy, the cumulative daemon counters
    (admissions, evictions, resumes, compiles, generations), and the
    snapshot age — entirely from the ping/list/metrics protocol ops, so
    watching a daemon never perturbs it (MUR1701).
    """
    from murmura_tpu.telemetry.top import run_top

    try:
        run_top(
            str(socket_path), interval_s=interval_s, iterations=iterations,
            echo=click.echo,
        )
    except KeyboardInterrupt:
        pass
    except (OSError, RuntimeError) as e:
        console.print(f"[bold red]{escape(str(e))}[/bold red]")
        raise SystemExit(1)


@app.command()
@click.argument(
    "roots", nargs=-1, type=click.Path(exists=True, path_type=Path)
)
@click.option("--json", "as_json", is_flag=True, default=False,
              help="Emit one JSON object per indexed run (JSON lines)")
def runs(roots, as_json: bool):
    """Cross-run registry: index every telemetry artifact under ROOTS
    (default: the current directory) — ``telemetry_runs/``, serve state
    dirs, bench manifests (ISSUE 19 leg 3).

    One row per run/submission: kind, schema version, platform, rounds,
    best accuracy, terminal state, and whether the event stream has a
    torn tail (a crash mid-append).  Newest first; ``murmura report
    --latest`` renders the top row.
    """
    from murmura_tpu.telemetry.registry import index_runs, render_rows

    rows = index_runs([Path(r) for r in roots] or [Path(".")])
    if as_json:
        for row in rows:
            click.echo(json.dumps(row))
        return
    if not rows:
        console.print("no telemetry runs found")
        return
    click.echo(render_rows(rows))


@app.command("list-components")
@click.argument("component_type", required=False, default=None)
def list_components(component_type):
    """List available components (reference: cli.py:215-259).

    Optionally filter one category the way the reference does
    (``murmura list-components aggregators``); with no argument the whole
    table is shown.
    """
    from murmura_tpu.aggregation import AGGREGATORS
    from murmura_tpu.attacks import ATTACKS
    from murmura_tpu.topology.generators import TOPOLOGY_TYPES

    rows = {
        "topologies": ", ".join(TOPOLOGY_TYPES),
        "aggregators": ", ".join(sorted(AGGREGATORS)),
        "attacks": ", ".join(sorted(ATTACKS)),
        "backends": "simulation, tpu, distributed",
        "models": (
            "mlp, leaf.femnist[.tiny/.small/.baseline/.large/.xlarge], "
            "leaf.celeba, leaf.shakespeare, wearables.{uci_har,pamap2,ppg_dalia}"
        ),
        "datasets": (
            "synthetic, synthetic_sequences, leaf.{femnist,celeba,shakespeare}, "
            "wearables.{uci_har,pamap2,ppg_dalia}"
        ),
    }
    if component_type is not None:
        if component_type not in rows:
            console.print(f"[red]Unknown component type: {component_type}[/red]")
            console.print("Available: " + ", ".join(rows))
            raise SystemExit(1)
        rows = {component_type: rows[component_type]}

    table = Table(title="murmura_tpu components")
    table.add_column("Category", style="cyan")
    table.add_column("Options")
    for k, v in rows.items():
        table.add_row(k, v)
    console.print(table)


def _display_results(history) -> None:
    """Rich results table (reference: cli.py:266-304)."""
    if not history.get("round"):
        console.print("[yellow]No evaluation rounds recorded[/yellow]")
        return
    table = Table(title="Training results")
    table.add_column("Round", justify="right")
    table.add_column("Mean acc", justify="right")
    table.add_column("Std", justify="right")
    table.add_column("Loss", justify="right")
    n = len(history["round"])
    show = sorted(set([0, n // 2, n - 1]))
    for i in show:
        table.add_row(
            str(history["round"][i]),
            f"{history['mean_accuracy'][i]:.4f}",
            f"{history['std_accuracy'][i]:.4f}",
            f"{history['mean_loss'][i]:.4f}",
        )
    console.print(table)
    final = history["mean_accuracy"][-1]
    console.print(f"Final mean accuracy: [bold green]{final:.4f}[/bold green]")


def main():
    app()


if __name__ == "__main__":
    main()

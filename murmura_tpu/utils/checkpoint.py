"""Per-round checkpoint/resume for the stacked network state.

The reference has no checkpointing at all — model states live only in
memory and history is returned at the end of ``train()`` (SURVEY §5;
reference: murmura/core/network.py:60-94).  Here the whole run state is a
handful of device arrays (stacked params pytree, aggregator state dict, RNG
key) plus host-side history, so a checkpoint is one msgpack blob + one JSON
sidecar:

    <dir>/state.msgpack   flax.serialization bytes of {params, agg_state, rng}
    <dir>/meta.json       {round, history, round_times, version}

Restore is exact: resuming reproduces the same arrays the run would have had
at that round boundary.
"""

import json
import os
from pathlib import Path
from typing import Any, Dict, Tuple

import jax
import numpy as np
from flax import serialization

# v3: per-round step keys changed from an advancing split() chain to
# fold_in(base, round) — the saved rng blob is now the static base key, not
# chain state.  A v2 checkpoint restored into a v3 build would resume with a
# silently different noise/SGD stream, so the version gate fails it loudly.
CKPT_VERSION = 3
STATE_FILE = "state.msgpack"
META_FILE = "meta.json"


def save_checkpoint(
    directory: str | Path,
    *,
    params: Any,
    agg_state: Dict[str, Any],
    rng: Any,
    round_num: int,
    history: Dict[str, list],
    round_times: list,
) -> Path:
    """Write a checkpoint; returns the directory written."""
    d = Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    blob = serialization.to_bytes(
        {
            "params": jax.device_get(params),
            "agg_state": jax.device_get(agg_state),
            "rng": jax.device_get(rng),
            # Duplicated in meta.json; restore cross-checks the two so a
            # crash landing between the two os.replace calls (new state,
            # old meta) is detected instead of silently replaying rounds.
            "round": np.int64(round_num),
        }
    )
    meta = json.dumps(
        {
            "version": CKPT_VERSION,
            "round": int(round_num),
            "history": history,
            "round_times": [float(t) for t in round_times],
        }
    )
    # Each file is replaced atomically, but the pair is not: a crash between
    # the two os.replace calls leaves NEW state beside OLD meta.  The round
    # number embedded in the blob lets restore detect that torn pair.
    tmp_state = d / (STATE_FILE + ".tmp")
    tmp_state.write_bytes(blob)
    os.replace(tmp_state, d / STATE_FILE)
    tmp_meta = d / (META_FILE + ".tmp")
    tmp_meta.write_text(meta)
    os.replace(tmp_meta, d / META_FILE)
    return d


def restore_checkpoint(
    directory: str | Path,
    *,
    params_target: Any,
    agg_state_target: Dict[str, Any],
    rng_target: Any,
) -> Tuple[Any, Dict[str, Any], Any, int, Dict[str, list], list]:
    """Load (params, agg_state, rng, round, history, round_times).

    Targets supply the pytree structure/dtypes; shapes are validated by
    flax.serialization against the saved leaves.
    """
    d = Path(directory)
    meta = json.loads((d / META_FILE).read_text())
    if meta.get("version") != CKPT_VERSION:
        hint = (
            " (the rng blob in a v2 checkpoint is ambiguous: depending on the "
            "build that wrote it, it is either split()-chain state or the "
            "fold_in base key this build expects — resuming the former would "
            "silently change the random stream, so both are rejected)"
            if meta.get("version") == 2
            else ""
        )
        raise ValueError(
            f"Checkpoint version {meta.get('version')} != {CKPT_VERSION}{hint}"
        )
    state = serialization.from_bytes(
        {
            "params": jax.device_get(params_target),
            "agg_state": jax.device_get(agg_state_target),
            "rng": jax.device_get(rng_target),
            "round": np.int64(0),
        },
        (d / STATE_FILE).read_bytes(),
    )
    if int(state["round"]) != int(meta["round"]):
        raise ValueError(
            f"Torn checkpoint: state.msgpack is at round {int(state['round'])} "
            f"but meta.json says round {int(meta['round'])} — the writer "
            "crashed between the two atomic replaces; restart from a clean "
            "checkpoint directory"
        )
    return (
        state["params"],
        state["agg_state"],
        np.asarray(state["rng"]),
        int(meta["round"]),
        meta["history"],
        list(meta["round_times"]),
    )


def has_checkpoint(directory: str | Path) -> bool:
    d = Path(directory)
    return (d / STATE_FILE).exists() and (d / META_FILE).exists()

"""Per-round checkpoint/resume for the stacked network state.

The reference has no checkpointing at all — model states live only in
memory and history is returned at the end of ``train()`` (SURVEY §5;
reference: murmura/core/network.py:60-94).  Here the whole run state is a
handful of device arrays (stacked params pytree, aggregator state dict, RNG
key) plus host-side history, so a checkpoint is one msgpack blob + one JSON
sidecar:

    <dir>/state.msgpack   flax.serialization bytes of {params, agg_state, rng}
    <dir>/meta.json       {round, history, round_times, version}

Restore is exact: resuming reproduces the same arrays the run would have had
at that round boundary.
"""

import json
import os
from pathlib import Path
from typing import Any, Dict, Tuple

import jax
import numpy as np
from flax import serialization

CKPT_VERSION = 1
STATE_FILE = "state.msgpack"
META_FILE = "meta.json"


def save_checkpoint(
    directory: str | Path,
    *,
    params: Any,
    agg_state: Dict[str, Any],
    rng: Any,
    round_num: int,
    history: Dict[str, list],
    round_times: list,
) -> Path:
    """Write a checkpoint; returns the directory written."""
    d = Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    blob = serialization.to_bytes(
        {
            "params": jax.device_get(params),
            "agg_state": jax.device_get(agg_state),
            "rng": jax.device_get(rng),
        }
    )
    meta = json.dumps(
        {
            "version": CKPT_VERSION,
            "round": int(round_num),
            "history": history,
            "round_times": [float(t) for t in round_times],
        }
    )
    # Atomic: a kill mid-write must not leave a readable-but-corrupt pair.
    # State lands before meta so a crash between the two leaves the old
    # meta pointing at old state, never new meta over truncated state.
    tmp_state = d / (STATE_FILE + ".tmp")
    tmp_state.write_bytes(blob)
    os.replace(tmp_state, d / STATE_FILE)
    tmp_meta = d / (META_FILE + ".tmp")
    tmp_meta.write_text(meta)
    os.replace(tmp_meta, d / META_FILE)
    return d


def restore_checkpoint(
    directory: str | Path,
    *,
    params_target: Any,
    agg_state_target: Dict[str, Any],
    rng_target: Any,
) -> Tuple[Any, Dict[str, Any], Any, int, Dict[str, list], list]:
    """Load (params, agg_state, rng, round, history, round_times).

    Targets supply the pytree structure/dtypes; shapes are validated by
    flax.serialization against the saved leaves.
    """
    d = Path(directory)
    meta = json.loads((d / META_FILE).read_text())
    if meta.get("version") != CKPT_VERSION:
        raise ValueError(
            f"Checkpoint version {meta.get('version')} != {CKPT_VERSION}"
        )
    state = serialization.from_bytes(
        {
            "params": jax.device_get(params_target),
            "agg_state": jax.device_get(agg_state_target),
            "rng": jax.device_get(rng_target),
        },
        (d / STATE_FILE).read_bytes(),
    )
    return (
        state["params"],
        state["agg_state"],
        np.asarray(state["rng"]),
        int(meta["round"]),
        meta["history"],
        list(meta["round_times"]),
    )


def has_checkpoint(directory: str | Path) -> bool:
    d = Path(directory)
    return (d / STATE_FILE).exists() and (d / META_FILE).exists()

"""Per-round checkpoint/resume for the stacked network state.

The reference has no checkpointing at all — model states live only in
memory and history is returned at the end of ``train()`` (SURVEY §5;
reference: murmura/core/network.py:60-94).  Here the whole run state is a
handful of device arrays (stacked params pytree, aggregator state dict, RNG
key) plus host-side history, so a checkpoint is one msgpack blob + one JSON
sidecar:

    <dir>/state.msgpack   flax.serialization bytes of {params, agg_state, rng}
    <dir>/meta.json       {round, history, round_times, version}

Restore is exact: resuming reproduces the same arrays the run would have had
at that round boundary.
"""

import json
import os
from pathlib import Path
from typing import Any, Dict, Tuple

import jax
import numpy as np
from flax import serialization

# v3: per-round step keys changed from an advancing split() chain to
# fold_in(base, round) — the saved rng blob is now the static base key, not
# chain state.  A v2 checkpoint restored into a v3 build would resume with a
# silently different noise/SGD stream, so the version gate fails it loudly.
CKPT_VERSION = 3
STATE_FILE = "state.msgpack"
META_FILE = "meta.json"


def save_checkpoint(
    directory: str | Path,
    *,
    params: Any,
    agg_state: Dict[str, Any],
    rng: Any,
    round_num: int,
    history: Dict[str, list],
    round_times: list,
) -> Path:
    """Write a checkpoint; returns the directory written."""
    d = Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    blob = serialization.to_bytes(
        {
            "params": jax.device_get(params),
            "agg_state": jax.device_get(agg_state),
            "rng": jax.device_get(rng),
            # Duplicated in meta.json; restore cross-checks the two so a
            # crash landing between the two os.replace calls (new state,
            # old meta) is detected instead of silently replaying rounds.
            "round": np.int64(round_num),
        }
    )
    meta = json.dumps(
        {
            "version": CKPT_VERSION,
            "round": int(round_num),
            "history": history,
            "round_times": [float(t) for t in round_times],
        }
    )
    # Each file is replaced atomically, but the pair is not: a crash between
    # the two os.replace calls leaves NEW state beside OLD meta.  The round
    # number embedded in the blob lets restore detect that torn pair.
    durable_replace(d, STATE_FILE, blob)
    durable_replace(d, META_FILE, meta.encode("utf-8"))
    return d


def durable_replace(directory: str | Path, name: str, data: bytes) -> None:
    """Write ``directory/name`` via a temp file so a crash at ANY point
    leaves either the old complete file or the new complete file.

    os.replace alone only gives atomicity against concurrent readers; a
    HOST crash can still lose the rename (or land an empty/partial temp
    file in it) unless the temp file's data is fsync'd before the rename
    and the directory entry is fsync'd after it.  Shared with the ZMQ
    backend's per-node crash-recovery checkpoints
    (distributed/node_process.py) — one durability path, not two.
    """
    directory = Path(directory)
    tmp = directory / (name + ".tmp")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        # os.write may write short (kernel caps one write at ~2 GiB;
        # EINTR): loop until every byte is down before the fsync.
        view = memoryview(data)
        while view:
            view = view[os.write(fd, view):]
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, directory / name)
    dfd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def restore_checkpoint(
    directory: str | Path,
    *,
    params_target: Any,
    agg_state_target: Dict[str, Any],
    rng_target: Any,
) -> Tuple[Any, Dict[str, Any], Any, int, Dict[str, list], list]:
    """Load (params, agg_state, rng, round, history, round_times).

    Targets supply the pytree structure/dtypes; shapes are validated by
    flax.serialization against the saved leaves.
    """
    d = Path(directory)
    meta = json.loads((d / META_FILE).read_text())
    if meta.get("version") != CKPT_VERSION:
        hint = (
            " (the rng blob in a v2 checkpoint is ambiguous: depending on the "
            "build that wrote it, it is either split()-chain state or the "
            "fold_in base key this build expects — resuming the former would "
            "silently change the random stream, so both are rejected)"
            if meta.get("version") == 2
            else ""
        )
        raise ValueError(
            f"Checkpoint version {meta.get('version')} != {CKPT_VERSION}{hint}"
        )
    state = serialization.from_bytes(
        {
            "params": jax.device_get(params_target),
            "agg_state": jax.device_get(agg_state_target),
            "rng": jax.device_get(rng_target),
            "round": np.int64(0),
        },
        (d / STATE_FILE).read_bytes(),
    )
    if int(state["round"]) != int(meta["round"]):
        raise ValueError(
            f"Torn checkpoint: state.msgpack is at round {int(state['round'])} "
            f"but meta.json says round {int(meta['round'])} — the writer "
            "crashed between the two atomic replaces; restart from a clean "
            "checkpoint directory"
        )
    return (
        state["params"],
        state["agg_state"],
        np.asarray(state["rng"]),
        int(meta["round"]),
        meta["history"],
        list(meta["round_times"]),
    )


def has_checkpoint(directory: str | Path) -> bool:
    d = Path(directory)
    return (d / STATE_FILE).exists() and (d / META_FILE).exists()

"""Per-round checkpoint/resume for the stacked network state.

The reference has no checkpointing at all — model states live only in
memory and history is returned at the end of ``train()`` (SURVEY §5;
reference: murmura/core/network.py:60-94).  Here the whole run state is a
handful of device arrays (stacked params pytree, aggregator state dict, RNG
key) plus host-side history, so a checkpoint is one msgpack blob + one JSON
commit record:

    <dir>/state.<round>.msgpack  flax.serialization bytes of
                                 {params, agg_state, rng, round}
    <dir>/extra.<round>.npz      orchestrator extra sections (optional)
    <dir>/meta.json              {round, history, round_times, version, ...}

``meta.json`` is the single COMMIT POINT: the generation-suffixed state
and extra files are written (fsync'd) first, the meta replace publishes
them, and only after that commit are older generations garbage-collected.
A crash at ANY point therefore leaves a complete restorable snapshot —
either the previous one (meta still names it, its files untouched) or the
new one — never a torn pair.  Restore is exact: resuming reproduces the
same arrays the run would have had at that round boundary.
"""

import io
import json
import os
from pathlib import Path
from typing import Any, Dict, Optional, Tuple

import jax
import numpy as np
from flax import serialization


def npz_bytes(arrays: Dict[str, np.ndarray]) -> bytes:
    """Serialize ``arrays`` to .npz bytes (the extra-section container)."""
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})
    return buf.getvalue()


def load_npz_bytes(data: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(data), allow_pickle=False) as z:
        return {k: np.array(z[k]) for k in z.files}

# v3: per-round step keys changed from an advancing split() chain to
# fold_in(base, round) — the saved rng blob is now the static base key, not
# chain state.  A v2 checkpoint restored into a v3 build would resume with a
# silently different noise/SGD stream, so the version gate fails it loudly.
# v3 also covers the durability extension (extra sections below): the core
# pair is unchanged, a v3 checkpoint without sections restores as before.
CKPT_VERSION = 3
META_FILE = "meta.json"
# Generation-suffixed payload files, committed by the meta.json replace.
# The legacy un-suffixed names are still READ (a pre-durability v3
# checkpoint restores fine) but never written.
_STATE_TMPL = "state.{round}.msgpack"
_LEGACY_STATE_FILE = "state.msgpack"
# Orchestrator-specific extra sections (durability/snapshot.py): the
# population engine's cohort/bank state, packed masks, ... — arbitrary
# named numpy arrays in one .npz beside the state blob, json-able scalars
# in meta["extra_meta"].  Absent when a snapshot has no extra sections.
# (No legacy un-suffixed twin: extra sections and the suffixed layout
# shipped together, so only state.msgpack has a pre-durability form.)
_EXTRA_TMPL = "extra.{round}.npz"
# Embedded in both payload files so a miscopied/spliced file is detected
# by the round cross-check even though the commit ordering already rules
# out writer-crash tearing.
_EXTRA_ROUND_KEY = "__round__"


def _payload_paths(directory: Path, round_num: int) -> Tuple[Path, Path]:
    return (
        directory / _STATE_TMPL.format(round=int(round_num)),
        directory / _EXTRA_TMPL.format(round=int(round_num)),
    )


def _resolve_state_path(directory: Path, round_num: int) -> Path:
    """The state blob ``meta.json`` (round ``round_num``) commits to —
    generation-suffixed, or the legacy un-suffixed name for snapshots
    written before the commit-point layout."""
    state, _ = _payload_paths(directory, round_num)
    if state.exists():
        return state
    legacy = directory / _LEGACY_STATE_FILE
    if legacy.exists():
        return legacy
    return state  # let the caller's read raise with the canonical name


def _gc_old_generations(directory: Path, keep_round: int) -> None:
    """Delete payload generations other than the just-committed one
    (including legacy un-suffixed files) — strictly AFTER the meta
    replace, so a crash mid-save never touches the live snapshot."""
    state_keep, extra_keep = _payload_paths(directory, keep_round)
    keep = {state_keep.name, extra_keep.name}
    for p in list(directory.glob("state.*.msgpack")) + list(
        directory.glob("extra.*.npz")
    ) + [directory / _LEGACY_STATE_FILE]:
        if p.name not in keep:
            try:
                p.unlink()
            except FileNotFoundError:
                pass


def save_checkpoint(
    directory: str | Path,
    *,
    params: Any,
    agg_state: Dict[str, Any],
    rng: Any,
    round_num: int,
    history: Dict[str, list],
    round_times: list,
    extra_arrays: Optional[Dict[str, np.ndarray]] = None,
    extra_meta: Optional[Dict[str, Any]] = None,
) -> Path:
    """Write a checkpoint; returns the directory written.

    ``extra_arrays``/``extra_meta`` are the durability extension
    (durability/snapshot.py): named numpy arrays land in ``extra.<round>.npz``,
    json-able metadata in ``meta.json["extra_meta"]``, and the section
    names are listed in ``meta.json["sections"]`` so restore knows what a
    complete snapshot of this run must contain.
    """
    d = Path(directory)
    d.mkdir(parents=True, exist_ok=True)
    extra_arrays = dict(extra_arrays or {})
    blob = serialization.to_bytes(
        {
            "params": jax.device_get(params),
            "agg_state": jax.device_get(agg_state),
            "rng": jax.device_get(rng),
            # Duplicated in meta.json; restore cross-checks the two so a
            # hand-copied/spliced state file from another snapshot is
            # detected instead of silently replaying rounds.
            "round": np.int64(round_num),
        }
    )
    meta = json.dumps(
        {
            "version": CKPT_VERSION,
            "round": int(round_num),
            "history": history,
            "round_times": [float(t) for t in round_times],
            "sections": sorted(extra_arrays),
            "extra_meta": extra_meta or {},
        }
    )
    # Commit-point ordering: the generation-suffixed payload files land
    # (fsync'd) under names no live snapshot uses, the meta.json replace
    # COMMITS them, and only then are older generations deleted.  A crash
    # anywhere in this sequence leaves meta.json naming a generation whose
    # files are complete — the previous snapshot before the commit, the
    # new one after it.
    state_path, extra_path = _payload_paths(d, round_num)
    if extra_arrays:
        durable_replace(
            d, extra_path.name,
            npz_bytes({
                **extra_arrays,
                _EXTRA_ROUND_KEY: np.asarray(round_num, np.int64),
            }),
        )
    durable_replace(d, state_path.name, blob)
    durable_replace(d, META_FILE, meta.encode("utf-8"))
    _gc_old_generations(d, round_num)
    return d


def durable_replace(directory: str | Path, name: str, data: bytes) -> None:
    """Write ``directory/name`` via a temp file so a crash at ANY point
    leaves either the old complete file or the new complete file.

    os.replace alone only gives atomicity against concurrent readers; a
    HOST crash can still lose the rename (or land an empty/partial temp
    file in it) unless the temp file's data is fsync'd before the rename
    and the directory entry is fsync'd after it.  Shared with the ZMQ
    backend's per-node crash-recovery checkpoints
    (distributed/node_process.py) — one durability path, not two.
    """
    directory = Path(directory)
    tmp = directory / (name + ".tmp")
    fd = os.open(tmp, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o644)
    try:
        # os.write may write short (kernel caps one write at ~2 GiB;
        # EINTR): loop until every byte is down before the fsync.
        view = memoryview(data)
        while view:
            view = view[os.write(fd, view):]
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, directory / name)
    dfd = os.open(directory, os.O_RDONLY)
    try:
        os.fsync(dfd)
    finally:
        os.close(dfd)


def restore_checkpoint(
    directory: str | Path,
    *,
    params_target: Any,
    agg_state_target: Dict[str, Any],
    rng_target: Any,
) -> Tuple[
    Any, Dict[str, Any], Any, int, Dict[str, list], list,
    Dict[str, np.ndarray], Dict[str, Any],
]:
    """Load (params, agg_state, rng, round, history, round_times,
    extra_arrays, extra_meta).

    Targets supply the pytree structure/dtypes; shapes are validated by
    flax.serialization against the saved leaves.  ``extra_arrays`` holds
    the sections ``meta.json["sections"]`` names (empty for snapshots
    without extras), round-cross-checked like the state/meta pair.
    """
    d = Path(directory)
    meta = json.loads((d / META_FILE).read_text())
    if meta.get("version") != CKPT_VERSION:
        hint = (
            " (the rng blob in a v2 checkpoint is ambiguous: depending on the "
            "build that wrote it, it is either split()-chain state or the "
            "fold_in base key this build expects — resuming the former would "
            "silently change the random stream, so both are rejected)"
            if meta.get("version") == 2
            else ""
        )
        raise ValueError(
            f"Checkpoint version {meta.get('version')} != {CKPT_VERSION}{hint}"
        )
    state_path = _resolve_state_path(d, meta["round"])
    state = serialization.from_bytes(
        {
            "params": jax.device_get(params_target),
            "agg_state": jax.device_get(agg_state_target),
            "rng": jax.device_get(rng_target),
            "round": np.int64(0),
        },
        state_path.read_bytes(),
    )
    if int(state["round"]) != int(meta["round"]):
        raise ValueError(
            f"Torn checkpoint: {state_path.name} is at round "
            f"{int(state['round'])} but meta.json says round "
            f"{int(meta['round'])} — the file was spliced from another "
            "snapshot (the commit-point writer cannot produce this); "
            "restart from a clean checkpoint directory"
        )
    sections = list(meta.get("sections", []))
    extra_arrays: Dict[str, np.ndarray] = {}
    if sections:
        extra_path = _payload_paths(d, meta["round"])[1]
        extra_arrays = load_npz_bytes(extra_path.read_bytes())
        extra_round = extra_arrays.pop(_EXTRA_ROUND_KEY, None)
        if extra_round is None or int(extra_round) != int(meta["round"]):
            raise ValueError(
                f"Torn checkpoint: {extra_path.name} is at round "
                f"{None if extra_round is None else int(extra_round)} but "
                f"meta.json says round {int(meta['round'])} — the file was "
                "spliced from another snapshot; restart from a clean "
                "checkpoint directory"
            )
        missing = sorted(set(sections) - set(extra_arrays))
        if missing:
            raise ValueError(
                f"Incomplete snapshot: meta.json lists sections {missing} "
                "that the extra section file does not contain"
            )
    return (
        state["params"],
        state["agg_state"],
        np.asarray(state["rng"]),
        int(meta["round"]),
        meta["history"],
        list(meta["round_times"]),
        extra_arrays,
        dict(meta.get("extra_meta", {})),
    )


def has_checkpoint(directory: str | Path) -> bool:
    """A restorable snapshot exists: a committed meta.json whose state
    generation is present (suffixed or legacy layout)."""
    d = Path(directory)
    meta_path = d / META_FILE
    if not meta_path.exists():
        return False
    try:
        meta = json.loads(meta_path.read_text())
    except (OSError, json.JSONDecodeError):
        return False
    return _resolve_state_path(d, meta.get("round", 0)).exists()

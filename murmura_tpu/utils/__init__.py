"""Shared wiring and utilities (reference: murmura/utils/)."""

from murmura_tpu.utils.seed import set_seed
from murmura_tpu.utils.factories import (
    build_attack,
    build_network_from_config,
)

__all__ = ["set_seed", "build_attack", "build_network_from_config"]

"""Seeding (reference: murmura/utils/seed.py:8-21).

JAX is functionally seeded (explicit PRNG keys threaded through the round
step), so unlike the reference there is no hidden framework RNG state to
pin; this helper seeds the host-side generators used by partitioners,
topology generation, and attack selection.
"""

import random

import numpy as np


def set_seed(seed: int) -> None:
    random.seed(seed)
    np.random.seed(seed)

"""Config -> object wiring shared by the CLI and backends
(reference: murmura/utils/factories.py:16-190).

``build_network_from_config`` is the single path from a validated Config to
a ready-to-train Network for the simulation and tpu backends; the ZMQ
distributed backend reuses the component builders for its per-process nodes.
"""

from typing import Optional

import numpy as np

from murmura_tpu.aggregation import build_aggregator
from murmura_tpu.attacks import ATTACKS
from murmura_tpu.attacks.base import Attack
from murmura_tpu.config.schema import Config
from murmura_tpu.core.network import Network
from murmura_tpu.levers import refusal_reason
from murmura_tpu.core.rounds import build_round_program
from murmura_tpu.data.registry import build_federated_data
from murmura_tpu.models.registry import build_model
from murmura_tpu.topology.dynamic import MobilityModel
from murmura_tpu.topology.generators import create_topology


def select_compromised_count(n: int, pct: float, seed: int) -> int:
    """Size of the compromised set a (n, pct, seed) selection yields —
    the fail-loud guards below need the count before building anything."""
    from murmura_tpu.attacks.base import select_compromised

    return int(select_compromised(n, pct, seed).sum())


def build_attack(config: Config) -> Optional[Attack]:
    """Instantiate the attack from config (reference: factories.py:123-174).

    With ``attack.adaptive.enabled`` (schema validated it against the
    backend/type), the static attack becomes its closed-loop twin
    (attacks/adaptive.py): ``alie`` maps to adaptive ALIE, every other
    broadcast attack is wrapped in the generic scale bisection.
    """
    if not config.attack.enabled or not config.attack.type:
        return None
    n = config.topology.num_nodes
    pct = config.attack.percentage
    p = config.attack.params
    ad = config.attack.adaptive
    if ad.enabled and config.backend == "distributed":
        # Schema already rejects this; direct library construction gets
        # the same loud refusal (the adaptation loop is in-jit only).
        raise ConfigError(
            "adaptive attacks are not wired into backend: distributed"
        )
    # Compromised-set selection seed.  Defaults to the experiment seed (the
    # reference's behavior); an explicit attack.params.seed pins the
    # Byzantine placement independently of experiment.seed — the knob gang
    # sweeps (core/gang.py) rely on: a gang varies member seeds under ONE
    # traced program whose attack closures (e.g. the gaussian scatter
    # matrix) bake in a static compromised set, so the placement must not
    # follow the member seed.
    seed = int(p.get("seed", config.experiment.seed))

    def _bisect(inner: Attack) -> Attack:
        """Apply the adaptive scale-bisection wrapper when configured."""
        if not ad.enabled:
            return inner
        from murmura_tpu.attacks.adaptive import make_bisection_attack

        return make_bisection_attack(
            inner,
            scale_init=ad.scale_init,
            scale_max=ad.scale_max,
            growth=ad.growth,
            accept_target=ad.accept_target,
            ema_beta=ad.ema_beta,
        )

    if config.attack.type == "gaussian":
        # "std" is the reference's alternate key for the noise scale
        # (examples/configs/uci_har_byzantine.yaml).
        return _bisect(ATTACKS["gaussian"](
            num_nodes=n,
            attack_percentage=pct,
            noise_std=float(p.get("noise_std", p.get("std", 10.0))),
            seed=seed,
        ))
    if config.attack.type == "directed_deviation":
        return _bisect(ATTACKS["directed_deviation"](
            num_nodes=n,
            attack_percentage=pct,
            lambda_param=float(p.get("lambda_param", -5.0)),
            seed=seed,
        ))
    if config.attack.type in ("alie", "ipm"):
        # Colluding attacks: on simulation/tpu the jitted round step
        # computes the colluding vector from the TRUE honest rows
        # (omniscient variant — stronger than the papers' constructions;
        # alie.py/ipm.py docstrings).  On the ZMQ backend each colluding
        # NodeProcess instead estimates the statistics from the
        # coalition's own benign states (the papers' estimators) — see
        # NodeProcess._colluding_state.
        if config.backend == "distributed" and config.dmtt is not None:
            # DMTTNodeProcess overrides _execute_round without the
            # coalition branch; letting a colluding attack fall through to
            # the per-node apply() would silently run NO attack while the
            # experiment reports it ran — fail loud instead.
            raise ConfigError(
                f"attack type '{config.attack.type}' is not wired into "
                "the DMTT distributed round protocol; use backend: "
                "simulation/tpu, or a different attack on the "
                "distributed backend"
            )
        if config.attack.type == "alie":
            estimator = str(p.get("estimator", "omniscient"))
            if estimator not in ("omniscient", "coalition"):
                raise ConfigError(
                    f"attack.params.estimator must be 'omniscient' or "
                    f"'coalition', got {estimator!r}"
                )
            if (
                config.backend == "distributed" or estimator == "coalition"
            ) and select_compromised_count(n, pct, seed) < 2:
                # The coalition estimator (the paper's construction —
                # the ZMQ backend always, the jitted backends under
                # params.estimator: coalition) needs >= 2 colluders:
                # with one, sigma over the coalition sample is 0 and
                # mu - z*s degenerates to the colluder's benign state
                # — a silent no-attack run labeled "under ALIE" (ipm
                # has no such minimum: -eps*own is still an attack).
                raise ConfigError(
                    "the ALIE coalition estimator needs at least 2 "
                    "compromised nodes (mu/sigma over the coalition "
                    "sample is degenerate with 1); raise "
                    "attack.percentage, or use the omniscient estimator "
                    "on backend: simulation/tpu"
                )
            if ad.enabled:
                from murmura_tpu.attacks.adaptive import (
                    make_adaptive_alie_attack,
                )

                return make_adaptive_alie_attack(
                    num_nodes=n,
                    attack_percentage=pct,
                    z=p.get("z"),
                    seed=seed,
                    estimator=estimator,
                    eta=ad.eta,
                    accept_target=ad.accept_target,
                    ema_beta=ad.ema_beta,
                    z_min=ad.z_min,
                    z_cap=ad.z_cap,
                )
            return ATTACKS["alie"](
                num_nodes=n,
                attack_percentage=pct,
                z=p.get("z"),
                seed=seed,
                estimator=estimator,
            )
        if ad.enabled:
            # IPM adapts its own semantic knob — the negation factor
            # epsilon walks the acceptance signal as carried state
            # (atk_eps) — rather than riding the generic perturbation
            # bisection: the converged strength then lives on the
            # paper's epsilon axis (attacks/adaptive.py).
            from murmura_tpu.attacks.adaptive import make_adaptive_ipm_attack

            return make_adaptive_ipm_attack(
                num_nodes=n,
                attack_percentage=pct,
                epsilon=p.get("epsilon"),
                seed=seed,
                eta=ad.eta,
                accept_target=ad.accept_target,
                ema_beta=ad.ema_beta,
            )
        return ATTACKS["ipm"](
            num_nodes=n,
            attack_percentage=pct,
            epsilon=p.get("epsilon"),
            seed=seed,
        )
    if config.attack.type == "label_flip":
        if config.backend == "distributed":
            # The ZMQ NodeProcess builds its own data shard; the poison
            # transform is not wired there, and an identity state attack
            # over clean data would be a silent no-attack run labeled
            # "under label_flip" — fail loud instead.
            raise ConfigError(
                "attack type 'label_flip' is not wired into the ZMQ "
                "distributed backend (per-process data is built without "
                "the poison transform); use backend: simulation/tpu"
            )
        ff = float(p.get("flip_fraction", 1.0))
        if not 0.0 < ff <= 1.0:
            raise ConfigError(
                f"attack.params.flip_fraction must be in (0, 1], got {ff}"
            )
        return ATTACKS["label_flip"](
            num_nodes=n,
            attack_percentage=pct,
            flip_fraction=ff,
            seed=seed,
        )
    if config.attack.type == "topology_liar":
        inner = None
        inner_type = p.get("model_attack_type")
        if inner_type == "gaussian":
            inner = ATTACKS["gaussian"](
                num_nodes=n,
                attack_percentage=pct,
                noise_std=float(p.get("noise_std", 10.0)),
                seed=seed,
            )
        elif inner_type == "directed_deviation":
            inner = ATTACKS["directed_deviation"](
                num_nodes=n,
                attack_percentage=pct,
                lambda_param=float(p.get("lambda_param", -5.0)),
                seed=seed,
            )
        elif inner_type is not None:
            # Fail loud: a typo'd or unsupported inner attack must not
            # silently degrade to topology-lies-only (the experiment would
            # measure the wrong threat model).  'alie' is deliberately not
            # wired here: DMTT liars already coordinate through claims, and
            # the colluding model vector would need the full-network view
            # inside the per-claim transform.
            raise ConfigError(
                f"topology_liar model_attack_type '{inner_type}' is not "
                "supported; use 'gaussian' or 'directed_deviation' (or omit "
                "for topology lies only)"
            )
        return ATTACKS["topology_liar"](
            num_nodes=n, attack_percentage=pct, seed=seed, model_attack=inner
        )
    return None


def build_mobility(config: Config) -> Optional[MobilityModel]:
    """MobilityModel from config.mobility (reference: factories.py:177-190)."""
    if config.mobility is None:
        return None
    m = config.mobility
    return MobilityModel(
        num_nodes=config.topology.num_nodes,
        area_size=m.area_size,
        comm_range=m.comm_range,
        max_speed=m.max_speed,
        seed=m.seed,
        ensure_connected=m.ensure_connected,
    )


def build_fault_schedule(config: Config):
    """FaultSchedule from config.faults, or None when the model is off.

    The single construction path for EVERY consumer — the simulation/tpu
    orchestrator, each ZMQ node process, and the runner's FaultInjector —
    so the deterministic schedule is identical across processes and
    backends by construction (faults/schedule.py module docstring).
    """
    f = config.faults
    if not f.enabled:
        return None
    from murmura_tpu.faults.schedule import FaultSchedule

    return FaultSchedule(
        config.topology.num_nodes,
        crash_prob=f.crash_prob,
        recovery_prob=f.recovery_prob,
        min_down_rounds=f.min_down_rounds,
        link_drop_prob=f.link_drop_prob,
        straggler_prob=f.straggler_prob,
        straggler_factor=f.straggler_factor,
        seed=f.seed,
    )


def default_telemetry_dir(config: Config) -> str:
    """The run directory a telemetry-enabled config writes to when
    ``telemetry.dir`` is unset — shared by every consumer (Network wiring,
    the Monitor process, the CLI's report hint) so they agree on one path."""
    import os

    return config.telemetry.dir or os.path.join(
        "murmura_runs", config.experiment.name
    )


def build_telemetry_writer(
    config: Config, kind: str = "run", run_id=None, resume: bool = False
):
    """TelemetryWriter from config.telemetry, or None when off.

    The single construction path for every consumer (the simulation/tpu
    orchestrator and the ZMQ Monitor process), so the manifest schema and
    run-dir resolution cannot drift between backends.  ``resume`` marks an
    intentional continuation (checkpoint restore) — the event stream
    appends; a fresh run into the same dir rotates the stale stream
    instead (writer.py).
    """
    t = config.telemetry
    if not t.enabled:
        return None
    from murmura_tpu.telemetry.writer import TelemetryWriter

    return TelemetryWriter(
        default_telemetry_dir(config),
        kind=kind,
        run_id=run_id,
        config=config,
        record_taps=True,
        phase_times=t.phase_times,
        memory_stats=t.memory_stats,
        profile_dir=t.profile_dir,
        profile_start_round=t.profile_start_round,
        profile_rounds=t.profile_rounds,
        resume=resume,
    )


def build_compression_spec(config: Config):
    """Trace-time CompressionSpec from config.compression, or None when
    off — the single construction path for every consumer (single runs and
    gangs), so codec semantics cannot drift between them."""
    c = config.compression
    if c.algorithm == "none":
        return None
    from murmura_tpu.ops.compress import CompressionSpec

    return CompressionSpec(
        algorithm=c.algorithm,
        block=c.block,
        topk_ratio=c.topk_ratio,
        error_feedback=c.error_feedback,
    )


def build_staleness_spec(config: Config, topology):
    """Trace-time StalenessSpec from config.exchange, or None when off —
    the single construction path for every consumer (single runs and
    gangs), so the base-graph/age semantics cannot drift between them.

    The base mask is the UNFAULTED exchange graph re-added stale edges
    are drawn from: the topology's static [N, N] mask (dense mode) or
    the all-active [k, N] edge mask (the static sparse exponential
    family; one_peer's round-varying mask was rejected at schema
    validation).
    """
    e = config.exchange
    if e.max_staleness <= 0:
        return None
    from murmura_tpu.core.stale import StalenessSpec
    from murmura_tpu.topology.sparse import SparseTopology

    if isinstance(topology, SparseTopology):
        base = np.ones(
            (len(topology.offsets), topology.num_nodes), np.float32
        )
    else:
        base = np.asarray(topology.mask(), dtype=np.float32)
    return StalenessSpec(
        max_staleness=e.max_staleness,
        discount=e.staleness_discount,
        base_mask=base,
    )


def pallas_agg_enabled(config: Config, node_axis_sharded: bool) -> bool:
    """Whether to route this build's aggregation through the fused Pallas
    kernels (tpu.pallas_agg, env twin MURMURA_PALLAS_AGG=1).  Never on a
    sharded NODE axis — pallas_call does not decompose under GSPMD, so
    that path keeps the lax kernels.  A sharded *param* axis is fine: the
    entry points themselves run shard-local grids under shard_map
    (ops/pallas_agg.py sharded-axis policy), so the toggle stays honest
    per axis rather than per mesh."""
    import os

    if node_axis_sharded:
        return False
    return bool(config.tpu.pallas_agg) or os.environ.get(
        "MURMURA_PALLAS_AGG"
    ) == "1"


def build_fault_spec(config: Config):
    """Trace-time FaultSpec from config.faults, or None when off."""
    f = config.faults
    if not f.enabled:
        return None
    from murmura_tpu.faults.schedule import FaultSpec

    return FaultSpec(
        nan_quarantine=f.nan_quarantine,
        nan_inject_nodes=tuple(f.nan_inject_nodes),
        nan_inject_from_round=f.nan_inject_from_round,
    )


class ConfigError(ValueError):
    """Wiring-level configuration error: the config validated structurally
    but its pieces cannot work together (data/model mismatch, unsupported
    exchange mode, ...).  The CLI renders these as messages, not
    tracebacks; unexpected ValueErrors stay loud."""


def resolved_param_dtype(config: Config) -> Optional[str]:
    """tpu.param_dtype with the documented large-N auto default: bfloat16
    from 64 nodes up (halves the [N, P] resident state and the SGD
    update's HBM traffic — the bench_sgd_micro lever; bench.py's 256-node
    north-star runs it), float32 below, explicit setting always wins."""
    if config.backend != "tpu":
        return None
    if config.tpu.param_dtype is not None:
        return config.tpu.param_dtype
    return "bfloat16" if config.topology.num_nodes >= 64 else "float32"


def resolve_model(config: Config, data):
    """Build the model for a config with data-aware parameter sync and a
    fail-fast shape check.

    Shared by the in-process backends (build_network_from_config) and the
    ZMQ worker processes (NodeProcess._build_node), so every backend gets
    the wearables input_dim auto-sync and the data/model consistency error
    instead of a raw XLA dot_general failure rounds later.
    """
    model_params = dict(config.model.params)
    if config.backend == "tpu":
        # MXU mixed precision: bfloat16 matmul/conv inputs, float32 params
        # and accumulation (tpu.compute_dtype, default bfloat16).
        model_params.setdefault("compute_dtype", config.tpu.compute_dtype)
        factory_lc = config.model.factory.lower()
        if config.tpu.conv_impl != "direct" and (
            "femnist" in factory_lc or "celeba" in factory_lc
        ):
            # CNN-only lever; non-conv models have no im2col formulation.
            model_params.setdefault("conv_impl", config.tpu.conv_impl)
    if (
        "wearables." in config.model.factory
        and "input_dim" not in model_params
        and data.x.ndim == 3
    ):
        # Window params on the data side (window_size, include_heart_rate)
        # change the sample dimensionality; keep the model input in sync
        # unless the user pinned it explicitly.
        model_params["input_dim"] = int(data.x.shape[-1])
    model = build_model(config.model.factory, model_params)

    # Compare element counts, not shapes: models accept layout-equivalent
    # inputs (e.g. [28, 28] images for a [28, 28, 1] CNN input).
    sample_shape = tuple(data.x.shape[2:])
    if (
        model.input_shape
        and sample_shape
        and int(np.prod(sample_shape)) != int(np.prod(model.input_shape))
    ):
        raise ConfigError(
            f"data/model mismatch: adapter '{config.data.adapter}' yields "
            f"samples of shape {sample_shape} "
            f"({int(np.prod(sample_shape))} values) but model factory "
            f"'{config.model.factory}' expects input_shape "
            f"{tuple(model.input_shape)} ({int(np.prod(model.input_shape))} "
            "values); set model.params.input_dim (or the adapter's shape "
            "params) so they agree"
        )
    return model


def apply_compilation_cache(config: Config) -> None:
    """Enable JAX's persistent compilation cache when configured.

    Shared by the in-process backends (via build_network_from_config) and
    the ZMQ worker processes (NodeProcess.run), so ``murmura run`` pays an
    identical round program's XLA compile once per machine, not once per
    run per process.
    """
    if config.tpu.compilation_cache_dir:
        import jax

        jax.config.update(
            "jax_compilation_cache_dir", config.tpu.compilation_cache_dir
        )
        # Process-level twin for jax-config-free consumers (the check
        # --ir budget sweep — analysis/budgets.apply_persistent_cache —
        # and any subprocess this run spawns): one cache per battery.
        import os

        os.environ.setdefault(
            "MURMURA_COMPILATION_CACHE_DIR",
            config.tpu.compilation_cache_dir,
        )


def _node_axis_sharded(config: Config, mesh=None) -> bool:
    """Whether the round step will run with the NODE axis sharded over a
    mesh — selects circulant shift lowerings (AggContext.node_axis_sharded).
    An explicitly passed mesh is authoritative (it IS the thing this flag
    describes) and is read per axis: a ("seed", "nodes", "param") mesh
    whose node axis is size 1 is NOT node-sharded however many param
    shards it carries.  Otherwise ``tpu.num_devices: null`` means "all
    available", so the device count is only known at build time — with
    param sharding configured, the node axis gets what the planned layout
    leaves it (parallel/mesh.plan_param_layout)."""
    if config.backend != "tpu":
        return False
    if mesh is not None:
        from murmura_tpu.parallel.mesh import mesh_node_axis

        return mesh_node_axis(mesh) > 1
    nd = config.tpu.num_devices
    if nd is None:
        import jax

        nd = jax.device_count()
    if config.tpu.param_shards > 1:
        from murmura_tpu.parallel.mesh import plan_param_layout

        try:
            _, nodes_ax, _ = plan_param_layout(
                config.topology.num_nodes, config.tpu.param_shards, nd
            )
        except ValueError:
            # Unfactorable layouts fail loudly at mesh build; the lowering
            # flag just needs a consistent answer until then.
            return nd > 1
        return nodes_ax > 1
    return nd > 1


def _gang_member_programs(config: Config, members, *, topology, attack,
                          sparse, node_axis_sharded, gang_param_shards):
    """Per-member RoundPrograms for a gang: data, init params and RNG are
    built per member seed while the attack placement / topology closures
    stay shared (the gang parity contract, core/gang.py).  Extracted from
    :func:`build_gang_from_config` so `murmura serve` can build a fresh
    generation's programs for value-only admission into a warm bucket
    (``GangNetwork.reset_run(member_programs=...)``) without constructing
    — and re-jitting — a new GangNetwork."""
    from murmura_tpu.core.gang import gang_hp_inputs
    from murmura_tpu.core.rounds import build_round_program as _build_program

    hp_inputs = gang_hp_inputs(members)
    n = config.topology.num_nodes
    rounds = config.experiment.rounds

    dmtt = None
    if config.dmtt is not None:
        from murmura_tpu.dmtt.protocol import DMTTParams

        dmtt = DMTTParams(**config.dmtt.model_dump(exclude={"allow_static"}))

    model = None
    agg = None
    probe_size = config.training.batch_size
    member_programs = []
    for i, member in enumerate(members):
        data = build_federated_data(
            config.data.adapter,
            config.data.params,
            num_nodes=n,
            seed=member.seed,
            max_samples=config.training.max_samples,
        )
        if attack is not None and attack.data_poison_fn is not None:
            if data.x_test is None:
                raise ConfigError(
                    "data-poisoning attacks need a clean eval split: this "
                    "adapter/config evaluates on the training shard "
                    "(holdout_fraction: 0.0); set holdout_fraction > 0 or "
                    "use an adapter with test shards"
                )
            data.y = attack.data_poison_fn(data.y, data.mask, data.num_classes)
        if i == 0:
            model = resolve_model(config, data)
            agg_params = dict(config.aggregation.params)
            if sparse:
                # Sparse topologies always run the [k, N] edge-mask
                # engine (the build_network_from_config wiring, shared
                # semantics — see the comment there).
                agg_params["exchange_offsets"] = list(topology.offsets)
                agg_params["sparse_exchange"] = True
            elif config.backend == "tpu" and config.tpu.exchange == "ppermute":
                if config.mobility is not None or config.dmtt is not None:
                    raise ConfigError(
                        "tpu.exchange: ppermute requires a static circulant "
                        "topology (mobility/dmtt graphs change per round)"
                    )
                offsets = topology.circulant_offsets()
                if offsets is None:
                    raise ConfigError(
                        f"tpu.exchange: ppermute requires a circulant "
                        f"topology (ring/k-regular); "
                        f"'{config.topology.type}' is not"
                    )
                agg_params["exchange_offsets"] = offsets
            if (
                config.aggregation.algorithm
                in ("krum", "median", "trimmed_mean", "geometric_median")
                and not sparse
                and config.mobility is None
                and config.dmtt is None
            ):
                agg_params.setdefault(
                    "max_candidates",
                    int(topology.mask().sum(axis=1).max()) + 1,
                )
            if config.aggregation.algorithm == "evidential_trust":
                probe_size = int(agg_params.get("max_eval_samples", 100))
            from murmura_tpu.ops.flatten import model_dimension, padded_dim
            import jax

            model_dim = model_dimension(
                jax.eval_shape(model.init, jax.random.PRNGKey(0))
            )
            if pallas_agg_enabled(config, node_axis_sharded):
                agg_params.setdefault("pallas", True)
            # Param-axis sharding pads the flat width (the
            # build_network_from_config contract): rules sizing buffers
            # from the flat dimension must see the padded width.
            agg_flat_dim = padded_dim(model_dim, gang_param_shards)
            if (
                gang_param_shards > 1
                and config.compression.algorithm == "int8"
                and (agg_flat_dim // gang_param_shards)
                % config.compression.block
            ):
                raise ConfigError(
                    f"compression.block={config.compression.block} does "
                    f"not divide the shard-local flat width "
                    f"{agg_flat_dim // gang_param_shards} (model_dim "
                    f"{model_dim} padded to {agg_flat_dim} over "
                    f"tpu.param_shards={gang_param_shards}) — "
                    + refusal_reason("compression", "sharding", "int8_block")
                )
            agg = build_aggregator(
                config.aggregation.algorithm, agg_params,
                model_dim=agg_flat_dim, total_rounds=rounds,
            )
        member_programs.append(_build_program(
            model,
            agg,
            data,
            local_epochs=config.training.local_epochs,
            batch_size=config.training.batch_size,
            lr=member.lr if member.lr is not None else config.training.lr,
            total_rounds=rounds,
            attack=attack,
            seed=member.seed,
            probe_size=probe_size,
            annealing_rounds=max(1, rounds // 2),
            lambda_weight=0.1,
            dmtt=dmtt,
            param_dtype=resolved_param_dtype(config),
            node_axis_sharded=node_axis_sharded,
            faults=build_fault_spec(config),
            audit_taps=config.telemetry.audit_taps,
            hp_inputs=hp_inputs,
            sparse_offsets=tuple(topology.offsets) if sparse else None,
            compression=build_compression_spec(config),
            staleness=build_staleness_spec(config, topology),
            pipeline=config.exchange.pipeline,
            param_shards=gang_param_shards,
        ))
    return member_programs


def build_gang_member_programs(config: Config, members):
    """Public per-member program builder for the serve admission path
    (serve/daemon.py): build ONE generation's RoundPrograms — per-seed
    data shards, init params, per-member lr — exactly as
    :func:`build_gang_from_config` would, without constructing a gang.
    The returned programs are VALUE sources for an existing warm bucket
    (``GangNetwork.reset_run(member_programs=...)``); they are never
    traced, so they must come from a config whose structural fingerprint
    matches the bucket template's (serve/scheduler.py enforces this)."""
    if config.backend == "distributed":
        raise ConfigError(
            "gang-batched serving needs the jitted backends; backend: "
            "distributed trains in per-node OS processes"
        )
    n = config.topology.num_nodes
    topology = create_topology(
        config.topology.type,
        num_nodes=n,
        p=config.topology.p,
        k=config.topology.k,
        seed=config.topology.seed,
    )
    from murmura_tpu.topology.sparse import SparseTopology

    sparse = isinstance(topology, SparseTopology)
    attack = build_attack(config)
    return _gang_member_programs(
        config, members,
        topology=topology,
        attack=attack,
        sparse=sparse,
        node_axis_sharded=_node_axis_sharded(config, None),
        gang_param_shards=(
            config.tpu.param_shards if config.backend == "tpu" else 1
        ),
    )


def build_gang_from_config(config: Config, seeds=None, mesh=None,
                           checkpoint_dir=None, retain_init=False,
                           min_batch=1):
    """Gang wiring (core/gang.py): one traced round program, S stacked
    member experiments — the ``murmura sweep`` / ``murmura run --seeds``
    path.

    Mirrors :func:`build_network_from_config` except that data, initial
    params, RNG bases and (optionally) traced scalar hyperparameters are
    built per member and stacked along a leading [S] axis, while the
    attack placement, topology, mobility and fault schedule stay shared
    (their seeds are independent of the experiment seed by construction —
    ``attack.params.seed`` defaults to the BASE config's experiment seed
    here so member programs share the attack's static closures).

    ``seeds``: explicit member-seed override (the CLI ``--seeds`` flag);
    otherwise ``config.sweep`` defines the members.
    """
    import os

    from murmura_tpu.core.gang import (
        GangNetwork,
        next_bucket,
        resolve_members,
    )

    if config.backend == "distributed":
        raise ConfigError(
            "gang-batched sweeps need the jitted backends; backend: "
            "distributed trains in per-node OS processes (run seeds as "
            "separate invocations there)"
        )
    if config.backend == "tpu" and config.tpu.multihost and mesh is None:
        from murmura_tpu.parallel.mesh import init_multihost

        init_multihost(
            coordinator_address=config.tpu.coordinator_address,
            num_processes=config.tpu.num_processes,
            process_id=config.tpu.process_id,
        )
    apply_compilation_cache(config)

    try:
        members = resolve_members(config, seeds)
    except ValueError as e:
        raise ConfigError(str(e))
    bucket = config.sweep.bucket if config.sweep is not None else True
    batch = (
        next_bucket(max(len(members), min_batch))
        if bucket else len(members)
    )

    n = config.topology.num_nodes
    topology = create_topology(
        config.topology.type,
        num_nodes=n,
        p=config.topology.p,
        k=config.topology.k,
        seed=config.topology.seed,
    )
    from murmura_tpu.topology.sparse import SparseTopology

    sparse = isinstance(topology, SparseTopology)
    if sparse and config.backend == "tpu":
        # The [k, N] edge mask rides the gang's vmap unbatched exactly
        # like the dense [N, N] matrix (lifted for ISSUE 11 — the
        # frontier sweeps sparse exponential graphs), but the gang MESH
        # still shards adjacency on node rows: the sparse mask needs the
        # edge_mask_sharding layout, which the gang path has not wired.
        raise ConfigError(refusal_reason("sparse", "sweep", "tpu_backend"))
    if config.population is not None and config.population.enabled:
        # The CLI `--seeds N` path reaches here with sweep=None, so the
        # schema's population x sweep validator never saw this pair.
        raise ConfigError(refusal_reason("population", "sweep"))
    # ONE attack for the whole gang: its compromised placement is seeded by
    # attack.params.seed (default: the base experiment seed), never by the
    # member seed — member programs share the attack's static closures
    # (e.g. the gaussian scatter matrix).  A single run reproduces a gang
    # member exactly by pinning attack.params.seed to this gang's base.
    attack = build_attack(config)
    mobility = build_mobility(config)

    gang_param_shards = (
        config.tpu.param_shards if config.backend == "tpu" else 1
    )
    if config.backend == "tpu" and mesh is None:
        if gang_param_shards > 1:
            # The sharding x sweep lift (ISSUE 16): a 4-D-role
            # ("seed", "nodes", "param") mesh so the gang's [S, N, P]
            # stacked state shards its trailing flat axis too.
            from murmura_tpu.parallel.mesh import make_gang_param_mesh

            mesh = make_gang_param_mesh(
                batch, n, gang_param_shards, config.tpu.num_devices
            )
        else:
            from murmura_tpu.parallel.mesh import make_gang_mesh

            mesh = make_gang_mesh(batch, n, config.tpu.num_devices)
    node_axis_sharded = (
        mesh is not None and dict(mesh.shape).get("nodes", 1) > 1
    )

    member_programs = _gang_member_programs(
        config, members,
        topology=topology,
        attack=attack,
        sparse=sparse,
        node_axis_sharded=node_axis_sharded,
        gang_param_shards=gang_param_shards,
    )

    writers = None
    if config.telemetry.enabled:
        # A gang resuming from an existing snapshot appends to its
        # members' event streams (the build_network_from_config contract,
        # automatically keyed off the snapshot's existence).
        gang_resume = False
        if checkpoint_dir is not None:
            from murmura_tpu.utils.checkpoint import has_checkpoint

            gang_resume = has_checkpoint(checkpoint_dir)
        base_dir = default_telemetry_dir(config)
        writers = []
        for member in members:
            mcfg = config.model_copy(deep=True)
            mcfg.experiment.seed = member.seed
            mcfg.telemetry.dir = os.path.join(base_dir, member.label)
            writers.append(build_telemetry_writer(mcfg, resume=gang_resume))

    try:
        return GangNetwork(
            program=member_programs[0],
            member_programs=member_programs,
            members=members,
            topology=topology,
            attack=attack,
            mobility=mobility,
            fault_schedule=build_fault_schedule(config),
            backend=(
                config.backend
                if config.backend in ("simulation", "tpu")
                else "simulation"
            ),
            mesh=mesh,
            num_devices=config.tpu.num_devices,
            donate=config.tpu.donate_state,
            bucket=bucket,
            base_lr=config.training.lr,
            recompile_guard=config.tpu.recompile_guard,
            transfer_guard=config.tpu.transfer_guard,
            telemetry_writers=writers,
            retain_init=retain_init,
            min_batch=min_batch,
        )
    except ValueError as e:
        # Gang-batchability failures (ragged member shapes, unfactorable
        # mesh) are wiring-level config errors — render as messages.
        raise ConfigError(str(e))


def build_network_from_config(
    config: Config, mesh=None, telemetry_resume: bool = False,
    checkpoint_dir=None,
) -> Network:
    """Full wiring: data + model + aggregator + attack -> Network.

    ``telemetry_resume``: this Network will continue a prior run (the CLI
    --resume path) — its telemetry appends to the run dir's existing event
    stream instead of rotating it.

    ``checkpoint_dir``: the durability snapshot location this run will
    resume from, when given.  It makes the telemetry-resume decision
    AUTOMATIC: the event stream appends exactly when a snapshot actually
    exists there (a resumed run must never rotate its own stream to
    ``*.prev``; a --resume with no snapshot yet is a fresh run and must
    rotate a stale one) — the caller no longer has to keep two flags in
    sync.
    """
    if checkpoint_dir is not None:
        from murmura_tpu.utils.checkpoint import has_checkpoint

        telemetry_resume = has_checkpoint(checkpoint_dir)
    if config.backend == "tpu" and config.tpu.multihost and mesh is None:
        # Must run before ANY jax call that initializes the XLA backend
        # (the eval_shape below would); jax.distributed.initialize refuses
        # to join a run after backend init.
        from murmura_tpu.parallel.mesh import init_multihost

        init_multihost(
            coordinator_address=config.tpu.coordinator_address,
            num_processes=config.tpu.num_processes,
            process_id=config.tpu.process_id,
        )

    apply_compilation_cache(config)

    n = config.topology.num_nodes
    seed = config.experiment.seed
    rounds = config.experiment.rounds

    data = build_federated_data(
        config.data.adapter,
        config.data.params,
        num_nodes=n,
        seed=seed,
        max_samples=config.training.max_samples,
    )
    model = resolve_model(config, data)

    topology = create_topology(
        config.topology.type,
        num_nodes=n,
        p=config.topology.p,
        k=config.topology.k,
        seed=config.topology.seed,
    )
    attack = build_attack(config)
    if attack is not None and attack.data_poison_fn is not None:
        if data.x_test is None:
            # Without a held-out split, evaluation falls back to the
            # training arrays — compromised nodes would be scored against
            # their own flipped labels and metric distortion would read
            # as attack damage.  Fail loud instead of measuring nonsense.
            raise ConfigError(
                "data-poisoning attacks need a clean eval split: this "
                "adapter/config evaluates on the training shard "
                "(holdout_fraction: 0.0); set holdout_fraction > 0 or "
                "use an adapter with test shards"
            )
        data.y = attack.data_poison_fn(data.y, data.mask, data.num_classes)
    mobility = build_mobility(config)

    # Probe sizing: evidential trust uses max_eval_samples
    # (evidential_trust.py:62-63); loss-probe rules use one training batch
    # (ubar.py:169).
    agg_params = dict(config.aggregation.params)

    from murmura_tpu.topology.sparse import SparseTopology

    sparse = isinstance(topology, SparseTopology)
    if sparse:
        # Sparse topologies (exponential/one_peer) ALWAYS run the [k, N]
        # edge-mask engine: the circulant rule paths with mask weights and
        # a round program whose adjacency input is the per-offset mask —
        # nothing O(N^2) is built on any backend (tpu.exchange is moot;
        # both settings route here).  Mobility/dmtt combinations were
        # rejected at schema validation.
        agg_params["exchange_offsets"] = list(topology.offsets)
        agg_params["sparse_exchange"] = True
    elif config.backend == "tpu" and config.tpu.exchange == "ppermute":
        # O(degree) neighbor exchange via circular shifts (circulant paths
        # in all six rules; krum assembles its candidate-pair distances
        # from rolled delta vectors instead of the global Gram matrix).
        if mobility is not None or config.dmtt is not None:
            raise ConfigError(
                "tpu.exchange: ppermute requires a static circulant topology "
                "(mobility/dmtt graphs change per round)"
            )
        offsets = topology.circulant_offsets()
        if offsets is None:
            raise ConfigError(
                f"tpu.exchange: ppermute requires a circulant topology "
                f"(ring/k-regular); '{config.topology.type}' is not"
            )
        agg_params["exchange_offsets"] = offsets
    if (
        config.aggregation.algorithm in ("krum", "median", "trimmed_mean", "geometric_median")
        and not sparse
        and mobility is None
        and config.dmtt is None
    ):
        # Static graph: bound the per-node candidate block at max-degree+1
        # so the candidate-gathering rules work on [N, m, ...] instead of
        # per-node [N, N, ...] copies (O(N^3) at m = N).  Dynamic graphs
        # (mobility/DMTT TopB) have no static degree bound and keep the
        # dense default.
        agg_params.setdefault(
            "max_candidates", int(topology.mask().sum(axis=1).max()) + 1
        )
    if config.aggregation.algorithm == "evidential_trust":
        probe_size = int(agg_params.get("max_eval_samples", 100))
    else:
        probe_size = config.training.batch_size

    # Need model_dim for sketchguard before building the program: derive from
    # a throwaway init (cheap, host-side).
    import jax

    from murmura_tpu.ops.flatten import model_dimension, padded_dim

    model_dim = model_dimension(
        jax.eval_shape(model.init, jax.random.PRNGKey(0))
    )
    if pallas_agg_enabled(config, _node_axis_sharded(config, mesh)):
        # Fused Pallas aggregation kernels (ops/pallas_agg.py); rules that
        # have no kernel path ignore the param.
        agg_params.setdefault("pallas", True)
    # Param-axis sharding pads the flat width; rules that size buffers
    # from the flat dimension (sketchguard's tables, krum candidate math)
    # must see the PADDED width — the width their [N, P] operand will
    # actually have.  The pad columns are exact zeros, inert everywhere.
    param_shards = config.tpu.param_shards if config.backend == "tpu" else 1
    agg_flat_dim = padded_dim(model_dim, param_shards)
    if (
        param_shards > 1
        and config.compression.algorithm == "int8"
        and (agg_flat_dim // param_shards) % config.compression.block
    ):
        # The build_round_program backstop raises the same refusal; here
        # it renders as a config message with the concrete numbers.
        raise ConfigError(
            f"compression.block={config.compression.block} does not "
            f"divide the shard-local flat width "
            f"{agg_flat_dim // param_shards} (model_dim {model_dim} "
            f"padded to {agg_flat_dim} over tpu.param_shards="
            f"{param_shards}) — "
            + refusal_reason("compression", "sharding", "int8_block")
        )
    agg = build_aggregator(
        config.aggregation.algorithm, agg_params, model_dim=agg_flat_dim,
        total_rounds=rounds,
    )

    dmtt = None
    if config.dmtt is not None:
        from murmura_tpu.dmtt.protocol import DMTTParams

        dmtt = DMTTParams(**config.dmtt.model_dump(exclude={"allow_static"}))

    program = build_round_program(
        model,
        agg,
        data,
        local_epochs=config.training.local_epochs,
        batch_size=config.training.batch_size,
        lr=config.training.lr,
        total_rounds=rounds,
        attack=attack,
        seed=seed,
        probe_size=probe_size,
        annealing_rounds=max(1, rounds // 2),
        lambda_weight=0.1,
        dmtt=dmtt,
        param_dtype=resolved_param_dtype(config),
        node_axis_sharded=_node_axis_sharded(config, mesh),
        faults=build_fault_spec(config),
        audit_taps=config.telemetry.audit_taps,
        sparse_offsets=tuple(topology.offsets) if sparse else None,
        compression=build_compression_spec(config),
        staleness=build_staleness_spec(config, topology),
        pipeline=config.exchange.pipeline,
        param_shards=param_shards,
    )

    if config.backend == "tpu" and mesh is None:
        if param_shards > 1:
            from murmura_tpu.parallel.mesh import make_param_mesh

            mesh = make_param_mesh(
                n, param_shards, config.tpu.num_devices
            )
        else:
            from murmura_tpu.parallel.mesh import make_mesh

            mesh = make_mesh(config.tpu.num_devices)

    net_kwargs = dict(
        program=program,
        topology=topology,
        attack=attack,
        mobility=mobility,
        backend=config.backend if config.backend in ("simulation", "tpu") else "simulation",
        mesh=mesh,
        seed=seed,
        donate=config.tpu.donate_state,
        profile_dir=config.tpu.profile_dir,
        recompile_guard=config.tpu.recompile_guard,
        transfer_guard=config.tpu.transfer_guard,
        fault_schedule=build_fault_schedule(config),
        telemetry=build_telemetry_writer(config, resume=telemetry_resume),
    )
    spec = build_population_spec(config)
    if spec is not None:
        from murmura_tpu.population import PopulationNetwork

        return PopulationNetwork(**net_kwargs, population=spec)
    return Network(**net_kwargs)


def build_population_spec(config: Config):
    """PopulationSpec from config.population, or None when off — the
    single construction path for every consumer, so cohort-draw semantics
    cannot drift between the orchestrator and any future tooling."""
    p = config.population
    if p is None or not p.enabled:
        return None
    from murmura_tpu.population import PopulationSpec

    return PopulationSpec(
        virtual_size=p.virtual_size,
        sampler=p.sampler,
        seed=p.seed,
        rounds_per_cohort=p.rounds_per_cohort,
        data_binding=p.data_binding,
        bank_dir=p.bank_dir,
        inherit=p.inherit,
    )

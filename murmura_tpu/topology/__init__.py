"""Graph topologies for decentralized FL (reference: murmura/topology/)."""

from murmura_tpu.topology.base import Topology
from murmura_tpu.topology.generators import (
    SPARSE_TOPOLOGY_TYPES,
    TOPOLOGY_TYPES,
    create_topology,
)
from murmura_tpu.topology.dynamic import MobilityModel
from murmura_tpu.topology.sparse import SparseTopology, exponential_offsets

__all__ = [
    "Topology",
    "SparseTopology",
    "create_topology",
    "exponential_offsets",
    "MobilityModel",
    "TOPOLOGY_TYPES",
    "SPARSE_TOPOLOGY_TYPES",
]

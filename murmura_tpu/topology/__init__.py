"""Graph topologies for decentralized FL (reference: murmura/topology/)."""

from murmura_tpu.topology.base import Topology
from murmura_tpu.topology.generators import create_topology, TOPOLOGY_TYPES
from murmura_tpu.topology.dynamic import MobilityModel

__all__ = ["Topology", "create_topology", "MobilityModel", "TOPOLOGY_TYPES"]

"""Sparse circulant topologies: the degree-O(log N) exchange engine.

Every aggregation path in the repo historically consumed a dense boolean
``[N, N]`` adjacency (topology/base.py) — either directly (the gathered
dense rules) or as an ignored companion of a static circulant offset list
(``tpu.exchange: ppermute``).  :class:`SparseTopology` replaces the dense
object for large-N graphs: a directed circulant graph represented purely by
its **offset list** — node ``i`` receives from ``(i + o) % N`` for each
offset ``o`` — plus a per-round ``[k, N]`` *edge mask* saying which of
those edges are active this round.  Nothing O(N²) is ever materialized on
the sparse path: the compiled round program takes the ``[k, N]`` mask where
the dense path takes the ``[N, N]`` adjacency (``murmura check --ir``
MUR600 pins this at the HLO level).

Two generator families ride on it (topology/generators.py):

- ``exponential`` (arXiv:2110.13363): static offsets ``2^i mod N`` for
  ``i in [0, ceil(log2 N))`` — degree O(log N), diameter O(log N), and the
  spectral gap that makes decentralized SGD converge at near-dense rates.
- ``one_peer``: the same offset set but only ONE offset active per round
  (``offsets[t mod k]``) — degree 1 per round, cycling through the
  exponential offsets.  The *trace* carries all k offsets; the per-round
  activation arrives as edge-mask **values**, so one compile covers every
  round (the faults-subsystem trick, MUR302).

The edge mask composes multiplicatively with the fault model exactly like
the dense adjacency does (``FaultSchedule.masked_edge_mask``): masks may
only remove edges, never add them.
"""

import math
from dataclasses import dataclass, field
from typing import List, Sequence, Tuple

import numpy as np


def exponential_offsets(n: int, horizon: "int | None" = None) -> Tuple[int, ...]:
    """Exponential-graph offsets ``2^i mod n`` for ``i in [0, horizon)``.

    ``horizon`` defaults to ``ceil(log2 n)`` — the arXiv:2110.13363
    construction.  At non-power-of-two ``n`` the raw sequence can collide
    (``2^i ≡ 2^j mod n``) once the horizon exceeds the default, and at
    power-of-two ``n`` an over-long horizon degenerates to offset 0
    (``2^i ≡ 0 mod n`` — a self-loop, which every aggregation neighbor
    mask in the repo assumes away).  Collisions are deduped; offset 0 is
    rejected loudly instead of silently emitting a self-loop graph.
    """
    if n < 2:
        raise ValueError(
            f"exponential offsets need num_nodes >= 2, got {n} (a "
            "1-node graph has no nonzero circulant offset)"
        )
    if horizon is None:
        horizon = max(1, math.ceil(math.log2(n)))
    raw = [pow(2, i, n) for i in range(horizon)]
    if 0 in raw:
        i = raw.index(0)
        raise ValueError(
            f"exponential offset 2^{i} mod {n} == 0 — a degenerate "
            "self-loop offset (horizon exceeds log2(n) at a power-of-two "
            "n); shrink the horizon"
        )
    # Dedupe, ascending: at non-power-of-two n an over-long horizon makes
    # 2^i mod n revisit earlier offsets; a duplicated offset would
    # double-count that neighbor in every weighted circulant kernel.
    return tuple(sorted(set(raw)))


@dataclass
class SparseTopology:
    """Directed circulant graph held as an offset list (never ``[N, N]``).

    Attributes:
        num_nodes: N.
        offsets: nonzero circulant offsets, deduped ascending; node ``i``
            receives from ``(i + o) % N`` for each offset ``o``.
        schedule: ``"static"`` (all offsets active every round) or
            ``"one_peer"`` (offset ``t mod k`` active in round ``t``).
    """

    num_nodes: int
    offsets: Tuple[int, ...]
    schedule: str = "static"

    def __post_init__(self) -> None:
        n = self.num_nodes
        if n < 2:
            raise ValueError(f"SparseTopology needs num_nodes >= 2, got {n}")
        offs = [int(o) % n for o in self.offsets]
        if any(o == 0 for o in offs):
            raise ValueError(
                f"SparseTopology offsets {tuple(self.offsets)} contain a "
                f"zero (mod {n}) offset — a self-loop every aggregation "
                "neighbor mask assumes away; drop it"
            )
        deduped = tuple(sorted(set(offs)))
        if len(deduped) != len(offs):
            raise ValueError(
                f"SparseTopology offsets {tuple(self.offsets)} collide mod "
                f"{n} (deduped: {deduped}) — a duplicated offset double-"
                "counts that neighbor in every weighted circulant kernel; "
                "pass the deduped list"
            )
        if not deduped:
            raise ValueError("SparseTopology needs at least one offset")
        if self.schedule not in ("static", "one_peer"):
            raise ValueError(
                f"unknown SparseTopology schedule {self.schedule!r} "
                "(expected 'static' or 'one_peer')"
            )
        self.offsets = deduped

    # -- sparse-native views ------------------------------------------------

    @property
    def degree(self) -> int:
        """Static in-degree k (per-round degree is 1 under one_peer)."""
        return len(self.offsets)

    def edge_mask(self, round_idx: int = 0) -> np.ndarray:
        """[k, N] float32 active-edge mask for one round.

        ``mask[j, i] == 1`` iff edge ``i <- (i + offsets[j]) % N`` is
        active.  Static schedules are all-ones; ``one_peer`` activates the
        single row ``round_idx % k``.  This is the sparse twin of
        ``Topology.mask()`` — the object the compiled round program takes
        as its adjacency input.
        """
        k = len(self.offsets)
        if self.schedule == "one_peer":
            mask = np.zeros((k, self.num_nodes), dtype=np.float32)
            mask[round_idx % k] = 1.0
            return mask
        return np.ones((k, self.num_nodes), dtype=np.float32)

    def in_degree_from_edge_mask(self, edge_mask: np.ndarray) -> np.ndarray:
        """[N] host-side sender in-degree under an edge mask: how many
        receivers will read node s's broadcast this round (the telemetry
        round-event signal the dense path gets from ``adj.sum(axis=0)``)."""
        deg = np.zeros(self.num_nodes, dtype=np.float32)
        for j, o in enumerate(self.offsets):
            # receiver i reads sender (i + o) % N => sender s is read by
            # receiver (s - o) % N.
            deg += np.roll(np.asarray(edge_mask[j], np.float32), o)
        return deg

    # -- dense-compat views (parity tests, contracts, small N only) ---------

    @property
    def adjacency(self) -> np.ndarray:
        """Dense directed bool view (receiver rows) — for small-N parity
        tests and the MUR103 zero-diagonal contract, never the round path."""
        n = self.num_nodes
        adj = np.zeros((n, n), dtype=bool)
        idx = np.arange(n)
        for o in self.offsets:
            adj[idx, (idx + o) % n] = True
        return adj

    def mask(self, dtype=np.float32) -> np.ndarray:
        """Dense directed numeric mask (see :attr:`adjacency`)."""
        return self.adjacency.astype(dtype)

    def circulant_offsets(self) -> List[int]:
        """Interface parity with :meth:`Topology.circulant_offsets`."""
        return list(self.offsets)

    @property
    def neighbors(self) -> List[List[int]]:
        """Receiver-side adjacency list (API parity with Topology)."""
        n = self.num_nodes
        return [sorted((i + o) % n for o in self.offsets) for i in range(n)]

    def is_connected(self) -> bool:
        """Strong connectivity of a directed circulant:
        gcd(n, offsets...) == 1."""
        g = self.num_nodes
        for o in self.offsets:
            g = math.gcd(g, o)
        return g == 1


# ---------------------------------------------------------------------------
# Composition manifest (murmura_tpu/levers.py; `murmura check --compose`).
# The single source of truth for this lever's cross-feature verdicts —
# guard sites in config/schema.py and utils/factories.py cite
# refusal_reason() so user-facing messages and the analyzer's grid can
# never drift apart (MUR1400).
# ---------------------------------------------------------------------------
from murmura_tpu.levers import LeverManifest, composes, refuses

LEVER_MANIFEST = LeverManifest(
    name="sparse",
    module="murmura_tpu.topology.sparse",
    stage="murmura.exchange",
    verdicts={
        "adaptive": composes(),
        "compression": composes(),
        "dmtt": refuses(
            "sparse topologies do not compose with dmtt (claim "
            "verification needs the dense exchange graph)"
        ),
        "faults": composes(),
        "mobility": refuses(
            "sparse topologies do not compose with mobility (G^t is a "
            "dense per-round graph); drop the mobility block or use a "
            "dense topology"
        ),
        "pipeline": composes(),
        "population": composes(),
        "sharding": composes(),
    },
)

"""Deterministic random-walk mobility model for time-varying G^t
(reference: murmura/topology/dynamic.py:16-105).

Positions evolve by a bounded random step on a 2-D torus, lazily generated
from one seeded generator so every process — or every host feeding masks to
the jitted TPU round loop — reconstructs the identical G^t with zero
communication (reference: dynamic.py:1-8). Distance computation is
vectorized: one [N, N] torus-distance matrix per round instead of the
reference's per-pair Python loop (dynamic.py:68-72).
"""

from typing import Dict, List

import numpy as np


class MobilityModel:
    """Bounded random-walk mobility on a 2-D torus.

    Args:
        num_nodes: Number of mobile nodes.
        area_size: Side length of the square arena.
        comm_range: Edge (i,j) in G^t iff torus-dist(r_i, r_j) < comm_range.
        max_speed: Max displacement magnitude per round.
        seed: RNG seed for initial positions and movement.
        ensure_connected: Attach isolated nodes to their nearest peer.
    """

    def __init__(
        self,
        num_nodes: int,
        area_size: float = 100.0,
        comm_range: float = 30.0,
        max_speed: float = 5.0,
        seed: int = 42,
        ensure_connected: bool = True,
    ):
        self.num_nodes = num_nodes
        self.area_size = area_size
        self.comm_range = comm_range
        self.max_speed = max_speed
        self.ensure_connected = ensure_connected

        self._rng = np.random.default_rng(seed)
        pos0 = self._rng.uniform(0.0, area_size, size=(num_nodes, 2))
        self._positions: Dict[int, np.ndarray] = {0: pos0}

    def positions_at(self, round_idx: int) -> np.ndarray:
        """(N, 2) positions at round_idx (reference: dynamic.py:53-61)."""
        last = max(self._positions)
        for r in range(last, round_idx):
            delta = self._rng.uniform(
                -self.max_speed, self.max_speed, size=(self.num_nodes, 2)
            )
            self._positions[r + 1] = (self._positions[r] + delta) % self.area_size
        return self._positions[round_idx]

    def _torus_dist_matrix(self, pos: np.ndarray) -> np.ndarray:
        """Pairwise torus distances as one [N, N] array."""
        diff = np.abs(pos[:, None, :] - pos[None, :, :])  # [N, N, 2]
        diff = np.minimum(diff, self.area_size - diff)
        return np.sqrt((diff**2).sum(-1))

    def adjacency_at(self, round_idx: int) -> np.ndarray:
        """Dense boolean adjacency [N, N] of G^t — the round-step mask."""
        pos = self.positions_at(round_idx)
        dist = self._torus_dist_matrix(pos)
        adj = dist < self.comm_range
        np.fill_diagonal(adj, False)
        if self.ensure_connected:
            self._connect_isolated(adj, dist)
        return adj

    def neighbors_at(self, round_idx: int) -> Dict[int, List[int]]:
        """Adjacency-list view (reference: dynamic.py:63-77)."""
        adj = self.adjacency_at(round_idx)
        return {i: list(np.flatnonzero(adj[i])) for i in range(self.num_nodes)}

    def torus_dist(self, i: int, j: int, round_idx: int) -> float:
        """Torus distance between nodes i and j (reference: dynamic.py:79-82)."""
        pos = self.positions_at(round_idx)
        return float(self._torus_dist_matrix(pos)[i, j])

    def _connect_isolated(self, adj: np.ndarray, dist: np.ndarray) -> None:
        """Attach each isolated node to its nearest peer (reference: dynamic.py:95-105)."""
        n = self.num_nodes
        if n < 2:
            return
        d = dist + np.where(np.eye(n, dtype=bool), np.inf, 0.0)
        for i in range(n):
            if not adj[i].any():
                nearest = int(np.argmin(d[i]))
                adj[i, nearest] = adj[nearest, i] = True


# ---------------------------------------------------------------------------
# Composition manifest (murmura_tpu/levers.py; `murmura check --compose`).
# The single source of truth for this lever's cross-feature verdicts —
# guard sites in config/schema.py and utils/factories.py cite
# refusal_reason() so user-facing messages and the analyzer's grid can
# never drift apart (MUR1400).
# ---------------------------------------------------------------------------
from murmura_tpu.levers import LeverManifest, composes, refuses

LEVER_MANIFEST = LeverManifest(
    name="mobility",
    module="murmura_tpu.topology.dynamic",
    verdicts={
        "adaptive": composes(),
        "compression": composes(),
        # dmtt NEEDS mobility's deterministic G^t; the constraint fires
        # when dmtt is armed without it (and allow_static is unset).
        "dmtt": composes(
            requires_mobility=(
                "dmtt requires a mobility section (claim verification "
                "needs the deterministic G^t); set dmtt.allow_static: "
                "true to verify claims against the static topology "
                "instead"
            ),
        ),
        "faults": composes(),
    },
)

"""Topology type (reference: murmura/topology/base.py:7-60).

TPU-first design note: the primary representation here is the dense boolean
adjacency matrix ``adjacency[N, N]`` — that is the object the jitted round
step consumes directly as the neighbor mask of the all-gathered state tensor.
The reference's adjacency-list / edge-list views (base.py:17-19) are derived
properties kept for API parity.
"""

from dataclasses import dataclass, field
from typing import List, Tuple

import numpy as np


@dataclass
class Topology:
    """Undirected communication graph over ``num_nodes`` FL peers.

    Attributes:
        num_nodes: Number of nodes.
        adjacency: Dense boolean [N, N] matrix; ``adjacency[i, j]`` is True iff
            i and j exchange models. Symmetric with a False diagonal.
    """

    num_nodes: int
    adjacency: np.ndarray = field(repr=False)

    def __post_init__(self) -> None:
        adj = np.asarray(self.adjacency, dtype=bool)
        if adj.shape != (self.num_nodes, self.num_nodes):
            raise ValueError(
                f"adjacency shape {adj.shape} != ({self.num_nodes}, {self.num_nodes})"
            )
        if not np.array_equal(adj, adj.T):
            raise ValueError("adjacency must be symmetric (undirected graph)")
        np.fill_diagonal(adj, False)
        self.adjacency = adj

    # -- reference-parity views (murmura/topology/base.py:17-19) ------------

    @property
    def neighbors(self) -> List[List[int]]:
        """Adjacency list: neighbors[i] = sorted list of i's neighbor ids."""
        return [list(np.flatnonzero(row)) for row in self.adjacency]

    @property
    def edges(self) -> List[Tuple[int, int]]:
        """Sorted undirected edge list as (lo, hi) pairs."""
        ii, jj = np.nonzero(np.triu(self.adjacency, k=1))
        return sorted(zip(ii.tolist(), jj.tolist()))

    def degree(self, node_id: int) -> int:
        """Degree of one node (reference: base.py:26-35)."""
        return int(self.adjacency[node_id].sum())

    def avg_degree(self) -> float:
        """Average degree (reference: base.py:37-39)."""
        return float(self.adjacency.sum()) / max(1, self.num_nodes)

    def is_connected(self) -> bool:
        """Connectivity via boolean matrix-power reachability (reference: base.py:41-60)."""
        if self.num_nodes == 0:
            return True
        reach = np.zeros(self.num_nodes, dtype=bool)
        reach[0] = True
        for _ in range(self.num_nodes):
            new = reach | (self.adjacency @ reach)
            if np.array_equal(new, reach):
                break
            reach = new
        return bool(reach.all())

    def mask(self, dtype=np.float32) -> np.ndarray:
        """Adjacency as a numeric mask for the jitted aggregation step."""
        return self.adjacency.astype(dtype)

    def circulant_offsets(self) -> "List[int] | None":
        """Non-zero offsets o with adjacency[i, (i+o) % N] True for all i,
        or None if the graph is not circulant.

        Ring and k-regular graphs are generated as circulants; on such
        graphs the neighbor exchange can be a sum of fixed circular shifts
        (tpu.exchange: ppermute) instead of an adjacency matmul.
        """
        n = self.num_nodes
        if n == 0:
            return []
        offsets = [int(o) for o in np.flatnonzero(self.adjacency[0])]
        expected = np.zeros_like(self.adjacency)
        cols = (np.arange(n)[:, None] + np.array(offsets, dtype=int)[None, :]) % n
        expected[np.arange(n)[:, None], cols] = True
        if np.array_equal(expected, self.adjacency):
            return offsets
        return None

    @classmethod
    def from_neighbors(cls, num_nodes: int, neighbors: List[List[int]]) -> "Topology":
        """Build from an adjacency list (reference-style constructor)."""
        adj = np.zeros((num_nodes, num_nodes), dtype=bool)
        for i, ns in enumerate(neighbors):
            for j in ns:
                adj[i, j] = True
                adj[j, i] = True
        return cls(num_nodes=num_nodes, adjacency=adj)

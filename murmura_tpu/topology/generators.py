"""Topology generators (reference: murmura/topology/generators.py:11-140).

Same four families with the same structural semantics — ring, fully
connected, seeded Erdős–Rényi with isolated-node fixup, circulant k-regular
(odd k bumped to k+1; k >= n degenerates to fully connected) — generated
vectorized as dense adjacency matrices instead of edge-list loops.
"""

from typing import Optional

import numpy as np

from murmura_tpu.topology.base import Topology
from murmura_tpu.topology.sparse import SparseTopology, exponential_offsets

# Sparse (offset-list) families: create_topology returns a SparseTopology
# for these — the round program then takes a [k, N] edge mask instead of
# the dense [N, N] adjacency (topology/sparse.py; docs/SCALING.md).
SPARSE_TOPOLOGY_TYPES = ("exponential", "one_peer")
TOPOLOGY_TYPES = ("ring", "fully", "erdos", "k-regular") + SPARSE_TOPOLOGY_TYPES


def create_topology(
    topology_type: str,
    num_nodes: int,
    p: Optional[float] = None,
    k: Optional[int] = None,
    seed: int = 12345,
    **_ignored,
) -> "Topology | SparseTopology":
    """Create a topology by name (reference: generators.py:11-46)."""
    t = topology_type.lower()
    if t == "ring":
        return ring(num_nodes)
    if t in ("fully", "full"):
        return fully_connected(num_nodes)
    if t in ("erdos", "er", "erdos-renyi"):
        return erdos_renyi(num_nodes, 0.3 if p is None else p, seed)
    if t in ("k-regular", "kregular"):
        return k_regular(num_nodes, 4 if k is None else k)
    if t == "exponential":
        return exponential(num_nodes)
    if t in ("one_peer", "one-peer"):
        return one_peer(num_nodes)
    raise ValueError(f"Unknown topology type: {topology_type}")


def _circulant(n: int, offsets) -> np.ndarray:
    """Adjacency of a circulant graph: i ~ (i + o) mod n for each offset o."""
    idx = np.arange(n)
    adj = np.zeros((n, n), dtype=bool)
    for o in offsets:
        adj[idx, (idx + o) % n] = True
        adj[(idx + o) % n, idx] = True
    np.fill_diagonal(adj, False)
    return adj


def ring(n: int) -> Topology:
    """Ring: each node linked to its two cyclic neighbors (reference: generators.py:49-64)."""
    return Topology(num_nodes=n, adjacency=_circulant(n, [1]))


def fully_connected(n: int) -> Topology:
    """Complete graph (reference: generators.py:67-78)."""
    adj = np.ones((n, n), dtype=bool)
    np.fill_diagonal(adj, False)
    return Topology(num_nodes=n, adjacency=adj)


def erdos_renyi(n: int, p: float, seed: int = 12345) -> Topology:
    """Seeded ER graph; isolated node i is attached to (i+1) mod n
    (reference: generators.py:81-108)."""
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"Edge probability p must be in [0, 1], got {p}")
    rng = np.random.default_rng(seed)
    upper = rng.random((n, n)) < p
    adj = np.triu(upper, k=1)
    adj = adj | adj.T
    # Isolated-node fixup, in node order, as the reference does.
    for i in range(n):
        if not adj[i].any():
            j = (i + 1) % n
            if i != j:
                adj[i, j] = adj[j, i] = True
    return Topology(num_nodes=n, adjacency=adj)


def exponential(n: int) -> SparseTopology:
    """Static exponential graph (arXiv:2110.13363): directed circulant with
    offsets ``2^i mod n`` — degree O(log n) at any n, never ``[N, N]``.
    Offsets are deduped and a degenerate 0 offset is rejected loudly
    (:func:`murmura_tpu.topology.sparse.exponential_offsets`)."""
    return SparseTopology(num_nodes=n, offsets=exponential_offsets(n))


def one_peer(n: int) -> SparseTopology:
    """One-peer exponential graph (arXiv:2110.13363 §one-peer): the same
    offset set as :func:`exponential`, but only offset ``t mod k`` active
    in round ``t`` — per-round degree 1, cycling through the exponential
    offsets.  The activation arrives as edge-mask values, so one compiled
    program covers every round."""
    return SparseTopology(
        num_nodes=n, offsets=exponential_offsets(n), schedule="one_peer"
    )


def k_regular(n: int, k: int) -> Topology:
    """Circulant k-regular lattice: k/2 successors + k/2 predecessors
    (reference: generators.py:111-140)."""
    if k % 2 != 0:
        k = k + 1
    if k >= n:
        return fully_connected(n)
    return Topology(num_nodes=n, adjacency=_circulant(n, range(1, k // 2 + 1)))

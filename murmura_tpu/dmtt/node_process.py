"""DMTT trust-protocol node process for the ZMQ distributed backend
(reference: murmura/dmtt/node_process.py:53-406).

Extends ``NodeProcess`` with the 11-step DMTT round
(murmura/dmtt/node_process.py:150-250):

1.  local train (honest only)
2.  outgoing state (+ wrapped model attack, topology_liar.py:57-72)
3.  TOPO_CLAIM = true G^t neighbors, or the liar's falsified set — true
    neighbors UNION the Byzantine coalition (topology_liar.py:78-102)
4.  PUSH MODEL_STATE + TOPO_CLAIM to current collaborators C_i^t
5.  collect both message types until the round deadline, dropping
    unexpected senders (node_process.py:288-289)
6.  link-reliability EMA from who answered (state.py:53-57)
7.  score received neighbor models on local probe data: accuracy +
    Dirichlet vacuity (node_process.py:309-363)
8.  verify claims against the locally recomputed deterministic G^t,
    update Beta evidence with forgetting (node_process.py:369-395,
    state.py:63-76)
9.  aggregate with the received subset
10. TopB over collaboration scores -> C_i^{t+1} (state.py:128-142)
11. evaluate + METRICS to the monitor

Per-neighbor trust is held as scalar dicts; the trust formulas are the
same functions the jitted TPU path uses (murmura_tpu/dmtt/protocol.py),
applied to [N]-vectors here, so the two backends cannot drift apart.
"""

import time
from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from murmura_tpu.distributed.messaging import (
    MsgType,
    decode,
    encode,
    pack_obj,
    pack_state,
    unpack_obj,
    unpack_state,
)
from murmura_tpu.distributed.node_process import NodeProcess
from murmura_tpu.dmtt.protocol import (
    DMTTParams,
    collab_score,
    model_score,
    topo_trust,
)


class DMTTNodeProcess(NodeProcess):
    """One DMTT FL node in its own OS process."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.config.dmtt is None:
            raise ValueError("DMTTNodeProcess requires config.dmtt")
        self.dmtt = DMTTParams(**self.config.dmtt.model_dump(exclude={"allow_static"}))
        # Per-neighbor trust state (reference: state.py:42-47).
        self._c_hat: Dict[int, float] = {}
        self._alpha: Dict[int, float] = {}
        self._beta: Dict[int, float] = {}
        # None = no TopB selection yet -> use G^t directly
        # (reference: node_process.py:111-118).
        self._collaborators: Optional[List[int]] = None
        self._dmtt_stats: Dict[str, float] = {}
        self._static_truth: Optional[Dict[int, Set[int]]] = None

    # ------------------------------------------------------------------

    def _setup_sockets(self) -> None:
        """Pre-connect PUSH to every peer: under a dynamic topology any node
        may become a collaborator (reference: dmtt/node_process.py:103-105)."""
        super()._setup_sockets()
        for nid in range(self.config.topology.num_nodes):
            if nid != self.node_id:
                self._push_to(nid)

    def current_collaborators(self, round_idx: int) -> List[int]:
        """C_i^t: last TopB selection, or G^t neighbors before the first one."""
        if self._collaborators is None:
            return self.current_neighbors(round_idx)
        return list(self._collaborators)

    # ------------------------------------------------------------------

    def _execute_round(self, round_idx: int) -> None:
        """The 11-step DMTT round (reference: dmtt/node_process.py:150-250)."""
        deadline = self.t_start + (round_idx + 1) * self.round_duration
        true_neighbors = self.current_neighbors(round_idx)
        collaborators = self.current_collaborators(round_idx)

        # 1. local training (honest only)
        if not self.is_compromised:
            self.node.local_train(round_idx)

        if time.monotonic() >= deadline:
            print(
                f"[node {self.node_id}] round {round_idx}: training overran "
                "the round window; skipping exchange",
                flush=True,
            )
            self._send_metrics(round_idx, skipped=True)
            return

        # 2. outgoing state (+ model poisoning for liars with a wrapped attack)
        out_flat = self._attacked_state(self.node.get_flat_state(), round_idx)

        # 3. TOPO_CLAIM (liars claim the Byzantine coalition as neighbors)
        claim = self._make_claim(true_neighbors)

        # 4. PUSH state + claim to current collaborators
        state_payload = pack_state(out_flat)
        claim_payload = pack_obj({"neighbors": claim})
        for nid in collaborators:
            try:
                sock = self._push_to(nid)
                sock.send_multipart(
                    encode(MsgType.MODEL_STATE, self.node_id, state_payload,
                           round_idx),
                    copy=False,
                )
                sock.send_multipart(
                    encode(MsgType.TOPO_CLAIM, self.node_id, claim_payload,
                           round_idx)
                )
            except Exception as e:  # pragma: no cover - socket teardown races
                print(f"[node {self.node_id}] push to {nid} failed: {e}", flush=True)

        # 5. collect MODEL_STATE + TOPO_CLAIM until deadline
        expected = set(collaborators)
        states, claims = self._collect_states_and_claims(expected, round_idx, deadline)

        # 6. link-reliability EMA over the expected set (state.py:53-57)
        for nid in expected:
            ack = 1.0 if nid in states else 0.0
            prev = self._c_hat.get(nid, 0.5)
            self._c_hat[nid] = (1.0 - self.dmtt.rho) * prev + self.dmtt.rho * ack

        # 7. score received models on local probe data (node_process.py:309-363)
        scores: Dict[int, float] = {}
        for nid, flat in states.items():
            probe = self.node.probe_eval_flat(flat)
            scores[nid] = float(
                model_score(
                    np.float32(probe["accuracy"]),
                    np.float32(probe["vacuity"]),
                    self.dmtt,
                )
            )

        # 8. verify claims vs the locally recomputed G^t -> Beta trust
        self._verify_claims(claims, round_idx)

        # 9. aggregate with whatever arrived (partial OK)
        if states:
            self.node.aggregate_with_neighbors(states, round_idx)

        # 10. TopB collaborator selection over direct G^t neighbors
        self._select_collaborators(true_neighbors, scores)

        # 11. evaluate + metrics
        self._dmtt_stats = {
            "dmtt_collab_count": float(len(self._collaborators or [])),
            "dmtt_received_count": float(len(states)),
            "dmtt_mean_topo_trust": self._mean_topo_trust(true_neighbors),
        }
        self._send_metrics(round_idx, skipped=False)

    # ------------------------------------------------------------------

    def _make_claim(self, true_neighbors: List[int]) -> List[int]:
        """Honest claim = true G^t neighbors; compromised nodes get theirs
        from the attack's claims_fn — the SAME [N, N] transform the jitted
        backend applies (reference: topology_liar.py:78-102), evaluated here
        for this node's row so the two backends emit identical claims."""
        if (
            self.is_compromised
            and self.attack is not None
            and self.attack.claims_fn is not None
        ):
            n = self.config.topology.num_nodes
            adj_row = np.zeros((n, n), np.float32)
            adj_row[self.node_id, true_neighbors] = 1.0
            comp_mask = np.zeros((n,), np.float32)
            comp_mask[sorted(self.compromised_ids)] = 1.0
            claimed = np.asarray(self.attack.claims_fn(adj_row, comp_mask))
            return sorted(int(j) for j in np.flatnonzero(claimed[self.node_id]))
        return sorted(true_neighbors)

    def _collect_states_and_claims(
        self, expected: Set[int], round_idx: int, deadline: float
    ) -> Tuple[Dict[int, np.ndarray], Dict[int, List[int]]]:
        """PULL both message types until every expected collaborator delivered
        both, or the deadline (reference: dmtt/node_process.py:256-303)."""
        import zmq

        states: Dict[int, np.ndarray] = {}
        claims: Dict[int, List[int]] = {}
        poller = zmq.Poller()
        poller.register(self._pull, zmq.POLLIN)
        while (
            (expected - set(states)) or (expected - set(claims))
        ) and time.monotonic() < deadline:
            timeout_ms = max(1, int((deadline - time.monotonic()) * 1000))
            events = dict(poller.poll(min(timeout_ms, 200)))
            if self._pull not in events:
                continue
            msg_type, sender, msg_round, payload = decode(
                self._pull.recv_multipart()
            )
            # drop unexpected senders (node_process.py:288-289) and
            # stragglers from earlier round windows (header round tag)
            if sender not in expected or msg_round != round_idx:
                continue
            if msg_type == MsgType.MODEL_STATE:
                states[sender] = unpack_state(payload)
            elif msg_type == MsgType.TOPO_CLAIM:
                claims[sender] = list(unpack_obj(payload).get("neighbors", []))
        return states, claims

    def _verify_claims(
        self, claims: Dict[int, List[int]], round_idx: int
    ) -> None:
        """d_j / x_j = confirmations / contradictions of j's claim vs the
        locally recomputed G^t; Beta update with forgetting, floored at 0.01
        (reference: dmtt/node_process.py:369-395, state.py:63-76)."""
        p = self.dmtt
        if self.mobility is not None:
            truth = {
                i: set(ns)
                for i, ns in self.mobility.neighbors_at(round_idx).items()
            }
        else:
            truth = self._static_ground_truth()
        for nid, claimed in claims.items():
            true_set = truth[nid]
            claimed_set = set(claimed) - {nid}
            d = float(len(claimed_set & true_set))
            x = float(len(claimed_set - true_set))
            alpha = p.lambda_forget * self._alpha.get(nid, 1.0) + p.w_d * d
            beta = p.lambda_forget * self._beta.get(nid, 1.0) + p.w_x * x
            self._alpha[nid] = max(0.01, alpha)
            self._beta[nid] = max(0.01, beta)

    def _static_ground_truth(self) -> Dict[int, Set[int]]:
        """Static topology: G^t is the fixed graph, recomputed once from the
        shared seed (every process reconstructs the same graph)."""
        if self._static_truth is None:
            from murmura_tpu.topology.generators import create_topology

            cfg = self.config.topology
            topo = create_topology(
                cfg.type, num_nodes=cfg.num_nodes, p=cfg.p, k=cfg.k,
                seed=cfg.seed,
            )
            self._static_truth = {
                i: set(ns) for i, ns in enumerate(topo.neighbors)
            }
        return self._static_truth

    def _select_collaborators(
        self,
        true_neighbors: List[int],
        scores: Dict[int, float],
    ) -> None:
        """TopB over q = λ1·s_model + λ2·T^topo + λ3·ĉ − λ4·c_comm among
        direct G^t neighbors (reference: dmtt/node_process.py:235-241,
        state.py:112-142)."""
        p = self.dmtt
        if not true_neighbors:
            self._collaborators = []
            return
        cand = np.asarray(true_neighbors)
        alpha = np.array([self._alpha.get(j, 1.0) for j in cand], np.float32)
        beta = np.array([self._beta.get(j, 1.0) for j in cand], np.float32)
        c_hat = np.array([self._c_hat.get(j, 0.5) for j in cand], np.float32)
        # default model score 0.5 where no model arrived (state.py:139)
        s_model = np.array([scores.get(j, 0.5) for j in cand], np.float32)
        t = np.asarray(topo_trust(alpha, beta, p))
        q = np.asarray(collab_score(s_model, t, c_hat, p))
        top = np.argsort(-q)[: p.budget_B]
        self._collaborators = sorted(int(cand[i]) for i in top)

    def _mean_topo_trust(self, true_neighbors: List[int]) -> float:
        if not true_neighbors:
            return 0.0
        p = self.dmtt
        alpha = np.array([self._alpha.get(j, 1.0) for j in true_neighbors], np.float32)
        beta = np.array([self._beta.get(j, 1.0) for j in true_neighbors], np.float32)
        return float(np.asarray(topo_trust(alpha, beta, p)).mean())

    def _send_metrics(self, round_idx: int, skipped: bool) -> None:
        metrics = {"round": round_idx, "node": self.node_id, "skipped": skipped}
        if skipped:
            self._counters["rounds_skipped"] += 1
        else:
            metrics.update(self.node.evaluate())
            stats = self.node.get_aggregator_statistics()
            stats.update(self._dmtt_stats)
            metrics["stats"] = stats
        metrics["compromised"] = self.is_compromised
        # Same cumulative counter stream as the base NodeProcess
        # (docs/OBSERVABILITY.md) — the monitor folds the last totals.
        metrics["counters"] = dict(self._counters)
        try:
            self._monitor_push.send_multipart(
                encode(MsgType.METRICS, self.node_id, pack_obj(metrics), round_idx)
            )
        except Exception as e:  # pragma: no cover
            print(f"[node {self.node_id}] metrics push failed: {e}", flush=True)

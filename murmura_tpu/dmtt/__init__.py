"""DMTT — dynamic-mobility topology trust protocol.

TPU-native redesign of the reference's per-process trust bookkeeping
(reference: murmura/dmtt/state.py:22-159, murmura/dmtt/node_process.py:53-406).
All per-(observer, subject) quantities are [N, N] arrays carried through the
jitted round step; claim exchange, verification, Beta-evidence updates, and
TopB collaborator selection are pure array transforms.
"""

from murmura_tpu.dmtt.protocol import (
    DMTTParams,
    collab_score,
    dmtt_round_update,
    init_dmtt_state,
    model_score,
    topo_trust,
)

__all__ = [
    "DMTTParams",
    "collab_score",
    "dmtt_round_update",
    "init_dmtt_state",
    "model_score",
    "topo_trust",
]

"""DMTT trust protocol as pure array transforms.

The reference tracks, per node i, dicts keyed by neighbor j: link-reliability
EMA ĉ_ij, Beta-evidence (α_ij, β_ij), and derives topo trust, model score and
a collaboration score used for TopB collaborator selection
(murmura/dmtt/state.py:22-159).  Claims are verified against the locally
recomputed deterministic mobility graph G^t
(murmura/dmtt/node_process.py:369-395).

Here every directed-edge quantity is one [N, N] array (entry [i, j] = what
observer i believes about subject j) and the whole 11-step DMTT round
(murmura/dmtt/node_process.py:150-250) reduces to a handful of masked array
updates that trace into the jitted round step.  The "send to collaborators /
collect from expected" ZMQ exchange becomes a single effective-exchange mask
E = C ∧ Cᵀ over the gathered state tensor: node j's broadcast reaches node i
iff j sends to i (i ∈ C_j) and i expects it (j ∈ C_i) — the same acceptance
rule the reference applies when it drops unexpected senders
(murmura/dmtt/node_process.py:288-289).
"""

from dataclasses import dataclass
from typing import Dict, Tuple

import jax.numpy as jnp

AggState = Dict[str, jnp.ndarray]


@dataclass(frozen=True)
class DMTTParams:
    """Static DMTT hyperparameters (reference: murmura/config/schema.py:114-139)."""

    budget_B: int = 5
    rho: float = 0.1
    lambda_forget: float = 0.9
    w_d: float = 1.0
    # w_c (corroboration) and collab_score's c_comm exist as tunables in the
    # reference schema but its round loop never feeds them non-default values
    # (reference: state.py:68+116 defaults, node_process.py:395 passes only
    # d/x) — kept for config parity, inert by the same design.
    w_c: float = 0.5
    w_x: float = 1.0
    tau_U: float = 0.3
    eta: float = 5.0
    w_a: float = 0.7
    tau_u: float = 0.5
    lambda1: float = 0.4
    lambda2: float = 0.3
    lambda3: float = 0.2
    lambda4: float = 0.1


def init_dmtt_state(num_nodes: int) -> AggState:
    """Initial trust state (reference: murmura/dmtt/state.py:42-47).

    ``dmtt_selected`` is the explicit no-selection-yet flag (the reference's
    ``self._collaborators is None``, murmura/dmtt/node_process.py:111-118):
    while 0 the round uses the G^t adjacency directly, and the first TopB
    selection sets it — so a legitimately empty TopB result (e.g. a round
    with no physical neighbors under mobility) is NOT confused with "never
    selected".  Keying on carried state (not the round index) keeps a
    resumed ``train()`` call from discarding the learned selection.
    """
    n = num_nodes
    return {
        "dmtt_c_hat": jnp.full((n, n), 0.5, jnp.float32),
        "dmtt_alpha": jnp.ones((n, n), jnp.float32),
        "dmtt_beta": jnp.ones((n, n), jnp.float32),
        "dmtt_collab": jnp.zeros((n, n), jnp.float32),
        "dmtt_selected": jnp.zeros((), jnp.float32),
    }


def topo_trust(
    alpha: jnp.ndarray, beta: jnp.ndarray, p: DMTTParams
) -> jnp.ndarray:
    """T^topo = R · exp(-η · max(0, U - τ_U)) with R the Beta posterior mean
    and U the posterior std (reference: murmura/dmtt/state.py:82-94)."""
    s = alpha + beta
    r = alpha / s
    u = jnp.sqrt(jnp.maximum(0.0, alpha * beta / (s * s * (s + 1.0))))
    return r * jnp.exp(-p.eta * jnp.maximum(0.0, u - p.tau_U))


def model_score(
    accuracy: jnp.ndarray, u_bar: jnp.ndarray, p: DMTTParams
) -> jnp.ndarray:
    """s^model = (1-ū)(w_a·a + (1-w_a)), penalized ×exp(-(ū-τ_u)) above the
    uncertainty threshold, floored at 0 (reference: murmura/dmtt/state.py:100-110)."""
    s_base = (1.0 - u_bar) * (p.w_a * accuracy + (1.0 - p.w_a))
    s_base = jnp.where(
        u_bar > p.tau_u, s_base * jnp.exp(-(u_bar - p.tau_u)), s_base
    )
    return jnp.maximum(0.0, s_base)


def collab_score(
    s_model: jnp.ndarray,
    t_topo: jnp.ndarray,
    c_hat: jnp.ndarray,
    p: DMTTParams,
    c_comm: float = 0.0,
) -> jnp.ndarray:
    """q = λ1·s_model + λ2·T^topo + λ3·ĉ - λ4·c_comm
    (reference: murmura/dmtt/state.py:112-122)."""
    return (
        p.lambda1 * s_model
        + p.lambda2 * t_topo
        + p.lambda3 * c_hat
        - p.lambda4 * c_comm
    )


def _top_b_mask(q: jnp.ndarray, valid: jnp.ndarray, b: int) -> jnp.ndarray:
    """Row-wise B-hot mask of the highest-q valid candidates
    (reference: murmura/dmtt/state.py:128-142).  Rows with fewer than B valid
    candidates keep them all."""
    masked = jnp.where(valid, q, -jnp.inf)
    order = jnp.argsort(-masked, axis=-1)
    ranks = jnp.argsort(order, axis=-1)
    return (valid & (ranks < b)).astype(jnp.float32)


def dmtt_round_update(
    state: AggState,
    adj: jnp.ndarray,
    claims: jnp.ndarray,
    probe_accuracy: jnp.ndarray,
    probe_vacuity: jnp.ndarray,
    p: DMTTParams,
) -> Tuple[jnp.ndarray, AggState, Dict[str, jnp.ndarray]]:
    """One DMTT round over the whole network.

    Mirrors steps 5-10 of the reference round
    (murmura/dmtt/node_process.py:208-241):

    1. effective collaborators C (no selection yet → G^t rows), exchange
       mask E = C∧Cᵀ;
    2. link-reliability EMA over the expected set (state.py:53-57) — on ICI
       every sent message arrives, so ack ≡ E;
    3. claim verification against G^t: d_j / x_j count subject j's claimed
       edges that match / contradict the true row (node_process.py:369-395)
       — identical for every observer, so computed once per subject;
    4. Beta-evidence update with forgetting, floored at 0.01, applied only on
       edges that received a claim (state.py:63-76);
    5. model-compatibility scores from the batched probe cross-eval, default
       0.5 where no model arrived (node_process.py:221-225, state.py:139);
    6. TopB over the *direct* G^t neighbors → C^{t+1}
       (node_process.py:235-241).

    Args:
        state: dict with dmtt_c_hat / dmtt_alpha / dmtt_beta / dmtt_collab.
        adj: [N, N] true G^t adjacency (0/1 float).
        claims: [N, N] claimed adjacency; row j is subject j's TOPO_CLAIM.
        probe_accuracy: [N, N], entry [i, j] = accuracy of model j on node
            i's probe data.
        probe_vacuity: [N, N] mean vacuity, zeros for softmax models.
        p: hyperparameters.

    Returns:
        (exchange_mask E [N, N] float, new state, per-node stats dict).
    """
    adj_b = adj > 0
    collab = state["dmtt_collab"]
    # No TopB selection has happened yet — use G^t directly.
    collab_eff = jnp.where(state["dmtt_selected"] > 0, collab, adj)
    collab_b = collab_eff > 0
    exchange = collab_b & collab_b.T

    # --- link reliability (expected = C_i row; received ≡ exchange) --------
    ack = exchange.astype(jnp.float32)
    c_hat = jnp.where(
        collab_b,
        (1.0 - p.rho) * state["dmtt_c_hat"] + p.rho * ack,
        state["dmtt_c_hat"],
    )

    # --- claim verification (per subject j, same for all observers) --------
    claims_b = claims > 0
    d = jnp.sum(claims_b & adj_b, axis=1).astype(jnp.float32)  # [N]
    x = jnp.sum(claims_b & ~adj_b, axis=1).astype(jnp.float32)  # [N]

    alpha_new = p.lambda_forget * state["dmtt_alpha"] + p.w_d * d[None, :]
    beta_new = p.lambda_forget * state["dmtt_beta"] + p.w_x * x[None, :]
    alpha = jnp.where(exchange, jnp.maximum(0.01, alpha_new), state["dmtt_alpha"])
    beta = jnp.where(exchange, jnp.maximum(0.01, beta_new), state["dmtt_beta"])

    # --- scores + TopB over direct G^t neighbors ---------------------------
    s_model = model_score(probe_accuracy, probe_vacuity, p)
    s_model = jnp.where(exchange, s_model, 0.5)
    t = topo_trust(alpha, beta, p)
    q = collab_score(s_model, t, c_hat, p)
    candidates = adj_b & ~jnp.eye(adj.shape[0], dtype=bool)
    collab_next = _top_b_mask(q, candidates, p.budget_B)

    new_state = {
        "dmtt_c_hat": c_hat,
        "dmtt_alpha": alpha,
        "dmtt_beta": beta,
        "dmtt_collab": collab_next,
        "dmtt_selected": jnp.ones((), jnp.float32),
    }
    stats = {
        "dmtt_collab_count": collab_next.sum(axis=1),
        "dmtt_received_count": ack.sum(axis=1),
        "dmtt_mean_topo_trust": (t * candidates).sum(axis=1)
        / jnp.maximum(candidates.sum(axis=1), 1.0),
    }
    return ack, new_state, stats


# ---------------------------------------------------------------------------
# Composition manifest (murmura_tpu/levers.py; `murmura check --compose`).
# The single source of truth for this lever's cross-feature verdicts —
# guard sites in config/schema.py and utils/factories.py cite
# refusal_reason() so user-facing messages and the analyzer's grid can
# never drift apart (MUR1400).
# ---------------------------------------------------------------------------
from murmura_tpu.levers import LeverManifest, composes, refuses

LEVER_MANIFEST = LeverManifest(
    name="dmtt",
    module="murmura_tpu.dmtt.protocol",
    # DMTT_STATE_KEYS lives in core/rounds.py (the program owns the
    # trust carry); the group name is what MUR1400 resolves.
    state_keys_group="DMTT_STATE_KEYS",
    stage="murmura.exchange",
    verdicts={
        "adaptive": refuses(
            "adaptive attacks do not compose with dmtt (the claims "
            "channel is a second feedback path the adaptation state "
            "does not model)"
        ),
        "compression": refuses(
            "compression does not compose with dmtt (claim "
            "cross-evaluation consumes the uncompressed broadcast)"
        ),
    },
)

"""Host-side per-user state bank: the persistent half of cohort streaming.

One flat float row per virtual user holds that user's model parameters
between activations.  The bank is the *host* side of the streaming design
(docs/SCALING.md): the device only ever holds the active cohort's
``[N, P]`` rows; everything else lives here, memory-mapped so a
1M-user x P-param population costs disk pages only for users that have
actually been activated (the file is created sparse and rows are touched
lazily), never resident RAM.

Initialization is lazy: a user that has never been activated has no row
yet — ``gather`` fills their slot from the caller's default rows (the
round program's seed-derived slot init), and the row becomes persistent on
the first ``scatter`` (write-back after training).  Two users first
activated in the same cohort slot therefore start from the same slot init;
their rows diverge from the first round on and persist individually — the
Teleportation-style virtual-population semantics (arXiv:2501.15259).
"""

import os
import tempfile
from typing import Optional

import numpy as np

# Populations whose full bank fits comfortably in RAM skip the memmap
# (and its TemporaryDirectory) entirely.
_IN_MEMORY_BYTES = 256 * 1024 * 1024


class PopulationBank:
    """[virtual_size, row_dim] lazily-initialized per-user row store.

    Args:
        virtual_size: number of virtual users U.
        row_dim: flat parameter dimension P per user.
        dtype: row dtype (the resident param dtype of the round program).
        directory: where the memory-mapped backing file lives; ``None``
            uses RAM for small banks and a TemporaryDirectory (cleaned up
            with the bank) for large ones.
    """

    def __init__(
        self,
        virtual_size: int,
        row_dim: int,
        dtype=np.float32,
        directory: Optional[str] = None,
    ):
        if virtual_size < 1:
            raise ValueError(f"virtual_size must be >= 1, got {virtual_size}")
        if row_dim < 1:
            raise ValueError(f"row_dim must be >= 1, got {row_dim}")
        self.virtual_size = int(virtual_size)
        self.row_dim = int(row_dim)
        self.dtype = np.dtype(dtype)
        nbytes = self.virtual_size * self.row_dim * self.dtype.itemsize
        self._tmpdir = None
        # Whether an existing backing file was adopted instead of created
        # — the durability resume path requires this for external banks.
        self.reattached = False
        if directory is None and nbytes <= _IN_MEMORY_BYTES:
            self.path = None
            self._rows = np.zeros(
                (self.virtual_size, self.row_dim), self.dtype
            )
        else:
            if directory is None:
                self._tmpdir = tempfile.TemporaryDirectory(
                    prefix="murmura_population_"
                )
                directory = self._tmpdir.name
            os.makedirs(directory, exist_ok=True)
            self.path = os.path.join(directory, "bank.dat")
            # A pre-existing file of the right size is REATTACHED ("r+")
            # instead of truncated — the durability resume path
            # (durability/snapshot.py) re-opens a flushed bank in place.
            # Stale rows in a reused directory are harmless: nothing reads
            # a row until its user is marked in ``_has_row``, which starts
            # all-False and is restored separately on resume.
            nominal = self.virtual_size * self.row_dim * self.dtype.itemsize
            existing = (
                os.path.getsize(self.path)
                if os.path.exists(self.path) else None
            )
            if existing is not None and existing != nominal:
                # mode="w+" would ftruncate a file that may be the flushed
                # row data of a live snapshot (durability/snapshot.py
                # "external" mode) — a config whose virtual_size/model
                # changed must refuse BEFORE destroying it, not after a
                # restore-time validation that would come too late.
                raise ValueError(
                    f"population bank {self.path} holds {existing} bytes "
                    f"but this config needs {nominal} "
                    f"({self.virtual_size} users x {self.row_dim} f32) — "
                    "refusing to truncate an existing bank; point "
                    "population.bank_dir at a clean directory or restore "
                    "the matching config"
                )
            reattach = existing is not None
            self.reattached = reattach
            # mode="w+" ftruncates to the nominal size; the file is sparse,
            # so disk/page-cache cost follows *touched* rows, not U x P.
            self._rows = np.memmap(
                self.path, dtype=self.dtype, mode="r+" if reattach else "w+",
                shape=(self.virtual_size, self.row_dim),
            )
        # Which users have a persistent row (first write-back sets it).
        self._has_row = np.zeros(self.virtual_size, dtype=bool)

    @property
    def activated(self) -> int:
        """Users with a persistent row (ever written back)."""
        return int(self._has_row.sum())

    def gather(self, users: np.ndarray, default_rows: np.ndarray) -> np.ndarray:
        """[C, P] rows for ``users``; slot ``j`` of a never-activated user
        falls back to ``default_rows[j]`` (the slot's seed init)."""
        users = np.asarray(users, dtype=np.int64)
        if users.min(initial=0) < 0 or users.max(initial=0) >= self.virtual_size:
            raise IndexError(
                f"user ids out of range [0, {self.virtual_size})"
            )
        out = np.array(default_rows, dtype=self.dtype, copy=True)
        known = self._has_row[users]
        if known.any():
            out[known] = self._rows[users[known]]
        return out

    def scatter(self, users: np.ndarray, rows: np.ndarray) -> None:
        """Write back ``rows`` for ``users``; marks them persistent."""
        users = np.asarray(users, dtype=np.int64)
        self._rows[users] = np.asarray(rows, dtype=self.dtype)
        self._has_row[users] = True

    def has_rows(self, users: np.ndarray) -> np.ndarray:
        """[C] bool: which of ``users`` have a persistent row."""
        return self._has_row[np.asarray(users, dtype=np.int64)].copy()

    def rows_of(self, users: np.ndarray) -> np.ndarray:
        """Raw rows (no default fallback) — test/inspection helper."""
        return np.array(self._rows[np.asarray(users, dtype=np.int64)])

    @property
    def activated_users(self) -> np.ndarray:
        """[activated] int64 ids of users with a persistent row."""
        return np.flatnonzero(self._has_row).astype(np.int64)

    def flush(self) -> None:
        """Push dirty pages to the backing file (memmap-backed banks;
        no-op in RAM) — the cheap half of a snapshot: the rows stay in
        place, only the activation mask rides the snapshot payload."""
        if self.path is not None:
            self._rows.flush()

    def restore_activation(self, has_row: np.ndarray) -> None:
        """Adopt a restored activation mask (durability resume)."""
        has_row = np.asarray(has_row, dtype=bool)
        if has_row.shape != (self.virtual_size,):
            raise ValueError(
                f"activation mask shape {has_row.shape} != "
                f"({self.virtual_size},)"
            )
        self._has_row = has_row.copy()

    def close(self) -> None:
        if self._tmpdir is not None:
            # Drop the memmap before the directory vanishes.
            self._rows = None
            self._tmpdir.cleanup()
            self._tmpdir = None

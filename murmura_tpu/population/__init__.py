"""Population engine: sampled-cohort streaming for virtual populations far
larger than the compiled node axis (ISSUE 6; docs/SCALING.md).

- :mod:`sampler` — seed-deterministic cohort draws (``SAMPLERS`` registry,
  MUR602-pinned against the config schema enum);
- :mod:`bank` — memory-mapped, lazily-initialized per-user model rows;
- :mod:`engine` — the cohort-streaming orchestrator
  (:class:`PopulationNetwork`) with double-buffered swap staging.
"""

from murmura_tpu.population.bank import PopulationBank
from murmura_tpu.population.engine import PopulationNetwork, PopulationSpec
from murmura_tpu.population.sampler import SAMPLERS, draw_cohort

__all__ = [
    "PopulationBank",
    "PopulationNetwork",
    "PopulationSpec",
    "SAMPLERS",
    "draw_cohort",
]

"""Seed-deterministic cohort sampling over a virtual population.

Every draw is a pure function of ``(seed, draw_index)`` — two processes
(or a crashed-and-restarted one) reconstruct the identical cohort sequence
with zero communication, the same determinism contract the fault schedule
(faults/schedule.py) and the mobility model already carry.  numpy's
``SeedSequence([seed, draw_idx])`` keys an independent, collision-resistant
stream per draw, so draw ``r`` never depends on having generated draws
``0..r-1`` first (a resumed run at round 1000 pays O(1), not O(rounds)).

Samplers (the ``population.sampler`` schema enum — MUR602 pins the
bijection with this registry):

- ``uniform``: cohort drawn uniformly without replacement from all U users.
- ``stratified``: the user-id space is split into ``cohort_size``
  contiguous strata and one user drawn per stratum — every region of the
  population is touched every round, and slot ``j`` always hosts a user
  from stratum ``j`` (useful when user ids encode a meaningful partition,
  e.g. geography or device class).
"""

from typing import Callable, Dict

import numpy as np


def _rng(seed: int, draw_idx: int) -> np.random.Generator:
    return np.random.default_rng(np.random.SeedSequence([int(seed), int(draw_idx)]))


def uniform_cohort(
    virtual_size: int, cohort_size: int, draw_idx: int, seed: int
) -> np.ndarray:
    """[cohort_size] int64 user ids, uniform without replacement."""
    return _rng(seed, draw_idx).choice(
        virtual_size, size=cohort_size, replace=False
    ).astype(np.int64)


def stratified_cohort(
    virtual_size: int, cohort_size: int, draw_idx: int, seed: int
) -> np.ndarray:
    """[cohort_size] int64 user ids, one per contiguous id stratum."""
    bounds = np.linspace(0, virtual_size, cohort_size + 1).astype(np.int64)
    rng = _rng(seed, draw_idx)
    lo, hi = bounds[:-1], bounds[1:]
    # Every stratum is non-empty (virtual_size >= cohort_size, schema-
    # validated), so hi > lo holds and the draw is well-defined.
    return (lo + rng.integers(0, hi - lo)).astype(np.int64)


SAMPLERS: Dict[str, Callable[[int, int, int, int], np.ndarray]] = {
    "uniform": uniform_cohort,
    "stratified": stratified_cohort,
}


def draw_cohort(
    sampler: str, virtual_size: int, cohort_size: int, draw_idx: int, seed: int
) -> np.ndarray:
    """One cohort draw — pure in (sampler, sizes, draw_idx, seed)."""
    if sampler not in SAMPLERS:
        raise ValueError(
            f"unknown population sampler {sampler!r} "
            f"(registered: {sorted(SAMPLERS)})"
        )
    if not 0 < cohort_size <= virtual_size:
        raise ValueError(
            f"cohort_size={cohort_size} must be in (0, virtual_size="
            f"{virtual_size}]"
        )
    return SAMPLERS[sampler](virtual_size, cohort_size, draw_idx, seed)

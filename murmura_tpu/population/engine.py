"""Cohort-streaming orchestrator: millions of virtual users through one
fixed-size compiled round program.

:class:`PopulationNetwork` extends the standard orchestrator
(core/network.py) with the sampled-activation loop (docs/SCALING.md):

- the compiled round program is EXACTLY the plain N-node program — cohort
  membership arrives as input *values* (param rows, data rows), never as
  structure, so one compile covers the whole population (the fault-mask
  mechanism, MUR302; the battery's ``--population`` pre-flight pins zero
  recompiles across cohort swaps);
- per-user model rows persist in a host-side :class:`PopulationBank`
  (memory-mapped, lazily initialized);
- cohort draws are a pure function of ``(population.seed, draw_index)``
  (population/sampler.py) — restartable and process-agreeing;
- double-buffered staging: while round ``r`` executes on device
  (dispatch is async), the host gathers round ``r+1``'s cohort rows from
  the bank and issues their H2D transfer, so the swap cost hides behind
  compute.  The only forced sync is the write-back ``device_get`` of the
  outgoing cohort at the swap boundary.

The bank stores rows as float32 regardless of the resident param dtype:
bf16 -> f32 -> bf16 round-trips are exact, numpy memmaps want a native
dtype, and the bank's disk pages are host-side where the bf16 HBM argument
does not apply.
"""

import time
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from murmura_tpu.core.network import Network
from murmura_tpu.ops.flatten import make_flatteners
from murmura_tpu.population.bank import PopulationBank
from murmura_tpu.population.sampler import draw_cohort


@dataclass(frozen=True)
class PopulationSpec:
    """Validated population settings (config/schema.py PopulationConfig)."""

    virtual_size: int
    sampler: str = "uniform"
    seed: int = 1234
    rounds_per_cohort: int = 1
    data_binding: str = "user"
    bank_dir: Optional[str] = None
    # First-activation model: "teleport" (a fresh user adopts the OUTGOING
    # cohort's trained slot model — arXiv:2501.15259's mechanism, the
    # reason a 1M-population run with near-zero re-activation still
    # accumulates learning) or "slot_init" (isolated per-user models from
    # the slot's seed init).
    inherit: str = "teleport"


class PopulationNetwork(Network):
    """Network whose node axis hosts a round-sampled cohort of a larger
    virtual population."""

    def __init__(self, *args, population: PopulationSpec, **kwargs):
        super().__init__(*args, **kwargs)
        self.population = population
        n = self.program.num_nodes
        if population.virtual_size < n:
            raise ValueError(
                f"virtual_size={population.virtual_size} < cohort size {n}"
            )

        template = jax.tree_util.tree_map(
            lambda l: l[0], self.program.init_params
        )
        ravel, unravel, self._flat_dim = make_flatteners(template)
        # Warmed here (one tiny compile each) so the per-round recompile
        # guard never attributes a swap-time compile to a training round.
        self._ravel_all = jax.jit(jax.vmap(ravel))
        self._unravel_all = jax.jit(jax.vmap(unravel))
        slot_flat = jax.device_get(self._ravel_all(self.program.init_params))
        self._flat_dtype = slot_flat.dtype
        # Per-slot seed-init rows: a user's first activation starts from
        # the init of the slot it lands in (bank.py module docstring).
        self._slot_init = np.asarray(slot_flat, dtype=np.float32)
        jax.block_until_ready(
            self._unravel_all(jnp.asarray(self._slot_init, self._flat_dtype))
        )

        self.bank = PopulationBank(
            population.virtual_size, self._flat_dim,
            dtype=np.float32, directory=population.bank_dir,
        )
        # Set the first time THIS instance flushes the bank into a
        # snapshot — the in-place-restore credential the validate hook
        # checks (a fresh process must instead reattach the flushed file).
        self._bank_flushed_here = False
        # Teleport composition (docs/SCALING.md): banked users resume
        # their own row, fresh users adopt the outgoing cohort's trained
        # slot row — composed ON DEVICE so the prefetched H2D copies stay
        # overlapped and no extra device_get is forced.  Warmed here so
        # the recompile guard never sees its compile inside a round.
        self._compose = jax.jit(
            lambda known, rows, current: jnp.where(known, rows, current)
        )
        jax.block_until_ready(
            self._compose(
                jnp.zeros((n, 1), bool),
                jnp.asarray(self._slot_init, self._flat_dtype),
                jnp.asarray(self._slot_init, self._flat_dtype),
            )
        )
        # Pristine host copy of the [N, ...] data arrays for user-bound
        # re-staging at swaps (rank-0 hp_* scalars and any non-node-leading
        # array are never rebound).
        self._host_data = {
            k: np.asarray(v) for k, v in self.program.data_arrays.items()
        }
        self.cohort: Optional[np.ndarray] = None
        self.cohorts_seen = 0
        self._prefetched = None  # (draw_idx, cohort, host_rows, dev_rows)

    # ------------------------------------------------------------------

    def _draw(self, draw_idx: int) -> np.ndarray:
        return draw_cohort(
            self.population.sampler,
            self.population.virtual_size,
            self.program.num_nodes,
            draw_idx,
            self.population.seed,
        )

    def _stage_cohort_rows(self, cohort: np.ndarray):
        """(dev_rows, dev_known) for a cohort: banked rows (slot seed-init
        placeholders where unbanked) plus the banked mask, both staged to
        device."""
        host_rows = self.bank.gather(cohort, self._slot_init)
        dev_rows = jax.device_put(
            jnp.asarray(host_rows).astype(self._flat_dtype)
        )
        dev_known = jax.device_put(
            jnp.asarray(self.bank.has_rows(cohort)[:, None])
        )
        return dev_rows, dev_known

    def _prefetch(self, draw_idx: int) -> None:
        """Stage the next cohort's rows while the current round computes:
        the bank gather is host work and ``device_put`` is an async H2D
        copy, both overlapping the in-flight device dispatch."""
        cohort = self._draw(draw_idx)
        self._prefetched = (draw_idx, cohort, *self._stage_cohort_rows(cohort))

    def _rebind_data(self, cohort: np.ndarray) -> None:
        """data_binding: user — each cohort member trains on the shard of
        its user id (``user mod N``), re-staged host-side at the swap."""
        n = self.program.num_nodes
        shard = cohort % n
        for key, arr in self._host_data.items():
            if arr.ndim >= 1 and arr.shape[0] == n:
                self._data[key] = self._stage(arr[shard], self._node_s)

    def _swap_to(self, draw_idx: int, round_idx: int) -> None:
        t0 = time.perf_counter()
        if self._prefetched is not None and self._prefetched[0] == draw_idx:
            _, cohort, dev_rows, dev_known = self._prefetched
        else:
            cohort = self._draw(draw_idx)
            dev_rows, dev_known = self._stage_cohort_rows(cohort)
        self._prefetched = None

        # The outgoing cohort's trained rows, device-resident (no sync).
        out_dev = self._ravel_all(self.params)
        swapped_out = 0
        if self.cohort is not None:
            # Write-back: the one forced device sync of the swap.
            self.bank.scatter(
                self.cohort,
                np.asarray(jax.device_get(out_dev), dtype=np.float32),
            )
            swapped_out = len(self.cohort)
            # Freshness patch: the prefetch staged the incoming rows
            # BEFORE this write-back (that is the point of the overlap),
            # so a user present in BOTH cohorts was staged one swap stale
            # (or as never-banked on their very first re-draw).  Re-stage
            # from the now-current bank when the cohorts overlap — rare at
            # large virtual_size (the prefetch stays fully effective),
            # mandatory for correctness at small ones.
            if np.intersect1d(self.cohort, cohort).size:
                dev_rows, dev_known = self._stage_cohort_rows(cohort)

        if self.population.inherit == "teleport":
            # Banked users resume their own row; fresh users adopt the
            # outgoing cohort's trained slot model (model teleportation,
            # arXiv:2501.15259) — before the first swap ``out_dev`` IS the
            # slot seed init, so the composition is uniform.
            new_flat = self._compose(dev_known, dev_rows, out_dev)
        else:
            new_flat = dev_rows
        self.params = self._unravel_all(new_flat)
        self._place_resident_state()
        if self.population.data_binding == "user":
            self._rebind_data(cohort)
        self.cohort = cohort
        self.cohorts_seen += 1
        if self.telemetry is not None:
            self.telemetry.emit(
                "cohort",
                round=round_idx,
                draw=draw_idx,
                swapped_out=swapped_out,
                activated_users=self.bank.activated,
                virtual_size=self.population.virtual_size,
                swap_s=round(time.perf_counter() - t0, 6),
            )

    # ------------------------------------------------------------------

    def train(
        self,
        rounds: int,
        verbose: bool = False,
        eval_every: int = 1,
        checkpoint_dir: Optional[str] = None,
        checkpoint_every: int = 0,
        defer_metrics: bool = False,
        rounds_per_dispatch: int = 1,
    ):
        """Cohort-streaming round loop (per-round dispatch).

        ``checkpoint_dir``/``checkpoint_every`` snapshot the COMPLETE
        streaming state (durability/snapshot.py): the base sections plus
        the resident cohort's slot↔user binding, the sampler position
        (derivable from the round — draws are pure in ``(seed,
        draw_idx)``), and the state bank (memmap flushed in place when
        ``population.bank_dir`` is set, activated rows embedded in the
        snapshot otherwise) — a resumed 100k-virtual-user run continues
        across cohort swaps with zero extra recompiles.
        ``rounds_per_dispatch > 1`` falls back to per-round dispatch with
        a warning — a fused scan would pin one cohort for the whole chunk.
        """
        if rounds_per_dispatch > 1 or defer_metrics:
            import warnings

            warnings.warn(
                "population streaming dispatches per round (the cohort "
                "swap is a host decision between dispatches); "
                "rounds_per_dispatch/defer_metrics are ignored",
                stacklevel=2,
            )
        profile = self.profile_dir is not None
        if profile:
            jax.profiler.start_trace(self.profile_dir)
        try:
            with self._sanitizer_scope():
                self._train_population(
                    rounds, verbose, eval_every, checkpoint_dir,
                    checkpoint_every,
                )
        finally:
            if profile:
                jax.profiler.stop_trace()
            self._profile_window_stop(self.current_round, force=True)
            if self.telemetry is not None:
                self.telemetry.finalize(history=self.history)
        return self.history

    def _train_population(
        self, rounds, verbose, eval_every, checkpoint_dir=None,
        checkpoint_every=0,
    ) -> None:
        comp = self._stage(self.compromised, self._node_s)
        rpc = self.population.rounds_per_cohort
        last_saved = -1
        for step_i in range(rounds):
            round_idx = self.current_round
            if round_idx % rpc == 0 or self.cohort is None:
                self._swap_to(round_idx // rpc, round_idx)
            self._profile_window_start(round_idx)
            t0 = time.perf_counter()
            warmup = "step" not in self._warmed
            if self._tracker is not None:
                self._tracker.begin(f"round {round_idx}")
            adj = self._stage(self._adjacency_for_round(round_idx), self._adj_s)
            step_key = self._stage(
                self._fold_in(
                    self._rng, jnp.asarray(np.asarray(round_idx, np.uint32))
                ),
                self._repl,
            )
            step_args = [
                self.params,
                self.agg_state,
                step_key,
                adj,
                comp,
                self._stage(np.asarray(round_idx, np.float32), self._repl),
                self._data,
            ]
            if self.program.faulted:
                step_args.insert(
                    5, self._stage(self._alive_for_round(round_idx), self._node_s)
                )
            self.params, self.agg_state, agg_metrics = self._step(*step_args)
            self._warmed.add("step")
            self.current_round = round_idx + 1
            # Double buffer: the step above is dispatched (async); stage
            # the NEXT cohort now so its bank gather + H2D copy overlap
            # the in-flight round instead of serializing at the boundary.
            next_round = self.current_round
            if step_i + 1 < rounds and next_round % rpc == 0:
                self._prefetch(next_round // rpc)
            if self.current_round % eval_every == 0:
                if self._tracker is not None:
                    self._tracker.mark(allow=warmup)
                warmup = "eval" not in self._warmed
                metrics = {**self._eval(self.params, self._data), **agg_metrics}
                self._warmed.add("eval")
                metrics = jax.device_get(metrics)
                self._record(self.current_round, metrics, verbose)
            if self._tracker is not None:
                self._tracker.end(allow=warmup)
            wall = time.perf_counter() - t0
            self.round_times.append(wall)
            if self.telemetry is not None:
                self.telemetry.phase_times(
                    round_idx, "population", wall,
                    evaluated=bool(self.current_round % eval_every == 0),
                    cohort_draw=round_idx // rpc,
                )
                self.telemetry.memory_event(round_idx)
                self._profile_window_stop(self.current_round)
            if (
                checkpoint_dir
                and checkpoint_every
                and self.current_round % checkpoint_every == 0
            ):
                # Crash-equivalent cadence snapshot: the bank is saved
                # AS-IS (no write-back of the resident cohort — those
                # rows ride the params section), so the restored bank is
                # byte-identical to the uninterrupted run's at this round.
                self.save_checkpoint(checkpoint_dir)
                last_saved = self.current_round
        # Final write-back so the bank holds every trained row when
        # train() returns (the resident cohort stays loaded for a
        # subsequent train() call).
        if self.cohort is not None and rounds > 0:
            out_flat = jax.device_get(self._ravel_all(self.params))
            self.bank.scatter(
                self.cohort, np.asarray(out_flat, dtype=np.float32)
            )
        if checkpoint_dir and rounds > 0 and self.current_round != last_saved:
            self.save_checkpoint(checkpoint_dir)

    # ------------------------------------------------------------------
    # durability hooks (durability/snapshot.py)

    def _durability_extra_state(self):
        """The streaming state beyond the base sections: the resident
        cohort's slot↔user binding, the swap counter, and the bank.

        Bank modes: ``external`` (``population.bank_dir`` set — the
        memmap is flushed in place and only the packed activation mask
        rides the snapshot; O(U/8) bytes) or ``embedded`` (RAM/tempdir
        banks whose backing dies with the process — activated ids + rows
        are copied into the snapshot).  The sampler needs NO saved state:
        draws are a pure function of ``(population.seed, draw_idx)`` and
        ``draw_idx`` is ``round // rounds_per_cohort`` (sampler.py).
        """
        from murmura_tpu.durability.snapshot import embed_bool_mask

        arrays, meta = super()._durability_extra_state()
        p = self.population
        external = p.bank_dir is not None
        if external:
            self.bank.flush()
            self._bank_flushed_here = True
        else:
            ids = self.bank.activated_users
            arrays["population/bank_user_ids"] = ids
            arrays["population/bank_rows"] = self.bank.rows_of(ids)
        arrays["population/bank_has_row"] = embed_bool_mask(
            self.bank._has_row
        )
        if self.cohort is not None:
            arrays["population/cohort"] = np.asarray(self.cohort, np.int64)
        meta["population"] = {
            "virtual_size": p.virtual_size,
            "sampler": p.sampler,
            "seed": p.seed,
            "rounds_per_cohort": p.rounds_per_cohort,
            "data_binding": p.data_binding,
            "inherit": p.inherit,
            "cohorts_seen": self.cohorts_seen,
            "bank_mode": "external" if external else "embedded",
            "bank_path": self.bank.path,
            "activated": self.bank.activated,
        }
        return arrays, meta

    def _durability_validate_extra(self, arrays, meta) -> None:
        pm = meta.get("population")
        if pm is None or "population/bank_has_row" not in arrays:
            raise ValueError(
                "snapshot carries no population section — it was written "
                "by a plain run; drop the population block or point "
                "--checkpoint-dir at a population snapshot"
            )
        p = self.population
        mismatched = {
            k: (pm.get(k), getattr(p, k))
            for k in ("virtual_size", "sampler", "seed", "rounds_per_cohort",
                      "data_binding", "inherit")
            if pm.get(k) != getattr(p, k)
        }
        if mismatched:
            raise ValueError(
                "population snapshot/config mismatch (snapshot vs config): "
                f"{mismatched} — the cohort stream would silently diverge "
                "from the interrupted run"
            )
        if pm["bank_mode"] == "external":
            # The flushed file IS the snapshot's row data, so identity
            # matters twice over.  (a) It must be the SAME file the
            # snapshot recorded: a reattached bank of the right size
            # under a different bank_dir is some other experiment's rows
            # and would silently diverge the continued history (MUR901).
            if self.bank.path != pm["bank_path"]:
                raise ValueError(
                    f"population snapshot records its memmapped bank at "
                    f"{pm['bank_path']!r} but this config's bank_dir="
                    f"{p.bank_dir!r} opens {self.bank.path!r} — resuming "
                    "onto a different bank file would continue from some "
                    "other run's rows; keep the bank at the path the "
                    "snapshot recorded"
                )
            # (b) The live memmap must actually BE that file's data:
            # reattached = a fresh process adopted the flushed file;
            # flushed here = the SAME instance that wrote the snapshot is
            # restoring in place (the CLI retry envelope).  Path equality
            # alone is NOT enough — a fresh build whose bank file
            # vanished recreates an empty file at the same path.
            if not (self.bank.reattached or self._bank_flushed_here):
                raise ValueError(
                    f"population snapshot expects the memmapped bank at "
                    f"{pm['bank_path']!r} but no matching bank file was "
                    f"found under bank_dir={p.bank_dir!r} — the flushed "
                    "rows are the snapshot's data; restore them first"
                )

    def _durability_restore_extra(self, arrays, meta) -> None:
        from murmura_tpu.durability.snapshot import unpack_bool_mask

        pm = meta["population"]
        p = self.population
        # An external memmap bank is already reattached in place
        # (validated pre-restore); an embedded bank's rows ride the
        # snapshot and are scattered back here.
        if pm["bank_mode"] != "external":
            ids = arrays["population/bank_user_ids"]
            if len(ids):
                self.bank.scatter(ids, arrays["population/bank_rows"])
        self.bank.restore_activation(
            unpack_bool_mask(
                arrays["population/bank_has_row"], p.virtual_size
            )
        )
        self.cohorts_seen = int(pm["cohorts_seen"])
        self._prefetched = None
        cohort = arrays.get("population/cohort")
        self.cohort = (
            np.asarray(cohort, np.int64) if cohort is not None else None
        )
        if self.cohort is not None and p.data_binding == "user":
            # Re-bind each slot's data shard to its restored user — the
            # restored params are the resident cohort's rows and must
            # train on the same shards they did before the interruption.
            self._rebind_data(self.cohort)


# ---------------------------------------------------------------------------
# Composition manifest (murmura_tpu/levers.py; `murmura check --compose`).
# The single source of truth for this lever's cross-feature verdicts —
# guard sites in config/schema.py and utils/factories.py cite
# refusal_reason() so user-facing messages and the analyzer's grid can
# never drift apart (MUR1400).
# ---------------------------------------------------------------------------
from murmura_tpu.levers import LeverManifest, composes, refuses

LEVER_MANIFEST = LeverManifest(
    name="population",
    module="murmura_tpu.population.engine",
    verdicts={
        "adaptive": composes(),
        # Stateless int8 survives cohort swaps; carried per-slot state
        # (EF residual / topk reference) would cross user streams.
        "compression": composes(
            carried_state=(
                "compression with carried state (error_feedback, or "
                "algorithm: topk) does not compose with population "
                "(cohort swaps reassign node slots); use stateless "
                "int8 or disable the population block"
            ),
        ),
        "dmtt": refuses(
            "population does not compose with dmtt (trust state is "
            "keyed by node identity, which cohort swaps reassign)"
        ),
        "faults": composes(),
        "mobility": composes(),
        "pipeline": refuses(
            "exchange.pipeline does not compose with population "
            "(the pipeline buffer is per-slot [N, P] carried state; "
            "cohort swaps reassign node slots, so a buffered row "
            "would be aggregated into the wrong user's stream — the "
            "compression/staleness carried-state rationale)"
        ),
    },
)

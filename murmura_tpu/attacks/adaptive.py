"""In-jit closed-loop (adaptive) attacks — ISSUE 11, docs/ROBUSTNESS.md.

Every static attack in this package closes over a fixed strength for the
whole run, so the robustness story only ever tests each rule against
adversaries that do not fight back.  This module closes the loop *inside*
the compiled round program: per-attack adaptation state rides ``agg_state``
under the reserved :data:`ATTACK_STATE_KEYS` (the ``COMPRESS_STATE_KEYS``/
``DMTT_STATE_KEYS`` pattern, so durability snapshots and the MUR900
completeness bijection pick it up for free), and each round the attacker
reads the audit-tap acceptance signal the aggregation rule itself emitted
(``tap_selected_by``/``tap_considered_by`` — telemetry leg of PR 4) for its
compromised rows and tunes its strength for the next round.

Three adaptive attacks ship:

- **adaptive ALIE** (:func:`make_adaptive_alie_attack`): the colluding
  vector's deviation factor ``z`` is per-node carried state updated by a
  multiplicative variance-quantile walk — accepted rounds push ``z`` up
  (the colluders creep toward the krum/BALANCE margin), rejected rounds
  pull it back inside the benign variance envelope.  The equilibrium z
  IS the empirical selection margin of the defense.
- **adaptive IPM** (:func:`make_adaptive_ipm_attack`): the inner-product
  manipulation's negation factor ``epsilon`` is per-node carried state
  driven by the same acceptance walk — the equilibrium epsilon is the
  largest mean-negation the defense admits, directly on the paper's
  own strength axis (``-epsilon * mu_honest``).
- **scale bisection** (:func:`make_bisection_attack`): a generic wrapper
  that turns ANY static broadcast attack into "largest strength still
  accepted" — per-node bracket state (``atk_lo`` = largest accepted,
  ``atk_hi`` = smallest rejected) drives a growth-then-bisection probe of
  the perturbation multiplier.

Design invariants (machine-checked by the MUR100x family,
analysis/adaptive.py):

- **Node-local feedback** — the acceptance signal is assembled from
  per-node tap columns the rules already compute (roll-assembled on
  circulant paths), and every state update is elementwise over node rows:
  the feedback path adds NO collectives beyond the static-attack tapped
  inventory (MUR1002) and no recompiles across strength/round variation
  (MUR1001).
- **Snapshot completeness** — all adaptation state lives under
  :data:`ATTACK_STATE_KEYS` in ``agg_state`` (MUR1000 bijection into the
  MUR900 registry), so a SIGKILL/`--resume` cycle restores the attacker
  mid-bisection byte-identically (the MUR901 grid's ``adaptive`` cell).
- **Bounded influence survives the loop** — taint from the adaptation
  state flows into the *attacker's* broadcast rows only; a bounded rule's
  per-coordinate influence cardinality is unchanged (MUR1003).

Rules that emit no selection taps (fedavg, median, trimmed_mean,
geometric_median, sketchguard) give the attacker only the fault
sentinel's scrub-survival signal (when faults are armed) or a constant
"accepted" — the adaptive program still compiles and runs against every
rule, it just has less to adapt to; the frontier treats those curves as
upper envelopes.  Quarantined/scrubbed compromised rows count as
REJECTED observations (the attack was too loud); dead rows (churn) are
not observations at all — their taps are masked out of the EMA entirely.
"""

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from murmura_tpu.attacks.base import Attack
from murmura_tpu.attacks.alie import resolve_alie_z

# Reserved round-program-level agg_state keys for attack adaptation state
# (the COMPRESS_STATE_KEYS pattern; registered in
# durability/snapshot.RESERVED_AGG_STATE_KEY_GROUPS so the MUR900 snapshot
# completeness bijection — and therefore SIGKILL/--resume — covers the
# attacker's bracket/EMA state for free).  Every adaptive attack's
# init_attack_state() keys must be drawn from this tuple and their union
# must equal it exactly (MUR1000, analysis/adaptive.py).  All entries are
# per-node [N] float32 rows, so gang vmap and the durability snapshot
# treat them exactly like any other node-indexed carried state.
ATTACK_STATE_KEYS = (
    "atk_accept_ema",  # EMA of the row's acceptance fraction
    "atk_eps",         # adaptive IPM: current negation factor epsilon
    "atk_hi",          # bisection: smallest strength observed rejected
    "atk_lo",          # bisection: largest strength observed accepted
    "atk_scale",       # bisection: strength probed next round
    "atk_z",           # adaptive ALIE: current deviation factor z
)


@dataclass(frozen=True)
class AdaptiveAttack(Attack):
    """A closed-loop attack: the static :class:`Attack` interface plus the
    adaptation triple (init state / strength-aware apply / feedback
    update).  ``apply`` stays populated with the initial-strength static
    transform so code paths that do not know about adaptation (direct
    library use) degrade to the static attack instead of crashing; the
    round program routes through ``apply_adaptive`` (core/rounds.py).
    """

    # agg_state keys this attack carries (subset of ATTACK_STATE_KEYS).
    state_keys: Tuple[str, ...] = ()
    # (num_nodes) -> {key: [N] float32} initial adaptation state.
    init_attack_state: Optional[Callable[[int], Dict[str, np.ndarray]]] = (
        field(default=None)
    )
    # (flat[N, P], compromised[N], key, round_idx, state) -> bcast'[N, P]
    apply_adaptive: Optional[Callable] = field(default=None)
    # (state, accept[N], observed[N], compromised[N]) -> state'
    update_attack_state: Optional[Callable] = field(default=None)
    # (state, compromised[N]) -> {stat: [N]} telemetry rows (masked to the
    # compromised set so history means read as coalition strength).
    strength_stats: Optional[Callable] = field(default=None)


def acceptance_feedback(
    agg_stats: Dict[str, jnp.ndarray],
    fault_stats: Dict[str, jnp.ndarray],
    in_degree: jnp.ndarray,
    alive: Optional[jnp.ndarray],
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The per-node acceptance signal an adaptive attacker reads each
    round: ``(accept[N] in [0, 1], observed[N] in {0, 1})``.

    ``accept[i]`` is the fraction of peers that selected/accepted node
    i's broadcast this round (``tap_selected_by / tap_considered_by``
    when the rule emits selection taps; a constant 1 otherwise — rules
    without taps leave the attacker blind, which is itself part of the
    robustness story).  A row the fault sentinel scrubbed or quarantined
    counts as REJECTED (accept forced to 0 — the attack overflowed), not
    as missing.  ``observed[i]`` gates the state update: dead rows
    (``alive == 0``) broadcast nothing and must not move the EMA at all
    — the churn-composition contract (tests/test_adaptive.py).

    Which branches exist is a trace-time property of the rule/audit
    configuration, so the lowered program is static per build; everything
    here is elementwise over node-local rows — no collectives (MUR1002).
    """
    sel = agg_stats.get("tap_selected_by")
    if sel is not None:
        cons = agg_stats.get("tap_considered_by")
        denom = cons if cons is not None else in_degree
        denom = jnp.maximum(denom.astype(jnp.float32), 1.0)
        accept = jnp.clip(sel.astype(jnp.float32) / denom, 0.0, 1.0)
        observed = (
            (cons if cons is not None else in_degree) > 0
        ).astype(jnp.float32)
    else:
        accept = jnp.ones_like(in_degree, dtype=jnp.float32)
        observed = jnp.ones_like(in_degree, dtype=jnp.float32)
    scrubbed = fault_stats.get("tap_attack_scrubbed")
    if scrubbed is not None:
        # An overflow scrub IS an observation: the row was rejected.
        accept = accept * (1.0 - scrubbed)
        observed = jnp.maximum(observed, scrubbed)
    quarantined = fault_stats.get("tap_quarantined")
    if quarantined is not None:
        accept = accept * (1.0 - quarantined)
        observed = jnp.maximum(observed, quarantined)
    if alive is not None:
        # A dead node broadcast nothing — no signal, no update.
        observed = observed * alive
    return accept, observed


def _gated(update_mask, new, old):
    """Elementwise state update gated by the per-node observation mask."""
    return jnp.where(update_mask > 0, new, old)


def coalition_stats(
    flat: jnp.ndarray, compromised_mask: jnp.ndarray, estimator: str
):
    """(mu[1, P], var[1, P]) of the ALIE construction under either
    estimator, reduced in f32 (the honest_mean rationale, base.py):

    - ``omniscient``: statistics over the TRUE honest rows — strictly
      stronger than the paper (the historical in-jit default; results
      labeled "ALIE" from it carry that caveat, alie.py docstring);
    - ``coalition``: statistics over the compromised rows' own
      benign-trained states ONLY — Baruch et al.'s actual construction
      (the ZMQ backend's estimator, now available in-jit).  Requires the
      colluders to train locally (``Attack.trains_locally``), else the
      sample is frozen init params, not benign grad", and >= 2 colluders
      for a non-degenerate sigma.
    """
    if estimator not in ("omniscient", "coalition"):
        raise ValueError(
            f"ALIE estimator must be 'omniscient' or 'coalition', "
            f"got {estimator!r}"
        )
    f32 = flat.astype(jnp.float32)
    comp = compromised_mask.astype(jnp.float32)[:, None]  # [N, 1]
    w = comp if estimator == "coalition" else (1.0 - comp)
    cnt = jnp.maximum(w.sum(), 1.0)
    mu = (f32 * w).sum(axis=0, keepdims=True) / cnt
    var = (jnp.square(f32 - mu) * w).sum(axis=0, keepdims=True) / cnt
    return mu, var


def make_adaptive_alie_attack(
    num_nodes: int,
    attack_percentage: float,
    z: Optional[float] = None,
    seed: int = 42,
    estimator: str = "omniscient",
    eta: float = 0.25,
    accept_target: float = 0.0,
    ema_beta: float = 0.5,
    z_min: float = 0.05,
    z_cap: Optional[float] = None,
) -> AdaptiveAttack:
    """ALIE whose deviation factor z is carried per-node state updated by
    a multiplicative variance-quantile walk against the observed
    acceptance: accepted rounds multiply z by ``1 + eta`` (creep toward
    the selection margin), rejected rounds by ``1 - eta`` (duck back
    inside the benign envelope), clamped to ``[z_min, z_cap]``.  The
    starting z is the paper's z_max (or the explicit override), exactly
    the static attack's strength — an adaptive run whose defense never
    rejects anything escalates from there.

    "Accepted" means the round's acceptance fraction is STRICTLY above
    ``accept_target`` — with the default 0, "some peer still
    selects/accepts my broadcast".  The absolute-fraction reading
    (target 0.5 = "most peers") misfires on single-winner rules like
    krum, where even an honest row's selection fraction is ~1/candidates;
    the any-peer default makes the equilibrium z exactly the defense's
    empirical selection margin.
    """
    from murmura_tpu.attacks.alie import make_alie_attack

    static = make_alie_attack(
        num_nodes, attack_percentage, z=z, seed=seed, estimator=estimator
    )
    comp_idx = np.flatnonzero(static.compromised)
    z0 = resolve_alie_z(num_nodes, len(comp_idx), z)
    cap = float(z_cap) if z_cap is not None else max(4.0 * abs(z0), 4.0)
    state_keys = ("atk_accept_ema", "atk_z")

    def init_attack_state(n: int) -> Dict[str, np.ndarray]:
        return {
            "atk_z": np.full(n, z0, np.float32),
            "atk_accept_ema": np.ones(n, np.float32),
        }

    def apply_adaptive(flat, compromised_mask, key, round_idx, state):
        if flat.shape[0] != num_nodes or not len(comp_idx):
            return flat  # per-node view: no population statistics here
        mu, var = coalition_stats(flat, compromised_mask, estimator)
        z_rows = state["atk_z"].astype(jnp.float32)[:, None]  # [N, 1]
        malicious = (mu - z_rows * jnp.sqrt(var)).astype(flat.dtype)
        return jnp.where(compromised_mask[:, None] > 0, malicious, flat)

    def update_attack_state(state, accept, observed, compromised_mask):
        upd = compromised_mask * observed
        ema = _gated(
            upd,
            (1.0 - ema_beta) * state["atk_accept_ema"] + ema_beta * accept,
            state["atk_accept_ema"],
        )
        # The step direction reads the ROUND's acceptance, not the EMA:
        # an EMA > 0 test never flips back after a rejection streak
        # (0.5^k stays positive), which would turn the walk into monotone
        # escalation.  The EMA is carried smoothed telemetry the frontier
        # summarizes, not the decision variable.
        accepted = (accept > accept_target).astype(jnp.float32)
        z_new = state["atk_z"] * jnp.where(accepted > 0, 1.0 + eta, 1.0 - eta)
        z_new = jnp.clip(z_new, z_min, cap)
        return {
            "atk_accept_ema": ema,
            "atk_z": _gated(upd, z_new, state["atk_z"]),
        }

    def strength_stats(state, compromised_mask):
        return {
            "atk_z": state["atk_z"] * compromised_mask,
            "atk_accept_ema": state["atk_accept_ema"] * compromised_mask,
        }

    return AdaptiveAttack(
        name="adaptive_alie",
        compromised=static.compromised,
        apply=static.apply,
        trains_locally=static.trains_locally,
        state_keys=state_keys,
        init_attack_state=init_attack_state,
        apply_adaptive=apply_adaptive,
        update_attack_state=update_attack_state,
        strength_stats=strength_stats,
    )


def make_adaptive_ipm_attack(
    num_nodes: int,
    attack_percentage: float,
    epsilon: Optional[float] = None,
    seed: int = 42,
    eta: float = 0.25,
    accept_target: float = 0.0,
    ema_beta: float = 0.5,
    eps_min: float = 0.05,
    eps_cap: Optional[float] = None,
) -> AdaptiveAttack:
    """IPM (attacks/ipm.py: ``malicious = -epsilon * mu_honest``) whose
    negation factor epsilon is per-node carried state under ``atk_eps``,
    updated by the same multiplicative acceptance walk as adaptive
    ALIE's z: accepted rounds multiply epsilon by ``1 + eta`` (push the
    inner product further negative — toward the outright update flip at
    epsilon >= 1), rejected rounds by ``1 - eta`` (duck back into the
    stealth regime distance filters admit), clamped to
    ``[eps_min, eps_cap]``.  The starting epsilon is the paper's default
    (or the explicit override) — exactly the static attack's strength.

    Where the generic bisection wrapper scales the *perturbation* of a
    benignly-trained state, this walks the attack's OWN semantic knob:
    the equilibrium epsilon is the largest mean-negation the defense
    still accepts, directly comparable to the paper's epsilon axis
    (PR 11 follow-up; ROADMAP item 4's remaining list).
    """
    from murmura_tpu.attacks.ipm import make_ipm_attack, resolve_ipm_epsilon

    static = make_ipm_attack(
        num_nodes, attack_percentage, epsilon=epsilon, seed=seed
    )
    comp_idx = np.flatnonzero(static.compromised)
    eps0 = resolve_ipm_epsilon(epsilon)
    cap = float(eps_cap) if eps_cap is not None else max(4.0 * abs(eps0), 4.0)
    state_keys = ("atk_accept_ema", "atk_eps")

    def init_attack_state(n: int) -> Dict[str, np.ndarray]:
        return {
            "atk_eps": np.full(n, eps0, np.float32),
            "atk_accept_ema": np.ones(n, np.float32),
        }

    def apply_adaptive(flat, compromised_mask, key, round_idx, state):
        if flat.shape[0] != num_nodes or not len(comp_idx):
            return flat  # per-node view: no population statistics here
        from murmura_tpu.attacks.base import honest_mean

        mu = honest_mean(flat, compromised_mask)  # [1, P] f32
        eps_rows = state["atk_eps"].astype(jnp.float32)[:, None]  # [N, 1]
        malicious = (-eps_rows * mu).astype(flat.dtype)
        return jnp.where(compromised_mask[:, None] > 0, malicious, flat)

    def update_attack_state(state, accept, observed, compromised_mask):
        upd = compromised_mask * observed
        ema = _gated(
            upd,
            (1.0 - ema_beta) * state["atk_accept_ema"] + ema_beta * accept,
            state["atk_accept_ema"],
        )
        # Round acceptance, not the EMA, drives the step direction (the
        # adaptive-ALIE rationale above: an EMA threshold never flips
        # back after a rejection streak).
        accepted = (accept > accept_target).astype(jnp.float32)
        eps_new = state["atk_eps"] * jnp.where(
            accepted > 0, 1.0 + eta, 1.0 - eta
        )
        eps_new = jnp.clip(eps_new, eps_min, cap)
        return {
            "atk_accept_ema": ema,
            "atk_eps": _gated(upd, eps_new, state["atk_eps"]),
        }

    def strength_stats(state, compromised_mask):
        return {
            "atk_eps": state["atk_eps"] * compromised_mask,
            "atk_accept_ema": state["atk_accept_ema"] * compromised_mask,
        }

    return AdaptiveAttack(
        name="adaptive_ipm",
        compromised=static.compromised,
        apply=static.apply,
        # The coalition trains benignly so the omniscient honest mean the
        # colluders negate tracks real gradients, and eps -> eps_min
        # degrades toward (scaled) honest behavior — the bisection
        # wrapper's rationale for the wrapped attacks.
        trains_locally=True,
        state_keys=state_keys,
        init_attack_state=init_attack_state,
        apply_adaptive=apply_adaptive,
        update_attack_state=update_attack_state,
        strength_stats=strength_stats,
    )


def make_bisection_attack(
    inner: Attack,
    scale_init: float = 1.0,
    scale_max: float = 8.0,
    growth: float = 2.0,
    accept_target: float = 0.0,
    ema_beta: float = 0.5,
) -> AdaptiveAttack:
    """Wrap ANY static broadcast attack into "largest strength still
    accepted": the broadcast becomes ``own + scale * (attacked - own)``
    with ``scale`` per-node carried state driven by a growth-then-
    bisection probe.  While no rejection has been observed (``atk_hi``
    still at its above-the-cap init sentinel) accepted rounds DOUBLE the
    probe
    (geometric growth finds the rejection region fast); once a rejection
    pins the bracket, the probe bisects ``[atk_lo, atk_hi]`` — ``atk_lo``
    converges to the defense's empirical breaking point from below, the
    number `murmura frontier` charts against the MUR800 declared bound.

    "Accepted" is a round's acceptance fraction STRICTLY above
    ``accept_target`` (default 0: some peer selected/accepted the row —
    the right reading for single-winner rules like krum, where even
    honest rows win only ~1/candidates of receivers).

    The wrapped attacker TRAINS LOCALLY (``Attack.trains_locally``),
    unlike the frozen-model static attacks it wraps: a bisection around
    a frozen-param broadcast is degenerate — distance filters reject the
    *staleness* at any scale, so the bracket collapses to 0 and measures
    nothing.  Training benignly and perturbing means scale -> 0 recovers
    honest behavior exactly, and the bracket converges to the filter's
    true perturbation margin.

    Data-poisoning attacks have no broadcast perturbation to scale and
    are rejected loudly (factories enforces this at config level too).
    """
    if inner.data_poison_fn is not None:
        raise ValueError(
            f"attack '{inner.name}' poisons data, not broadcasts — there "
            "is no broadcast perturbation for the bisection wrapper to "
            "scale"
        )
    if not scale_max > 0:
        raise ValueError(f"scale_max must be > 0, got {scale_max}")
    scale_init = float(min(scale_init, scale_max))
    state_keys = ("atk_accept_ema", "atk_hi", "atk_lo", "atk_scale")

    # atk_hi's "no rejection observed yet" sentinel sits strictly ABOVE
    # scale_max: a real rejection at exactly scale_max must pin the
    # bracket (hi = scale_max, growth phase over), which an init of
    # scale_max itself cannot distinguish — the probe would stay wedged
    # at the cap forever and atk_lo would understate the true margin by
    # up to the growth factor.
    hi_init = float(scale_max) * float(growth)

    def init_attack_state(n: int) -> Dict[str, np.ndarray]:
        return {
            "atk_scale": np.full(n, scale_init, np.float32),
            "atk_lo": np.zeros(n, np.float32),
            "atk_hi": np.full(n, hi_init, np.float32),
            "atk_accept_ema": np.ones(n, np.float32),
        }

    def apply_adaptive(flat, compromised_mask, key, round_idx, state):
        base = inner.apply(flat, compromised_mask, key, round_idx)
        scale = state["atk_scale"].astype(jnp.float32)[:, None]
        f32 = flat.astype(jnp.float32)
        return (
            f32 + scale * (base.astype(jnp.float32) - f32)
        ).astype(flat.dtype)

    def update_attack_state(state, accept, observed, compromised_mask):
        upd = compromised_mask * observed
        scale, lo, hi = state["atk_scale"], state["atk_lo"], state["atk_hi"]
        ema = _gated(
            upd,
            (1.0 - ema_beta) * state["atk_accept_ema"] + ema_beta * accept,
            state["atk_accept_ema"],
        )
        accepted = (accept > accept_target).astype(jnp.float32)
        lo_new = jnp.where(accepted > 0, jnp.maximum(lo, scale), lo)
        hi_new = jnp.where(accepted > 0, hi, jnp.minimum(hi, scale))
        # Strictly above scale_max <=> still the init sentinel <=> no
        # rejection has ever been observed (a rejection sets hi to the
        # probed scale, which min(scale_init, scale_max) caps).
        growing = (hi_new > scale_max).astype(jnp.float32)
        probe = jnp.where(
            growing > 0,
            jnp.minimum(scale * growth, scale_max),
            0.5 * (lo_new + hi_new),
        )
        return {
            "atk_accept_ema": ema,
            "atk_scale": _gated(upd, probe, scale),
            "atk_lo": _gated(upd, lo_new, lo),
            "atk_hi": _gated(upd, hi_new, hi),
        }

    def strength_stats(state, compromised_mask):
        return {
            "atk_scale": state["atk_scale"] * compromised_mask,
            "atk_lo": state["atk_lo"] * compromised_mask,
            "atk_hi": state["atk_hi"] * compromised_mask,
            "atk_accept_ema": state["atk_accept_ema"] * compromised_mask,
        }

    return AdaptiveAttack(
        name=f"bisection_{inner.name}",
        compromised=inner.compromised,
        apply=inner.apply,
        trains_locally=True,
        state_keys=state_keys,
        init_attack_state=init_attack_state,
        apply_adaptive=apply_adaptive,
        update_attack_state=update_attack_state,
        strength_stats=strength_stats,
    )


# Adaptive attack builders the MUR1000 bijection sweeps: every factory
# here must emit state keys drawn from — and jointly covering —
# ATTACK_STATE_KEYS.  New adaptive attacks register here or fail MUR1000.
def _probe_bisection() -> AdaptiveAttack:
    from murmura_tpu.attacks.gaussian import make_gaussian_attack

    return make_bisection_attack(
        make_gaussian_attack(4, attack_percentage=0.25, noise_std=1.0)
    )


ADAPTIVE_ATTACKS: Dict[str, Callable[[], AdaptiveAttack]] = {
    "adaptive_alie": lambda: make_adaptive_alie_attack(
        4, attack_percentage=0.25
    ),
    "adaptive_ipm": lambda: make_adaptive_ipm_attack(
        4, attack_percentage=0.25
    ),
    "bisection": _probe_bisection,
}


# ---------------------------------------------------------------------------
# Composition manifest (murmura_tpu/levers.py; `murmura check --compose`).
# The single source of truth for this lever's cross-feature verdicts —
# guard sites in config/schema.py and utils/factories.py cite
# refusal_reason() so user-facing messages and the analyzer's grid can
# never drift apart (MUR1400).
# ---------------------------------------------------------------------------
from murmura_tpu.levers import LeverManifest, composes, refuses

LEVER_MANIFEST = LeverManifest(
    name="adaptive",
    module="murmura_tpu.attacks.adaptive",
    state_keys_group="ATTACK_STATE_KEYS",
    stage="murmura.exchange",
    # First lever alphabetically: every pair it belongs to is declared
    # by the later peer (levers.py declaration convention).
    verdicts={},
)

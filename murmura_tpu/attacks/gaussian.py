"""Gaussian noise attack (reference: murmura/attacks/gaussian.py:10-90).

Compromised nodes broadcast state + N(0, noise_std^2) noise; all parameters
here are float (no BatchNorm integer buffers — see models/core.py), so the
reference's dtype special-casing (gaussian.py:82-88) has no counterpart.
"""

import jax
import jax.numpy as jnp

from murmura_tpu.attacks.base import Attack, select_compromised


def make_gaussian_attack(
    num_nodes: int,
    attack_percentage: float,
    noise_std: float = 10.0,
    seed: int = 42,
) -> Attack:
    compromised = select_compromised(num_nodes, attack_percentage, seed)

    def apply(flat, compromised_mask, key, round_idx):
        noise = jax.random.normal(key, flat.shape, flat.dtype) * noise_std
        return jnp.where(compromised_mask[:, None] > 0, flat + noise, flat)

    return Attack(name="gaussian", compromised=compromised, apply=apply)

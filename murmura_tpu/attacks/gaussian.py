"""Gaussian noise attack (reference: murmura/attacks/gaussian.py:10-90).

Compromised nodes broadcast state + N(0, noise_std^2) noise; all parameters
here are float (no BatchNorm integer buffers — see models/core.py), so the
reference's dtype special-casing (gaussian.py:82-88) has no counterpart.
"""

import jax
import jax.numpy as jnp
import numpy as np

from murmura_tpu.attacks.base import Attack, select_compromised


def make_gaussian_attack(
    num_nodes: int,
    attack_percentage: float,
    noise_std: float = 10.0,
    seed: int = 42,
) -> Attack:
    compromised = select_compromised(num_nodes, attack_percentage, seed)
    comp_idx = np.flatnonzero(compromised)

    # Static one-hot scatter matrix [N, C]: row expansion happens as a
    # matmul instead of a scatter-add.  The scatter is both slower (~4x on
    # a [20, 6.5M] state) and poisons XLA's layout choice for every [N, P]
    # tensor downstream — scatter prefers a node-minor tiled layout that
    # pads the node axis to 128 lanes (2x HBM at N=64, the 64-node OOM in
    # bench_scaling's first run), and the layout copy propagates through
    # the whole exchange.
    scatter = np.zeros((num_nodes, len(comp_idx)), dtype=np.float32)
    scatter[comp_idx, np.arange(len(comp_idx))] = 1.0

    def apply(flat, compromised_mask, key, round_idx):
        if flat.shape[0] == num_nodes and len(comp_idx):
            # Full-network view (the jitted round step): the compromised set
            # is static, so draw noise for those C rows only — a [C, P]
            # threefry instead of [N, P] (RNG generation is a measurable
            # slice of the round on TPU; bench_breakdown.json).  The traced
            # mask still gates the add, so semantics match the dense path.
            noise = (
                jax.random.normal(key, (len(comp_idx),) + flat.shape[1:], flat.dtype)
                * noise_std
                * compromised_mask[comp_idx, None]
            )
            return flat + (
                jnp.asarray(scatter, flat.dtype) @ noise
            ).astype(flat.dtype)
        # Per-node views (ZMQ backend passes [1, P] with a ones mask).
        noise = jax.random.normal(key, flat.shape, flat.dtype) * noise_std
        return jnp.where(compromised_mask[:, None] > 0, flat + noise, flat)

    return Attack(name="gaussian", compromised=compromised, apply=apply)

"""Gaussian noise attack (reference: murmura/attacks/gaussian.py:10-90).

Compromised nodes broadcast state + N(0, noise_std^2) noise; all parameters
here are float (no BatchNorm integer buffers — see models/core.py), so the
reference's dtype special-casing (gaussian.py:82-88) has no counterpart.
"""

import jax
import jax.numpy as jnp
import numpy as np

from murmura_tpu.attacks.base import Attack, select_compromised


def make_gaussian_attack(
    num_nodes: int,
    attack_percentage: float,
    noise_std: float = 10.0,
    seed: int = 42,
) -> Attack:
    compromised = select_compromised(num_nodes, attack_percentage, seed)
    comp_idx = np.flatnonzero(compromised)

    def apply(flat, compromised_mask, key, round_idx):
        if flat.shape[0] == num_nodes and len(comp_idx):
            # Full-network view (the jitted round step): the compromised set
            # is static, so draw noise for those C rows only — a [C, P]
            # threefry instead of [N, P] (RNG generation is a measurable
            # slice of the round on TPU; bench_breakdown.json).  The traced
            # mask still gates the add, so semantics match the dense path.
            noise = (
                jax.random.normal(key, (len(comp_idx),) + flat.shape[1:], flat.dtype)
                * noise_std
                * compromised_mask[comp_idx, None]
            )
            return flat.at[comp_idx].add(noise)
        # Per-node views (ZMQ backend passes [1, P] with a ones mask).
        noise = jax.random.normal(key, flat.shape, flat.dtype) * noise_std
        return jnp.where(compromised_mask[:, None] > 0, flat + noise, flat)

    return Attack(name="gaussian", compromised=compromised, apply=apply)

"""Byzantine attack simulation (reference: murmura/attacks/)."""

from murmura_tpu.attacks.base import Attack, select_compromised
from murmura_tpu.attacks.gaussian import make_gaussian_attack
from murmura_tpu.attacks.directed import make_directed_deviation_attack
from murmura_tpu.attacks.topology_liar import make_topology_liar_attack, false_claims
from murmura_tpu.attacks.alie import make_alie_attack
from murmura_tpu.attacks.ipm import make_ipm_attack
from murmura_tpu.attacks.label_flip import make_label_flip, poison_labels
from murmura_tpu.attacks.adaptive import (
    ADAPTIVE_ATTACKS,
    ATTACK_STATE_KEYS,
    AdaptiveAttack,
    make_adaptive_alie_attack,
    make_bisection_attack,
)

ATTACKS = {
    "gaussian": make_gaussian_attack,
    "directed_deviation": make_directed_deviation_attack,
    "topology_liar": make_topology_liar_attack,
    "alie": make_alie_attack,
    "ipm": make_ipm_attack,
    "label_flip": make_label_flip,
}

__all__ = [
    "Attack",
    "AdaptiveAttack",
    "select_compromised",
    "make_gaussian_attack",
    "make_directed_deviation_attack",
    "make_topology_liar_attack",
    "make_alie_attack",
    "make_ipm_attack",
    "make_label_flip",
    "make_adaptive_alie_attack",
    "make_bisection_attack",
    "poison_labels",
    "false_claims",
    "ATTACKS",
    "ADAPTIVE_ATTACKS",
    "ATTACK_STATE_KEYS",
]

"""Byzantine attack simulation (reference: murmura/attacks/)."""

from murmura_tpu.attacks.base import Attack, select_compromised
from murmura_tpu.attacks.gaussian import make_gaussian_attack
from murmura_tpu.attacks.directed import make_directed_deviation_attack
from murmura_tpu.attacks.topology_liar import make_topology_liar_attack, false_claims
from murmura_tpu.attacks.alie import make_alie_attack
from murmura_tpu.attacks.ipm import make_ipm_attack

ATTACKS = {
    "gaussian": make_gaussian_attack,
    "directed_deviation": make_directed_deviation_attack,
    "topology_liar": make_topology_liar_attack,
    "alie": make_alie_attack,
    "ipm": make_ipm_attack,
}

__all__ = [
    "Attack",
    "select_compromised",
    "make_gaussian_attack",
    "make_directed_deviation_attack",
    "make_topology_liar_attack",
    "make_alie_attack",
    "make_ipm_attack",
    "false_claims",
    "ATTACKS",
]

"""ALIE — "A Little Is Enough" colluding attack (Baruch et al.,
NeurIPS 2019).  No reference counterpart (murmura ships gaussian /
directed_deviation / topology_liar); included beyond parity because it is
the canonical *stealth* Byzantine attack the robust-aggregation literature
evaluates against: instead of shouting (large noise / sign flips), the
colluding nodes all broadcast the same vector

    mu_honest - z * sigma_honest        (coordinate-wise)

placed just inside the benign variance envelope, where distance- and
score-based defenses cannot distinguish it from an honest straggler.  The
deviation factor z is chosen from the normal quantile so that the
malicious value is closer to the honest mean than the furthest
``s = floor(n/2) + 1 - m`` honest nodes are expected to be (the paper's
z_max rule), or can be overridden via ``params: {z: ...}``.

This is a *colluding* attack, and the two backends realize the collusion
differently:

- simulation/tpu (the jitted round step): mu/sigma are computed over the
  TRUE honest rows of the ``[N, P]`` broadcast tensor.  This is the
  *omniscient* variant — strictly STRONGER than the paper's construction
  (Baruch et al. estimate the population statistics from the m corrupted
  workers' own benign gradients).  Results labeled "ALIE" from these
  backends should carry that caveat (see experiments/extras and
  RESULTS_SUMMARY).
- distributed (ZMQ): no process sees the honest population, so each
  colluder broadcasts its benign locally-trained state to the coalition
  (``MsgType.COLLUDE_STATE`` — attackers coordinate out-of-band by
  construction) and estimates mu/sigma from the coalition sample.  This
  IS the paper's estimator; see
  ``NodeProcess._colluding_state``/``colluding_vector`` below.
"""

from statistics import NormalDist
from typing import Optional

import jax.numpy as jnp
import numpy as np

from murmura_tpu.attacks.base import Attack, select_compromised


def alie_z_max(num_nodes: int, num_compromised: int) -> float:
    """The paper's z_max: the largest z with
    phi(z) <= (n - m - s) / (n - m), s = floor(n/2) + 1 - m.

    For m >= the majority (s <= 0) the quantile saturates; the clamp keeps
    the construction defined (the attack is trivially unstoppable there).
    """
    n, m = int(num_nodes), int(num_compromised)
    honest = max(n - m, 1)
    s = n // 2 + 1 - m
    cdf = (honest - s) / honest
    cdf = min(max(cdf, 1e-9), 1.0 - 1e-9)
    return float(NormalDist().inv_cdf(cdf))


def resolve_alie_z(
    num_nodes: int, num_compromised: int, z: Optional[float] = None
) -> float:
    """Single z-resolution rule shared by the jitted attack
    (make_alie_attack) and the ZMQ coalition path
    (NodeProcess._colluding_state): explicit override wins, else the
    paper's z_max."""
    return float(z) if z is not None else alie_z_max(num_nodes, num_compromised)


def colluding_vector(benign_states: np.ndarray, z: float) -> np.ndarray:
    """The paper's malicious vector from a coalition sample: mu - z*sigma
    over the colluders' own benign states ([M, P], M >= 1).

    Statistics accumulate in f64 on the host (this runs in the ZMQ
    NodeProcess, outside jit) and return f32 — the wire dtype.  With a
    single colluder sigma is 0 and the vector degenerates to the benign
    state (the paper's construction needs M >= 2 to estimate variance).
    """
    s = np.asarray(benign_states, dtype=np.float64)
    mu = s.mean(axis=0)
    sigma = s.std(axis=0)
    return (mu - float(z) * sigma).astype(np.float32)


def make_alie_attack(
    num_nodes: int,
    attack_percentage: float,
    z: Optional[float] = None,
    seed: int = 42,
    estimator: str = "omniscient",
) -> Attack:
    """``estimator`` selects whose rows the mu/sigma statistics come from
    on the jitted backends (``attack.params.estimator``):

    - ``"omniscient"`` (default, the historical behavior): the TRUE
      honest rows — strictly STRONGER than the paper's construction
      (module docstring caveat applies to results labeled "ALIE");
    - ``"coalition"``: the compromised rows' own benign-trained states
      only — Baruch et al.'s actual estimator, matching the ZMQ
      backend's ``_colluding_state``.  The colluders must therefore RUN
      local training (``trains_locally``, like label_flip) so their rows
      hold benign gradients rather than frozen init params.
    """
    if estimator not in ("omniscient", "coalition"):
        raise ValueError(
            f"ALIE estimator must be 'omniscient' or 'coalition', "
            f"got {estimator!r}"
        )
    compromised = select_compromised(num_nodes, attack_percentage, seed)
    comp_idx = np.flatnonzero(compromised)
    z_val = resolve_alie_z(num_nodes, len(comp_idx), z)

    def apply(flat, compromised_mask, key, round_idx):
        if flat.shape[0] != num_nodes or not len(comp_idx):
            # Per-node view: no population statistics exist here.
            # The ZMQ backend never routes ALIE through this function —
            # NodeProcess._execute_round branches to the coalition
            # estimator (_colluding_state) instead, and the factory
            # rejects the one distributed path without that branch
            # (alie+dmtt).  Reachable only from direct library use; pass
            # through rather than fabricate a non-colluding variant.
            return flat
        # Coordinate statistics in f32 (base.honest_mean; the variance
        # shares its mask/count for the same bf16-quantization reason).
        from murmura_tpu.attacks.adaptive import coalition_stats

        mu, var = coalition_stats(flat, compromised_mask, estimator)
        malicious = (mu - z_val * jnp.sqrt(var)).astype(flat.dtype)  # [1, P]
        # Elementwise select, not scatter (same layout rationale as the
        # gaussian attack's one-hot rewrite): every compromised row
        # broadcasts the identical colluding vector.
        return jnp.where(compromised_mask[:, None] > 0, malicious, flat)

    return Attack(
        name="alie",
        compromised=compromised,
        apply=apply,
        trains_locally=(estimator == "coalition"),
    )

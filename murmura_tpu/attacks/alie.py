"""ALIE — "A Little Is Enough" colluding attack (Baruch et al.,
NeurIPS 2019).  No reference counterpart (murmura ships gaussian /
directed_deviation / topology_liar); included beyond parity because it is
the canonical *stealth* Byzantine attack the robust-aggregation literature
evaluates against: instead of shouting (large noise / sign flips), the
colluding nodes all broadcast the same vector

    mu_honest - z * sigma_honest        (coordinate-wise)

placed just inside the benign variance envelope, where distance- and
score-based defenses cannot distinguish it from an honest straggler.  The
deviation factor z is chosen from the normal quantile so that the
malicious value is closer to the honest mean than the furthest
``s = floor(n/2) + 1 - m`` honest nodes are expected to be (the paper's
z_max rule), or can be overridden via ``params: {z: ...}``.

This is a *colluding* attack: computing mu/sigma over the honest rows
needs the full-network view, which the jitted round step has (the whole
``[N, P]`` broadcast tensor).  The per-process ZMQ backend has no such
view, so the factory rejects ``backend: distributed`` with a readable
ConfigError rather than silently running a weaker attack.
"""

from statistics import NormalDist
from typing import Optional

import jax.numpy as jnp
import numpy as np

from murmura_tpu.attacks.base import Attack, select_compromised


def alie_z_max(num_nodes: int, num_compromised: int) -> float:
    """The paper's z_max: the largest z with
    phi(z) <= (n - m - s) / (n - m), s = floor(n/2) + 1 - m.

    For m >= the majority (s <= 0) the quantile saturates; the clamp keeps
    the construction defined (the attack is trivially unstoppable there).
    """
    n, m = int(num_nodes), int(num_compromised)
    honest = max(n - m, 1)
    s = n // 2 + 1 - m
    cdf = (honest - s) / honest
    cdf = min(max(cdf, 1e-9), 1.0 - 1e-9)
    return float(NormalDist().inv_cdf(cdf))


def make_alie_attack(
    num_nodes: int,
    attack_percentage: float,
    z: Optional[float] = None,
    seed: int = 42,
) -> Attack:
    compromised = select_compromised(num_nodes, attack_percentage, seed)
    comp_idx = np.flatnonzero(compromised)
    z_val = (
        float(z) if z is not None else alie_z_max(num_nodes, len(comp_idx))
    )

    def apply(flat, compromised_mask, key, round_idx):
        if flat.shape[0] != num_nodes or not len(comp_idx):
            # Per-node view (ZMQ backend): no honest-population statistics
            # exist here — the factory rejects that wiring at build time,
            # so this is only reachable from direct library use; pass
            # through rather than fabricate a non-colluding variant.
            return flat
        # Honest-population coordinate statistics in f32 (a bf16 variance
        # over N rows would quantize the small sigmas the stealth margin
        # depends on).
        f32 = flat.astype(jnp.float32)
        hm = (1.0 - compromised_mask.astype(jnp.float32))[:, None]  # [N, 1]
        cnt = jnp.maximum(hm.sum(), 1.0)
        mu = (f32 * hm).sum(axis=0, keepdims=True) / cnt
        var = (jnp.square(f32 - mu) * hm).sum(axis=0, keepdims=True) / cnt
        malicious = (mu - z_val * jnp.sqrt(var)).astype(flat.dtype)  # [1, P]
        # Elementwise select, not scatter (same layout rationale as the
        # gaussian attack's one-hot rewrite): every compromised row
        # broadcasts the identical colluding vector.
        return jnp.where(compromised_mask[:, None] > 0, malicious, flat)

    return Attack(name="alie", compromised=compromised, apply=apply)

"""Label-flipping data poisoning — beyond-parity threat model #3.

No reference counterpart (murmura's three attacks all perturb the
*broadcast model states*; murmura/attacks/).  Label flipping poisons the
TRAINING DATA of compromised nodes instead: their local SGD then produces
honest-looking parameter updates whose statistics sit inside the benign
distribution, so distance-based Byzantine filters (Krum, BALANCE,
trimmed mean) have nothing to reject — the canonical argument for why
robust aggregation alone is not a data-poisoning defense (Tolpegin et
al. 2020, "Data Poisoning Attacks Against Federated Learning Systems").

Mechanics:

- the broadcast transform is the identity (states pass through exactly);
- compromised nodes are NOT frozen during local training
  (``Attack.trains_locally``) — the poison rides their gradients;
- the flip itself happens once at build time (factories): a seeded
  ``flip_fraction`` of each compromised node's training labels is rotated
  ``y -> (y + 1) % num_classes`` (deterministic offset flip, the standard
  untargeted variant; eval splits stay clean so accuracy measures real
  damage, not mislabeled tests).
"""

from typing import Optional

import numpy as np

from murmura_tpu.attacks.base import Attack, select_compromised


def poison_labels(
    y: np.ndarray,
    sample_mask: np.ndarray,
    compromised: np.ndarray,
    num_classes: int,
    flip_fraction: float = 1.0,
    seed: int = 42,
) -> np.ndarray:
    """Rotated-label copy of ``y`` on the compromised rows.

    Args:
        y: [N, S] int labels (padded positions ignored via sample_mask).
        sample_mask: [N, S] 1.0 where the sample is real.
        compromised: [N] bool.
        flip_fraction: fraction of each compromised node's REAL samples
            flipped (seeded choice without replacement).
    """
    if not 0.0 < flip_fraction <= 1.0:
        raise ValueError(
            f"flip_fraction must be in (0, 1], got {flip_fraction}"
        )
    out = np.array(y, copy=True)
    rng = np.random.default_rng(seed)
    for i in np.flatnonzero(compromised):
        real = np.flatnonzero(np.asarray(sample_mask[i]) > 0)
        if real.size == 0:
            continue
        k = max(1, int(round(flip_fraction * real.size)))
        chosen = rng.choice(real, size=min(k, real.size), replace=False)
        out[i, chosen] = (out[i, chosen] + 1) % num_classes
    return out


def make_label_flip(
    num_nodes: int,
    attack_percentage: float,
    flip_fraction: float = 1.0,
    seed: int = 42,
    **_params,
) -> Attack:
    if not 0.0 < flip_fraction <= 1.0:
        raise ValueError(
            f"flip_fraction must be in (0, 1], got {flip_fraction}"
        )
    compromised = select_compromised(num_nodes, attack_percentage, seed)

    def apply(flat, compromised_mask, key, round_idx):
        # Identity: the poison is in the data, not the broadcast states.
        return flat

    def data_poison_fn(y, sample_mask, num_classes):
        return poison_labels(
            y, sample_mask, compromised, num_classes,
            flip_fraction=flip_fraction, seed=seed,
        )

    return Attack(
        name="label_flip",
        compromised=compromised,
        apply=apply,
        trains_locally=True,
        data_poison_fn=data_poison_fn,
    )

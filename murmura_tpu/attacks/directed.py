"""Directed deviation attack (reference: murmura/attacks/directed.py:10-89).

Compromised nodes broadcast lambda * state (default lambda = -5.0: push in
the opposite direction, amplified).
"""

import jax.numpy as jnp

from murmura_tpu.attacks.base import Attack, select_compromised


def make_directed_deviation_attack(
    num_nodes: int,
    attack_percentage: float,
    lambda_param: float = -5.0,
    seed: int = 42,
) -> Attack:
    compromised = select_compromised(num_nodes, attack_percentage, seed)

    def apply(flat, compromised_mask, key, round_idx):
        return jnp.where(compromised_mask[:, None] > 0, lambda_param * flat, flat)

    return Attack(name="directed_deviation", compromised=compromised, apply=apply)

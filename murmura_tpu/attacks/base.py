"""Attack interface and compromised-node selection
(reference: murmura/attacks/base.py:8-52).

An attack is a pure transform of the *outgoing* broadcast states:
``apply(flat[N, P], compromised[N], key, round_idx) -> flat'`` — honest rows
pass through untouched.  Compromised nodes additionally skip local training
(frozen models) exactly as in the reference (murmura/core/network.py:99-101);
that masking lives in the round step, keyed off the same mask produced here.
"""

import random
from dataclasses import dataclass, field
from typing import Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np


def select_compromised(num_nodes: int, percentage: float, seed: int = 42) -> np.ndarray:
    """Seeded compromised-node selection with the reference's exact rule
    (gaussian.py:36-44): ceil-to-1 when percentage > 0, ``random.sample``
    under ``random.seed(seed)``.

    Returns:
        [N] boolean mask.
    """
    num = int(num_nodes * percentage)
    if num == 0 and percentage > 0:
        num = 1
    rng = random.Random(seed)
    chosen = rng.sample(range(num_nodes), min(num, num_nodes)) if num > 0 else []
    mask = np.zeros(num_nodes, dtype=bool)
    mask[list(chosen)] = True
    return mask


def honest_mean(flat: jnp.ndarray, compromised_mask: jnp.ndarray) -> jnp.ndarray:
    """[1, P] mean over the honest rows of the broadcast tensor, reduced
    in f32 regardless of param dtype (a bf16 accumulation over N rows
    would quantize the statistics the colluding attacks manipulate).
    Shared by the omniscient paths of ALIE and IPM."""
    f32 = flat.astype(jnp.float32)
    hm = (1.0 - compromised_mask.astype(jnp.float32))[:, None]  # [N, 1]
    cnt = jnp.maximum(hm.sum(), 1.0)
    return (f32 * hm).sum(axis=0, keepdims=True) / cnt


@dataclass(frozen=True)
class Attack:
    """A named attack with its compromised set and pure state transform."""

    name: str
    compromised: np.ndarray  # [N] bool
    apply: Callable[
        [jnp.ndarray, jnp.ndarray, Optional[jax.Array], jnp.ndarray], jnp.ndarray
    ]
    # DMTT topology-liar claims hook (None for model-only attacks)
    claims_fn: Optional[Callable] = field(default=None)
    # Data-poisoning attacks (label_flip) need their compromised nodes to
    # RUN local SGD — the poison propagates through honest-looking
    # gradients — where every model-state attack keeps them frozen
    # (reference: murmura/core/network.py:99-101).  The round step keys
    # its training mask off this flag.
    trains_locally: bool = False
    # Build-time data transform for poisoning attacks:
    # (y [N, S], sample_mask [N, S], num_classes) -> y'.  Closes over the
    # attack's own compromised set / fraction / seed so the factories
    # never re-parse attack params (single source of truth).
    data_poison_fn: Optional[Callable] = field(default=None)

    def is_compromised(self, node_id: int) -> bool:
        return bool(self.compromised[node_id])

    def get_compromised_nodes(self) -> set:
        return set(np.flatnonzero(self.compromised).tolist())

    @property
    def honest_mask(self) -> np.ndarray:
        return ~self.compromised

"""IPM — inner-product manipulation colluding attack (Xie, Koyejo &
Gupta, UAI 2020 "Fall of Empires").  No reference counterpart (murmura
ships gaussian / directed_deviation / topology_liar); included beyond
parity as the second canonical colluding attack the robust-aggregation
literature evaluates against, complementing ALIE:

    malicious = -epsilon * mu_honest

Every colluder broadcasts the negated (scaled) honest mean, so the inner
product between the aggregate and the true descent direction is driven
negative (epsilon >= 1 flips the update outright; small epsilon slows
convergence while staying inside distance filters — the stealth regime).
Where ALIE hides inside the per-coordinate variance envelope, IPM attacks
the *direction* of the aggregate.

Backend realization mirrors ALIE exactly (attacks/alie.py module
docstring): the jitted backends use the omniscient honest-population mean
(strictly stronger than the paper's estimator); the ZMQ backend estimates
the mean from the coalition's own benign states via the same
COLLUDE_STATE exchange (``NodeProcess._colluding_state``); a single
colluder degenerates to broadcasting ``-epsilon * own_benign_state``,
which — unlike ALIE's sigma=0 case — is still a real attack, so no
minimum-coalition guard is needed.
"""

from typing import Optional

import jax.numpy as jnp
import numpy as np

from murmura_tpu.attacks.base import Attack, honest_mean, select_compromised

# Shared by the factory and the ZMQ coalition path so the two backends
# resolve the same epsilon for the same config (the resolve_alie_z
# pattern).
DEFAULT_EPSILON = 1.5


def resolve_ipm_epsilon(epsilon: Optional[float] = None) -> float:
    return DEFAULT_EPSILON if epsilon is None else float(epsilon)


def ipm_vector(benign_states: np.ndarray, epsilon: float) -> np.ndarray:
    """The paper's malicious vector from a coalition sample ([M, P]):
    -epsilon * mean.  f64 host statistics, f32 wire dtype (same contract
    as alie.colluding_vector)."""
    s = np.asarray(benign_states, dtype=np.float64)
    return (-float(epsilon) * s.mean(axis=0)).astype(np.float32)


def make_ipm_attack(
    num_nodes: int,
    attack_percentage: float,
    epsilon: Optional[float] = None,
    seed: int = 42,
) -> Attack:
    compromised = select_compromised(num_nodes, attack_percentage, seed)
    comp_idx = np.flatnonzero(compromised)
    eps = resolve_ipm_epsilon(epsilon)

    def apply(flat, compromised_mask, key, round_idx):
        if flat.shape[0] != num_nodes or not len(comp_idx):
            # Per-node view: the ZMQ backend routes IPM through the
            # coalition estimator (NodeProcess._colluding_state), never
            # through this function — reachable only from direct library
            # use; pass through (same contract as alie.py).
            return flat
        malicious = (-eps * honest_mean(flat, compromised_mask)).astype(
            flat.dtype
        )  # [1, P]
        return jnp.where(compromised_mask[:, None] > 0, malicious, flat)

    return Attack(name="ipm", compromised=compromised, apply=apply)

"""Topology liar attack for DMTT (reference: murmura/attacks/topology_liar.py:14-102).

Liars optionally poison their broadcast model via a wrapped inner attack
(topology_liar.py:57-72) and falsify their TOPO_CLAIM: the claimed neighbor
set is the true G^t neighbors UNION all other Byzantine nodes
(topology_liar.py:78-102), inflating the apparent connectivity of the
Byzantine coalition.
"""

from typing import Optional

import jax.numpy as jnp
import numpy as np

from murmura_tpu.attacks.base import Attack, select_compromised


def false_claims(
    true_adj: jnp.ndarray, compromised_mask: jnp.ndarray
) -> jnp.ndarray:
    """Claimed-adjacency tensor [N, N]: row i is node i's TOPO_CLAIM.

    Honest rows equal the true adjacency; liar rows add every other
    compromised node (reference: topology_liar.py:78-102).
    """
    comp = compromised_mask > 0
    coalition = comp[None, :] & comp[:, None]
    coalition = coalition & ~jnp.eye(true_adj.shape[0], dtype=bool)
    liar_rows = (true_adj > 0) | coalition
    return jnp.where(comp[:, None], liar_rows, true_adj > 0).astype(true_adj.dtype)


def make_topology_liar_attack(
    num_nodes: int,
    attack_percentage: float,
    seed: int = 42,
    model_attack: Optional[Attack] = None,
) -> Attack:
    compromised = select_compromised(num_nodes, attack_percentage, seed)
    if model_attack is not None and not np.array_equal(
        model_attack.compromised, compromised
    ):
        # The inner attack's static fast paths (e.g. gaussian's
        # compromised-rows-only noise) key off ITS compromised set; a
        # mismatched selection would silently leave some liars unpoisoned.
        # The factories construct both from the same (n, pct, seed), so a
        # mismatch here is always a wiring bug — fail loudly.
        raise ValueError(
            "topology_liar's wrapped model_attack selected a different "
            "compromised set; build the inner attack with the same "
            "num_nodes/attack_percentage/seed"
        )

    def apply(flat, compromised_mask, key, round_idx):
        """Model poisoning is delegated to the wrapped inner attack
        (topology_liar.py:57-72); pure liars broadcast honest states.
        The liar's compromised mask is passed through, and construction
        guarantees the inner attack's own selection matches it."""
        if model_attack is None:
            return flat
        return model_attack.apply(flat, compromised_mask, key, round_idx)

    return Attack(
        name="topology_liar",
        compromised=compromised,
        apply=apply,
        claims_fn=false_claims,
    )

"""Serving layer (ISSUE 18; docs/ROBUSTNESS.md "Serving"): the
compile-compatible grid scheduler (`murmura grid`) and the
crash-surviving multi-tenant daemon (`murmura serve` / `murmura submit`).

Both legs stand on the same invariant: a config's trace-relevant content
(its structural fingerprint / jaxpr skeleton) decides which compiled
bucket can run it, and everything else — seed, lr, attack strength — is
a traced input spliced into warm lanes.  Contracted as MUR1600-1603
(analysis/serve.py, in the default `murmura check` package gate).
"""

from murmura_tpu.serve.scheduler import (
    GridBucket,
    GridCell,
    expand_cells,
    load_grid,
    plan_grid,
    program_skeleton,
    run_grid,
    structural_fingerprint,
    write_grid,
)
from murmura_tpu.serve.daemon import (
    ServeDaemon,
    SubmissionError,
    normalize_submission,
)
from murmura_tpu.serve.protocol import ServerSocket, send_request

__all__ = [
    "GridBucket",
    "GridCell",
    "expand_cells",
    "load_grid",
    "plan_grid",
    "program_skeleton",
    "run_grid",
    "structural_fingerprint",
    "write_grid",
    "ServeDaemon",
    "SubmissionError",
    "normalize_submission",
    "ServerSocket",
    "send_request",
]

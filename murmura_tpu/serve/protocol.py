"""The daemon's wire protocol (ISSUE 18 leg (b)): JSON-line requests over
a local unix-domain socket.

Deliberately minimal — one request, one JSON object per line, one JSON
response, close.  The daemon is a single-host experiment multiplexer,
not a network service: the socket exists so `murmura submit` (and the
soak harness) can hand work to a long-lived process without sharing a
Python heap.  Requests:

- ``{"op": "submit", "config": {...}}`` -> ``{"ok": true, "id": ...,
  "bucket": ...}``
- ``{"op": "status", "id": ...}`` -> the submission's ledger record
- ``{"op": "list"}`` -> every submission's summary row + cumulative
  daemon counters
- ``{"op": "ping"}`` -> liveness, uptime, package/schema versions,
  cumulative counters, bucket census
- ``{"op": "metrics"}`` -> the daemon's OpenMetrics scrape
  (telemetry/metrics.py; ``text`` carries the exposition, read-only —
  MUR1701 guarantees a polling loop cannot perturb tenants)
- ``{"op": "shutdown"}`` -> graceful stop after the current generation

Client sends ride :func:`durability.dispatch.run_with_retry` with the
socket-layer transient classification (``classify_error``): a daemon
mid-restart (connection refused / reset / stale socket file) is a
transient to retry into, not a fatal error — exactly the crash-surviving
story the daemon exists for.
"""

import json
import os
import socket
import time
from typing import Any, Dict, Optional

from murmura_tpu.durability.dispatch import (
    RetryPolicy,
    classify_error,
    run_with_retry,
)

# One request/response per connection; a well-formed line is tiny, so a
# hard cap keeps a garbage client from ballooning the daemon's memory.
MAX_LINE_BYTES = 4 * 1024 * 1024


def _read_line(sock: socket.socket) -> bytes:
    chunks = []
    total = 0
    while True:
        chunk = sock.recv(65536)
        if not chunk:
            break
        chunks.append(chunk)
        total += len(chunk)
        if total > MAX_LINE_BYTES:
            raise ValueError(
                f"request exceeds {MAX_LINE_BYTES} bytes — not a protocol "
                "line"
            )
        if chunk.endswith(b"\n"):
            break
    return b"".join(chunks)


def send_request(
    socket_path: str,
    request: Dict[str, Any],
    *,
    timeout: float = 30.0,
    retries: int = 5,
    base_delay_s: float = 0.2,
    sleep=time.sleep,
) -> Dict[str, Any]:
    """Send one request; returns the decoded response dict.

    Socket-layer failures (refused/reset/broken pipe/timeout — a daemon
    that is restarting after a SIGKILL) are classified transient by
    ``classify_error`` and retried with backoff; anything else raises
    through immediately."""
    policy = RetryPolicy(
        max_retries=retries, base_delay_s=base_delay_s, max_delay_s=2.0,
    )

    def attempt(_try_idx: int) -> Dict[str, Any]:
        with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as sock:
            sock.settimeout(timeout)
            try:
                sock.connect(str(socket_path))
            except FileNotFoundError as e:
                # A unix-socket path that does not exist yet means the
                # daemon has not bound (starting, or restarting after a
                # kill) — semantically "connection refused", which is
                # transient; a bare ENOENT would classify fatal.
                raise ConnectionRefusedError(
                    f"no daemon socket at {socket_path} (not bound yet?)"
                ) from e
            sock.sendall(
                json.dumps(request).encode("utf-8") + b"\n"
            )
            payload = _read_line(sock)
        if not payload:
            # The daemon died between accept and reply: transient.
            raise ConnectionResetError(
                f"daemon at {socket_path} closed the connection without "
                "replying"
            )
        return json.loads(payload.decode("utf-8"))

    return run_with_retry(
        attempt, policy=policy, classify=classify_error, sleep=sleep,
    )


class ServerSocket:
    """The daemon's listening unix socket, with stale-file recovery.

    A SIGKILL'd daemon leaves its socket file behind; the restarted
    daemon must reclaim the address.  Binding retries through
    ``run_with_retry`` with ``EADDRINUSE`` classified transient
    (durability/dispatch.py), unlinking the stale file between
    attempts — a LIVE daemon on the same path still wins (its bind
    holds the address after the unlink race is lost at connect time).
    """

    def __init__(self, path: str, *, backlog: int = 16):
        self.path = str(path)
        self._sock: Optional[socket.socket] = None
        policy = RetryPolicy(
            max_retries=3, base_delay_s=0.05, max_delay_s=0.5,
        )

        def attempt(try_idx: int) -> socket.socket:
            if try_idx > 0 and os.path.exists(self.path):
                # Stale socket file from a killed daemon: reclaim it.
                try:
                    os.unlink(self.path)
                except OSError:
                    pass
            sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            try:
                sock.bind(self.path)
            except OSError:
                sock.close()
                raise
            sock.listen(backlog)
            return sock

        self._sock = run_with_retry(
            attempt, policy=policy, classify=classify_error,
        )

    def accept(self, timeout: Optional[float] = None):
        assert self._sock is not None
        self._sock.settimeout(timeout)
        return self._sock.accept()

    def close(self) -> None:
        if self._sock is not None:
            self._sock.close()
            self._sock = None
        try:
            os.unlink(self.path)
        except OSError:
            pass


def serve_connection(conn: socket.socket, handler) -> None:
    """Read one request line, dispatch to ``handler(dict) -> dict``,
    reply, close.  A malformed request gets an error response instead of
    killing the listener."""
    try:
        with conn:
            conn.settimeout(30.0)
            payload = _read_line(conn)
            if not payload:
                return
            try:
                request = json.loads(payload.decode("utf-8"))
                response = handler(request)
            except Exception as e:  # noqa: BLE001 — reply, don't die
                response = {
                    "ok": False,
                    "error": f"{type(e).__name__}: {e}",
                }
            conn.sendall(json.dumps(response).encode("utf-8") + b"\n")
    except OSError:
        # The client vanished mid-reply — its problem, not the daemon's.
        pass

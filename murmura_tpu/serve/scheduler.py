"""`murmura grid <yaml>`: the compile-compatible grid scheduler
(ISSUE 18 leg (a); docs/ROBUSTNESS.md "Serving").

The paper's evaluation grid is rule x attack x topology x strength x
seed, but only a FRACTION of those axes is trace-relevant: strength is a
traced ``attack_scale`` input and seed is an RNG lane, while rule, attack
type and topology family change the traced program.  This scheduler makes
that split explicit and machine-checked:

- **Bucketing key = the jaxpr skeleton.**  Every (rule, attack, topology)
  cell class traces one representative member program and takes
  :func:`analysis.ir.jaxpr_signature` of it — the depth-annotated
  primitive sequence MUR203/MUR500 already use for structural equality.
  Cells share a bucket iff their skeletons are equal (MUR1600).  Classes
  whose skeletons collide but whose configs are not value-compatible
  (different trace-time closure constants — e.g. two rules that happen to
  lower to the same primitive sequence with different baked parameters)
  cannot share one *compiled* bucket, so the scheduler refuses them loud
  instead of silently paying a hidden recompile.
- **One compile per bucket.**  A bucket's strength x seed cells become
  gang members (core/gang.py) padded to the power-of-two ``next_bucket``
  lane count, trained on the fused multi-round path — ONE compile covers
  every cell in the bucket, verified per bucket by
  :class:`analysis.sanitizers.CompileTracker` and recorded in the
  manifest.
- **One cross-cell manifest.**  ``grid.json`` carries the bucket plan
  (cells per bucket, compiles, wall), per-cell accuracy and phase-time
  accounting — rendered by ``murmura report --grid``.

The daemon (serve/daemon.py) reuses :func:`structural_fingerprint` as its
admission key: submissions whose configs differ only in trace-irrelevant
fields (experiment seed/name, training.lr — lifted to a traced ``hp_lr``
input) land in one warm bucket.
"""

import hashlib
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from murmura_tpu.config.schema import Config, GridConfig

GRID_SCHEMA_VERSION = 1

# Config sections that never reach the traced round program: identity,
# observability, durability and driver blocks.  Everything else is
# structural — it either changes the jaxpr skeleton or a trace-time
# closure constant, and therefore the bucket.
_NON_STRUCTURAL_SECTIONS = (
    "telemetry", "durability", "sweep", "frontier", "grid", "serve",
)
# Trace-irrelevant leaves inside structural sections: the member axis.
# ``training.lr`` is only value-varying when the gang lifts it to a
# traced ``hp_lr`` input, which the serve path always does.
_MEMBER_LEAVES = (("experiment", "name"), ("experiment", "seed"),
                  ("experiment", "verbose"), ("training", "lr"))


def structural_fingerprint(config: Config) -> str:
    """Stable hash of the config's trace-relevant content — the daemon's
    admission key.  Two configs with equal fingerprints build member
    programs that are value-compatible with one warm compiled bucket:
    same jaxpr skeleton AND same trace-time closure constants (attack
    placement/std, topology seed, rule params, shapes).  The executable
    MUR1600 contract verifies the skeleton half of this claim by
    re-tracing probe cells independently."""
    raw = config.model_dump()
    for section in _NON_STRUCTURAL_SECTIONS:
        raw.pop(section, None)
    for section, leaf in _MEMBER_LEAVES:
        if isinstance(raw.get(section), dict):
            raw[section].pop(leaf, None)
    blob = json.dumps(raw, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode("utf-8")).hexdigest()[:12]


def program_skeleton(prog) -> Tuple[str, ...]:
    """The round program's jaxpr skeleton: trace ``train_step`` over
    canonical inputs (the analysis/composition.py recipe) and take the
    MUR203 structural signature.  Trace-only — nothing compiles."""
    from murmura_tpu.analysis.composition import _trace_program
    from murmura_tpu.analysis.ir import jaxpr_signature

    return jaxpr_signature(_trace_program(prog))


@dataclass(frozen=True)
class GridCell:
    """One executable point of the grid."""

    rule: str
    attack: str
    topology: str
    strength: float
    seed: int

    @property
    def cell_id(self) -> str:
        return (
            f"{self.rule}/{self.attack}/{self.topology}"
            f"/g{self.strength:g}/s{self.seed}"
        )

    @property
    def class_key(self) -> Tuple[str, str, str]:
        """The cell's structural class: the axes that change the traced
        program.  Strength and seed are traced inputs inside a class."""
        return (self.rule, self.attack, self.topology)


@dataclass
class GridBucket:
    """One compile-compatible bucket: every cell shares the skeleton (and
    the class config's closure constants), so one gang = one compile."""

    key: str
    rule: str
    attack: str
    topology: str
    skeleton: Tuple[str, ...] = field(repr=False, default=())
    cells: List[GridCell] = field(default_factory=list)
    config: Optional[Config] = field(repr=False, default=None)


def expand_cells(config: Config, g: GridConfig) -> List[GridCell]:
    """The configured grid as a flat cell list.  Benign (``none``) cells
    carry strength 0 only — there is no perturbation to scale."""
    seeds = (
        [int(s) for s in g.seeds]
        if g.seeds is not None
        else [config.experiment.seed, config.experiment.seed + 1]
    )
    cells: List[GridCell] = []
    for rule in g.rules:
        for attack in g.attacks:
            strengths = [0.0] if attack == "none" else list(g.strengths)
            for topology in g.topologies:
                for strength in strengths:
                    for seed in seeds:
                        cells.append(GridCell(
                            rule=rule, attack=attack, topology=topology,
                            strength=float(strength), seed=int(seed),
                        ))
    return cells


def class_config(
    config: Config, g: GridConfig, rule: str, attack: str, topology: str,
    members: Optional[List[Dict[str, Any]]] = None,
) -> Config:
    """One structural class's runnable config, derived from the base
    experiment (the frontier._cell_config discipline): rule params come
    from the user's config for the configured rule, else the canonical
    AGG_CASES inventory; the attack placement is pinned to the base
    experiment seed so every member of every generation shares the
    attack's static closures; telemetry/durability/driver blocks are
    stripped — the grid manifest IS the output."""
    from murmura_tpu.analysis.ir import AGG_CASES

    raw = config.model_dump()
    raw["aggregation"] = {
        "algorithm": rule,
        "params": (
            dict(config.aggregation.params)
            if rule == config.aggregation.algorithm
            else dict(AGG_CASES.get(rule, {}))
        ),
    }
    base_attack = config.attack
    if attack == "none":
        raw["attack"] = {"enabled": False}
    else:
        params: Dict[str, Any] = {}
        if attack == "gaussian":
            params["noise_std"] = float(
                base_attack.params.get("noise_std", 10.0)
            ) if base_attack.type == "gaussian" else 10.0
        elif attack == "alie" and base_attack.type == "alie":
            if "z" in base_attack.params:
                params["z"] = base_attack.params["z"]
        # Pin the compromised placement to the base experiment seed so
        # every member shares the attack's static closures (the gang
        # contract, core/gang.py).
        params["seed"] = int(
            base_attack.params.get("seed", config.experiment.seed)
        )
        raw["attack"] = {
            "enabled": True,
            "type": attack,
            "percentage": (
                base_attack.percentage if base_attack.enabled else 0.25
            ),
            "params": params,
        }
    n = config.topology.num_nodes
    if topology == "sparse":
        raw["topology"] = {"type": "exponential", "num_nodes": n}
    elif config.topology.type in ("exponential", "one_peer"):
        raw["topology"] = {
            "type": "k-regular", "num_nodes": n, "k": min(4, n - 1),
        }
    else:
        raw["topology"] = config.topology.model_dump()
    if g.rounds is not None:
        raw["experiment"] = {**raw["experiment"], "rounds": int(g.rounds)}
    raw["experiment"]["verbose"] = False
    for section in _NON_STRUCTURAL_SECTIONS:
        raw.pop(section, None)
    if members is not None:
        raw["sweep"] = {"members": members}
    try:
        return Config.model_validate(raw)
    except Exception as e:  # noqa: BLE001 — surface as the CLI's error kind
        from murmura_tpu.utils.factories import ConfigError

        raise ConfigError(
            f"grid cell class {rule} x {attack} x {topology} does not "
            f"validate against the base config: {e}"
        ) from e


def _cell_members(cells: Sequence[GridCell], attack: str) -> List[Dict[str, Any]]:
    if attack == "none":
        return [{"seed": c.seed} for c in cells]
    return [
        {"seed": c.seed, "attack_scale": c.strength} for c in cells
    ]


def cell_skeleton(config: Config, g: GridConfig, cell: GridCell) -> Tuple[str, ...]:
    """One cell's INDEPENDENTLY-derived jaxpr skeleton: build that exact
    cell's single-member program and trace it.  The MUR1600 verification
    primitive — the planner's per-class representative trace must agree
    with every member cell's own trace."""
    from murmura_tpu.core.gang import resolve_members
    from murmura_tpu.utils.factories import build_gang_member_programs

    cfg = class_config(
        config, g, cell.rule, cell.attack, cell.topology,
        members=_cell_members([cell], cell.attack),
    )
    members = resolve_members(cfg)
    return program_skeleton(build_gang_member_programs(cfg, members)[0])


def plan_grid(config: Config, g: Optional[GridConfig] = None) -> List[GridBucket]:
    """Partition the configured grid into compile-compatible buckets.

    One representative member program is traced per structural class
    (rule x attack x topology); classes with equal skeletons would merge
    — but two classes with equal skeletons and DIFFERENT class configs
    have different trace-time closure constants, so a merged bucket could
    not actually share a compile, and the planner refuses loud (the
    MUR1600 ⇔ contract stays honest: on every grid this scheduler runs,
    same bucket ⇔ structurally equal skeletons).  Trace-only: nothing
    compiles or executes here."""
    from murmura_tpu.core.gang import resolve_members
    from murmura_tpu.utils.factories import ConfigError, build_gang_member_programs

    g = g or config.grid or GridConfig()
    from murmura_tpu.aggregation import AGGREGATORS

    unknown = sorted(set(g.rules) - set(AGGREGATORS))
    if unknown:
        raise ConfigError(
            f"grid.rules names unregistered aggregation rule(s) "
            f"{unknown}; known: {sorted(AGGREGATORS)}"
        )
    cells = expand_cells(config, g)
    classes: Dict[Tuple[str, str, str], List[GridCell]] = {}
    for cell in cells:
        classes.setdefault(cell.class_key, []).append(cell)

    by_skeleton: Dict[Tuple[str, ...], GridBucket] = {}
    buckets: List[GridBucket] = []
    for (rule, attack, topology), cls_cells in classes.items():
        cfg = class_config(
            config, g, rule, attack, topology,
            members=_cell_members(cls_cells, attack),
        )
        probe_cfg = class_config(
            config, g, rule, attack, topology,
            members=_cell_members(cls_cells[:1], attack),
        )
        probe = build_gang_member_programs(
            probe_cfg, resolve_members(probe_cfg)
        )[0]
        skeleton = program_skeleton(probe)
        prior = by_skeleton.get(skeleton)
        if prior is not None:
            raise ConfigError(
                f"grid classes {prior.rule} x {prior.attack} x "
                f"{prior.topology} and {rule} x {attack} x {topology} "
                "have structurally equal jaxpr skeletons but different "
                "configs — their trace-time closure constants differ, so "
                "one compiled bucket cannot serve both; differentiate "
                "the grid axes (or run them as separate grids)"
            )
        key = hashlib.sha256(
            "\n".join(skeleton).encode("utf-8")
        ).hexdigest()[:12]
        bucket = GridBucket(
            key=key, rule=rule, attack=attack, topology=topology,
            skeleton=skeleton, cells=list(cls_cells), config=cfg,
        )
        by_skeleton[skeleton] = bucket
        buckets.append(bucket)
    return buckets


def run_grid(
    config: Config,
    progress: Optional[Callable[[str], None]] = None,
) -> Dict[str, Any]:
    """Plan + execute the full grid; returns the ``grid.json`` manifest.

    Every bucket runs as one gang on the fused dispatch path
    (``rounds_per_dispatch=rounds``): one compile per bucket, counted by
    CompileTracker and recorded per bucket AND as the manifest total —
    the ≤-compiles acceptance gate is checkable from the artifact
    alone."""
    import time

    from murmura_tpu.analysis.sanitizers import track_compiles
    from murmura_tpu.core.gang import resolve_members
    from murmura_tpu.utils.factories import build_gang_from_config

    say = progress or (lambda s: None)
    g = config.grid or GridConfig()
    buckets = plan_grid(config, g)
    say(
        f"grid: {sum(len(b.cells) for b in buckets)} cells in "
        f"{len(buckets)} compile-compatible buckets"
    )

    bucket_rows: List[Dict[str, Any]] = []
    cell_rows: List[Dict[str, Any]] = []
    total_compiles = 0
    for bucket in buckets:
        cfg = bucket.config
        rounds = cfg.experiment.rounds
        say(
            f"bucket {bucket.key} ({bucket.rule} x {bucket.attack} x "
            f"{bucket.topology}): {len(bucket.cells)} cells"
        )
        gang = build_gang_from_config(cfg)
        t0 = time.perf_counter()
        with track_compiles() as tracker:
            histories = gang.train(
                rounds=rounds, eval_every=rounds,
                rounds_per_dispatch=rounds,
            )
        wall = time.perf_counter() - t0
        compiles = tracker.total
        total_compiles += compiles
        bucket_rows.append({
            "key": bucket.key,
            "rule": bucket.rule,
            "attack": bucket.attack,
            "topology": bucket.topology,
            "cells": [c.cell_id for c in bucket.cells],
            "batch": gang.batch,
            "gang_size": gang.gang_size,
            "rounds": rounds,
            "compiles": compiles,
            "wall_s": wall,
            "skeleton_eqns": len(bucket.skeleton),
        })
        mean_round_s = (
            float(np.mean(gang.round_times)) if gang.round_times else 0.0
        )
        for i, cell in enumerate(bucket.cells):
            hist = histories[i]
            honest = hist.get("honest_accuracy") or hist.get("mean_accuracy")
            mean = hist.get("mean_accuracy")
            cell_rows.append({
                "id": cell.cell_id,
                "rule": cell.rule,
                "attack": cell.attack,
                "topology": cell.topology,
                "strength": cell.strength,
                "seed": cell.seed,
                "bucket": bucket.key,
                "final_accuracy": float(mean[-1]) if mean else None,
                "honest_accuracy": float(honest[-1]) if honest else None,
                "phase_times": {
                    "mode": "gang_fused",
                    "rounds": rounds,
                    "bucket_wall_s": wall,
                    "mean_round_s": mean_round_s,
                },
            })

    seeds = (
        [int(s) for s in g.seeds]
        if g.seeds is not None
        else [config.experiment.seed, config.experiment.seed + 1]
    )
    return {
        "schema_version": GRID_SCHEMA_VERSION,
        "generated_by": "murmura grid",
        "experiment": config.experiment.name,
        "grid": {
            "rules": list(g.rules),
            "attacks": list(g.attacks),
            "topologies": list(g.topologies),
            "strengths": list(g.strengths),
            "seeds": seeds,
            "rounds": g.rounds or config.experiment.rounds,
            "num_nodes": config.topology.num_nodes,
        },
        "buckets": bucket_rows,
        "cells": cell_rows,
        "total_cells": len(cell_rows),
        "total_compiles": total_compiles,
    }


def write_grid(artifact: Dict[str, Any], path) -> Path:
    """Durably write the manifest (the frontier/checkpoint fsync
    discipline — a grid run is minutes of compute the write must not
    tear)."""
    from murmura_tpu.utils.checkpoint import durable_replace

    path = Path(path).resolve()
    path.parent.mkdir(parents=True, exist_ok=True)
    durable_replace(
        path.parent, path.name,
        (json.dumps(artifact, indent=2) + "\n").encode("utf-8"),
    )
    return path


def load_grid(path) -> Dict[str, Any]:
    with open(path, encoding="utf-8") as fh:
        artifact = json.load(fh)
    if "buckets" not in artifact or "cells" not in artifact:
        raise ValueError(
            f"{path} is not a grid manifest (no 'buckets'/'cells' section)"
        )
    return artifact

"""`murmura serve`: the crash-surviving multi-tenant daemon (ISSUE 18
leg (b); docs/ROBUSTNESS.md "Serving").

The daemon multiplexes independently-submitted experiments onto warm
compiled gang buckets:

- **Admission key = the structural fingerprint**
  (:func:`serve.scheduler.structural_fingerprint`).  Submissions whose
  configs agree on every trace-relevant field — differing only in
  experiment seed/name and ``training.lr`` (a traced ``hp_lr`` input) —
  share one bucket.
- **Power-of-two bucket growth = the admission policy.**  A bucket's
  gang is built ONCE, with ``min_batch = serve.capacity`` pre-growing
  the compiled lane count to the capacity bucket (``next_bucket``), so
  admitting any 1..capacity tenants is a value-only
  ``GangNetwork.reset_run(member_programs=...)`` splice into frozen
  lanes — zero recompiles across admissions (MUR1601).  More than
  ``capacity`` queued tenants for one fingerprint simply form multiple
  *generations* through the same warm bucket.
- **``freeze_member`` = eviction/degradation.**  An evicted tenant's
  lane stops recording; survivors are untouched (MUR1602) because a
  vmap lane can no more perturb its neighbours than a padding lane can.
- **Crash survival is the ledger + the snapshot.**  Every submission is
  a durably-written ``submissions/<id>.json`` record
  (queued -> running -> done/failed/evicted); every generation writes
  its member composition to ``buckets/<fp>/gen_<n>/generation.json``
  BEFORE training starts and snapshots the full gang state on the
  ``serve.checkpoint_every`` cadence through the durability path
  (MUR900-903).  SIGKILL the daemon at any point: :meth:`recover`
  replays the ledger, rebuilds each in-flight generation's gang from
  the recorded tenant configs (paying that bucket's one compile again),
  restores the snapshot, and continues — byte-identical to the
  uninterrupted run by MUR901, completing every submission (MUR1603).

Threading model: one listener thread owns the unix socket and only
touches the ledger/queue under the lock; the main thread
(:meth:`serve_forever` / :meth:`drain`) runs generations.  Submissions
enqueue at any time and ride the next generation of their bucket.
"""

import json
import os
import socket
import threading
import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from murmura_tpu.config.schema import Config
from murmura_tpu.durability.dispatch import (
    RetryPolicy,
    RetryStats,
    classify_error,
    run_with_retry,
)
from murmura_tpu.serve.scheduler import (
    _NON_STRUCTURAL_SECTIONS,
    structural_fingerprint,
)

# Submission lifecycle states.  Terminal: done / failed / evicted.
TERMINAL_STATES = ("done", "failed", "evicted")


def _jsonable(obj):
    """History/metric payloads carry numpy scalars; flatten for JSON."""
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    return obj


class SubmissionError(ValueError):
    """The submitted config cannot be served (refused at admission)."""


def normalize_submission(raw: Dict[str, Any]) -> Tuple[Config, str]:
    """Validate + normalize one submitted config; returns
    ``(config, fingerprint)``.

    Driver blocks are the daemon's job, not the tenant's: ``sweep`` /
    ``frontier`` / ``grid`` / ``serve`` sections are refused (a tenant is
    ONE experiment), the multi-process ``distributed`` backend is refused
    (its lifecycle cannot ride a gang lane), and observability/durability
    sections are stripped — the daemon owns telemetry and checkpointing.
    """
    if not isinstance(raw, dict):
        raise SubmissionError(
            f"submission config must be a mapping, got {type(raw).__name__}"
        )
    for section in ("sweep", "frontier", "grid", "serve"):
        if raw.get(section) is not None:
            raise SubmissionError(
                f"submission carries a '{section}' section — a tenant is "
                "one experiment; the daemon owns multiplexing"
            )
    raw = dict(raw)
    for section in _NON_STRUCTURAL_SECTIONS:
        raw.pop(section, None)
    try:
        config = Config.model_validate(raw)
    except Exception as e:  # noqa: BLE001 — the client gets the real reason
        raise SubmissionError(f"submission config invalid: {e}") from e
    if config.backend == "distributed":
        raise SubmissionError(
            "backend=distributed cannot be served — the ZMQ process "
            "lifecycle does not fit a gang lane; submit simulation or tpu"
        )
    config.experiment.verbose = False
    return config, structural_fingerprint(config)


class ServeDaemon:
    """The experiment daemon behind ``murmura serve <yaml>``."""

    def __init__(self, config: Config):
        if config.serve is None:
            raise ValueError(
                "murmura serve needs a `serve:` section (state_dir at "
                "minimum) in the daemon config"
            )
        s = config.serve
        self.config = config
        self.capacity = int(s.capacity)
        self.checkpoint_every = int(s.checkpoint_every)
        self.poll_interval_s = float(s.poll_interval_s)
        self.state_dir = Path(s.state_dir).resolve()
        self.socket_path = str(
            s.socket if s.socket else self.state_dir / "daemon.sock"
        )
        (self.state_dir / "submissions").mkdir(parents=True, exist_ok=True)
        (self.state_dir / "buckets").mkdir(parents=True, exist_ok=True)

        self._lock = threading.RLock()
        self._ledger: Dict[str, Dict[str, Any]] = {}
        self._pending: List[str] = []
        # fp -> {"gang": GangNetwork, "gen": int, "lanes": {lane: id}}
        self._buckets: Dict[str, Dict[str, Any]] = {}
        self._stop = threading.Event()
        self._listener: Optional[threading.Thread] = None
        self._server = None
        self._seq = 0
        # Observability plane (ISSUE 19): process-lifetime cumulative
        # counters (ping/top header + the metrics op) and the live
        # TelemetryWriter of each currently-running tenant, so eviction
        # can land a lifecycle event in the tenant's own stream.
        self.started_at = time.time()
        self._counters: Dict[str, int] = {
            "admissions": 0, "evictions": 0, "resumes": 0,
            "compiles": 0, "generations": 0,
        }
        self._tenant_writers: Dict[str, Any] = {}
        self._load_ledger()

    # ------------------------------------------------------------------
    # Durable ledger

    def _record_path(self, sub_id: str) -> Path:
        return self.state_dir / "submissions" / f"{sub_id}.json"

    def _write_record(self, rec: Dict[str, Any]) -> None:
        from murmura_tpu.utils.checkpoint import durable_replace

        durable_replace(
            self.state_dir / "submissions",
            f"{rec['id']}.json",
            (json.dumps(_jsonable(rec), indent=2) + "\n").encode("utf-8"),
        )

    def _update(self, sub_id: str, **fields) -> Dict[str, Any]:
        with self._lock:
            rec = self._ledger[sub_id]
            rec.update(fields)
            self._write_record(rec)
            return rec

    def _load_ledger(self) -> None:
        for path in sorted((self.state_dir / "submissions").glob("*.json")):
            with open(path, encoding="utf-8") as fh:
                rec = json.load(fh)
            self._ledger[rec["id"]] = rec
            num = rec["id"].rsplit("-", 1)[-1]
            if num.isdigit():
                self._seq = max(self._seq, int(num))
            if rec["state"] == "queued":
                self._pending.append(rec["id"])
        self._pending.sort()

    # ------------------------------------------------------------------
    # Admission

    def submit_config(self, raw: Dict[str, Any]) -> Dict[str, Any]:
        """Admit one submission (the in-process twin of the socket
        ``submit`` op); returns the durably-written ledger record."""
        config, fp = normalize_submission(raw)
        with self._lock:
            self._seq += 1
            sub_id = f"sub-{self._seq:05d}"
            rec = {
                "id": sub_id,
                "state": "queued",
                "fingerprint": fp,
                "config": config.model_dump(),
                "submitted_at": time.time(),
                "rounds": config.experiment.rounds,
            }
            self._ledger[sub_id] = rec
            self._write_record(rec)
            self._pending.append(sub_id)
            self._counters["admissions"] += 1
        return dict(rec)

    def evict(self, sub_id: str, reason: str = "evicted") -> Dict[str, Any]:
        """Evict a submission: queued tenants never run; a running
        tenant's lane is frozen (``GangNetwork.freeze_member`` — its
        history stops, survivors are untouched, MUR1602)."""
        with self._lock:
            rec = self._ledger.get(sub_id)
            if rec is None:
                raise KeyError(f"unknown submission {sub_id}")
            if rec["state"] in TERMINAL_STATES:
                return dict(rec)
            if rec["state"] == "queued":
                self._pending = [i for i in self._pending if i != sub_id]
            elif rec["state"] == "running":
                bucket = self._buckets.get(rec["fingerprint"])
                if bucket is not None and rec.get("lane") is not None:
                    bucket["gang"].freeze_member(int(rec["lane"]), reason)
            writer = self._tenant_writers.get(sub_id)
            if writer is not None:
                writer.serve_event(
                    "evicted", reason=reason, gen=rec.get("gen"),
                    lane=rec.get("lane"),
                )
            self._counters["evictions"] += 1
            return dict(self._update(sub_id, state="evicted", error=reason))

    # ------------------------------------------------------------------
    # Buckets and generations

    def _tenant_config(self, sub_id: str) -> Config:
        return Config.model_validate(self._ledger[sub_id]["config"])

    def _member_for(self, config: Config):
        from murmura_tpu.core.gang import GangMember

        # lr is set explicitly for EVERY member so it is always lifted to
        # the traced hp_lr input — tenants with different lr share the
        # compiled program (scheduler._MEMBER_LEAVES).
        return GangMember(
            seed=int(config.experiment.seed),
            lr=float(config.training.lr),
        )

    def _writer(self, sub_id: str, config: Config, resume: bool):
        from murmura_tpu.telemetry.writer import TelemetryWriter

        return TelemetryWriter(
            str(self.state_dir / "telemetry" / sub_id),
            kind="run",
            run_id=sub_id,
            config=config,
            record_taps=True,
            phase_times=True,
            resume=resume,
        )

    def _ensure_bucket(self, fp: str, template: Config) -> Dict[str, Any]:
        """The warm bucket for fingerprint ``fp``, building it on first
        use: a 1-member template gang with ``min_batch=capacity``, so the
        compiled lane count is already the capacity bucket and every
        later admission is value-only."""
        from murmura_tpu.utils.factories import build_gang_from_config

        with self._lock:
            bucket = self._buckets.get(fp)
            if bucket is not None:
                return bucket
        raw = template.model_dump()
        member = self._member_for(template)
        raw["sweep"] = {
            "members": [{"seed": member.seed, "lr": member.lr}]
        }
        template_cfg = Config.model_validate(raw)
        gang = build_gang_from_config(
            template_cfg, min_batch=self.capacity,
        )
        bucket = {"gang": gang, "gen": 0, "lanes": {}}
        with self._lock:
            self._buckets[fp] = bucket
        return bucket

    def _gen_dir(self, fp: str, gen: int) -> Path:
        return self.state_dir / "buckets" / fp / f"gen_{gen}"

    def _next_generation(self) -> Optional[Tuple[str, List[str]]]:
        """The next generation to run: the oldest queued submission's
        fingerprint group, up to ``capacity`` tenants, FIFO."""
        with self._lock:
            if not self._pending:
                return None
            fp = self._ledger[self._pending[0]]["fingerprint"]
            ids = [
                i for i in self._pending
                if self._ledger[i]["fingerprint"] == fp
            ][: self.capacity]
            self._pending = [i for i in self._pending if i not in ids]
            return fp, ids

    def _run_generation(
        self,
        fp: str,
        ids: Sequence[str],
        *,
        gen: Optional[int] = None,
        resume: bool = False,
    ) -> None:
        """Run one generation of bucket ``fp`` with tenants ``ids``.

        The composition record (``generation.json``) is durably written
        BEFORE any training so a SIGKILL at any later point leaves enough
        on disk to rebuild the exact gang and resume it."""
        from murmura_tpu.utils.checkpoint import durable_replace
        from murmura_tpu.utils.factories import build_gang_member_programs

        ids = list(ids)
        tenants = [(i, self._tenant_config(i)) for i in ids]
        bucket = self._ensure_bucket(fp, tenants[0][1])
        gang = bucket["gang"]
        if gen is None:
            gen = bucket["gen"] + 1
        gen_dir = self._gen_dir(fp, gen)
        gen_dir.mkdir(parents=True, exist_ok=True)
        rounds = int(tenants[0][1].experiment.rounds)

        members = [self._member_for(cfg) for _, cfg in tenants]
        if not resume:
            durable_replace(
                gen_dir, "generation.json",
                (json.dumps({
                    "fingerprint": fp,
                    "gen": gen,
                    "rounds": rounds,
                    "submissions": [
                        {"id": i, "seed": m.seed, "lr": m.lr}
                        for i, m in zip(ids, members)
                    ],
                }, indent=2) + "\n").encode("utf-8"),
            )
        with self._lock:
            bucket["lanes"] = {lane: i for lane, i in enumerate(ids)}
            for lane, sub_id in enumerate(ids):
                self._update(
                    sub_id, state="running", bucket=fp, gen=gen, lane=lane,
                )

        progs = [
            build_gang_member_programs(cfg, [m])[0]
            for (_, cfg), m in zip(tenants, members)
        ]
        writers = [
            self._writer(i, cfg, resume=resume) for i, cfg in tenants
        ]
        # Lifecycle events through each tenant's OWN stream (ISSUE 19
        # satellite): the trace/report side of the ledger transitions.
        # ``submitted`` is backdated to the ledger's submitted_at — the
        # writer only exists from admission, but the queue time is real.
        compile_baseline = self._compile_count()
        for lane, ((sub_id, _cfg), w) in enumerate(zip(tenants, writers)):
            rec = self._ledger[sub_id]
            if not resume:
                w.serve_event("submitted", _t=rec.get("submitted_at"),
                              bucket=fp)
                w.serve_event("admitted", bucket=fp, gen=gen, lane=lane)
            else:
                w.serve_event("resumed", bucket=fp, gen=gen, lane=lane)
            w.serve_event("generation_start", gen=gen, lane=lane)
        with self._lock:
            if resume:
                self._counters["resumes"] += len(ids)
            self._counters["generations"] += 1
            self._tenant_writers.update(zip(ids, writers))
        gang.reset_run(
            members, member_programs=progs, telemetry_writers=writers,
        )
        snapshot_exists = (gen_dir / "meta.json").exists()
        if resume and snapshot_exists:
            gang.restore_checkpoint(str(gen_dir))

        def attempt(try_idx: int):
            if try_idx > 0 and (gen_dir / "meta.json").exists():
                # Retrying with consumed (donated) buffers is never safe:
                # the restore IS the retry mechanism (dispatch.py).
                gang.restore_checkpoint(str(gen_dir))
            remaining = rounds - gang.current_round
            if remaining > 0:
                gang.train(
                    rounds=remaining,
                    eval_every=1,
                    checkpoint_dir=str(gen_dir),
                    checkpoint_every=self.checkpoint_every,
                )
            return gang.histories

        retry_stats = RetryStats()

        def on_retry(exc, try_idx, delay):
            # The envelope's degradations land in every tenant stream —
            # the dispatch-retry leg of the metrics fold.
            retry_stats.hook(exc, try_idx, delay)
            for w in writers:
                w.emit(
                    "backend_degraded", kind="retry",
                    reason=retry_stats.last_reason, retry=try_idx,
                    delay_s=delay,
                )

        try:
            histories = run_with_retry(
                attempt,
                policy=RetryPolicy(max_retries=2, base_delay_s=0.1,
                                   max_delay_s=1.0, seed=0),
                classify=classify_error,
                on_retry=on_retry,
            )
        except Exception as e:  # noqa: BLE001 — per-tenant fate recording
            for sub_id, w in zip(ids, writers):
                if self._ledger[sub_id]["state"] == "running":
                    self._update(
                        sub_id, state="failed",
                        error=f"{type(e).__name__}: {e}",
                    )
                w.serve_event(
                    "generation_done", gen=gen,
                    outcome=self._ledger[sub_id]["state"],
                )
            self._finish_generation(
                fp, gen, ids, writers, compile_baseline, retry_stats,
            )
            return

        for lane, sub_id in enumerate(ids):
            if self._ledger[sub_id]["state"] != "running":
                # Evicted mid-generation: its state is terminal and its
                # eviction event already landed in the stream.
                continue
            hist = histories[lane]
            mean = hist.get("mean_accuracy") or []
            honest = hist.get("honest_accuracy") or mean
            self._update(
                sub_id,
                state="done",
                final_accuracy=float(mean[-1]) if mean else None,
                honest_accuracy=float(honest[-1]) if honest else None,
                history=_jsonable(hist),
                phase_times={
                    "mode": "gang_per_round",
                    "rounds": rounds,
                    "mean_round_s": (
                        float(np.mean(gang.round_times))
                        if gang.round_times else 0.0
                    ),
                },
            )
            writers[lane].serve_event(
                "generation_done", gen=gen, outcome="done",
            )
        self._finish_generation(
            fp, gen, ids, writers, compile_baseline, retry_stats,
        )

    def _compile_count(self) -> int:
        """Process-wide backend compile counter (sanitizers.py); 0 when
        jax has not initialized yet (nothing can have compiled)."""
        try:
            from murmura_tpu.analysis.sanitizers import compile_count

            return compile_count()
        except Exception:  # noqa: BLE001 — accounting must not kill serving
            return 0

    def _finish_generation(self, fp, gen, ids, writers,
                           compile_baseline, retry_stats=None) -> None:
        """Close the generation: fold the compile delta and the dispatch
        envelope's retry totals into each tenant's manifest, retire the
        live writers, and advance the bucket."""
        compiled = max(0, self._compile_count() - compile_baseline)
        for w in writers:
            if compiled:
                w.add_counters({"serve_compiles": compiled})
            if retry_stats is not None and retry_stats.retries:
                w.add_counters(retry_stats.counters())
            try:
                w.finalize()
                w.close()
            except Exception:  # noqa: BLE001 — teardown best-effort
                pass
        with self._lock:
            self._counters["compiles"] += compiled
            for sub_id in ids:
                self._tenant_writers.pop(sub_id, None)
            bucket = self._buckets.get(fp)
            if bucket is not None:
                bucket["gen"] = max(bucket["gen"], gen)
                bucket["lanes"] = {}

    # ------------------------------------------------------------------
    # Crash recovery

    def recover(self) -> List[str]:
        """Resume every in-flight generation from its on-disk record
        (MUR1603): rebuild the gang from the recorded tenant configs
        (paying that bucket's one compile again), restore the latest
        snapshot when one exists, and run the remaining rounds — or the
        whole generation when the kill landed before the first cadence
        snapshot.  Either way the completed histories are byte-identical
        to the uninterrupted run (MUR901).  Returns the recovered
        submission ids."""
        in_flight: Dict[Tuple[str, int], List[str]] = {}
        with self._lock:
            for sub_id, rec in self._ledger.items():
                if rec["state"] == "running":
                    key = (rec["fingerprint"], int(rec["gen"]))
                    in_flight.setdefault(key, []).append(sub_id)
        recovered: List[str] = []
        for (fp, gen), _ids in sorted(in_flight.items()):
            gen_dir = self._gen_dir(fp, gen)
            record_path = gen_dir / "generation.json"
            if not record_path.exists():
                for sub_id in _ids:
                    self._update(
                        sub_id, state="failed",
                        error="generation record lost before first write",
                    )
                continue
            with open(record_path, encoding="utf-8") as fh:
                record = json.load(fh)
            ids = [s["id"] for s in record["submissions"]]
            self._run_generation(fp, ids, gen=gen, resume=True)
            recovered.extend(ids)
        return recovered

    # ------------------------------------------------------------------
    # Drive

    def drain(self) -> None:
        """Run generations until the queue is empty (tests / one-shot)."""
        while True:
            nxt = self._next_generation()
            if nxt is None:
                return
            self._run_generation(*nxt)

    def serve_forever(self) -> None:
        """Bind the socket, recover in-flight work, then serve until a
        ``shutdown`` request (graceful: the current generation always
        completes — every state transition is durable anyway)."""
        self._start_listener()
        try:
            self.recover()
            while not self._stop.is_set():
                nxt = self._next_generation()
                if nxt is not None:
                    self._run_generation(*nxt)
                else:
                    self._stop.wait(self.poll_interval_s)
        finally:
            self.close()

    def _start_listener(self) -> None:
        from murmura_tpu.serve.protocol import ServerSocket

        self._server = ServerSocket(self.socket_path)
        self._listener = threading.Thread(
            target=self._listen, name="murmura-serve-listener", daemon=True,
        )
        self._listener.start()

    def _listen(self) -> None:
        from murmura_tpu.serve.protocol import serve_connection

        while not self._stop.is_set():
            try:
                conn, _ = self._server.accept(timeout=0.2)
            except socket.timeout:
                continue
            except OSError:
                break
            serve_connection(conn, self.handle_request)

    def close(self) -> None:
        self._stop.set()
        if self._server is not None:
            self._server.close()
            self._server = None
        if self._listener is not None:
            self._listener.join(timeout=2.0)
            self._listener = None

    # ------------------------------------------------------------------
    # Protocol handler

    def metrics_registry(self):
        """The daemon's scrape (``{"op": "metrics"}``): cumulative
        counters + ledger-state census + queue/bucket gauges, then each
        tenant's durable event stream folded per-tenant.  Everything is
        a replay of durable state — the MUR1700 parity contract."""
        from murmura_tpu.telemetry.metrics import (
            MetricsRegistry,
            fold_run_events,
        )

        reg = MetricsRegistry()
        with self._lock:
            reg.set_gauge(
                "murmura_serve_uptime_seconds",
                time.time() - self.started_at,
                help="daemon uptime",
            )
            reg.set_gauge(
                "murmura_serve_queue_depth", len(self._pending),
                help="queued submissions awaiting a generation",
            )
            for cname, cval in self._counters.items():
                reg.inc(
                    "murmura_serve_lifetime", float(cval),
                    labels={"counter": cname},
                    help="cumulative daemon counters (admissions, "
                         "evictions, resumes, compiles, generations)",
                )
            states: Dict[str, int] = {}
            tenant_ids = []
            for sub_id, rec in self._ledger.items():
                states[rec["state"]] = states.get(rec["state"], 0) + 1
                tenant_ids.append(sub_id)
            for state, count in sorted(states.items()):
                reg.set_gauge(
                    "murmura_serve_submissions", count,
                    labels={"state": state},
                    help="ledger census by lifecycle state",
                )
            for fp, b in self._buckets.items():
                reg.set_gauge(
                    "murmura_serve_bucket_lanes", b["gang"].batch,
                    labels={"bucket": fp}, help="compiled lane capacity",
                )
                reg.set_gauge(
                    "murmura_serve_bucket_running", len(b["lanes"]),
                    labels={"bucket": fp}, help="occupied lanes",
                )
        for sub_id in tenant_ids:
            run_dir = self.state_dir / "telemetry" / sub_id
            if run_dir.exists():
                fold_run_events(reg, run_dir, labels={"tenant": sub_id})
        return reg

    def handle_request(self, request: Dict[str, Any]) -> Dict[str, Any]:
        from murmura_tpu import __version__
        from murmura_tpu.telemetry.schema import MANIFEST_SCHEMA_VERSION

        op = request.get("op")
        if op == "ping":
            with self._lock:
                return {
                    "ok": True,
                    "pid": os.getpid(),
                    "uptime_s": time.time() - self.started_at,
                    "version": __version__,
                    "schema_version": MANIFEST_SCHEMA_VERSION,
                    "counters": dict(self._counters),
                    "queued": len(self._pending),
                    "buckets": {
                        fp: {
                            "gen": b["gen"],
                            "batch": b["gang"].batch,
                            "running": len(b["lanes"]),
                        }
                        for fp, b in self._buckets.items()
                    },
                }
        if op == "metrics":
            from murmura_tpu.telemetry.metrics import render_openmetrics

            return {
                "ok": True,
                "content_type": "application/openmetrics-text; "
                                "version=1.0.0; charset=utf-8",
                "text": render_openmetrics(self.metrics_registry()),
            }
        if op == "submit":
            rec = self.submit_config(request.get("config"))
            return {
                "ok": True, "id": rec["id"], "bucket": rec["fingerprint"],
            }
        if op == "status":
            with self._lock:
                rec = self._ledger.get(request.get("id"))
            if rec is None:
                return {"ok": False, "error": f"unknown id {request.get('id')}"}
            return {"ok": True, "submission": _jsonable(rec)}
        if op == "list":
            with self._lock:
                rows = [
                    {
                        "id": r["id"],
                        "state": r["state"],
                        "bucket": r["fingerprint"],
                        "gen": r.get("gen"),
                        "lane": r.get("lane"),
                        "rounds": r.get("rounds"),
                        "final_accuracy": r.get("final_accuracy"),
                    }
                    for _, r in sorted(self._ledger.items())
                ]
                counters = dict(self._counters)
            return {
                "ok": True,
                "uptime_s": time.time() - self.started_at,
                "counters": counters,
                "submissions": rows,
            }
        if op == "evict":
            rec = self.evict(
                request.get("id"), request.get("reason", "evicted"),
            )
            return {"ok": True, "submission": _jsonable(rec)}
        if op == "shutdown":
            self._stop.set()
            return {"ok": True}
        return {"ok": False, "error": f"unknown op {op!r}"}
